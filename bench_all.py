"""Extended benchmark suite covering the BASELINE.json configs beyond the
headline row-conversion metric (bench.py remains the driver's single-line
entry):

  config 2: hash group-by aggregate on a 1e7-row int64/float64 table
  config 3: inner join on two large int64 tables
  config 4: string ops (get_json_object + parse_url + substring) on 1e6
            rows
  plus: murmur3/xxhash64 hash throughput, OOM state machine ops/sec
        (python vs native)

Writes BENCH_EXTRA.json and prints it.  Timings that touch the device
use the chained-dependency pattern from bench_impl.py; host-path ops use
plain wall clock.
"""

import json
import time

import jax

jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402


def bench_groupby(n=10_000_000, groups=10_000):
    from spark_rapids_tpu.columns.column import Column
    from spark_rapids_tpu.columns.table import Table
    from spark_rapids_tpu.ops import groupby as gb
    rng = np.random.default_rng(0)
    keys = Table([Column.from_numpy(
        rng.integers(0, groups, n, dtype=np.int64))])
    vals = Column.from_numpy(rng.normal(size=n))
    results = {}
    for label in ("cold", "warm"):  # cold includes eager-op compiles
        t0 = time.perf_counter()
        out = gb.groupby_aggregate(keys, [vals, vals],
                                   [gb.SUM, gb.COUNT])
        total = int(np.asarray(out.columns[2].data).sum())
        dt = time.perf_counter() - t0
        assert total == n
        results[label] = round(dt, 3)
    return {"rows": n, "groups": groups, "seconds": results,
            "warm_rows_per_sec_M": round(n / results["warm"] / 1e6, 1)}


def bench_join(n=10_000_000, keyspace=1_000_000):
    from spark_rapids_tpu.columns.column import Column
    from spark_rapids_tpu.columns.table import Table
    from spark_rapids_tpu.ops import joins
    rng = np.random.default_rng(1)
    left = Table([Column.from_numpy(
        rng.integers(0, keyspace, n, dtype=np.int64))])
    right = Table([Column.from_numpy(
        np.arange(keyspace, dtype=np.int64))])
    results = {}
    for label in ("cold", "warm"):  # cold includes eager-op compiles
        t0 = time.perf_counter()
        li, ri = joins.sort_merge_inner_join(left, right)
        import jax
        jax.block_until_ready((li, ri))
        dt = time.perf_counter() - t0
        pairs = int(li.shape[0])
        results[label] = round(dt, 3)
    return {"left_rows": n, "right_rows": keyspace, "pairs": pairs,
            "seconds": results,
            "warm_rows_per_sec_M": round(n / results["warm"] / 1e6, 1)}


def bench_strings(n=1_000_000):
    from spark_rapids_tpu.columns.column import Column
    from spark_rapids_tpu.ops import json_path, parse_uri
    from spark_rapids_tpu.ops.substring_index import substring_index
    docs = [f'{{"user": {{"id": {i}, "name": "u{i}"}}, "n": {i % 97}}}'
            for i in range(n // 10)]  # 100k json docs
    jcol = Column.from_strings(docs)
    t0 = time.perf_counter()
    out = json_path.get_json_object(jcol, "$.user.name")
    dt_json = time.perf_counter() - t0
    assert out.to_pylist()[1] == "u1"

    urls = [f"https://host{i % 50}.example.com/p/{i}?k={i}&x=1"
            for i in range(n // 10)]
    ucol = Column.from_strings(urls)
    t0 = time.perf_counter()
    hosts = parse_uri.parse_uri_to_host(ucol)
    dt_uri = time.perf_counter() - t0

    strs = Column.from_strings(
        [f"a{i}.b{i}.c{i}" for i in range(n)])
    t0 = time.perf_counter()
    sub = substring_index(strs, ".", 2)
    dt_sub = time.perf_counter() - t0
    return {
        "get_json_object_rows_per_sec":
            round(len(docs) / dt_json / 1e3, 1),
        "parse_url_rows_per_sec": round(len(urls) / dt_uri / 1e3, 1),
        "substring_index_rows_per_sec": round(n / dt_sub / 1e6, 2),
        "units": "k or M rows/sec (host paths except substring)",
    }


def bench_hash(n=10_000_000):
    import jax.numpy as jnp
    from spark_rapids_tpu.columns import dtypes
    from spark_rapids_tpu.columns.column import Column
    from spark_rapids_tpu.ops import hash as H
    rng = np.random.default_rng(2)
    col = Column.from_numpy(rng.integers(-2**60, 2**60, n,
                                         dtype=np.int64))

    def step(salt):
        c = Column(dtypes.INT64, n, data=col.data + salt)
        h = H.murmur3_32([c], 42).data
        x = H.xxhash64([c]).data
        # return the hash arrays: jit outputs must be materialized
        return h, x, h[0].astype(jnp.int64) + salt

    stepj = jax.jit(step)
    tiny = jax.jit(lambda v: v + 1)
    int(tiny(jnp.int64(0)))
    _h, _x, salt = stepj(jnp.int64(0))
    int(salt)
    t0 = time.perf_counter()
    int(tiny(jnp.int64(1)))
    rtt = time.perf_counter() - t0
    K = 20
    t0 = time.perf_counter()
    for _ in range(K):
        _h, _x, salt = stepj(salt)
    int(salt)
    dt = max(time.perf_counter() - t0 - rtt, 1e-9) / K
    return {"rows": n, "seconds_per_pass": round(dt, 4),
            "hash_rows_per_sec_M": round(n / dt / 1e6, 0),
            "note": "murmur3_32 + xxhash64 per pass, chained timing"}


def bench_oom_machine(ops=20_000):
    import threading
    results = {}
    for impl in ("python", "native"):
        if impl == "python":
            from spark_rapids_tpu.memory.resource import \
                LimitingMemoryResource
            from spark_rapids_tpu.memory.spark_resource_adaptor import \
                SparkResourceAdaptor
            a = SparkResourceAdaptor(LimitingMemoryResource(1 << 40))
        else:
            from spark_rapids_tpu.memory import native_adaptor
            if not native_adaptor.available():
                continue
            a = native_adaptor.NativeSparkResourceAdaptor(1 << 40)
        tid = threading.get_ident()
        a.start_dedicated_task_thread(tid, 1)
        t0 = time.perf_counter()
        for _ in range(ops):
            a.allocate(64)
            a.deallocate(64)
        dt = time.perf_counter() - t0
        a.task_done(1)
        a.shutdown()
        results[impl] = round(ops * 2 / dt / 1e3, 1)
    return {"alloc_dealloc_kops_per_sec": results}


def main():
    out = {
        "groupby_1e7": bench_groupby(),
        "join_1e7": bench_join(),
        "string_ops_1e6": bench_strings(),
        "hash_1e7": bench_hash(),
        "oom_machine": bench_oom_machine(),
    }
    with open("BENCH_EXTRA.json", "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
