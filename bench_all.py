"""Extended benchmark suite covering the BASELINE.json configs beyond the
headline row-conversion metric (bench.py remains the driver's single-line
entry):

  config 2: hash group-by aggregate on a 1e7-row int64/float64 table
  config 3: inner join on two large int64 tables
  config 4: string ops (get_json_object + parse_url + substring) on 1e6
            rows
  plus: murmur3/xxhash64 hash throughput, OOM state machine ops/sec
        (python vs native)

Writes BENCH_EXTRA.json and prints it.  Timings that touch the device
use the chained-dependency pattern from bench_impl.py; host-path ops use
plain wall clock.
"""

import json
import time

import jax

jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

# fight for the TPU relay the same way bench.py does (a wedged relay
# hangs any in-process jax.devices()); CPU fallback is recorded in the
# output's "backend" field.  BENCH_FIGHT_SECONDS=1 for a quick CPU run.
if __name__ == "__main__":
    from bench import _fight_for_backend

    _backend, _attempts = _fight_for_backend()
    if _backend != "tpu":
        jax.config.update("jax_platforms", "cpu")


def _path_snapshot():
    from spark_rapids_tpu import observability as obs
    fam = obs.METRICS.snapshot().get("srt_kernel_path_total", {})
    return {tuple(s["labels"]): s["value"] for s in fam.get("series", [])}


def _taken_path(op, before):
    """Calibrated engine(s) ``op`` actually ran since ``before`` (a
    _path_snapshot) — the bench table's path field is routing evidence
    read back from srt_kernel_path_total, not a hard-coded guess
    (ISSUE 9)."""
    grown = sorted({k[1] for k, v in _path_snapshot().items()
                    if k[0] == op and v > before.get(k, 0)})
    return "calibrated: " + "+".join(grown) if grown else "?"


def bench_groupby(n=10_000_000, groups=10_000):
    from spark_rapids_tpu.columns.column import Column
    from spark_rapids_tpu.columns.table import Table
    from spark_rapids_tpu.ops import groupby as gb
    rng = np.random.default_rng(0)
    keys = Table([Column.from_numpy(
        rng.integers(0, groups, n, dtype=np.int64))])
    vals = Column.from_numpy(rng.normal(size=n))
    results = {}
    for label in ("cold", "warm"):  # cold includes eager-op compiles
        t0 = time.perf_counter()
        out = gb.groupby_aggregate(keys, [vals, vals],
                                   [gb.SUM, gb.COUNT])
        total = int(np.asarray(out.columns[2].data).sum())
        dt = time.perf_counter() - t0
        assert total == n
        results[label] = round(dt, 3)
    return {"rows": n, "groups": groups, "seconds": results,
            "warm_rows_per_sec_M": round(n / results["warm"] / 1e6, 1)}


def bench_join(n=10_000_000, keyspace=1_000_000):
    from spark_rapids_tpu.columns.column import Column
    from spark_rapids_tpu.columns.table import Table
    from spark_rapids_tpu.ops import joins
    rng = np.random.default_rng(1)
    left = Table([Column.from_numpy(
        rng.integers(0, keyspace, n, dtype=np.int64))])
    right = Table([Column.from_numpy(
        np.arange(keyspace, dtype=np.int64))])
    results = {}
    for label in ("cold", "warm"):  # cold includes calibration+compiles
        before = _path_snapshot()
        t0 = time.perf_counter()
        li, ri = joins.sort_merge_inner_join(left, right)
        jax.block_until_ready((li, ri))
        dt = time.perf_counter() - t0
        pairs = int(li.shape[0])
        results[label] = round(dt, 3)
    path = _taken_path("join.inner", before)
    out = {"left_rows": n, "right_rows": keyspace, "pairs": pairs,
           "seconds": results, "path": path,
           "warm_rows_per_sec_M": round(n / results["warm"] / 1e6, 1)}

    # string-key variant (short keys: device-encodable)
    sl = Table([Column.from_strings(
        ["k%07d" % (i % keyspace) for i in range(n // 10)])])
    sr = Table([Column.from_strings(
        ["k%07d" % i for i in range(keyspace // 10)])])
    joins.sort_merge_inner_join(sl, sr)
    before = _path_snapshot()
    t0 = time.perf_counter()
    li, ri = joins.sort_merge_inner_join(sl, sr)
    jax.block_until_ready((li, ri))
    dt = time.perf_counter() - t0
    out["string_keys_1e6"] = {
        "left_rows": n // 10, "seconds": round(dt, 3),
        "warm_rows_per_sec_M": round(n / 10 / dt / 1e6, 2),
        "path": _taken_path("join.inner", before)}
    return out


def bench_strings(n=1_000_000):
    """All figures in k rows/sec; every entry names its code path."""
    from spark_rapids_tpu.columns.column import Column
    from spark_rapids_tpu.ops import json_path, parse_uri
    from spark_rapids_tpu.ops.substring_index import substring_index

    def timed(fn, *args):
        fn(*args)                      # warm (compile)
        t0 = time.perf_counter()
        out = fn(*args)
        return out, time.perf_counter() - t0

    docs = [f'{{"user": {{"id": {i}, "name": "u{i}"}}, "n": {i % 97}}}'
            for i in range(n)]
    jcol = Column.from_strings(docs)
    before_json = _path_snapshot()
    out, dt_json = timed(json_path.get_json_object, jcol,
                         "$.user.name")
    assert out.to_pylist()[1] == "u1"

    urls = [f"https://host{i % 50}.example.com/p/{i}?k={i}&x=1"
            for i in range(n)]
    ucol = Column.from_strings(urls)
    # warm the compile on a SEPARATE column so the timed first-extract
    # below really pays the span analysis (the analysis memo is
    # per-column; timing a second call on the same column would measure
    # the cached regime — that's the next_3_components entry)
    parse_uri.parse_uri_to_host(Column.from_strings(urls))
    t0 = time.perf_counter()
    _hosts = parse_uri.parse_uri_to_host(ucol)
    dt_uri = time.perf_counter() - t0
    # subsequent components reuse the cached span analysis
    t0 = time.perf_counter()
    parse_uri.parse_uri_to_protocol(ucol)
    parse_uri.parse_uri_to_query(ucol)
    parse_uri.parse_uri_to_path(ucol)
    dt_uri_rest = time.perf_counter() - t0

    strs = Column.from_strings([f"a{i}.b{i}.c{i}" for i in range(n)])
    _sub, dt_sub = timed(substring_index, strs, ".", 2)
    return {
        "rows": n,
        "unit": "k_rows_per_sec",
        "get_json_object": {
            "k_rows_per_sec": round(n / dt_json / 1e3, 1),
            "path": _taken_path("get_json_object", before_json)},
        "parse_url_host_first": {
            "k_rows_per_sec": round(n / dt_uri / 1e3, 1),
            "path": "device analyze + materialize"},
        "parse_url_next_3_components": {
            "k_rows_per_sec": round(3 * n / dt_uri_rest / 1e3, 1),
            "path": "cached device analysis, materialize only"},
        "substring_index": {
            "k_rows_per_sec": round(n / dt_sub / 1e3, 1),
            "path": "device match scan + numpy gather (r4 fix)"},
    }


def bench_decoders(n=1_000_000):
    """protobuf / from_json / GBK — the four r3 host-loop families,
    now device/vectorized (r4).  k rows/sec, path-labeled."""
    import struct as _st

    from spark_rapids_tpu.columns import dtypes
    from spark_rapids_tpu.columns.column import Column
    from spark_rapids_tpu.ops import protobuf as pb
    from spark_rapids_tpu.ops import json_utils as JU
    from spark_rapids_tpu.ops import strings_misc as SM

    def timed(fn, *args):
        fn(*args)
        t0 = time.perf_counter()
        fn(*args)
        return time.perf_counter() - t0

    def varint(v):
        out = b""
        v &= (1 << 64) - 1
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                out += bytes([b | 0x80])
            else:
                return out + bytes([b])

    msgs = [(b"\x08" + varint(i)                      # field 1 varint
             + b"\x12" + varint(8) + b"payload%d" % (i % 10)  # field 2
             + b"\x19" + _st.pack("<d", 1.5 * i))     # field 3 fixed64
            for i in range(n)]
    pcol = Column.from_strings(msgs)
    pfields = [pb.Field(1, dtypes.INT64, name="a"),
               pb.Field(2, dtypes.STRING, name="s"),
               pb.Field(3, dtypes.FLOAT64, encoding=pb.FIXED,
                        name="d")]
    dt_pb = timed(pb.decode_protobuf_to_struct, pcol, pfields)

    jdocs = [f'{{"a": {i}, "s": "u{i}", "d": {i}.5}}'
             for i in range(n)]
    jcol = Column.from_strings(jdocs)
    jfields = [("a", dtypes.INT64), ("s", dtypes.STRING),
               ("d", dtypes.FLOAT64)]
    before_fj = _path_snapshot()
    dt_fj = timed(JU.from_json_to_structs, jcol, jfields)

    gbk_rows = [("值%d中文" % i).encode("gbk") for i in range(n)]
    gcol = Column.from_strings(gbk_rows)
    dt_gbk = timed(SM.decode_to_utf8, gcol, "GBK", SM.REPLACE)

    rmdocs = [f'{{"id": {i}, "tag": "t{i % 9}", "ok": true}}'
              for i in range(n)]
    rmcol = Column.from_strings(rmdocs)
    before_rm = _path_snapshot()
    dt_rm = timed(JU.from_json_to_raw_map, rmcol)

    return {
        "rows": n,
        "from_json_raw_map": {
            "k_rows_per_sec": round(n / dt_rm / 1e3, 1),
            "path": _taken_path("from_json_raw_map", before_rm)},
        "protobuf_decode": {
            "k_rows_per_sec": round(n / dt_pb / 1e3, 1),
            "path": "device masked-scan (protobuf_device)"},
        "from_json_structs": {
            "k_rows_per_sec": round(n / dt_fj / 1e3, 1),
            "path": _taken_path("from_json_structs", before_fj)},
        "gbk_decode": {
            "k_rows_per_sec": round(n / dt_gbk / 1e3, 1),
            "path": "vectorized table decode (r4; was per-row codec)"},
    }


def bench_hash(n=10_000_000):
    import jax.numpy as jnp
    from spark_rapids_tpu.columns import dtypes
    from spark_rapids_tpu.columns.column import Column
    from spark_rapids_tpu.ops import hash as H
    rng = np.random.default_rng(2)
    col = Column.from_numpy(rng.integers(-2**60, 2**60, n,
                                         dtype=np.int64))

    def step(salt):
        c = Column(dtypes.INT64, n, data=col.data + salt)
        h = H.murmur3_32([c], 42).data
        x = H.xxhash64([c]).data
        # return the hash arrays: jit outputs must be materialized
        return h, x, h[0].astype(jnp.int64) + salt

    stepj = jax.jit(step)
    tiny = jax.jit(lambda v: v + 1)
    int(tiny(jnp.int64(0)))
    _h, _x, salt = stepj(jnp.int64(0))
    int(salt)
    t0 = time.perf_counter()
    int(tiny(jnp.int64(1)))
    rtt = time.perf_counter() - t0
    K = 20
    t0 = time.perf_counter()
    for _ in range(K):
        _h, _x, salt = stepj(salt)
    int(salt)
    dt = max(time.perf_counter() - t0 - rtt, 1e-9) / K
    return {"rows": n, "seconds_per_pass": round(dt, 4),
            "hash_rows_per_sec_M": round(n / dt / 1e6, 0),
            "note": "murmur3_32 + xxhash64 per pass, chained timing"}


def bench_oom_machine(ops=20_000):
    import threading
    results = {}
    for impl in ("python", "native"):
        if impl == "python":
            from spark_rapids_tpu.memory.resource import \
                LimitingMemoryResource
            from spark_rapids_tpu.memory.spark_resource_adaptor import \
                SparkResourceAdaptor
            a = SparkResourceAdaptor(LimitingMemoryResource(1 << 40))
        else:
            from spark_rapids_tpu.memory import native_adaptor
            if not native_adaptor.available():
                continue
            a = native_adaptor.NativeSparkResourceAdaptor(1 << 40)
        tid = threading.get_ident()
        a.start_dedicated_task_thread(tid, 1)
        t0 = time.perf_counter()
        for _ in range(ops):
            a.allocate(64)
            a.deallocate(64)
        dt = time.perf_counter() - t0
        a.task_done(1)
        a.shutdown()
        results[impl] = round(ops * 2 / dt / 1e3, 1)
    return {"alloc_dealloc_kops_per_sec": results}


def bench_tpcds(rows=2_000_000):
    """TPC-DS-shaped flagship pipelines (models/tpcds.py): per-query
    wall time for one fully-jitted scan->join->group->order program,
    warm (post-compile) timings."""
    from spark_rapids_tpu.models import tpcds
    out = {}

    d5 = tpcds.gen_q5(rows=rows, stores=64, days=120)
    q5 = tpcds.make_q5(64, join_capacity=1 << 19)
    t0 = time.perf_counter()
    res5 = q5(d5)
    jax.block_until_ready(res5)
    assert not bool(res5[-1]), "q5 bench overflowed its join capacity"
    out["q5_compile_plus_run_s"] = round(time.perf_counter() - t0, 3)
    t0 = time.perf_counter()
    jax.block_until_ready(q5(d5))
    warm = time.perf_counter() - t0
    out["q5_warm_s"] = round(warm, 4)
    out["q5_rows_per_s"] = round(rows / warm)

    q, p, n = tpcds.gen_q9(rows=rows)
    jax.block_until_ready(tpcds.run_q9(q, p, n))
    t0 = time.perf_counter()
    jax.block_until_ready(tpcds.run_q9(q, p, n))
    warm = time.perf_counter() - t0
    out["q9_warm_s"] = round(warm, 4)
    out["q9_rows_per_s"] = round(rows / warm)

    # fact-fact pair count ~ cs*inv/items: 250k*250k/16k ~ 3.8M < 2^22
    d72 = tpcds.gen_q72(cs_rows=rows // 8, inv_rows=rows // 8,
                        items=16384, days=70)
    q72 = tpcds.make_q72(16384, 16, join_capacity=1 << 22,
                         week0=11_000 // 7)
    res = q72(d72)
    jax.block_until_ready(res)
    assert not bool(res[-1]), "q72 bench overflowed its join capacity"
    t0 = time.perf_counter()
    jax.block_until_ready(q72(d72))
    warm = time.perf_counter() - t0
    out["q72_warm_s"] = round(warm, 4)
    out["q72_cs_rows_per_s"] = round(rows // 8 / warm)

    d3 = tpcds.gen_q3(rows=rows, items=1024, days=730, brands=64)
    q3 = tpcds.make_q3(10_957, years=3, brands=64, manufact=2)
    jax.block_until_ready(q3(d3))
    t0 = time.perf_counter()
    jax.block_until_ready(q3(d3))
    warm = time.perf_counter() - t0
    out["q3_warm_s"] = round(warm, 4)
    out["q3_rows_per_s"] = round(rows / warm)

    d7 = tpcds.gen_q7(rows=rows, items=1024)
    q7 = tpcds.make_q7(1024)
    jax.block_until_ready(q7(d7))
    t0 = time.perf_counter()
    jax.block_until_ready(q7(d7))
    warm = time.perf_counter() - t0
    out["q7_warm_s"] = round(warm, 4)
    out["q7_rows_per_s"] = round(rows / warm)
    return out


def main():
    # the path fields are read back from srt_kernel_path_total — the
    # registry must be on for the evidence to exist
    from spark_rapids_tpu import observability as obs
    obs.enable()
    out = {
        "backend": jax.default_backend(),
        "measured": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "groupby_1e7": bench_groupby(),
        "join_1e7": bench_join(),
        "string_ops_1e6": bench_strings(),
        "decoders_1e6": bench_decoders(),
        "hash_1e7": bench_hash(),
        "oom_machine": bench_oom_machine(),
        "tpcds_2e6": bench_tpcds(),
    }
    with open("BENCH_EXTRA.json", "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
