"""Whole-stage fusion compiler (ISSUE 11 tentpole).

Takes a :class:`~spark_rapids_tpu.plan.ir.StagePlan` and runs it as
ONE XLA executable: every node between two shuffle boundaries traces
into a single program, AOT-lowered through the process compile cache
(perf/jit_cache) under ``(stage-plan digest, schema-layout digest,
power-of-two row bucket)`` — so a TPC-DS stage pays one dispatch and
zero HBM round-trips between its ops, and the second same-bucket
query compiles NOTHING.

Engine choice is calibrated at STAGE granularity (perf/calibrate,
promoted from the PR-9 per-op verdicts): the fused program inlines the
device hash-join probe and friends, the op-by-op walk lets every op
take its own calibrated engine — the first large stage of a given
shape digest times both and the winner is cached.  Operators can force
either side with ``SPARK_RAPIDS_TPU_STAGE_FUSION=1|0`` (the escape
hatch); both paths are byte-identical by contract, fusion is a SPEED
choice only.

Execution modes from one plan:

  * :meth:`CompiledStage.run` — single process, one AOT executable;
  * :meth:`CompiledStage.run_unfused` — eager op-by-op walk (the
    dispatch-per-op world this PR retires; kept as the calibration
    candidate and the fused-vs-unfused bench oracle);
  * :func:`fused_pipeline_fn` — the WHOLE pipeline (boundaries elided,
    ``Reduce`` -> ``lax.psum``) as one function for ``shard_map``: a
    mesh rank runs one program end to end;
  * stage-by-stage through the distributed runner, with the kudo
    socket shuffle carrying each boundary (distributed/runner.py).
"""

from __future__ import annotations

import os
import time
from typing import Dict, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu.plan import ir

# ------------------------------------------------------------------- knobs


def fusion_mode() -> str:
    """'off' | 'on' | 'auto' from SPARK_RAPIDS_TPU_STAGE_FUSION
    (dynamic read — flipping it mid-process works, same contract as
    the jit-cache switch).  'auto' calibrates fused vs op-by-op per
    (stage, shape digest, backend)."""
    v = os.environ.get("SPARK_RAPIDS_TPU_STAGE_FUSION", "")
    if v == "0":
        return "off"
    if v == "1":
        return "on"
    return "auto"


# stage calibration samples bucketed inputs past this many rows (the
# PR-9 join discipline: timing both engines over an unbounded stage
# would stall the first query under the lifeguard deadline; the size
# CLASS still keys the verdict)
_STAGE_CALIB_MAX_ROWS = 1 << 18


def _canon_dtype(a) -> str:
    """The dtype string the traced program will actually see, without
    materializing a device copy (numpy/jnp arrays AND python scalars
    must digest identically to their jnp.asarray form)."""
    import numpy as np

    from jax.dtypes import canonicalize_dtype
    dt = getattr(a, "dtype", None)
    if dt is None:
        dt = np.asarray(a).dtype
    return str(canonicalize_dtype(dt))


# -------------------------------------------------------------- evaluation

_BIN = {
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "mul": lambda a, b: a * b,
    "div": lambda a, b: a / b,
    "floordiv": lambda a, b: a // b,
    "mod": lambda a, b: a % b,
    "and": lambda a, b: a & b,
    "or": lambda a, b: a | b,
    "eq": lambda a, b: a == b,
    "ne": lambda a, b: a != b,
    "lt": lambda a, b: a < b,
    "le": lambda a, b: a <= b,
    "gt": lambda a, b: a > b,
    "ge": lambda a, b: a >= b,
    "max": jnp.maximum,
    "min": jnp.minimum,
}

_CAST = {"i32": jnp.int32, "i64": jnp.int64, "f64": jnp.float64,
         "b": jnp.bool_}


def _eval(e, env):
    if isinstance(e, ir.Col):
        return env[e.name]
    if isinstance(e, ir.Lit):
        if e.dtype is None:
            return e.value          # weak python scalar, like a literal
        return jnp.asarray(e.value, dtype=e.dtype)
    if isinstance(e, ir.Bin):
        return _BIN[e.op](_eval(e.a, env), _eval(e.b, env))
    if isinstance(e, ir.Un):
        a = _eval(e.a, env)
        if e.op == "neg":
            return -a
        if e.op == "not":
            return ~a
        if e.op == "sum":
            return jnp.sum(a)
        return a.astype(_CAST[e.op])
    if isinstance(e, ir.Where):
        return jnp.where(_eval(e.cond, env), _eval(e.a, env),
                         _eval(e.b, env))
    if isinstance(e, ir.Idx):
        return _eval(e.src, env)[_eval(e.idx, env)]
    if isinstance(e, ir.Mask):
        return env[f"__mask__{e.input}"]
    if isinstance(e, ir.Arange):
        return jnp.arange(e.n, dtype=e.dtype)
    if isinstance(e, ir.Sl):
        return _eval(e.a, env)[e.start:e.stop]
    if isinstance(e, ir.Stack):
        return jnp.stack([_eval(p, env) for p in e.parts])
    raise TypeError(f"unknown expr {type(e).__name__}")


def _expr_is_bool(e, bool_names=frozenset()) -> bool:
    """Statically decide whether an expression evaluates to a boolean
    (a predicate/mask) from the IR alone — the tap planner must pick
    its nodes BEFORE tracing, and the choice must be a pure function
    of the plan so tapped executables key on digest alone.
    ``bool_names`` carries the names already known boolean upstream
    (JoinProbe ``.valid`` outputs, earlier predicate Projects), so a
    conjunction like ``j.valid AND qty < limit`` still taps."""
    if isinstance(e, ir.Bin):
        if e.op in ("and", "or"):
            return (_expr_is_bool(e.a, bool_names)
                    and _expr_is_bool(e.b, bool_names))
        return e.op in ("eq", "ne", "lt", "le", "gt", "ge")
    if isinstance(e, ir.Un):
        if e.op == "not":
            return _expr_is_bool(e.a, bool_names)
        return e.op == "b"
    if isinstance(e, ir.Where):
        return (_expr_is_bool(e.a, bool_names)
                and _expr_is_bool(e.b, bool_names))
    if isinstance(e, ir.Mask):
        return True
    if isinstance(e, ir.Idx):
        return _expr_is_bool(e.src, bool_names)
    if isinstance(e, ir.Sl):
        return _expr_is_bool(e.a, bool_names)
    if isinstance(e, ir.Lit):
        return isinstance(e.value, bool)
    if isinstance(e, ir.Col):
        return e.name in bool_names
    return False


def _tap_spec(plan: ir.StagePlan) -> list:
    """The per-node row-count taps this plan admits, in node order:
    ``(node_id, kind, env_key)`` triples.  Only DATA-DEPENDENT
    cardinalities are tapped — JoinProbe match totals (already
    computed by the probe) and boolean Project predicates (one
    popcount each); every other node's output size is statically
    known from its inputs, so observing it would buy nothing."""
    taps = []
    bool_names = set()
    for node in plan.nodes:
        if isinstance(node, ir.JoinProbe):
            bool_names.add(f"{node.prefix}.valid")
            taps.append((node.prefix, "JoinProbe",
                         f"{node.prefix}.total"))
        elif isinstance(node, ir.Project) and \
                _expr_is_bool(node.expr, bool_names):
            bool_names.add(node.out)
            taps.append((node.out, "Project", node.out))
    return taps


def _tap_counts(plan: ir.StagePlan, env) -> list:
    """Scalar int32 observed-row counts for every tap, evaluated from
    the node outputs ALREADY in ``env`` (shared by the fused trace and
    the eager walk — same expressions, so the two engines observe
    identical counts).  A predicate popcount is one reduction over a
    value the program computed anyway; traced, it fuses into the same
    executable."""
    vals = []
    for _nid, kind, key in _tap_spec(plan):
        v = jnp.asarray(env[key])
        if kind == "JoinProbe":
            vals.append(v.astype(jnp.int32))
        else:
            vals.append(jnp.sum(v.astype(jnp.int32))
                        .astype(jnp.int32))
    return vals


def _eval_node(node, env, reduce_axis: Optional[str]) -> None:
    """Evaluate one node into ``env`` (shared by the fused trace and
    the op-by-op walk — one evaluator, so the two engines cannot
    drift)."""
    if isinstance(node, ir.Project):
        env[node.out] = _eval(node.expr, env)
    elif isinstance(node, ir.JoinProbe):
        from spark_rapids_tpu.ops.device_join import inner_join_device
        lv = (None if node.left_valid is None
              else _eval(node.left_valid, env))
        rv = (None if node.right_valid is None
              else _eval(node.right_valid, env))
        pairs = inner_join_device(_eval(node.left, env),
                                  _eval(node.right, env),
                                  node.capacity,
                                  left_valid=lv, right_valid=rv)
        p = node.prefix
        env[f"{p}.li"] = pairs.left_indices
        env[f"{p}.ri"] = pairs.right_indices
        env[f"{p}.valid"] = pairs.valid
        env[f"{p}.total"] = pairs.total
    elif isinstance(node, ir.SegmentSum):
        env[node.out] = jax.ops.segment_sum(
            _eval(node.value, env), _eval(node.ids, env),
            num_segments=node.num_segments)
    elif isinstance(node, ir.Sort):
        res = lax.sort(tuple(_eval(o, env) for o in node.operands),
                       num_keys=node.num_keys)
        for name, arr in zip(node.names, res):
            env[name] = arr
    elif isinstance(node, ir.Reduce):
        v = _eval(node.value, env)
        if reduce_axis is None:
            env[node.out] = v
        elif node.kind == "any":
            env[node.out] = lax.psum(v.astype(jnp.int32),
                                     reduce_axis) > 0
        else:
            env[node.out] = lax.psum(v, reduce_axis)
    elif isinstance(node, ir.WindowSum):
        part = _eval(node.part, env)
        sums = jax.ops.segment_sum(
            _eval(node.value, env), part,
            num_segments=node.num_partitions)
        env[node.out] = sums[part]
    elif isinstance(node, ir.WindowRank):
        part = _eval(node.part, env).astype(jnp.int64)
        okey = _eval(node.order, env).astype(jnp.int64)
        n = part.shape[0]
        iota = jnp.arange(n, dtype=jnp.int64)
        p_s, _o, row_s = lax.sort((part, okey, iota), num_keys=3)
        # rank within partition = sorted position minus the running
        # partition start (one cummax, no data-dependent loops)
        first = jnp.concatenate(
            [jnp.ones(1, jnp.bool_), p_s[1:] != p_s[:-1]])
        start = lax.cummax(jnp.where(first, iota, 0))
        env[node.out] = jnp.zeros(n, jnp.int64).at[row_s].set(
            iota - start)
    elif isinstance(node, ir.Rollup):
        n1, n2 = node.cards
        m = _eval(node.mask, env)
        k1 = jnp.where(m, _eval(node.keys[0], env), 0)
        k2 = jnp.where(m, _eval(node.keys[1], env), 0)
        w = jnp.where(m, _eval(node.value, env), 0)
        c = m.astype(jnp.int64)
        gid = k1.astype(jnp.int64) * n2 + k2
        sum0 = jax.ops.segment_sum(w, gid, num_segments=n1 * n2)
        cnt0 = jax.ops.segment_sum(c, gid, num_segments=n1 * n2)
        p = node.prefix
        env[f"{p}.sum0"], env[f"{p}.cnt0"] = sum0, cnt0
        # coarser grouping sets fold from the finest level's exact int
        # sums — byte-stable in any fold order
        env[f"{p}.sum1"] = sum0.reshape(n1, n2).sum(axis=1)
        env[f"{p}.cnt1"] = cnt0.reshape(n1, n2).sum(axis=1)
        env[f"{p}.sumt"] = jnp.sum(sum0)
        env[f"{p}.cntt"] = jnp.sum(cnt0)
        if node.mode == "cube":
            env[f"{p}.sum2"] = sum0.reshape(n1, n2).sum(axis=0)
            env[f"{p}.cnt2"] = cnt0.reshape(n1, n2).sum(axis=0)
    else:
        raise TypeError(f"unknown node {type(node).__name__}")


# --------------------------------------------------------- compiled stage


class CompiledStage:
    """One stage, three engines (fused AOT / op-by-op / shard_map
    body), one evaluator."""

    def __init__(self, plan: ir.StagePlan):
        self.plan = plan.validate()
        # (digest, bucket) -> jitted fn when the process jit cache is
        # disabled: jit's own trace cache then carries same-shape
        # reuse instead of retracing per call (bounded by the distinct
        # shape classes this stage object sees)
        self._nocache: Dict[tuple, object] = {}

    # number of op dispatches the unfused walk pays (the fused program
    # pays exactly 1) — the before/after evidence in BENCH_r07
    @property
    def dispatch_count(self) -> int:
        return len(self.plan.nodes)

    # ------------------------------------------------------------ binding

    def _shape_parts(self, inputs: Mapping[str, Sequence]):
        """Digest ingredients for operands_digest — every input's
        canonical dtypes plus its row bucket (exact shape for
        unbucketed inputs) — WITHOUT materializing any padded copy.
        Returns (parts, max_bucket)."""
        import numpy as np

        from spark_rapids_tpu.perf.jit_cache import bucket_rows
        parts, max_bucket = [], 0
        for inp in self.plan.inputs:
            arrs = list(inputs[inp.name])
            if len(arrs) != len(inp.columns):
                raise ValueError(
                    f"input {inp.name!r} expects {len(inp.columns)} "
                    f"columns, got {len(arrs)}")
            if inp.bucket:
                b = bucket_rows(int(np.shape(arrs[0])[0]))
                max_bucket = max(max_bucket, b)
                parts.append((",".join(_canon_dtype(a)
                                       for a in arrs), b))
            else:
                parts.append((",".join(
                    f"{_canon_dtype(a)}{tuple(np.shape(a))}"
                    for a in arrs), 0))
        return parts, max_bucket

    def _bind_args(self, inputs: Mapping[str, Sequence]):
        """Pad bucketed inputs to their power-of-two row bucket and
        flatten to the fused arg list: [*columns..., *n_valids...].
        Returns (args, shape_parts, max_bucket)."""
        from spark_rapids_tpu.perf.jit_cache import bucket_rows
        parts, max_bucket = self._shape_parts(inputs)
        cols, nvalids = [], []
        for inp in self.plan.inputs:
            arrs = [jnp.asarray(a) for a in inputs[inp.name]]
            if inp.bucket:
                rows = int(arrs[0].shape[0])
                b = bucket_rows(rows)
                for spec, a in zip(inp.columns, arrs):
                    if a.shape[0] != rows:
                        raise ValueError(
                            f"ragged input {inp.name!r}")
                    if a.shape[0] != b:
                        widths = ([(0, b - rows)]
                                  + [(0, 0)] * (a.ndim - 1))
                        a = jnp.pad(a, widths,
                                    constant_values=spec.pad)
                    cols.append(a)
                nvalids.append(jnp.int32(rows))
            else:
                cols.extend(arrs)
        return cols + nvalids, parts, max_bucket

    def _fused_callable(self, taps: bool = False):
        """The generic evaluator as a pure fn(*args) for jit: binds
        the flat arg list back to named columns + row masks, then
        walks the nodes — XLA sees ONE program.  With ``taps`` the
        program additionally returns one stacked int32 vector of
        per-node observed row counts (ISSUE 20): the values already
        exist inside the trace (JoinProbe totals, predicate masks),
        so the same single executable carries them out — zero extra
        dispatches."""
        plan = self.plan

        def fn(*args):
            env: Dict[str, object] = {}
            pos = 0
            bucketed = []
            for inp in plan.inputs:
                for spec in inp.columns:
                    env[spec.name] = args[pos]
                    pos += 1
                if inp.bucket:
                    bucketed.append(inp)
            for i, inp in enumerate(bucketed):
                n_valid = args[pos + i]
                rows = env[inp.columns[0].name].shape[0]
                env[f"__mask__{inp.name}"] = (
                    jnp.arange(rows, dtype=jnp.int32) < n_valid)
            for inp in plan.inputs:
                if not inp.bucket:
                    first = env[inp.columns[0].name]
                    rows = first.shape[0] if first.ndim else 0
                    env[f"__mask__{inp.name}"] = jnp.ones(
                        rows, jnp.bool_)
            for node in plan.nodes:
                _eval_node(node, env, None)
            outs = tuple(env[o] for o in plan.outputs)
            if taps:
                vals = _tap_counts(plan, env)
                counts = (jnp.stack(vals) if vals
                          else jnp.zeros(0, jnp.int32))
                return outs + (counts,)
            return outs

        return fn

    def fused_fn(self, reduce_axis: Optional[str] = None):
        """Unpadded evaluator for shard_map bodies: args are the raw
        input columns (flattened in input order, no n_valid scalars),
        ``Reduce`` nodes psum over ``reduce_axis``."""
        plan = self.plan

        def fn(*args):
            env: Dict[str, object] = {}
            pos = 0
            for inp in plan.inputs:
                for spec in inp.columns:
                    env[spec.name] = args[pos]
                    pos += 1
                first = env[inp.columns[0].name]
                rows = first.shape[0] if first.ndim else 0
                env[f"__mask__{inp.name}"] = jnp.ones(rows, jnp.bool_)
            for node in plan.nodes:
                _eval_node(node, env, reduce_axis)
            return tuple(env[o] for o in plan.outputs)

        return fn

    # ------------------------------------------------------------ engines

    def _run_digest(self, parts) -> str:
        """The full run key: stage-plan digest | all-operand schema
        digest — the jit-cache key, the calibration verdict key, AND
        the stage_fusion journal digest (one derivation, no drift)."""
        from spark_rapids_tpu.perf.calibrate import operands_digest
        return f"{self.plan.digest}|{operands_digest(parts)}"

    def _run_fused(self, inputs, run_digest: Optional[str] = None,
                   taps: bool = False) -> tuple:
        """ONE AOT executable through the process compile cache,
        keyed by (stage-plan digest, all-operand schema digest, row
        bucket).  Returns (outputs, compile_ns, run_digest, counts) —
        ``compile_ns`` is the lower+compile wall when THIS call built
        the executable, 0 on a cache hit (truthiness keeps the old
        compiled-now contract; the attribution ledger carves the
        nanoseconds out of the stage's compute).  ``counts`` is the
        tapped per-node row-count vector (None without ``taps``); a
        tapped program is a DIFFERENT executable, so the compile-cache
        key gets a ``|taps`` suffix while the reported run digest
        stays the base one — journal/profile/calibration rows fold
        together whichever way the stats switch points."""
        from spark_rapids_tpu import observability as _obs
        from spark_rapids_tpu.perf import jit_cache as _jc

        args, parts, bucket = self._bind_args(inputs)
        digest = run_digest or self._run_digest(parts)
        key_digest = f"{digest}|taps" if taps else digest
        fn = self._fused_callable(taps=taps)
        compiled_now = []

        def build():
            t0 = time.monotonic_ns()
            with _obs.TRACER.span(
                    "stage_compile", kind="compile",
                    attrs={"stage": self.plan.name, "digest": digest,
                           "bucket": bucket,
                           "nodes": self.dispatch_count}):
                ex = jax.jit(fn).lower(*args).compile()
            compiled_now.append(time.monotonic_ns() - t0)
            return ex

        if _jc.CACHE.enabled():
            ex = _jc.CACHE.get_or_build(
                f"stage.{self.plan.name}", key_digest, bucket, build,
                cost_bytes=_jc._tree_nbytes(args))
            out = ex(*args)
        else:
            # cache disabled: keep ONE jit wrapper per shape class so
            # jit's trace cache still reuses the traced program — a
            # fresh wrapper per call would retrace+recompile every
            # query (the exchange._step_for discipline)
            jf = self._nocache.get((digest, bucket, taps))
            if jf is None:
                jf = self._nocache.setdefault(
                    (digest, bucket, taps), jax.jit(fn))
            out = jf(*args)
        counts = None
        if taps:
            counts, out = out[-1], out[:-1]
        return out, (compiled_now[0] if compiled_now else 0), \
            digest, counts

    def _walk_env(self, inputs) -> Dict[str, object]:
        """The eager op-by-op walk's full environment (every node
        output by name) — run_unfused projects the plan outputs out
        of it, the stats tap reads the same count expressions the
        fused program stacks."""
        env: Dict[str, object] = {}
        for inp in self.plan.inputs:
            arrs = [jnp.asarray(a) for a in inputs[inp.name]]
            for spec, a in zip(inp.columns, arrs):
                env[spec.name] = a
            first = arrs[0]
            rows = first.shape[0] if first.ndim else 0
            env[f"__mask__{inp.name}"] = jnp.ones(rows, jnp.bool_)
        for node in self.plan.nodes:
            _eval_node(node, env, None)
        return env

    def _host_counts(self, env) -> list:
        """Tapped counts off an eager walk's env, as python ints."""
        return [int(v) for v in _tap_counts(self.plan, env)]

    def run_unfused(self, inputs) -> tuple:
        """Op-by-op eager walk on unpadded inputs: every node pays its
        own dispatch + HBM round trip.  Byte-identical to the fused
        program (same evaluator, exact int aggregates) — the escape
        hatch, the calibration rival, and the bench baseline."""
        env = self._walk_env(inputs)
        return tuple(env[o] for o in self.plan.outputs)

    # -------------------------------------------------------------- entry

    def run(self, inputs: Mapping[str, Sequence]) -> tuple:
        """Execute the stage under the current fusion mode, recording
        ``srt_stage_fusion_total{stage,outcome}`` + a ``stage_fusion``
        journal event either way.  Walls are measured past
        ``block_until_ready`` (an async backend's dispatch-only time
        would lie), and a first-call calibration's measurement time is
        NOT folded into the winner's recorded wall."""
        from spark_rapids_tpu import observability as _obs

        mode = fusion_mode()
        # data-statistics tap (ISSUE 20): ONE attribute read when the
        # stats plane is off — no observation dict, no extra outputs,
        # the exact executable PR 11 shipped
        taps = _obs.STATS.enabled
        compiled = False
        compile_ns = 0
        wall_ns = None
        counts = None
        # the event digest is the full RUN key (plan | operand
        # shapes): the stages table must not average walls across row
        # buckets, or a small escape-hatch run would skew the ratio a
        # large fused workload reads as its regression signal
        if mode == "auto":
            out, compiled, outcome, wall_ns, digest, compile_ns, \
                counts = self._run_calibrated(inputs, taps=taps)
        else:
            t0 = time.monotonic_ns()
            if mode == "off":
                if taps:
                    env = self._walk_env(inputs)
                    out = tuple(env[o] for o in self.plan.outputs)
                    counts = self._host_counts(env)
                else:
                    out = self.run_unfused(inputs)
                outcome = "unfused"
                digest = self._run_digest(
                    self._shape_parts(inputs)[0])
            else:
                out, compile_ns, digest, counts = self._run_fused(
                    inputs, taps=taps)
                compiled = bool(compile_ns)
                outcome = "fused"
            jax.block_until_ready(out)
            wall_ns = time.monotonic_ns() - t0
        _obs.record_stage_fusion(
            self.plan.name, outcome, digest=digest,
            wall_ns=wall_ns, nodes=self.dispatch_count,
            compiled=compiled)
        stats = (self._note_stats(inputs, digest, counts)
                 if taps else None)
        # query-profile feed (ISSUE 13): one structured record per
        # stage execution while the calling thread profiles a query.
        # active() is one attribute read when profiling is off — the
        # record dict (node descriptors, pad-waste) is never built
        if _obs.PROFILER.active():
            _obs.PROFILER.note_stage(self._profile_record(
                inputs, digest=digest, engine=outcome,
                wall_ns=wall_ns, compiled=compiled,
                compile_ns=compile_ns, stats=stats))
        return out

    def _note_stats(self, inputs, digest: str, counts) -> Optional[dict]:
        """Fold one execution's observation into the stats plane and
        return the profile's per-stage ``stats`` section.  Input row
        counts are host-known (the n_valid scalars the binder already
        computed); tapped counts arrive as the executable's int32
        vector (fused) or python ints (eager walk) — np.asarray is
        the only device sync and it reads values the program computed
        anyway."""
        import numpy as np

        from spark_rapids_tpu import observability as _obs
        spec = _tap_spec(self.plan)
        vals = []
        if counts is not None:
            vals = [int(x) for x in
                    np.asarray(counts).reshape(-1)[:len(spec)]]
        nodes = [{"node": nid, "kind": kind, "rows": v}
                 for (nid, kind, _key), v in zip(spec, vals)]
        ins, cols = [], {}
        for inp in self.plan.inputs:
            arrs = inputs.get(inp.name)
            if not arrs:
                continue
            shape = np.shape(arrs[0])
            ins.append({"name": inp.name,
                        "rows": int(shape[0]) if shape else 0})
            cols[inp.name] = arrs[0]
        return _obs.STATS.note_stage(
            {"stage": self.plan.name,
             "plan_digest": self.plan.digest,
             "run_digest": digest, "inputs": ins, "nodes": nodes},
            columns=cols)

    def run_spilled(self, partitions: Sequence[Mapping[str, object]]
                    ) -> list:
        """ISSUE 18 seam: a ShuffleBoundary is also a SPILL boundary.
        Run this stage once per hash partition of spilled inputs —
        WITHOUT unfusing: every partition goes through the ordinary
        :meth:`run` entry, so same-bucket partitions share ONE fused
        executable (the second partition is a jit-cache hit, asserted
        by tests/test_spill.py and scripts/spill_smoke.py).

        Each element of ``partitions`` maps input name -> either a
        plain column sequence or a memory/spill.SpillHandle, whose
        batch is streamed back (recording ``srt_spill_restores_total``
        and ``spill_wait``) just-in-time for its partition, PINNED
        (victim-ineligible) while the partition runs, and stays
        registered — spillable again — afterwards; the CALLER owns
        handle close().  Returns the per-partition output tuples in
        partition order (correctness requires hash-partitioned,
        per-partition-complete inputs — the ops/out_of_core
        contract)."""
        import contextlib

        from spark_rapids_tpu.columns.column import Column
        from spark_rapids_tpu.memory.spill import SpillHandle
        outs = []
        for part in partitions:
            with contextlib.ExitStack() as pins:
                stage_inputs = {}
                for name, v in part.items():
                    cols = (pins.enter_context(v.pin())
                            if isinstance(v, SpillHandle) else v)
                    # the store serializes Column batches; stages
                    # consume raw arrays — unwrap through the
                    # logical-dtype host view (the from_numpy inverse)
                    stage_inputs[name] = tuple(
                        c.to_numpy() if isinstance(c, Column) else c
                        for c in cols)
                outs.append(self.run(stage_inputs))
        return outs

    def _profile_record(self, inputs, *, digest: str, engine: str,
                        wall_ns, compiled: bool,
                        compile_ns: int = 0,
                        stats: Optional[dict] = None) -> dict:
        """The typed per-stage profile row: plan structure (node
        kinds + outputs), per-input rows/bucket/pad-waste, engine,
        wall, compile-vs-cache-hit (plus the build's own wall, for
        the attribution ledger's compile bucket), dispatch count, and
        the monotonic dispatch window the critical path orders by."""
        import numpy as np

        from spark_rapids_tpu.perf.jit_cache import bucket_rows
        ins = []
        for inp in self.plan.inputs:
            arrs = inputs.get(inp.name)
            if not arrs:
                continue
            shape = np.shape(arrs[0])
            rows = int(shape[0]) if shape else 0
            bucket = bucket_rows(rows) if inp.bucket else rows
            ins.append({"name": inp.name, "rows": rows,
                        "bucket": bucket,
                        "pad_rows": max(bucket - rows, 0)})
        t_end_ns = time.monotonic_ns()
        rec = {
            "stage": self.plan.name,
            "digest": digest,
            "engine": ("unfused" if engine == "unfused" else "fused"),
            "compiled": bool(compiled),
            "compile_ns": int(compile_ns),
            "wall_ns": int(wall_ns or 0),
            "t_start_ns": t_end_ns - int(wall_ns or 0),
            "t_end_ns": t_end_ns,
            "dispatches": (self.dispatch_count
                           if engine == "unfused" else 1),
            "nodes_total": self.dispatch_count,
            "nodes": [{"kind": type(n).__name__,
                       "outs": list(n.outs())}
                      for n in self.plan.nodes],
            "inputs": ins,
        }
        if stats is not None:
            rec["stats"] = stats
        return rec

    def _calibration_sample(self, inputs):
        """Row-slice oversized bucketed inputs for the measurement
        runs (the verdict still keys on the FULL-size digest — size
        class separation is operands_digest's job).  Returns
        (sample_inputs, sampled?)."""
        sampled = False
        out = {}
        for inp in self.plan.inputs:
            arrs = tuple(inputs[inp.name])
            if inp.bucket and \
                    int(arrs[0].shape[0]) > _STAGE_CALIB_MAX_ROWS:
                arrs = tuple(a[:_STAGE_CALIB_MAX_ROWS] for a in arrs)
                sampled = True
            out[inp.name] = arrs
        return out, sampled

    def _run_calibrated(self, inputs, taps: bool = False):
        """Stage-granularity engine verdict: the first stage of a
        given (plan digest, operand shapes, backend) measures fused vs
        op-by-op — on row-sliced samples past _STAGE_CALIB_MAX_ROWS,
        so a huge first query can't stall under the lifeguard deadline
        — and every later one takes the cached winner.  Both engines
        are byte-identical, so calibration is a speed choice only (the
        PR-9 contract, promoted from per-op to per-stage).  Returns
        (outputs, compiled, outcome, wall_ns, run_digest, compile_ns,
        counts) with the wall of the winning engine's OWN execution
        (measurement runs excluded); ``counts`` is the winner's
        tapped row-count vector (None without ``taps``, and None when
        a sampled measurement won on sliced inputs — sliced counts
        would reconcile against nothing)."""
        from spark_rapids_tpu.perf import calibrate

        parts, _bucket = self._shape_parts(inputs)
        digest = self._run_digest(parts)
        compiled = []
        last: Dict[str, tuple] = {}
        walls: Dict[str, int] = {}
        tap_cell: Dict[str, object] = {}
        calib_inputs, sampled = self._calibration_sample(inputs)

        def timed(tag, fn):
            def go():
                t0 = time.monotonic_ns()
                out = fn()
                jax.block_until_ready(out)
                last[tag] = out
                walls[tag] = time.monotonic_ns() - t0
                return out
            return go

        def fused_body():
            # sampled inputs key their own (smaller) executable; the
            # full-size digest stays the verdict key
            out, c, _d, cts = self._run_fused(
                calib_inputs, run_digest=None if sampled else digest,
                taps=taps)
            if c:
                compiled.append(c)
            if cts is not None:
                tap_cell["fused"] = cts
            return out

        def unfused_body():
            if not taps:
                return self.run_unfused(calib_inputs)
            env = self._walk_env(calib_inputs)
            tap_cell["op_by_op"] = self._host_counts(env)
            return tuple(env[o] for o in self.plan.outputs)

        path = calibrate.pick_path(
            f"stage:{self.plan.name}", digest,
            {"fused": timed("fused", fused_body),
             "op_by_op": timed("op_by_op", unfused_body)},
            default="fused")
        if path not in ("fused", "op_by_op"):
            # pick_path returns env pins verbatim — callers validate
            # membership (the join-router discipline); an unknown pin
            # falls back to the default rather than dereferencing it
            path = "fused"
        outcome = "unfused" if path == "op_by_op" else "fused"
        if not sampled and path in last:
            # calibration just ran the winner on the REAL inputs —
            # reuse its outputs and its measured wall instead of
            # paying a third execution
            return (last[path], bool(compiled), outcome, walls[path],
                    digest, sum(compiled), tap_cell.get(path))
        t0 = time.monotonic_ns()
        counts = None
        if path == "op_by_op":
            if taps:
                env = self._walk_env(inputs)
                out = tuple(env[o] for o in self.plan.outputs)
                counts = self._host_counts(env)
            else:
                out = self.run_unfused(inputs)
        else:
            out, c, _d, counts = self._run_fused(
                inputs, run_digest=digest, taps=taps)
            if c:
                compiled.append(c)
        jax.block_until_ready(out)
        return (out, bool(compiled), outcome,
                time.monotonic_ns() - t0, digest, sum(compiled),
                counts)


# plan-verify gate (ISSUE 12): every distinct plan digest is verified
# ONCE before anything lowers — a malformed plan fails as a typed
# PlanVerifyError naming the offending node instead of an XLA trace
# error three layers down.  Memoized by digest so the hot path pays a
# dict hit; SPARK_RAPIDS_TPU_PLAN_VERIFY=0 is the escape hatch.
_VERIFIED: Dict[str, bool] = {}
_VERIFIED_CAP = 4096


def _verify_once(plan_or_pipeline) -> None:
    if os.environ.get("SPARK_RAPIDS_TPU_PLAN_VERIFY", "") == "0":
        return
    digest = plan_or_pipeline.digest
    if digest in _VERIFIED:
        return
    from spark_rapids_tpu.analysis import plan_verify
    if isinstance(plan_or_pipeline, ir.Pipeline):
        plan_verify.verify_pipeline(plan_or_pipeline)
    else:
        plan_verify.verify_stage(plan_or_pipeline)
    if len(_VERIFIED) >= _VERIFIED_CAP:
        for k in list(_VERIFIED)[:_VERIFIED_CAP // 2]:
            del _VERIFIED[k]
    _VERIFIED[digest] = True


# one CompiledStage per plan digest, process-wide: catalog entry
# points build plans per call, and per-instance state (the
# jit-cache-disabled _nocache memo) must survive across calls or the
# "no retrace per query" contract only holds for callers that keep
# the object themselves.  Bounded: oldest half dropped past the cap
# (plan digests are few — catalog shapes x capacity steps).
_STAGE_MEMO: "Dict[str, CompiledStage]" = {}
_STAGE_MEMO_CAP = 128


def compile_stage(plan: ir.StagePlan) -> CompiledStage:
    cs = _STAGE_MEMO.get(plan.digest)
    if cs is None:
        _verify_once(plan)
        cs = CompiledStage(plan)
        if len(_STAGE_MEMO) >= _STAGE_MEMO_CAP:
            for k in list(_STAGE_MEMO)[:_STAGE_MEMO_CAP // 2]:
                del _STAGE_MEMO[k]
        _STAGE_MEMO[plan.digest] = cs
    return cs


# ------------------------------------------------------------- pipelines


class CompiledPipeline:
    """Stages executed in order; columns carried across each boundary
    feed the next stage's matching ScanBind by NAME (single-process:
    direct handoff — the distributed runner replaces this handoff with
    the kudo socket shuffle)."""

    def __init__(self, pipeline: ir.Pipeline):
        _verify_once(pipeline)      # seam checks on top of per-stage
        self.pipeline = pipeline
        self.stages = [compile_stage(s) for s in pipeline.stages]

    def run(self, inputs: Mapping[str, Sequence]) -> tuple:
        # semantic stage cache (ISSUE 19): with the result cache
        # armed, each stage consults a content-keyed entry (plan
        # digest + input bytes) before executing — an unchanged
        # upstream stage short-circuits and only the delta recomputes
        cache = None
        from spark_rapids_tpu.perf import result_cache as _rc
        if _rc.cache_enabled():
            cache = _rc.CACHE
        feed: Dict[str, object] = {}
        out: Tuple = ()
        for cs in self.stages:
            stage_inputs = {}
            for inp in cs.plan.inputs:
                if feed and all(c.name in feed for c in inp.columns):
                    stage_inputs[inp.name] = tuple(
                        feed[c.name] for c in inp.columns)
                else:
                    stage_inputs[inp.name] = inputs[inp.name]
            if cache is not None:
                out = cache.stage_run(cs, stage_inputs)
            else:
                out = cs.run(stage_inputs)
            feed.update(zip(cs.plan.outputs, out))
        return out


def compile_pipeline(pipeline: ir.Pipeline) -> CompiledPipeline:
    return CompiledPipeline(pipeline)


def fused_pipeline_fn(pipeline: ir.Pipeline,
                      reduce_axis: Optional[str] = None):
    """The WHOLE pipeline as one function (boundaries elided, Reduce
    -> psum over ``reduce_axis``) for shard_map: a mesh rank runs ONE
    XLA program between collectives.  Args are the external inputs'
    columns flattened in declaration order; boundary-fed ScanBinds
    (every column already defined upstream) consume no args.  Returns
    (fn, n_args)."""
    _verify_once(pipeline)
    defined = set()
    external = []
    for stage in pipeline.stages:
        for inp in stage.inputs:
            if not all(c.name in defined for c in inp.columns):
                external.append(inp)
                defined.update(c.name for c in inp.columns)
        for node in stage.nodes:
            defined.update(node.outs())
    n_args = sum(len(i.columns) for i in external)
    last = pipeline.stages[-1]

    def fn(*args):
        env: Dict[str, object] = {}
        pos = 0
        for inp in external:
            for spec in inp.columns:
                env[spec.name] = args[pos]
                pos += 1
        for stage in pipeline.stages:
            for inp in stage.inputs:
                first = env[inp.columns[0].name]
                rows = first.shape[0] if getattr(first, "ndim", 0) \
                    else 0
                env[f"__mask__{inp.name}"] = jnp.ones(rows, jnp.bool_)
            for node in stage.nodes:
                _eval_node(node, env, reduce_axis)
        return tuple(env[o] for o in last.outputs)

    return fn, n_args
