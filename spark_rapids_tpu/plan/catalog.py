"""TPC-DS catalog stages expressed in the stage IR (ISSUE 11).

The hand-fused kernels in models/tpcds.py stay exactly where they are
— they are the byte-identity ORACLES — and this module re-expresses
the same queries as :class:`~spark_rapids_tpu.plan.ir.StagePlan`
pipelines compiled through plan/compiler.py:

  * q3, q9     — one stage each (no shuffle boundary): scan-bind ->
                 project/filter -> segment aggregate -> sort/limit as
                 ONE executable;
  * q5, q72    — two stages joined by a typed ShuffleBoundary
                 (partials | finish), the exact seam the PR-10
                 distributed runner ships over the kudo socket
                 shuffle; single-process runs hand the carry straight
                 across, a mesh rank fuses the WHOLE pipeline into one
                 shard_map program with psum at the Reduce nodes;
  * q67-shape  — GROUP BY ROLLUP(category, class) + rank() OVER
                 (PARTITION BY category ORDER BY sales DESC): the new
                 Rollup and WindowRank nodes (real q67 uses exactly
                 this pair);
  * q89-shape  — sum(sales) OVER (PARTITION BY store) broadcast back
                 to each (store, item) group: the WindowSum node.

Every expression here mirrors its hand-kernel twin operation for
operation (same dtypes, same literal promotion, exact int64
aggregates), which is what makes the fused outputs byte-identical.
Fact inputs pad their join-key columns with side-specific sentinels
(-1 left, -2 right) so bucket-pad rows can never match each other,
and dense-lookup filters AND in ``Mask(input)`` so pad rows never
reach an aggregate.
"""

from __future__ import annotations

from spark_rapids_tpu.plan.compiler import (compile_pipeline,
                                            compile_stage,
                                            fused_pipeline_fn)
from spark_rapids_tpu.plan.ir import (Arange, Bin, Col, ColSpec, Idx,
                                      JoinProbe, Lit, Mask, Pipeline,
                                      Project, Reduce, Rollup, ScanBind,
                                      SegmentSum, ShuffleBoundary, Sl,
                                      Sort, StagePlan, Stack, Un, Where,
                                      WindowRank, WindowSum)

I64_SENTINEL = Lit(2 ** 62, "int64")


def _and(*es):
    out = es[0]
    for e in es[1:]:
        out = Bin("and", out, e)
    return out


def _gt0(e):
    return Bin("gt", e, Lit(0))


# ------------------------------------------------------------------- q5


def q5_partials_plan(stores: int, join_capacity: int) -> StagePlan:
    """Map side of q5 (mirrors models.tpcds._q5_partials): two
    fact-to-date-window join probes, per-store segment sums, overflow
    flag."""
    nodes = []
    for side, (date, key, amt_a, amt_b, j) in (
            ("s", ("s_date", "s_store", "s_price", "s_profit", "j1")),
            ("r", ("r_date", "r_store", "r_amt", "r_loss", "j2"))):
        valid = Col(f"{j}.valid")
        li = Col(f"{j}.li")
        nodes += [
            JoinProbe(j, Col(date), Col("d_date"), join_capacity),
            Project(f"{side}_st",
                    Where(valid, Idx(Col(key), li), Lit(0))),
            SegmentSum(f"{side}_sum_a",
                       Where(valid, Idx(Col(amt_a), li), Lit(0)),
                       Col(f"{side}_st"), stores),
            SegmentSum(f"{side}_sum_b",
                       Where(valid, Idx(Col(amt_b), li), Lit(0)),
                       Col(f"{side}_st"), stores),
            SegmentSum(f"{side}_seen", Un("i64", valid),
                       Col(f"{side}_st"), stores),
        ]
    nodes += [
        Project("profit", Bin("sub", Col("s_sum_b"), Col("r_sum_b"))),
        Project("seen", Bin("add", Col("s_seen"), Col("r_seen"))),
        Project("of", Bin("or",
                          Bin("gt", Col("j1.total"),
                              Lit(join_capacity)),
                          Bin("gt", Col("j2.total"),
                              Lit(join_capacity)))),
    ]
    return StagePlan(
        name="q5_partials",
        inputs=(
            ScanBind("s", (ColSpec("s_date", pad=-1),
                           ColSpec("s_store"), ColSpec("s_price"),
                           ColSpec("s_profit"))),
            ScanBind("r", (ColSpec("r_date", pad=-1),
                           ColSpec("r_store"), ColSpec("r_amt"),
                           ColSpec("r_loss"))),
            ScanBind("d", (ColSpec("d_date", pad=-2),)),
        ),
        nodes=tuple(nodes),
        outputs=("s_sum_a", "r_sum_a", "profit", "seen", "of"),
    )


def q5_finish_plan(stores: int) -> StagePlan:
    """Reduce side of q5 (mirrors models.tpcds._q5_finish): global
    group table -> ORDER BY s_store_id.  The Reduce nodes are the
    cross-shard seam: identity single-chip, psum on the mesh, replaced
    by the kudo exchange in the distributed runner."""
    return StagePlan(
        name="q5_finish",
        inputs=(
            ScanBind("xchg", (ColSpec("s_sum_a"), ColSpec("r_sum_a"),
                              ColSpec("profit"), ColSpec("seen"),
                              ColSpec("of")), bucket=False),
            ScanBind("dims", (ColSpec("st_id"),), bucket=False),
        ),
        nodes=(
            Reduce("g_sales", Col("s_sum_a")),
            Reduce("g_rets", Col("r_sum_a")),
            Reduce("g_profit", Col("profit")),
            Reduce("g_seen", Col("seen")),
            Reduce("g_of", Col("of"), kind="any"),
            Project("key", Where(_gt0(Col("g_seen")), Col("st_id"),
                                 Lit(2 ** 31 - 1, "int32"))),
            Sort(("key_s", "sales_s", "ret_s", "profit_s"),
                 (Col("key"), Col("g_sales"), Col("g_rets"),
                  Col("g_profit")), num_keys=1),
        ),
        outputs=("key_s", "sales_s", "ret_s", "profit_s", "g_of"),
    )


def q5_pipeline(stores: int, join_capacity: int) -> Pipeline:
    return Pipeline(
        name="q5",
        stages=(q5_partials_plan(stores, join_capacity),
                q5_finish_plan(stores)),
        boundaries=(ShuffleBoundary(
            ("s_sum_a", "r_sum_a", "profit", "seen", "of")),),
    )


def _note_estimates(stage: str, rows_by_input) -> None:
    """Register generator-size row estimates for a stage's scan
    inputs (the est side of ISSUE 20's est-vs-actual feedback loop).
    One attribute read when the stats plane is off."""
    from spark_rapids_tpu import observability as _obs
    if not _obs.STATS.enabled:
        return
    _obs.STATS.register_input_estimates(
        stage, {k: len(v) for k, v in rows_by_input.items()},
        origin="catalog")


def run_q5(d, stores: int, capacity: int):
    """Fused q5 under the centralized capacity-retry driver.  Returns
    the same tuple as models.tpcds.make_q5(...)(d)."""
    from spark_rapids_tpu.parallel.exchange import with_capacity_retry

    _note_estimates("q5_partials", {"s": d.s_date, "r": d.r_date,
                                    "d": d.d_date})

    def build(cap):
        pipe = compile_pipeline(q5_pipeline(stores, cap))
        return lambda *a: pipe.run({"s": a[0:4], "r": a[4:8],
                                    "d": (a[8],), "dims": (a[9],)})

    outs, _cap = with_capacity_retry(build, capacity, max_doublings=16)(
        d.s_date, d.s_store, d.s_price, d.s_profit,
        d.r_date, d.r_store, d.r_amt, d.r_loss, d.d_date, d.st_id)
    return outs


def run_q5_partials(args, stores: int, capacity: int, *, ctx=None):
    """Distributed map side: ONE executable per rank before the kudo
    exchange.  ``args`` = 8 sharded fact columns + the replicated
    d_date window; returns ((sales, rets, profit, seen, of), cap).

    ``ctx`` (optional QueryContext) makes the stage CANCELLABLE: the
    elastic fleet's speculative re-executions pass their cancel-capable
    context so a speculation whose original arrived mid-run unwinds
    between capacity attempts through the lifeguard machinery instead
    of finishing a result nobody will merge."""
    from spark_rapids_tpu.parallel.exchange import with_capacity_retry

    def build(cap):
        st = compile_stage(q5_partials_plan(stores, cap))
        return lambda *a: st.run({"s": a[0:4], "r": a[4:8],
                                  "d": (a[8],)})

    return with_capacity_retry(
        build, capacity, max_doublings=16,
        check=ctx.check_cancel if ctx is not None else None)(*args)


def run_q5_finish(sales, rets, profit, seen, of, st_id, stores: int):
    """Distributed reduce side: ONE executable per rank after the
    exchange (inputs are already globally summed; the plan's Reduce
    nodes are identity here)."""
    st = compile_stage(q5_finish_plan(stores))
    return st.run({"xchg": (sales, rets, profit, seen, of),
                   "dims": (st_id,)})


# ------------------------------------------------------------------ q72


def q72_partials_plan(items: int, max_week: int, join_capacity: int,
                      week0: int) -> StagePlan:
    """Map side of q72 (mirrors models.tpcds._q72_partials): fact-fact
    join probe + week-offset/shortage filters + (item, week) counts."""
    n_groups = items * max_week
    return StagePlan(
        name="q72_partials",
        inputs=(
            ScanBind("cs", (ColSpec("cs_item", pad=-1),
                            ColSpec("cs_date"), ColSpec("cs_qty"))),
            ScanBind("inv", (ColSpec("inv_item", pad=-2),
                             ColSpec("inv_date"), ColSpec("inv_qty"))),
            ScanBind("dim", (ColSpec("item_id"),), bucket=False),
        ),
        nodes=(
            JoinProbe("j", Col("cs_item"), Col("inv_item"),
                      join_capacity),
            Project("ow", Bin("floordiv",
                              Idx(Col("cs_date"), Col("j.li")),
                              Lit(7))),
            Project("iw", Bin("floordiv",
                              Idx(Col("inv_date"), Col("j.ri")),
                              Lit(7))),
            Project("wk", Bin("sub", Col("ow"), Lit(week0))),
            Project("keep", _and(
                Col("j.valid"),
                Bin("eq", Col("iw"), Bin("add", Col("ow"), Lit(1))),
                Bin("lt", Idx(Col("inv_qty"), Col("j.ri")),
                    Idx(Col("cs_qty"), Col("j.li"))),
                Bin("ge", Col("wk"), Lit(0)),
                Bin("lt", Col("wk"), Lit(max_week)))),
            Project("iid", Idx(Col("item_id"),
                               Idx(Col("cs_item"), Col("j.li")))),
            Project("gid", Where(
                Col("keep"),
                Bin("add", Bin("mul", Col("iid"), Lit(max_week)),
                    Col("wk")), Lit(0))),
            SegmentSum("counts", Un("i64", Col("keep")), Col("gid"),
                       n_groups),
            Project("of", Bin("gt", Col("j.total"),
                              Lit(join_capacity))),
        ),
        outputs=("counts", "of"),
    )


def q72_finish_plan(items: int, max_week: int, limit: int,
                    week0: int) -> StagePlan:
    """Reduce side of q72 (mirrors models.tpcds._q72_finish): top-k
    over the global count vector."""
    n_groups = items * max_week
    return StagePlan(
        name="q72_finish",
        inputs=(ScanBind("xchg", (ColSpec("counts"), ColSpec("of")),
                         bucket=False),),
        nodes=(
            Reduce("g_counts", Col("counts")),
            Reduce("g_of", Col("of"), kind="any"),
            Project("gidx", Arange(n_groups, "int64")),
            Project("skey", Where(_gt0(Col("g_counts")),
                                  Un("neg", Col("g_counts")),
                                  I64_SENTINEL)),
            Sort(("_k", "gid_s", "cnt_s"),
                 (Col("skey"), Col("gidx"), Col("g_counts")),
                 num_keys=2),
            Project("item", Bin("floordiv", Sl(Col("gid_s"), 0, limit),
                                Lit(max_week))),
            Project("week", Bin("add",
                                Bin("mod", Sl(Col("gid_s"), 0, limit),
                                    Lit(max_week)), Lit(week0))),
            Project("cnt", Sl(Col("cnt_s"), 0, limit)),
        ),
        outputs=("item", "week", "cnt", "g_of"),
    )


def q72_pipeline(items: int, max_week: int, join_capacity: int,
                 limit: int = 100, week0: int = 0) -> Pipeline:
    return Pipeline(
        name="q72",
        stages=(q72_partials_plan(items, max_week, join_capacity,
                                  week0),
                q72_finish_plan(items, max_week, limit, week0)),
        boundaries=(ShuffleBoundary(("counts", "of")),),
    )


def run_q72(d, items: int, max_week: int, capacity: int,
            limit: int = 100, week0: int = 0):
    """Fused q72 under capacity retry — same tuple as make_q72."""
    from spark_rapids_tpu.parallel.exchange import with_capacity_retry

    _note_estimates("q72_partials", {"cs": d.cs_item,
                                     "inv": d.inv_item,
                                     "dim": d.item_id})

    def build(cap):
        pipe = compile_pipeline(
            q72_pipeline(items, max_week, cap, limit, week0))
        return lambda *a: pipe.run({"cs": a[0:3], "inv": a[3:6],
                                    "dim": (a[6],)})

    outs, _cap = with_capacity_retry(build, capacity, max_doublings=16)(
        d.cs_item, d.cs_date, d.cs_qty, d.inv_item, d.inv_date,
        d.inv_qty, d.item_id)
    return outs


def run_q72_partials(args, items: int, max_week: int, capacity: int,
                     week0: int):
    from spark_rapids_tpu.parallel.exchange import with_capacity_retry

    def build(cap):
        st = compile_stage(
            q72_partials_plan(items, max_week, cap, week0))
        return lambda *a: st.run({"cs": a[0:3], "inv": a[3:6],
                                  "dim": (a[6],)})

    return with_capacity_retry(build, capacity, max_doublings=16)(*args)


def run_q72_finish(counts, of, items: int, max_week: int, limit: int,
                   week0: int):
    st = compile_stage(q72_finish_plan(items, max_week, limit, week0))
    return st.run({"xchg": (counts, of)})


# ------------------------------------------------------------------- q3


def q3_plan(base: int, years: int, brands: int, manufact: int,
            month: int = 11, limit: int = 100) -> StagePlan:
    """q3 as ONE stage (mirrors models.tpcds._q3_kernel): dense date +
    item dim lookups, month/manufacturer filters, (year, brand) sums,
    three-key order-by with LIMIT."""
    n_groups = years * brands
    return StagePlan(
        name="q3",
        inputs=(
            ScanBind("s", (ColSpec("s_date", pad=base),
                           ColSpec("s_item"), ColSpec("s_price"))),
            ScanBind("dims", (ColSpec("d_moy"), ColSpec("d_year"),
                              ColSpec("i_brand"),
                              ColSpec("i_manufact")), bucket=False),
        ),
        nodes=(
            Project("di", Bin("sub", Col("s_date"), Lit(base))),
            Project("year_idx", Bin("sub",
                                    Idx(Col("d_year"), Col("di")),
                                    Idx(Col("d_year"), Lit(0)))),
            # Mask('s') last: pad rows (s_date=base -> a real day)
            # must never reach the aggregates
            Project("keep", _and(
                Bin("eq", Idx(Col("d_moy"), Col("di")), Lit(month)),
                Bin("eq", Idx(Col("i_manufact"), Col("s_item")),
                    Lit(manufact)),
                Bin("ge", Col("year_idx"), Lit(0)),
                Bin("lt", Col("year_idx"), Lit(years)),
                Mask("s"))),
            Project("brand", Idx(Col("i_brand"), Col("s_item"))),
            Project("gid", Where(
                Col("keep"),
                Bin("add", Bin("mul", Col("year_idx"), Lit(brands)),
                    Col("brand")), Lit(0))),
            Project("amt", Where(Col("keep"), Col("s_price"),
                                 Lit(0))),
            SegmentSum("sums0", Col("amt"), Col("gid"), n_groups),
            Reduce("sums", Col("sums0")),
            SegmentSum("cnts0", Un("i64", Col("keep")), Col("gid"),
                       n_groups),
            Reduce("cnts", Col("cnts0")),
            Project("gidx", Arange(n_groups, "int64")),
            Project("year_of_g", Bin("floordiv", Col("gidx"),
                                     Lit(brands))),
            Project("brand_of_g", Bin("mod", Col("gidx"),
                                      Lit(brands))),
            Project("k1", Where(_gt0(Col("cnts")), Col("year_of_g"),
                                I64_SENTINEL)),
            Project("k2", Where(_gt0(Col("cnts")),
                                Un("neg", Col("sums")),
                                I64_SENTINEL)),
            Sort(("_a", "_b", "_c", "g_s", "sum_s", "cnt_s"),
                 (Col("k1"), Col("k2"), Col("brand_of_g"),
                  Col("gidx"), Col("sums"), Col("cnts")), num_keys=3),
            Project("live", _gt0(Sl(Col("cnt_s"), 0, limit))),
            Project("yrs", Where(
                Col("live"),
                Bin("add", Bin("floordiv", Sl(Col("g_s"), 0, limit),
                               Lit(brands)),
                    Idx(Col("d_year"), Lit(0))),
                Lit(2 ** 31 - 1, "int64"))),
            Project("brands_out", Bin("mod", Sl(Col("g_s"), 0, limit),
                                      Lit(brands))),
            Project("sums_out", Sl(Col("sum_s"), 0, limit)),
            Project("total", Un("sum", Col("cnts"))),
        ),
        outputs=("yrs", "brands_out", "sums_out", "total"),
    )


def run_q3(d, base: int, years: int, brands: int, manufact: int,
           month: int = 11, limit: int = 100):
    _note_estimates("q3", {"s": d.s_date, "dims": d.d_moy})
    st = compile_stage(q3_plan(base, years, brands, manufact, month,
                               limit))
    return st.run({"s": (d.s_date, d.s_item, d.s_price),
                   "dims": (d.d_moy, d.d_year, d.i_brand,
                            d.i_manufact)})


# ------------------------------------------------------------------- q9

_Q9_BUCKETS = ((1, 20), (21, 40), (41, 60), (61, 80), (81, 100))


def q9_plan() -> StagePlan:
    """q9 as ONE stage (mirrors models.tpcds._run_q9_jit): five
    CASE-WHEN quantity buckets, exact int64 sums, f64 avgs at the
    edge.  Pad rows carry quantity 0, outside every bucket."""
    nodes = []
    cs, aps, ans = [], [], []
    for k, (lo, hi) in enumerate(_Q9_BUCKETS):
        m = f"m{k}"
        nodes += [
            Project(m, Bin("and",
                           Bin("ge", Col("quantity"), Lit(lo)),
                           Bin("le", Col("quantity"), Lit(hi)))),
            Project(f"c{k}", Un("sum", Un("i64", Col(m)))),
            Project(f"sp{k}", Un("sum", Where(Col(m), Col("price"),
                                              Lit(0)))),
            Project(f"sn{k}", Un("sum", Where(Col(m), Col("profit"),
                                              Lit(0)))),
            Project(f"ap{k}", Bin("div", Un("f64", Col(f"sp{k}")),
                                  Un("f64", Bin("max", Col(f"c{k}"),
                                                Lit(1))))),
            Project(f"an{k}", Bin("div", Un("f64", Col(f"sn{k}")),
                                  Un("f64", Bin("max", Col(f"c{k}"),
                                                Lit(1))))),
        ]
        cs.append(Col(f"c{k}"))
        aps.append(Col(f"ap{k}"))
        ans.append(Col(f"an{k}"))
    nodes += [Project("counts", Stack(tuple(cs))),
              Project("avg_p", Stack(tuple(aps))),
              Project("avg_n", Stack(tuple(ans)))]
    return StagePlan(
        name="q9",
        inputs=(ScanBind("f", (ColSpec("quantity"), ColSpec("price"),
                               ColSpec("profit"))),),
        nodes=tuple(nodes),
        outputs=("counts", "avg_p", "avg_n"),
    )


def run_q9(quantity, price, profit):
    st = compile_stage(q9_plan())
    return st.run({"f": (quantity, price, profit)})


# ------------------------------------------- q67-shape (rollup + rank)


def q67_plan(ncat: int, ncls: int) -> StagePlan:
    """q67-shape: sum(sales) GROUP BY ROLLUP(category, class), then
    rank() OVER (PARTITION BY category ORDER BY sum DESC) on the
    finest level, presented sorted by (category, rank).  Dead groups
    sort last under int sentinels."""
    n = ncat * ncls
    return StagePlan(
        name="q67",
        inputs=(ScanBind("f", (ColSpec("cat"), ColSpec("cls"),
                               ColSpec("sales"))),),
        nodes=(
            Rollup("r", (Col("cat"), Col("cls")), (ncat, ncls),
                   Col("sales"), Mask("f"), mode="rollup"),
            Project("part", Bin("floordiv", Arange(n, "int64"),
                                Lit(ncls))),
            Project("okey", Where(_gt0(Col("r.cnt0")),
                                  Un("neg", Col("r.sum0")),
                                  I64_SENTINEL)),
            WindowRank("rank", Col("part"), Col("okey")),
            Project("kcat", Where(_gt0(Col("r.cnt0")), Col("part"),
                                  Lit(2 ** 31 - 1, "int64"))),
            Sort(("cat_s", "rank_s", "gid_s", "sum_s", "cnt_s"),
                 (Col("kcat"), Col("rank"), Arange(n, "int64"),
                  Col("r.sum0"), Col("r.cnt0")), num_keys=2),
            Project("cls_s", Bin("mod", Col("gid_s"), Lit(ncls))),
        ),
        outputs=("cat_s", "cls_s", "sum_s", "rank_s", "cnt_s",
                 "r.sum1", "r.sumt"),
    )


def run_q67(d, ncat: int, ncls: int):
    st = compile_stage(q67_plan(ncat, ncls))
    return st.run({"f": (d.cat, d.cls, d.sales)})


def cube_plan(ncat: int, ncls: int) -> StagePlan:
    """The CUBE variant of the grouping-sets node: all four grouping
    sets of (cat, cls) as exact int64 folds of the finest level."""
    return StagePlan(
        name="cube2",
        inputs=(ScanBind("f", (ColSpec("cat"), ColSpec("cls"),
                               ColSpec("sales"))),),
        nodes=(Rollup("r", (Col("cat"), Col("cls")), (ncat, ncls),
                      Col("sales"), Mask("f"), mode="cube"),),
        outputs=("r.sum0", "r.cnt0", "r.sum1", "r.cnt1", "r.sumt",
                 "r.cntt", "r.sum2", "r.cnt2"),
    )


def run_cube(d, ncat: int, ncls: int):
    st = compile_stage(cube_plan(ncat, ncls))
    return st.run({"f": (d.cat, d.cls, d.sales)})


# ------------------------------------ q89-shape (sum-over-partition)


def q89_plan(stores: int, items: int) -> StagePlan:
    """q89-shape: per-(store, item) sales vs the whole store's total —
    sum(sales) OVER (PARTITION BY store) broadcast back to each group
    row, presented sorted by (store, item), live groups first."""
    n = stores * items
    return StagePlan(
        name="q89",
        inputs=(ScanBind("f", (ColSpec("store"), ColSpec("item"),
                               ColSpec("sales"))),),
        nodes=(
            Project("gid", Where(
                Mask("f"),
                Bin("add", Bin("mul", Un("i64", Col("store")),
                               Lit(items)),
                    Un("i64", Col("item"))), Lit(0))),
            Project("w", Where(Mask("f"), Col("sales"), Lit(0))),
            SegmentSum("g_sales", Col("w"), Col("gid"), n),
            SegmentSum("g_cnt", Un("i64", Mask("f")), Col("gid"), n),
            Project("part", Bin("floordiv", Arange(n, "int64"),
                                Lit(items))),
            WindowSum("tot", Col("part"), Col("g_sales"), stores),
            Project("key", Where(_gt0(Col("g_cnt")),
                                 Arange(n, "int64"), I64_SENTINEL)),
            Sort(("key_s", "gid_s", "sales_s", "tot_s", "cnt_s"),
                 (Col("key"), Arange(n, "int64"), Col("g_sales"),
                  Col("tot"), Col("g_cnt")), num_keys=1),
            Project("store_s", Bin("floordiv", Col("gid_s"),
                                   Lit(items))),
            Project("item_s", Bin("mod", Col("gid_s"), Lit(items))),
        ),
        outputs=("store_s", "item_s", "sales_s", "tot_s", "cnt_s"),
    )


def run_q89(d, stores: int, items: int):
    st = compile_stage(q89_plan(stores, items))
    return st.run({"f": (d.store, d.item, d.sales)})


# --------------------------------------------------- mesh (shard_map)


def make_q5_multichip_fused(mesh, stores: int, join_capacity: int):
    """The WHOLE q5 pipeline as ONE shard_map program per mesh rank
    (facts row-sharded, date window / store dim replicated, psum at
    the Reduce seam) — the fused twin of models.tpcds
    make_q5_multichip."""
    import jax
    from jax.sharding import PartitionSpec as P

    from spark_rapids_tpu.utils.jax_compat import shard_map as smap

    axis = mesh.axis_names[0]
    fn, n_args = fused_pipeline_fn(q5_pipeline(stores, join_capacity),
                                   reduce_axis=axis)
    assert n_args == 10
    shard, rep = P(axis), P()
    return jax.jit(smap(fn, mesh=mesh,
                        in_specs=(shard,) * 8 + (rep, rep),
                        out_specs=(rep,) * 5))


def make_q72_multichip_fused(mesh, items: int, max_week: int,
                             join_capacity: int, limit: int = 100,
                             week0: int = 0):
    """Fused twin of make_q72_multichip: one program per rank."""
    import jax
    from jax.sharding import PartitionSpec as P

    from spark_rapids_tpu.utils.jax_compat import shard_map as smap

    axis = mesh.axis_names[0]
    fn, n_args = fused_pipeline_fn(
        q72_pipeline(items, max_week, join_capacity, limit, week0),
        reduce_axis=axis)
    assert n_args == 7
    shard, rep = P(axis), P()
    return jax.jit(smap(fn, mesh=mesh,
                        in_specs=(shard, shard, shard) + (rep,) * 4,
                        out_specs=(rep,) * 4))
