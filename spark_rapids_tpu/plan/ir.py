"""Stage IR: the typed plan nodes whole-stage fusion compiles
(ISSUE 11 tentpole).

A *stage* is everything a query does between two shuffle boundaries.
The hand-fused TPC-DS pipelines in models/tpcds.py prove the shape —
scan, join probe, filter, segment aggregate, sort — composes into ONE
XLA program; this module makes that composition a data structure
instead of a hand-written kernel, so the compiler (plan/compiler.py)
can fuse ANY stage the same way, key the executable in the PR-4
jit_cache, and new operators (window functions, rollup/cube) become
IR nodes instead of new hand kernels.

Design rules:

  * nodes are frozen dataclasses with a canonical ``key()`` string;
    the stage digest is a sha1 over every node's key, so two builds of
    the same logical stage — in different processes, sessions, or
    plan-object identities — hit the same compiled executable;
  * expressions (`Col`/`Lit`/`Bin`/`Un`/`Where`/`Idx`/...) are scalarish
    columnar algebra: they evaluate to jnp arrays with EXACTLY the
    dtype-promotion behavior the hand kernels had (python literals
    stay weak-typed; `Lit(v, dtype)` pins a dtype like ``jnp.int64(v)``
    did), which is what makes fused results byte-identical to the
    hand-fused oracles;
  * static shapes only: joins are the fixed-capacity device probe
    (`ops/device_join.inner_join_device`), filters are masks, group
    tables are sized by the query's domain — the same TPU-first
    decisions the hand pipelines made;
  * `Reduce` marks the cross-shard reduction point: identity on a
    single chip, `lax.psum` under shard_map, and *replaced by the kudo
    exchange* in the multi-process runner — one plan, three execution
    modes that cannot drift;
  * `ShuffleBoundary` is the typed seam between stages of a
    `Pipeline`: the compiler fuses everything between boundaries into
    one executable, and the distributed runner ships the boundary's
    columns over the socket shuffle.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Optional, Tuple

# ------------------------------------------------------------- expressions


class Expr:
    """Base class for stage expressions (columnar algebra)."""

    def key(self) -> str:  # pragma: no cover - abstract
        raise NotImplementedError


def _k(e) -> str:
    """Canonical key of an Expr operand (plain ints/strings allowed as
    static parameters)."""
    return e.key() if isinstance(e, Expr) else repr(e)


@dataclass(frozen=True)
class Col(Expr):
    """Reference to a bound column (a scan-bind column, a node output,
    or a join-probe output like ``j.li``)."""
    name: str

    def key(self):
        return f"c({self.name})"


@dataclass(frozen=True)
class Lit(Expr):
    """Literal. ``dtype=None`` stays a weak python scalar (promotes
    exactly like a literal in the hand kernels); a dtype string
    ('int32', 'int64', 'float64', ...) pins it like ``jnp.int64(v)``."""
    value: object
    dtype: Optional[str] = None

    def key(self):
        return f"l({self.value!r}:{self.dtype})"


@dataclass(frozen=True)
class Bin(Expr):
    """Binary op: add sub mul floordiv mod and or eq ne lt le gt ge
    max min."""
    op: str
    a: Expr
    b: Expr

    def key(self):
        return f"b({self.op},{_k(self.a)},{_k(self.b)})"


@dataclass(frozen=True)
class Un(Expr):
    """Unary op: neg, not, i32/i64/f64/b (casts), sum (full reduction
    to a scalar)."""
    op: str
    a: Expr

    def key(self):
        return f"u({self.op},{_k(self.a)})"


@dataclass(frozen=True)
class Where(Expr):
    cond: Expr
    a: Expr
    b: Expr

    def key(self):
        return f"w({_k(self.cond)},{_k(self.a)},{_k(self.b)})"


@dataclass(frozen=True)
class Idx(Expr):
    """Gather: ``src[idx]`` — dense-dimension lookups and join-pair
    gathers."""
    src: Expr
    idx: Expr

    def key(self):
        return f"i({_k(self.src)},{_k(self.idx)})"


@dataclass(frozen=True)
class Mask(Expr):
    """Row-validity of a bucketed input: True for real rows, False for
    the pad tail (``arange(bucket) < n_valid``).  All-true for
    unbucketed inputs.  Plans AND this into their keep conditions so
    pad rows can never reach an aggregate."""
    input: str

    def key(self):
        return f"m({self.input})"


@dataclass(frozen=True)
class Arange(Expr):
    n: int
    dtype: str = "int64"

    def key(self):
        return f"a({self.n}:{self.dtype})"


@dataclass(frozen=True)
class Sl(Expr):
    """Static slice ``x[start:stop]`` (ORDER BY ... LIMIT)."""
    a: Expr
    start: int
    stop: int

    def key(self):
        return f"s({_k(self.a)},{self.start},{self.stop})"


@dataclass(frozen=True)
class Stack(Expr):
    """``jnp.stack`` of scalar expressions (q9's bucket vectors)."""
    parts: Tuple[Expr, ...]

    def key(self):
        return "k(" + ",".join(_k(p) for p in self.parts) + ")"


# ------------------------------------------------------------------- nodes


class Node:
    """Base class for stage nodes.  ``outs()`` names every column the
    node defines; ``key()`` is the canonical digest contribution."""

    def outs(self) -> Tuple[str, ...]:  # pragma: no cover - abstract
        raise NotImplementedError

    def key(self) -> str:  # pragma: no cover - abstract
        raise NotImplementedError


@dataclass(frozen=True)
class Project(Node):
    """Bind ``out`` to an expression (projections AND filter masks —
    a filter in this static-shape world is a boolean column)."""
    out: str
    expr: Expr

    def outs(self):
        return (self.out,)

    def key(self):
        return f"P({self.out}={_k(self.expr)})"


@dataclass(frozen=True)
class JoinProbe(Node):
    """Fixed-capacity device inner-join probe
    (ops/device_join.inner_join_device — the PR-9 device engine,
    inlined instead of round-tripped).  Defines ``<p>.li`` ``<p>.ri``
    (int32 pair indices), ``<p>.valid`` (bool per slot) and
    ``<p>.total`` (int64 TRUE pair count; ``total > capacity`` is the
    overflow signal the capacity-retry driver doubles on)."""
    prefix: str
    left: Expr
    right: Expr
    capacity: int
    left_valid: Optional[Expr] = None
    right_valid: Optional[Expr] = None

    def outs(self):
        p = self.prefix
        return (f"{p}.li", f"{p}.ri", f"{p}.valid", f"{p}.total")

    def key(self):
        return (f"J({self.prefix},{_k(self.left)},{_k(self.right)},"
                f"{self.capacity},{_k(self.left_valid)},"
                f"{_k(self.right_valid)})")


@dataclass(frozen=True)
class SegmentSum(Node):
    """Hash-aggregate workhorse: ``segment_sum(value, ids,
    num_segments)`` over dictionary-encoded group ids."""
    out: str
    value: Expr
    ids: Expr
    num_segments: int

    def outs(self):
        return (self.out,)

    def key(self):
        return (f"G({self.out}={_k(self.value)}@{_k(self.ids)}"
                f"/{self.num_segments})")


@dataclass(frozen=True)
class Sort(Node):
    """``lax.sort`` over equal-length 1-D operands; the first
    ``num_keys`` operands are the lexicographic sort keys (ORDER BY)."""
    names: Tuple[str, ...]
    operands: Tuple[Expr, ...]
    num_keys: int

    def outs(self):
        return self.names

    def key(self):
        return ("S(" + ",".join(self.names) + "="
                + ",".join(_k(o) for o in self.operands)
                + f"/{self.num_keys})")


@dataclass(frozen=True)
class Reduce(Node):
    """Cross-shard reduction point: identity single-chip, psum under
    shard_map, REPLACED by the kudo exchange in the distributed
    runner.  kind 'sum' (exact int64 partials — any reduction order is
    byte-identical) or 'any' (overflow flags)."""
    out: str
    value: Expr
    kind: str = "sum"

    def outs(self):
        return (self.out,)

    def key(self):
        return f"R({self.out}={_k(self.value)}:{self.kind})"


@dataclass(frozen=True)
class WindowSum(Node):
    """Window aggregate ``sum(value) OVER (PARTITION BY part)``
    broadcast back to every row: segment-sum + gather."""
    out: str
    part: Expr
    value: Expr
    num_partitions: int

    def outs(self):
        return (self.out,)

    def key(self):
        return (f"WS({self.out}={_k(self.value)}@{_k(self.part)}"
                f"/{self.num_partitions})")


@dataclass(frozen=True)
class WindowRank(Node):
    """``rank() OVER (PARTITION BY part ORDER BY order ASC)`` (callers
    negate for DESC), 0-based, ties broken by row index — one
    lax.sort + cummax, no data-dependent loops."""
    out: str
    part: Expr
    order: Expr

    def outs(self):
        return (self.out,)

    def key(self):
        return f"WR({self.out}={_k(self.order)}@{_k(self.part)})"


@dataclass(frozen=True)
class Rollup(Node):
    """GROUP BY ROLLUP/CUBE over two key columns with cardinalities
    ``cards`` — the grouping-sets aggregate as one node.  Defines
    ``<p>.sum0``/``<p>.cnt0`` (k1 x k2 finest level), ``<p>.sum1``/
    ``<p>.cnt1`` (per-k1, k2 rolled up), ``<p>.sumt``/``<p>.cntt``
    (grand total), and for mode='cube' additionally ``<p>.sum2``/
    ``<p>.cnt2`` (per-k2).  Coarser levels fold from the finest level's
    exact int sums, so every level is byte-stable in any order."""
    prefix: str
    keys: Tuple[Expr, Expr]
    cards: Tuple[int, int]
    value: Expr
    mask: Expr
    mode: str = "rollup"

    def outs(self):
        p = self.prefix
        base = (f"{p}.sum0", f"{p}.cnt0", f"{p}.sum1", f"{p}.cnt1",
                f"{p}.sumt", f"{p}.cntt")
        if self.mode == "cube":
            base = base + (f"{p}.sum2", f"{p}.cnt2")
        return base

    def key(self):
        return (f"U({self.prefix},{_k(self.keys[0])},{_k(self.keys[1])}"
                f",{self.cards},{_k(self.value)},{_k(self.mask)},"
                f"{self.mode})")


# ------------------------------------------------------------------ inputs


@dataclass(frozen=True)
class ColSpec:
    """One bound input column.  ``pad`` is the value the compiler pads
    the bucket tail with — join-key columns use side-specific
    sentinels (-1 vs -2) so pad rows can never match each other, and
    dense-lookup indices pad with an in-range value while ``Mask``
    kills their contribution."""
    name: str
    pad: int = 0


@dataclass(frozen=True)
class ScanBind(Node):
    """Stage input: binds caller arrays to named columns.  Bucketed
    inputs (facts) are padded to the next power-of-two row bucket and
    carry a traced ``n_valid`` scalar (so nearby batch sizes share one
    executable — the PR-4 contract); unbucketed inputs (group tables,
    dims, scalars) keep exact shapes, folded into the digest."""
    name: str
    columns: Tuple[ColSpec, ...]
    bucket: bool = True

    def outs(self):
        return tuple(c.name for c in self.columns)

    def key(self):
        cols = ",".join(f"{c.name}:{c.pad}" for c in self.columns)
        return f"I({self.name},[{cols}],{int(self.bucket)})"


@dataclass(frozen=True)
class ShuffleBoundary:
    """Typed seam between two stages of a Pipeline: ``carry`` names the
    columns that cross (single-chip: direct handoff; distributed: kudo
    tables over the socket shuffle).  Everything on either side fuses
    into its own single executable."""
    carry: Tuple[str, ...]

    def key(self):
        return "B(" + ",".join(self.carry) + ")"


# ------------------------------------------------------------------- plans


@dataclass(frozen=True)
class StagePlan:
    """One fusable stage: inputs, an SSA-ordered node list (each node
    may only reference columns defined above it), and named outputs."""
    name: str
    inputs: Tuple[ScanBind, ...]
    nodes: Tuple[Node, ...]
    outputs: Tuple[str, ...]

    @property
    def digest(self) -> str:
        s = ";".join([self.name]
                     + [i.key() for i in self.inputs]
                     + [n.key() for n in self.nodes]
                     + list(self.outputs))
        return hashlib.sha1(s.encode()).hexdigest()[:16]

    def validate(self) -> "StagePlan":
        defined = set()
        for i in self.inputs:
            defined.update(i.outs())
        for n in self.nodes:
            for out in n.outs():
                if out in defined:
                    raise ValueError(f"duplicate column {out!r} in "
                                     f"stage {self.name!r}")
                defined.add(out)
        missing = [o for o in self.outputs if o not in defined]
        if missing:
            raise ValueError(f"stage {self.name!r} outputs undefined "
                             f"columns {missing}")
        return self


@dataclass(frozen=True)
class Pipeline:
    """Stages joined by typed shuffle boundaries:
    ``stages[i] -> boundaries[i] -> stages[i+1]``.  A stage after a
    boundary binds the carried columns through a ScanBind whose column
    names EQUAL the carry names (the compiler feeds them by name)."""
    name: str
    stages: Tuple[StagePlan, ...]
    boundaries: Tuple[ShuffleBoundary, ...] = field(default=())

    @property
    def digest(self) -> str:
        s = ";".join([self.name] + [st.digest for st in self.stages]
                     + [b.key() for b in self.boundaries])
        return hashlib.sha1(s.encode()).hexdigest()[:16]
