"""Whole-stage fusion (ISSUE 11): a small stage IR + compiler that
fuses everything a query does between shuffle boundaries into ONE XLA
executable, AOT-keyed in the perf/jit_cache and calibrated (fused vs
op-by-op) at stage granularity.

  ir.py        typed plan nodes (scan-bind, project/filter exprs,
               hash-join probe, segment/window/rollup aggregates,
               sort, cross-shard reduce, shuffle boundary)
  compiler.py  one evaluator, three engines: fused AOT executable,
               op-by-op escape hatch, shard_map pipeline body
  catalog.py   TPC-DS stages (q3/q5/q9/q72 re-expressed — the hand
               kernels in models/tpcds stay as byte-identity oracles —
               plus the new q67 rollup+rank and q89 window shapes)
"""

from spark_rapids_tpu.plan import catalog, compiler, ir  # noqa: F401
from spark_rapids_tpu.plan.compiler import (  # noqa: F401
    CompiledStage, compile_pipeline, compile_stage, fused_pipeline_fn,
    fusion_mode)
