"""Flat handle-based API mirroring the reference JNI export surface
(src/main/cpp/src/*Jni.cpp pattern: unwrap jlong handles ->
column_views -> call the op -> release_as_jlong).  This is the layer a
real JNI/C-FFI binding calls; every function takes/returns int64 handles
and plain scalars, mirroring the Java native method signatures
(Hash.java:44 murmurHash32, RowConversion.java:35 convertToRows, ...).
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.table import Table
from spark_rapids_tpu.shim.handles import REGISTRY
from spark_rapids_tpu.utils.fault_injection import maybe_inject
from spark_rapids_tpu.utils.profiler import op_range


def _cols(handles: Sequence[int]) -> List[Column]:
    return [REGISTRY.get(h) for h in handles]


def make_column_from_host(values, dtype) -> int:
    col = (Column.from_strings(values) if dtype.is_string
           else Column.from_pylist(values, dtype))
    return REGISTRY.register(col)


def release_column(handle: int) -> None:
    REGISTRY.release(handle)


def column_to_host(handle: int):
    return REGISTRY.get(handle).to_pylist()


# --------------------------------------------------------------- ops
# (each export follows the reference JNI shape: inject-check, NVTX-like
# range, unwrap handles, run, wrap result)


def murmur_hash3_32(seed: int, column_handles: Sequence[int]) -> int:
    maybe_inject("murmur3_32")
    with op_range("murmur3_32"):
        from spark_rapids_tpu.ops import murmur3_32
        return REGISTRY.register(murmur3_32(_cols(column_handles), seed))


def xx_hash_64(seed: int, column_handles: Sequence[int]) -> int:
    maybe_inject("xxhash64")
    with op_range("xxhash64"):
        from spark_rapids_tpu.ops import xxhash64
        return REGISTRY.register(xxhash64(_cols(column_handles), seed))


def hive_hash(column_handles: Sequence[int]) -> int:
    maybe_inject("hive_hash")
    with op_range("hive_hash"):
        from spark_rapids_tpu.ops import hive_hash as _hh
        return REGISTRY.register(_hh(_cols(column_handles)))


def convert_to_rows(table_handles: Sequence[int]) -> int:
    maybe_inject("convert_to_rows")
    with op_range("convert_to_rows"):
        from spark_rapids_tpu.ops.row_conversion import convert_to_rows
        return REGISTRY.register(
            convert_to_rows(Table(_cols(table_handles))))


def convert_from_rows(rows_handle: int, type_ids: Sequence[str],
                      scales: Sequence[int]) -> List[int]:
    maybe_inject("convert_from_rows")
    with op_range("convert_from_rows"):
        from spark_rapids_tpu.columns.dtypes import DType
        from spark_rapids_tpu.ops.row_conversion import convert_from_rows
        schema = [DType(k, s) for k, s in zip(type_ids, scales)]
        out = convert_from_rows(REGISTRY.get(rows_handle), schema)
        return [REGISTRY.register(c) for c in out.columns]


def string_to_integer(column_handle: int, type_id: str,
                      ansi_mode: bool, strip: bool) -> int:
    maybe_inject("string_to_integer")
    with op_range("string_to_integer"):
        from spark_rapids_tpu.columns.dtypes import DType
        from spark_rapids_tpu.ops.cast_string import string_to_integer
        return REGISTRY.register(string_to_integer(
            REGISTRY.get(column_handle), DType(type_id), ansi_mode,
            strip))


def get_json_object(column_handle: int, path: str) -> int:
    maybe_inject("get_json_object")
    with op_range("get_json_object"):
        from spark_rapids_tpu.ops.json_path import get_json_object
        return REGISTRY.register(
            get_json_object(REGISTRY.get(column_handle), path))


def sort_merge_inner_join(left_handles: Sequence[int],
                          right_handles: Sequence[int],
                          compare_nulls_equal: bool) -> List[int]:
    maybe_inject("sort_merge_inner_join")
    with op_range("sort_merge_inner_join"):
        import jax.numpy as jnp
        from spark_rapids_tpu.columns import dtypes
        from spark_rapids_tpu.ops import joins
        li, ri = joins.sort_merge_inner_join(
            Table(_cols(left_handles)), Table(_cols(right_handles)),
            joins.NULL_EQUAL if compare_nulls_equal
            else joins.NULL_UNEQUAL)
        lc = Column(dtypes.INT32, int(li.shape[0]), data=li)
        rc = Column(dtypes.INT32, int(ri.shape[0]), data=ri)
        return [REGISTRY.register(lc), REGISTRY.register(rc)]


# --------------------------------------------------------------- ingest
# (the storage-side doors: zero-copy Arrow C-interface hand-off and the
# columnar parquet reader — reference NativeParquetJni surface)


def arrow_ingest(batch) -> List[int]:
    """Wrap an Arrow RecordBatch (or any ``__arrow_c_array__``
    exporter) as device columns WITHOUT copying; returns one handle
    per column.  The registry entries keep the Arrow buffers alive —
    the caller may free its batch immediately."""
    maybe_inject("arrow_ingest")
    with op_range("arrow_ingest"):
        from spark_rapids_tpu.io.arrow_cabi import ingest
        cols, _names = ingest(batch)
        handles = [REGISTRY.register(c) for c in cols]
        # ingest-epoch door (ISSUE 19): an Arrow batch has no stable
        # file identity, so every hand-off is new data — results
        # keyed over the "arrow" source go stale unconditionally
        try:
            from spark_rapids_tpu.perf.result_cache import \
                bump_ingest_epoch
            bump_ingest_epoch("arrow")
        except Exception:
            pass
        return handles


def parquet_read_table(path: str, columns=None,
                       case_sensitive: bool = True) -> List[int]:
    """Columnar parquet read with footer-pruned projection pushdown;
    returns one handle per (kept) column, in file schema order."""
    maybe_inject("parquet_read_table")
    with op_range("parquet_read_table"):
        from spark_rapids_tpu.io.parquet_reader import read_table
        table = read_table(path, columns=columns,
                           case_sensitive=case_sensitive)
        return [REGISTRY.register(c) for c in table.columns]


# --------------------------------------------------------- observability
# (reference: RmmSpark getAndReset* + Profiler control surface; here the
# unified registry/journal is exported to the JVM as text/JSON blobs so
# the binding needs no schema compiler)


def metrics_set_enabled(enabled: bool) -> bool:
    """Flip the process-wide observability switch; returns prior state."""
    from spark_rapids_tpu import observability as obs
    prior = obs.is_enabled()
    (obs.enable if enabled else obs.disable)()
    return prior


def metrics_enabled() -> bool:
    from spark_rapids_tpu import observability as obs
    return obs.is_enabled()


def metrics_expose_text() -> str:
    """Prometheus text-format exposition of the process registry."""
    from spark_rapids_tpu import observability as obs
    return obs.expose_text()


def metrics_snapshot_json() -> str:
    """JSON snapshot (registry + per-task rollup + journal stats) for
    the JVM shim."""
    import json

    from spark_rapids_tpu import observability as obs
    return json.dumps(obs.snapshot(), sort_keys=True)


def metrics_journal_dump(path: str) -> int:
    """Dump the event journal (+ task rollups + registry snapshot) as
    JSONL; returns records written."""
    from spark_rapids_tpu import observability as obs
    return obs.dump_journal_jsonl(path)


def metrics_reset() -> None:
    from spark_rapids_tpu import observability as obs
    obs.reset()


# -------------------------------------------------------------- tracing
# (span tracing control surface: the JVM enables tracing around a query
# and flushes finished spans to a JSONL file it owns — the per-process
# input of tools/trace_export.py)


def tracing_set_enabled(enabled: bool) -> bool:
    """Flip structured span tracing; returns prior state."""
    from spark_rapids_tpu import observability as obs
    prior = obs.is_tracing_enabled()
    (obs.enable_tracing if enabled else obs.disable_tracing)()
    return prior


def tracing_enabled() -> bool:
    from spark_rapids_tpu import observability as obs
    return obs.is_tracing_enabled()


def tracing_dump(path: str) -> int:
    """Write the finished-span ring as JSONL; returns spans written."""
    from spark_rapids_tpu import observability as obs
    return obs.dump_spans_jsonl(path)


def tracing_flush(path: str) -> int:
    """Like tracing_dump but DRAINS the ring (repeated flushes between
    export intervals never re-export a span).  The write is atomic
    (tmp + rename): a failure mid-write leaves any previous flush file
    intact AND requeues the drained spans."""
    import json

    from spark_rapids_tpu import observability as obs
    from spark_rapids_tpu.observability.dumpio import atomic_write
    recs = obs.TRACER.drain()

    def _write(f):
        for r in recs:
            f.write(json.dumps(r) + "\n")

    try:
        atomic_write(path, _write)
    except BaseException:
        # an unwritable path OR a mid-write failure (disk full, quota)
        # must not lose the drained spans: put them back so a corrected
        # retry re-exports everything
        obs.TRACER.requeue(recs)
        raise
    return len(recs)


def tracing_reset() -> None:
    from spark_rapids_tpu import observability as obs
    obs.TRACER.reset()


# ------------------------------------------------------- query profiles
# (EXPLAIN ANALYZE control surface: the JVM flips profiling around a
# workload, then pulls per-query artifacts by id — reference analog:
# the profiler sidecar's capture window + profile_converter pull)


def profile_set_enabled(enabled: bool) -> bool:
    """Flip per-query profile assembly; returns prior state."""
    from spark_rapids_tpu import observability as obs
    prior = obs.is_profiling_enabled()
    (obs.enable_profiling if enabled else obs.disable_profiling)()
    return prior


def profile_enabled() -> bool:
    from spark_rapids_tpu import observability as obs
    return obs.is_profiling_enabled()


def profile_last_json() -> str:
    """Most recently assembled query profile as JSON ('' when none
    has been assembled yet)."""
    import json

    from spark_rapids_tpu import observability as obs
    prof = obs.PROFILER.last()
    return json.dumps(prof, sort_keys=True, default=str) \
        if prof is not None else ""


def server_profile_json(query_id: str) -> str:
    """The server-retained profile for one query id as JSON —
    ``{"ok": true, "profile": {...}}`` or a typed miss (never
    profiled / evicted by the tenant's last-K window)."""
    import json

    from spark_rapids_tpu import server as srv
    s = srv.get_server()
    if s is None:
        raise RuntimeError("query server is not running")
    prof = s.profile(str(query_id))
    if prof is None:
        return json.dumps({"ok": False,
                           "error": {"type": "UnknownProfile",
                                     "message": f"no retained "
                                                f"profile for "
                                                f"{query_id!r}"}})
    return json.dumps({"ok": True, "profile": prof},
                      sort_keys=True, default=str)


# ------------------------------------------------------ flight recorder
# (reference: the CUPTI profiler dump + RmmSpark state dump the JVM
# pulls on failure; here the JVM arms the recorder, forces bundles,
# and lists/fetches what the anomaly detectors froze)


def flight_recorder_set_enabled(enabled: bool) -> bool:
    """Arm/disarm the flight recorder; returns prior state."""
    from spark_rapids_tpu import observability as obs
    prior = obs.is_flight_recorder_enabled()
    (obs.enable_flight_recorder if enabled
     else obs.disable_flight_recorder)()
    return prior


def flight_recorder_enabled() -> bool:
    from spark_rapids_tpu import observability as obs
    return obs.is_flight_recorder_enabled()


def flight_recorder_configure(out_dir: str = "", max_bytes: int = 0,
                              min_interval_s: float = -1.0) -> None:
    """Set bundle directory / byte budget / rate-limit interval;
    zero/negative/empty values leave the current setting."""
    from spark_rapids_tpu import observability as obs
    obs.FLIGHT.configure(
        out_dir=out_dir or None,
        max_bytes=int(max_bytes) if max_bytes > 0 else None,
        min_interval_s=(float(min_interval_s)
                        if min_interval_s >= 0 else None))


def incident_dump(reason: str = "manual") -> str:
    """Force an incident bundle NOW (bypasses the enabled flag and the
    rate limit; still honors the byte budget).  Returns the bundle
    path, or '' when the byte budget suppressed it."""
    from spark_rapids_tpu import observability as obs
    path = obs.FLIGHT.trigger("manual", force=True, severity="info",
                              reason=str(reason))
    return path or ""


def incident_list() -> str:
    """JSON list of complete bundles in the recorder's directory
    (path, trigger kind, severity, wall-clock, bytes)."""
    import json

    from spark_rapids_tpu import observability as obs
    return json.dumps(obs.FLIGHT.incident_list())


def health_json() -> str:
    """One-call process health rollup (switches, ring fill/drops,
    recorder stats, memory-ledger summary) as JSON."""
    import json

    from spark_rapids_tpu import observability as obs
    return json.dumps(obs.health(), sort_keys=True, default=str)


# ------------------------------------------------------ telemetry plane
# (windowed time-series + per-tenant SLO control surface: the JVM
# flips the sampler/monitor around a workload, pulls the window ring
# for its own dashboards, and polls burn-rate status between stages)


def timeseries_set_enabled(enabled: bool) -> bool:
    """Flip the windowed time-series sampler; returns prior state."""
    from spark_rapids_tpu import observability as obs
    prior = obs.is_timeseries_enabled()
    (obs.enable_timeseries if enabled else obs.disable_timeseries)()
    return prior


def timeseries_enabled() -> bool:
    from spark_rapids_tpu import observability as obs
    return obs.is_timeseries_enabled()


def timeseries_snapshot_json() -> str:
    """The window ring (per-window counter deltas, gauge last-values,
    windowed histogram buckets) plus SLO status when the monitor is
    armed, as JSON — the same shape the fleet publishes to rank 0."""
    import json

    from spark_rapids_tpu import observability as obs
    return json.dumps(obs.timeseries_snapshot(), sort_keys=True)


def slo_set_enabled(enabled: bool) -> bool:
    """Arm/disarm per-tenant SLO burn-rate monitoring; returns prior
    state."""
    from spark_rapids_tpu import observability as obs
    prior = obs.is_slo_enabled()
    (obs.enable_slo if enabled else obs.disable_slo)()
    return prior


def slo_enabled() -> bool:
    from spark_rapids_tpu import observability as obs
    return obs.is_slo_enabled()


def slo_status_json() -> str:
    """Per-tenant SLO status (target, objective, attainment, fast/slow
    burn rates, breach count) as JSON."""
    import json

    from spark_rapids_tpu import observability as obs
    return json.dumps(obs.SLO.status(), sort_keys=True)


def slo_evaluate_json() -> str:
    """Force a burn-rate evaluation NOW (bypasses the throttle the
    Monitor thread uses) and return any fired alerts as a JSON list —
    each alert also routed through the normal slo_burn incident path."""
    import json

    from spark_rapids_tpu import observability as obs
    return json.dumps(obs.evaluate_slo(), sort_keys=True)


# ----------------------------------------------------- time attribution
# (the "where did the time go" ledger: the JVM arms it around a
# workload and pulls the last query's bucket waterfall for its own
# p99-miss triage)


def attribution_set_enabled(enabled: bool) -> bool:
    """Flip per-query time-attribution ledgers; returns prior state."""
    from spark_rapids_tpu import observability as obs
    prior = obs.is_attribution_enabled()
    (obs.enable_attribution if enabled
     else obs.disable_attribution)()
    return prior


def attribution_enabled() -> bool:
    from spark_rapids_tpu import observability as obs
    return obs.is_attribution_enabled()


def attribution_last_json() -> str:
    """Most recent query's time-attribution ledger (bucket ns,
    fractions, dominant bucket, conservation verdict) as JSON
    ('' when no profiled query has completed with the switch on)."""
    import json

    from spark_rapids_tpu import observability as obs
    led = obs.attribution_last()
    return json.dumps(led, sort_keys=True, default=str) \
        if led is not None else ""


# ------------------------------------------------------ fault injection
# (reference: libcufaultinj loaded via CUDA_INJECTION64_PATH with a
# FAULT_INJECTOR_CONFIG_PATH JSON; here the JVM drives the same
# hot-reloadable injector through the shim)


def fault_injection_install(config_path: str = "", watch: bool = True,
                            interval_ms: int = 0) -> int:
    """Install the process-global injector; an empty path falls back
    to $FAULT_INJECTOR_CONFIG_PATH.  interval_ms <= 0 keeps the
    default watch poll.  Returns the active rule count (a missing
    config is tolerated: 0 rules, watcher retrying)."""
    from spark_rapids_tpu.utils import fault_injection as fi
    interval_ms = int(interval_ms)
    inj = fi.install(config_path or None, watch=bool(watch),
                     interval_ms=interval_ms if interval_ms > 0
                     else None)
    return len(inj.active_rules())


def fault_injection_uninstall() -> None:
    from spark_rapids_tpu.utils import fault_injection as fi
    fi.uninstall()


def fault_injection_config_path() -> str:
    """The installed injector's config path ('' when no injector or no
    path is installed)."""
    from spark_rapids_tpu.utils import fault_injection as fi
    inj = fi.installed()
    return (inj.config_path or "") if inj is not None else ""


def fault_injection_rules_json() -> str:
    """Live rule snapshot as JSON (match/probability/remaining/
    exception per rule) — the JVM-side hot-reload assertion surface."""
    import json

    from spark_rapids_tpu.utils import fault_injection as fi
    inj = fi.installed()
    return json.dumps(inj.active_rules() if inj is not None else [])


# ----------------------------------------------------------- jit cache
# (compile-cache control surface: the JVM polls hit rates between
# stages and clears the cache around schema migrations)


def jit_cache_stats() -> str:
    """JSON stats of the process kernel compile cache (perf/jit_cache):
    entries/bytes, hit/miss/eviction/compile totals, and per-kernel
    breakdowns."""
    import json

    from spark_rapids_tpu.perf import jit_cache
    return json.dumps(jit_cache.CACHE.stats(), sort_keys=True)


def jit_cache_clear(reset_stats: bool = False) -> int:
    """Drop every cached executable; returns the number dropped.
    ``reset_stats`` additionally zeroes the cumulative counters."""
    from spark_rapids_tpu.perf import jit_cache
    return jit_cache.CACHE.clear(reset_stats=bool(reset_stats))


# -------------------------------------------------------- result cache
# (semantic result/subplan cache control surface, ISSUE 19: the JVM
# polls hit rates, clears around catalog reloads, and bumps a source's
# ingest epoch when ITS ingest path — not ours — landed new data)


def result_cache_stats() -> str:
    """JSON stats of the semantic result/subplan cache
    (perf/result_cache): entries/bytes, hit/miss/eviction/put/fold
    totals, per-scope entry counts."""
    import json

    from spark_rapids_tpu.perf import result_cache
    return json.dumps(result_cache.CACHE.stats(), sort_keys=True)


def result_cache_clear(reset_stats: bool = False) -> int:
    """Drop every cached result/subplan entry; returns the number
    dropped.  ``reset_stats`` additionally zeroes the counters."""
    from spark_rapids_tpu.perf import result_cache
    return result_cache.CACHE.clear(reset_stats=bool(reset_stats))


def result_cache_bump_epoch(source: str) -> int:
    """Advance ``source``'s ingest epoch (externally-landed data):
    every cached result keyed over it goes stale; returns the new
    epoch."""
    from spark_rapids_tpu.perf import result_cache
    return result_cache.bump_ingest_epoch(str(source))


# ----------------------------------------------------------- data stats
# (per-node cardinality observatory, ISSUE 20: the JVM arms the
# collector during plan-quality investigations, then pulls one JSON
# snapshot of est-vs-actual rows per stage/node; disabled it costs one
# attribute read per stage run)


def stats_set_enabled(enabled: bool) -> bool:
    """Arm/disarm the per-node statistics collector; returns the new
    state."""
    from spark_rapids_tpu import observability as obs
    if enabled:
        obs.enable_stats()
    else:
        obs.disable_stats()
    return obs.is_stats_enabled()


def stats_enabled() -> bool:
    from spark_rapids_tpu import observability as obs
    return obs.is_stats_enabled()


def stats_snapshot_json() -> str:
    """JSON snapshot of the statistics collector: observation and
    misestimate totals, registered estimates and source row counts,
    and the latest per-node section per stage."""
    import json

    from spark_rapids_tpu import observability as obs
    return json.dumps(obs.STATS.snapshot(), sort_keys=True,
                      default=str)


def stats_store_clear() -> None:
    """Drop the persistent StatsStore (process map and file layer)."""
    from spark_rapids_tpu import observability as obs
    obs.STATS.store.clear()


# --------------------------------------------------------- query server
# (the resident multi-tenant front door, server/: the JVM starts the
# pool once per executor, then every Spark task thread submits through
# these flat entries; backpressure crosses as a JSON error payload so
# the binding needs no exception-class plumbing)


def server_start(max_concurrency: int = 0, max_queue: int = 0,
                 socket_path: str = "") -> bool:
    """Start the process-global query server (idempotent; returns
    True when this call started it).  Zero values take the
    SPARK_RAPIDS_TPU_SERVER_* env defaults."""
    from spark_rapids_tpu import server as srv
    cfg = srv.ServerConfig.from_env()
    if max_concurrency > 0:
        cfg.max_concurrency = int(max_concurrency)
    if max_queue > 0:
        cfg.max_queue = int(max_queue)
    # created-flag decided under the singleton lock: two racing JVM
    # threads cannot both be told they started the server
    _server, created = srv.ensure_server(
        cfg, socket_path=socket_path or None)
    return created


def server_stop() -> None:
    from spark_rapids_tpu import server as srv
    srv.stop_server()


def server_set_tenant_quota(tenant: str, max_inflight: int = -1,
                            max_device_bytes: int = -1,
                            weight: float = -1.0) -> None:
    from spark_rapids_tpu import server as srv
    s = srv.get_server()
    if s is None:
        raise RuntimeError("query server is not running")
    s.set_tenant_quota(str(tenant), max_inflight=int(max_inflight),
                       max_device_bytes=int(max_device_bytes),
                       weight=float(weight))


def server_submit(tenant: str, query: str,
                  params_json: str = "",
                  deadline_s: float = -1.0) -> str:
    """Submit; returns JSON — {"ok": true, "query_id": ...} or the
    typed backpressure payload {"ok": false, "error": {...,
    "reason": "queue_full"|"quarantined"|"draining"|...}}.
    ``deadline_s > 0`` bounds the query's whole lifetime (the
    lifeguard cancels and escalates past it); <= 0 takes the
    server-wide default."""
    import json

    from spark_rapids_tpu import server as srv
    from spark_rapids_tpu.models import UnknownQueryError
    s = srv.get_server()
    if s is None:
        raise RuntimeError("query server is not running")
    params = json.loads(params_json) if params_json else {}
    try:
        qid = s.submit(str(tenant), str(query), params,
                       deadline_s=float(deadline_s)
                       if deadline_s > 0 else None)
        return json.dumps({"ok": True, "query_id": qid})
    except srv.ServerOverloaded as e:
        return json.dumps({"ok": False, "error": e.to_dict()})
    except UnknownQueryError as e:
        return json.dumps({"ok": False,
                           "error": {"type": "UnknownQuery",
                                     "message": str(e)}})


def server_poll(query_id: str, timeout_s: float = -1.0) -> str:
    """Job status as JSON (state queued|running|done|failed|cancelled
    |unknown, result when done, typed error when failed)."""
    import json

    from spark_rapids_tpu import server as srv
    s = srv.get_server()
    if s is None:
        raise RuntimeError("query server is not running")
    return json.dumps(s.poll(
        str(query_id),
        timeout_s=float(timeout_s) if timeout_s >= 0 else None))


def server_cancel(query_id: str) -> bool:
    from spark_rapids_tpu import server as srv
    s = srv.get_server()
    if s is None:
        return False
    return s.cancel(str(query_id))


def server_stats_json() -> str:
    """Per-tenant accounting + scheduler fair-share evidence + the
    task-priority registry snapshot, as JSON."""
    import json

    from spark_rapids_tpu import server as srv
    s = srv.get_server()
    if s is None:
        return json.dumps({"started": False})
    return json.dumps(s.stats(), sort_keys=True)


def server_drain(deadline_s: float = -1.0,
                 flush_dir: str = "") -> str:
    """Gracefully drain the process-global server (ISSUE 7): refuse
    new submits typed (``draining``), finish in-flight work under the
    drain deadline, flush journal/spans/metrics via dumpio, stop the
    pool, and clear the singleton — a later ``server_start`` serves
    again with the jit cache warm.  Returns the drain report as
    JSON (``{"state": "not_running"}`` when no server exists)."""
    import json

    from spark_rapids_tpu import server as srv
    report = srv.drain_server(
        deadline_s=float(deadline_s) if deadline_s > 0 else None,
        flush_dir=str(flush_dir) or None)
    return json.dumps(report, sort_keys=True, default=str)


# ------------------------------------------------------------ kudo crc


def kudo_set_crc_enabled(enabled: bool) -> bool:
    """Flip KCRC-trailer writing for the Python kudo engine; returns
    the prior setting.  Read-side verification is always on when a
    trailer is present."""
    from spark_rapids_tpu.shuffle import kudo
    return kudo.set_crc_enabled(bool(enabled))


def kudo_crc_enabled() -> bool:
    from spark_rapids_tpu.shuffle import kudo
    return kudo.crc_enabled()


# -------------------------------------------------------- spill store
# (ISSUE 18: the JVM installs/uninstalls the tiered spill store around
# a workload, asks for synchronous headroom before its own device
# allocations, and polls tier occupancy for executor dashboards)


def spill_store_install() -> bool:
    """Install the process spill store and wire it into the installed
    SparkResourceAdaptor's OOM state machine (idempotent).  Returns
    True when an adaptor was present to hook."""
    from spark_rapids_tpu.memory import rmm_spark, spill
    spill.install()
    return rmm_spark.installed_adaptor() is not None


def spill_store_uninstall() -> None:
    """Unhook and drop the process spill store (every handle and its
    disk files released)."""
    from spark_rapids_tpu.memory import spill
    spill.uninstall()


def spill_ensure_headroom(num_bytes: int) -> int:
    """Synchronously spill registered batches until ``num_bytes`` of
    device memory are free (or nothing spillable remains); returns the
    bytes actually freed (0 with no store installed)."""
    from spark_rapids_tpu.memory import spill
    store = spill.installed_store()
    if store is None:
        return 0
    return int(store.ensure_headroom(int(num_bytes)))


def spill_store_stats_json() -> str:
    """Tier occupancy + lifetime spill/restore/corruption counters for
    the installed store as JSON (``{"installed": false}`` without
    one)."""
    import json

    from spark_rapids_tpu.memory import spill
    store = spill.installed_store()
    if store is None:
        return json.dumps({"installed": False})
    out = {"installed": True}
    out.update(store.stats())
    return json.dumps(out, sort_keys=True)
