from spark_rapids_tpu.shim.handles import HandleRegistry  # noqa: F401
from spark_rapids_tpu.shim import jni_api  # noqa: F401
