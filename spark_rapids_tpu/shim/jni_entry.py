"""Embedded-interpreter entry points for the JNI shim
(native/jni/spark_rapids_tpu_jni.cpp).

Every function here takes/returns only primitives, strings, and flat
lists of them — the shapes a hand-written JNI layer can marshal without
any Python C-API object gymnastics.  This is the process-boundary twin
of shim/jni_api.py: jni_api mirrors the reference's *Jni.cpp export
signatures (unwrap jlong handles -> op -> wrap), and this module adapts
those to the embedded-CPython calling convention used by the real JVM
binding (reference: src/main/cpp/src/hash/HashJni.cpp:31-46 unwraps
jlongs the same way before calling the native op).

The JVM side lives in java/src/com/nvidia/spark/rapids/jni/ (same
package as the reference so spark-rapids GpuExec-facing code keeps its
imports); runnable class files for this JRE-only image are emitted by
scripts/gen_java_classes.py.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from spark_rapids_tpu.analysis.lockdep import make_lock
from spark_rapids_tpu.shim.errors import ShimArgumentError, ShimStateError

_INITIALIZED = False


def initialize() -> None:
    """One-time runtime init inside the embedded interpreter."""
    global _INITIALIZED
    if _INITIALIZED:
        return
    import os

    import jax
    # Env vars are too late on this image (sitecustomize pre-imports jax
    # with the axon TPU plugin — see Makefile dryrun note), so platform
    # pinning must go through jax.config.
    platform = os.environ.get("SPARK_RAPIDS_TPU_PLATFORM", "")
    if platform:
        jax.config.update("jax_platforms", platform)
    # virtual CPU device count for mesh programs driven from the JVM
    # (must be set before the backend initializes)
    ndev = os.environ.get("SPARK_RAPIDS_TPU_CPU_DEVICES", "")
    if ndev:
        n = int(ndev)              # malformed values must FAIL loudly
        try:
            jax.config.update("jax_num_cpu_devices", n)
        except RuntimeError:
            pass   # backend already up: device count locked
        except AttributeError:
            # jax<0.4.38: no such option — the XLA_FLAGS path below is
            # the only pre-backend-init knob there
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags
                    + f" --xla_force_host_platform_device_count={n}"
                ).strip()
    jax.config.update("jax_enable_x64", True)
    from spark_rapids_tpu.utils.jax_compat import \
        ensure_partitionable_threefry
    ensure_partitionable_threefry()
    _INITIALIZED = True


def shutdown() -> None:
    import sys

    from spark_rapids_tpu.shim.handles import REGISTRY
    from spark_rapids_tpu.utils.profiler import Profiler
    # stop the query server first (its pool threads hold handles);
    # sys.modules check: shutdown must not IMPORT the server package
    # into a process that never used it
    srv = sys.modules.get("spark_rapids_tpu.server")
    if srv is not None:
        try:
            srv.stop_server(timeout_s=5)
        except Exception:
            pass
    _KUDO_WRITE_CACHE.clear()
    REGISTRY.clear()
    _HOST_TABLES.clear()   # spilled buffers are handles too
    Profiler.shutdown()    # stops the flusher, closes file sinks


def live_handles() -> int:
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.live_count()


# ------------------------------------------------------------- columns


def from_longs(values: Sequence[int]) -> int:
    from spark_rapids_tpu.columns import dtypes
    from spark_rapids_tpu.shim import jni_api
    return jni_api.make_column_from_host(list(values), dtypes.INT64)


def from_ints(values: Sequence[int]) -> int:
    from spark_rapids_tpu.columns import dtypes
    from spark_rapids_tpu.shim import jni_api
    return jni_api.make_column_from_host(list(values), dtypes.INT32)


def from_doubles(values: Sequence[float]) -> int:
    from spark_rapids_tpu.columns import dtypes
    from spark_rapids_tpu.shim import jni_api
    return jni_api.make_column_from_host(list(values), dtypes.FLOAT64)


def from_strings(values: Sequence[Optional[str]]) -> int:
    from spark_rapids_tpu.columns import dtypes
    from spark_rapids_tpu.shim import jni_api
    return jni_api.make_column_from_host(list(values), dtypes.STRING)


def from_strings_bulk(chars: bytes, offsets_le: bytes,
                      validity: Optional[bytes]) -> int:
    """Bulk string-column ingest: ONE chars buffer + ONE little-endian
    int32 offsets buffer (+ optional packed validity) cross the JNI
    boundary as whole primitive arrays — no per-element boxing
    (VERDICT r4 weak #4; reference discipline: HashJni.cpp:31-46
    moves handles/primitive arrays, never object lists)."""
    import jax.numpy as jnp
    import numpy as np

    from spark_rapids_tpu.shim.handles import REGISTRY
    offs = np.frombuffer(offsets_le, "<i4")
    if len(offs) == 0:
        raise ShimArgumentError(
            "offsets must hold at least one entry (the leading 0)")
    rows = len(offs) - 1
    if offs[0] != 0 or (rows > 0 and (np.diff(offs) < 0).any()):
        raise ShimArgumentError("offsets must start at 0 and be "
                                "non-decreasing")
    if int(offs[-1]) > len(chars):
        raise ShimArgumentError(
            f"last offset {int(offs[-1])} exceeds chars length "
            f"{len(chars)}")
    if validity is not None and len(validity) < (rows + 7) // 8:
        raise ShimArgumentError("validity shorter than ceil(rows/8) bytes")
    # no host-side .copy(): jnp.asarray copies the read-only views
    # into device buffers anyway; an extra memcpy on a multi-MB
    # payload is pure waste on the path this entry exists to speed up
    return REGISTRY.register(_string_column_from_buffers(
        np.frombuffer(chars, np.uint8), offs, validity, rows))


def _string_column_from_buffers(chars_np, offs_np, validity, rows):
    """Shared STRING Column assembly from raw buffers (packed
    LSB-first validity or None) — used by the bulk ingest above and
    the kudo host-table import below."""
    import jax.numpy as jnp
    import numpy as np

    from spark_rapids_tpu.columns import dtypes
    from spark_rapids_tpu.columns.column import Column
    mask = None
    if validity is not None:
        bits = np.unpackbits(np.frombuffer(validity, np.uint8),
                             bitorder="little")[:rows]
        mask = jnp.asarray(bits.astype(np.uint8))
    return Column(dtypes.STRING, rows, data=jnp.asarray(chars_np),
                  validity=mask, offsets=jnp.asarray(offs_np))


def string_column_chars(handle: int) -> bytes:
    """Bulk readback: the whole UTF-8 chars buffer as one byte[]."""
    import numpy as np

    from spark_rapids_tpu.shim.handles import REGISTRY
    col = REGISTRY.get(handle)
    assert col.dtype.is_string
    return (b"" if col.data is None
            else np.asarray(col.data).tobytes())


def string_column_offsets(handle: int) -> bytes:
    """Bulk readback: the int32 offsets as one little-endian byte[]."""
    import numpy as np

    from spark_rapids_tpu.shim.handles import REGISTRY
    col = REGISTRY.get(handle)
    assert col.dtype.is_string
    return np.ascontiguousarray(np.asarray(col.offsets),
                                "<i4").tobytes()


def free(handle: int) -> None:
    """Release a column handle (exactly once — a double free raises
    ``ValueError`` from the registry without corrupting the table).
    Release happens FIRST: once it succeeds this caller owns the
    cleanup, and a concurrent ``kudo_write`` can no longer resolve the
    handle, so it cannot re-insert a memo entry for freed columns
    after the purge below (the purge-first order had that race)."""
    from spark_rapids_tpu.shim import jni_api
    jni_api.release_column(handle)
    _kudo_cache_purge(handle)


def gather(values_handle: int, indices_handle: int) -> int:
    """TpuColumns.gather: take rows of `values` at `indices` (the
    composition primitive GpuExec-shaped plans use between a join's
    index columns and downstream ops)."""
    from spark_rapids_tpu.ops import copying
    from spark_rapids_tpu.shim.handles import REGISTRY
    vals = REGISTRY.get(values_handle)
    idx = REGISTRY.get(indices_handle)
    return REGISTRY.register(copying.gather(vals, idx.data))


def column_to_host(handle: int):
    from spark_rapids_tpu.shim import jni_api
    return jni_api.column_to_host(handle)


# ----------------------------------------------------------------- ops


def murmur_hash3_32(seed: int, handles: Sequence[int]) -> int:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.murmur_hash3_32(seed, handles)


def xx_hash_64(seed: int, handles: Sequence[int]) -> int:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.xx_hash_64(seed, handles)


def hive_hash(handles: Sequence[int]) -> int:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.hive_hash(handles)


def convert_to_rows(handles: Sequence[int]) -> int:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.convert_to_rows(handles)


def convert_from_rows(rows_handle: int, type_ids: Sequence[str],
                      scales: Sequence[int]) -> List[int]:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.convert_from_rows(rows_handle, type_ids, scales)


def string_to_integer(handle: int, type_id: str, ansi: bool,
                      strip: bool) -> int:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.string_to_integer(handle, type_id, ansi, strip)


def string_to_float(handle: int, type_id: str, ansi: bool) -> int:
    from spark_rapids_tpu.columns.dtypes import DType
    from spark_rapids_tpu.ops.cast_string import string_to_float as stf
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(
        stf(REGISTRY.get(handle), DType(type_id), ansi))


def float_to_string(handle: int) -> int:
    from spark_rapids_tpu.ops.cast_string import float_to_string as fts
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(fts(REGISTRY.get(handle)))


def get_json_object(handle: int, path: str) -> int:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.get_json_object(handle, path)


def random_uuids(rows: int, seed: int) -> int:
    from spark_rapids_tpu.ops.string_utils import random_uuids as ru
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(ru(rows, seed))


def parse_uri(handle: int, what: str, ansi: bool) -> int:
    """ParseURI.java surface: what in protocol|host|query|path."""
    from spark_rapids_tpu.ops import parse_uri as PU
    from spark_rapids_tpu.shim.handles import REGISTRY
    fn = {"protocol": PU.parse_uri_to_protocol,
          "host": PU.parse_uri_to_host,
          "query": PU.parse_uri_to_query,
          "path": PU.parse_uri_to_path}[what]
    return REGISTRY.register(fn(REGISTRY.get(handle), ansi))


def parse_uri_query_with_key(handle: int, key: str, ansi: bool) -> int:
    from spark_rapids_tpu.ops import parse_uri as PU
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(PU.parse_uri_to_query_with_key(
        REGISTRY.get(handle), key, ansi))


def substring_index(handle: int, delim: str, count: int) -> int:
    from spark_rapids_tpu.ops.substring_index import substring_index as si
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(si(REGISTRY.get(handle), delim, count))


def charset_decode_to_utf8(handle: int, charset: str,
                           on_error: str) -> int:
    from spark_rapids_tpu.ops.strings_misc import decode_to_utf8
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(
        decode_to_utf8(REGISTRY.get(handle), charset, on_error))


def interleave_bits(handles: Sequence[int]) -> int:
    from spark_rapids_tpu.ops.zorder import interleave_bits as ib
    from spark_rapids_tpu.shim import jni_api
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(ib(jni_api._cols(handles)))


def hilbert_index(num_bits: int, handles: Sequence[int]) -> int:
    from spark_rapids_tpu.ops.zorder import hilbert_index as hi
    from spark_rapids_tpu.shim import jni_api
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(hi(num_bits, jni_api._cols(handles)))


def select_first_true_index(handles: Sequence[int]) -> int:
    from spark_rapids_tpu.ops.case_when import select_first_true_index
    from spark_rapids_tpu.shim import jni_api
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(
        select_first_true_index(jni_api._cols(handles)))


def number_converter_convert(handle: int, from_base: int,
                             to_base: int) -> int:
    from spark_rapids_tpu.ops.strings_misc import convert
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(
        convert(REGISTRY.get(handle), from_base, to_base))


def datetime_truncate(handle: int, component: str) -> int:
    from spark_rapids_tpu.ops.datetime_ops import truncate
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(truncate(REGISTRY.get(handle), component))


def datetime_rebase(handle: int, to_julian: bool) -> int:
    from spark_rapids_tpu.ops import datetime_ops as DT
    from spark_rapids_tpu.shim.handles import REGISTRY
    fn = (DT.rebase_gregorian_to_julian if to_julian
          else DT.rebase_julian_to_gregorian)
    return REGISTRY.register(fn(REGISTRY.get(handle)))


def sort_merge_inner_join(left_handles: Sequence[int],
                          right_handles: Sequence[int],
                          nulls_equal: bool) -> List[int]:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.sort_merge_inner_join(left_handles, right_handles,
                                         nulls_equal)


def bloom_filter_create(num_hashes: int, num_longs: int,
                        version: int) -> int:
    from spark_rapids_tpu.ops import bloom_filter as BF
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(BF.create(num_hashes, num_longs, version))


def bloom_filter_put(bf_handle: int, col_handle: int) -> int:
    from spark_rapids_tpu.ops import bloom_filter as BF
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(
        BF.put(REGISTRY.get(bf_handle), REGISTRY.get(col_handle)))


def bloom_filter_probe(bf_handle: int, col_handle: int) -> int:
    from spark_rapids_tpu.ops import bloom_filter as BF
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(
        BF.probe(REGISTRY.get(bf_handle), REGISTRY.get(col_handle)))


def bloom_filter_merge(bf_handles: Sequence[int]) -> int:
    from spark_rapids_tpu.ops import bloom_filter as BF
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(
        BF.merge([REGISTRY.get(h) for h in bf_handles]))


def bloom_filter_serialize(bf_handle: int) -> bytes:
    from spark_rapids_tpu.ops import bloom_filter as BF
    from spark_rapids_tpu.shim.handles import REGISTRY
    return BF.serialize(REGISTRY.get(bf_handle))


def bloom_filter_deserialize(data: bytes) -> int:
    from spark_rapids_tpu.ops import bloom_filter as BF
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(BF.deserialize(bytes(data)))


def extract_chunk32_from_64bit(handle: int, type_id: str,
                               chunk: int) -> int:
    from spark_rapids_tpu.columns.dtypes import DType
    from spark_rapids_tpu.ops.aggregation64 import \
        extract_chunk32_from_64bit as ec
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(
        ec(REGISTRY.get(handle), DType(type_id), chunk))


def assemble64_from_sum(low_handle: int, high_handle: int,
                        type_id: str) -> List[int]:
    from spark_rapids_tpu.columns.dtypes import DType
    from spark_rapids_tpu.ops.aggregation64 import \
        assemble64_from_sum as asm
    from spark_rapids_tpu.shim.handles import REGISTRY
    out = asm(REGISTRY.get(low_handle), REGISTRY.get(high_handle),
              DType(type_id))
    return [REGISTRY.register(c) for c in out]


def literal_range_pattern(handle: int, literal: str, range_len: int,
                          start: int, end: int) -> int:
    from spark_rapids_tpu.ops.strings_misc import \
        literal_range_pattern as lrp
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(lrp(REGISTRY.get(handle), literal,
                                 range_len, start, end))


def timezone_convert(handle: int, zone_id: str, to_utc: bool) -> int:
    from spark_rapids_tpu.ops import datetime_ops as DT
    from spark_rapids_tpu.shim.handles import REGISTRY
    fn = (DT.convert_timestamp_to_utc if to_utc
          else DT.convert_utc_timestamp_to_timezone)
    return REGISTRY.register(fn(REGISTRY.get(handle), zone_id))


def arithmetic_multiply(lhs: int, rhs: int, ansi: bool,
                        try_mode: bool) -> int:
    from spark_rapids_tpu.ops.arithmetic import multiply
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(multiply(REGISTRY.get(lhs),
                                      REGISTRY.get(rhs), ansi,
                                      try_mode))


def arithmetic_round(handle: int, decimal_places: int,
                     mode: str) -> int:
    from spark_rapids_tpu.ops.arithmetic import round_column
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(round_column(REGISTRY.get(handle),
                                          decimal_places,
                                          method=mode))


def histogram_create(values: int, frequencies: int) -> int:
    from spark_rapids_tpu.ops.histogram import create_histogram_if_valid
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(create_histogram_if_valid(
        REGISTRY.get(values), REGISTRY.get(frequencies)))


def histogram_percentile(histogram: int,
                         percentages: Sequence[float]) -> int:
    from spark_rapids_tpu.ops.histogram import percentile_from_histogram
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(percentile_from_histogram(
        REGISTRY.get(histogram), list(percentages)))


def get_json_object_multiple_paths(handle: int, paths: Sequence[str],
                                   mem_budget: int,
                                   parallel_override: int) -> List[int]:
    from spark_rapids_tpu.ops.json_path import \
        get_json_object_multiple_paths as gj
    from spark_rapids_tpu.shim.handles import REGISTRY
    out = gj(REGISTRY.get(handle), list(paths), mem_budget,
             parallel_override)
    return [REGISTRY.register(c) for c in out]


def cast_strings_to_date(handle: int, ansi: bool) -> int:
    from spark_rapids_tpu.ops.cast_more import parse_strings_to_date
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(
        parse_strings_to_date(REGISTRY.get(handle), ansi))


def long_to_binary_string(handle: int) -> int:
    from spark_rapids_tpu.ops.cast_more import long_to_binary_string
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(long_to_binary_string(
        REGISTRY.get(handle)))


def format_number(handle: int, digits: int) -> int:
    from spark_rapids_tpu.ops.cast_more import format_number as fnum
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(fnum(REGISTRY.get(handle), digits))


def map_sort(handle: int, descending: bool) -> int:
    from spark_rapids_tpu.ops.map_utils import sort_map_column
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(sort_map_column(REGISTRY.get(handle),
                                             descending))


def protobuf_decode_to_struct(handle: int,
                              field_numbers: Sequence[int],
                              type_ids: Sequence[str],
                              encodings: Sequence[int],
                              required: Sequence[bool]) -> int:
    """Protobuf.java surface over the flat-schema device decoder
    (ops/protobuf_device.py; ProtobufSchemaDescriptor's parallel
    vectors collapse to these arrays for flat messages)."""
    from spark_rapids_tpu.columns.dtypes import DType
    from spark_rapids_tpu.ops import protobuf as pb
    from spark_rapids_tpu.shim.handles import REGISTRY
    fields = [pb.Field(n, DType(t), enc, False, bool(req))
              for n, t, enc, req in zip(field_numbers, type_ids,
                                        encodings, required)]
    return REGISTRY.register(
        pb.decode_protobuf_to_struct(REGISTRY.get(handle), fields))


def struct_child(handle: int, index: int) -> int:
    """Child column of a STRUCT/LIST handle (cudf-java
    ColumnView.getChildColumnView shape)."""
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(REGISTRY.get(handle).children[index])


def iceberg_bucket(handle: int, num_buckets: int) -> int:
    from spark_rapids_tpu.ops import iceberg as IB
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(IB.bucket(REGISTRY.get(handle),
                                       num_buckets))


def iceberg_truncate(handle: int, width: int) -> int:
    from spark_rapids_tpu.ops import iceberg as IB
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(IB.truncate(REGISTRY.get(handle), width))


def iceberg_datetime(handle: int, component: str) -> int:
    from spark_rapids_tpu.ops import iceberg as IB
    from spark_rapids_tpu.shim.handles import REGISTRY
    table = {"year": IB.year, "month": IB.month, "day": IB.day,
             "hour": IB.hour}
    if component not in table:
        raise ShimArgumentError(f"unsupported component {component!r}: "
                                f"expected year|month|day|hour")
    return REGISTRY.register(table[component](REGISTRY.get(handle)))


def hllpp_reduce(handle: int, precision: int) -> int:
    """HLL++ sketch of a whole column (reduce path,
    hyper_log_log_plus_plus.hpp reduce_hyper_log_log_plus_plus)."""
    from spark_rapids_tpu.ops.hllpp import reduce_hllpp
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(reduce_hllpp(REGISTRY.get(handle),
                                          precision))


def hllpp_estimate(handle: int, precision: int) -> int:
    from spark_rapids_tpu.ops.hllpp import estimate_from_hll_sketches
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(estimate_from_hll_sketches(
        REGISTRY.get(handle), precision))


def arrow_ingest(batch) -> List[int]:
    """Zero-copy Arrow ingest door (embedded-interpreter twin of
    jni_api.arrow_ingest): the JVM hands over a PyCapsule-protocol
    object (``__arrow_c_array__``) or a pyarrow RecordBatch it built
    through its own Arrow FFI; buffers are wrapped, never copied."""
    from spark_rapids_tpu.shim import jni_api
    return jni_api.arrow_ingest(batch)


def parquet_read_table(path: str, columns: Sequence[str] = (),
                       case_sensitive: bool = True) -> List[int]:
    """File->columns door: columnar parquet read with projection
    pushdown; an empty ``columns`` list reads every column."""
    from spark_rapids_tpu.shim import jni_api
    return jni_api.parquet_read_table(
        str(path), columns=list(columns) or None,
        case_sensitive=bool(case_sensitive))


def parquet_footer_read_and_filter(data: bytes,
                                   keep_names: Sequence[str],
                                   case_sensitive: bool) -> bytes:
    """ParquetFooter.readAndFilter (ParquetFooter.java:225): parse the
    thrift footer, prune to the requested columns, re-serialize."""
    from spark_rapids_tpu.io import parquet_footer as PF
    tree = PF.parse_footer(bytes(data))
    pruned = PF.prune_columns(tree, list(keep_names),
                              case_sensitive=case_sensitive)
    return PF.serialize_footer(pruned)


def version_is_vanilla_320(platform: int, major: int, minor: int,
                           patch: int) -> bool:
    from spark_rapids_tpu.utils.platform import SparkSystem
    return SparkSystem(platform, major, minor, patch).is_vanilla_320()


def registry_add_thread(native_id: int) -> None:
    from spark_rapids_tpu.memory.thread_state_registry import REGISTRY
    REGISTRY.add_thread(native_id)


def registry_remove_thread(native_id: int) -> None:
    from spark_rapids_tpu.memory.thread_state_registry import REGISTRY
    REGISTRY.remove_thread(native_id)


def registry_known_threads() -> List[int]:
    from spark_rapids_tpu.memory.thread_state_registry import REGISTRY
    return REGISTRY.known_threads()


def task_priority_get(attempt_id: int) -> int:
    from spark_rapids_tpu.memory import task_priority
    return task_priority.get_task_priority(attempt_id)


def task_priority_done(attempt_id: int) -> None:
    from spark_rapids_tpu.memory import task_priority
    task_priority.task_done(attempt_id)


def from_decimals(unscaled: Sequence[int], scale: int,
                  type_id: str) -> int:
    """Decimal column from UNSCALED int values (cudf-java
    ColumnVector.decimalFromLongs shape; scale follows the cudf
    convention — negative scale = fraction digits)."""
    from spark_rapids_tpu.columns.dtypes import DType
    from spark_rapids_tpu.shim import jni_api
    return jni_api.make_column_from_host(list(unscaled),
                                         DType(type_id, scale))


def decimal128_binop(op: str, a: int, b: int,
                     out_scale: int) -> List[int]:
    """DecimalUtils surface: returns (overflow BOOL8, result) handles
    (the decimal_utils.hpp:2-33 (flag, column) table shape)."""
    from spark_rapids_tpu.ops import decimal_utils as DU
    from spark_rapids_tpu.shim.handles import REGISTRY
    fn = {"multiply": DU.multiply_decimal128,
          "divide": DU.divide_decimal128,
          "add": DU.add_decimal128,
          "sub": DU.sub_decimal128}[op]
    ovf, res = fn(REGISTRY.get(a), REGISTRY.get(b), out_scale)
    return [REGISTRY.register(ovf), REGISTRY.register(res)]


def device_attr_is_integrated() -> bool:
    from spark_rapids_tpu.utils.platform import is_integrated_gpu
    return is_integrated_gpu()


# ---------------------------------------------------------- Profiler


def profiler_init(output_path: str, flush_period_millis: int,
                  alloc_capture: bool) -> None:
    """Profiler.init with a file sink (the reference's DataWriter
    callback shape delivered to a path instead of a JVM method —
    Profiler.java:36-120, profiler_serializer.hpp:30-65).  'wb': a
    profile file holds ONE process's records (t_ns is per-process
    monotonic; appended runs would interleave in the converter)."""
    from spark_rapids_tpu.utils.profiler import Config, Profiler
    f = open(output_path, "wb")

    def writer(blob: bytes):
        f.write(blob)
        f.flush()

    cfg = Config(flush_period_millis=flush_period_millis,
                 alloc_capture=alloc_capture)
    try:
        prof = Profiler.init(writer, cfg)
    except Exception:
        f.close()          # double-init must not leak the descriptor
        raise
    prof.sink_close = f.close  # Profiler.shutdown closes every path


def profiler_start() -> None:
    from spark_rapids_tpu.utils.profiler import Profiler
    inst = Profiler.get()
    if inst is not None:
        inst.start()


def profiler_stop() -> None:
    from spark_rapids_tpu.utils.profiler import Profiler
    inst = Profiler.get()
    if inst is not None:
        inst.stop()


def profiler_shutdown() -> None:
    from spark_rapids_tpu.utils.profiler import Profiler
    Profiler.shutdown()


# ------------------------------------------------------- observability
# (primitive-only twins of jni_api's metrics entries: the JVM pulls the
# registry as a Prometheus text blob or a JSON string and dumps the
# journal to a path it owns)


def metrics_set_enabled(enabled: bool) -> bool:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.metrics_set_enabled(bool(enabled))


def metrics_enabled() -> bool:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.metrics_enabled()


def metrics_expose_text() -> str:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.metrics_expose_text()


def metrics_snapshot_json() -> str:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.metrics_snapshot_json()


def metrics_journal_dump(path: str) -> int:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.metrics_journal_dump(path)


def metrics_reset() -> None:
    from spark_rapids_tpu.shim import jni_api
    jni_api.metrics_reset()


def tracing_set_enabled(enabled: bool) -> bool:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.tracing_set_enabled(bool(enabled))


def tracing_enabled() -> bool:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.tracing_enabled()


def tracing_dump(path: str) -> int:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.tracing_dump(path)


def tracing_flush(path: str) -> int:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.tracing_flush(path)


def tracing_reset() -> None:
    from spark_rapids_tpu.shim import jni_api
    jni_api.tracing_reset()


def profile_set_enabled(enabled: bool) -> bool:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.profile_set_enabled(bool(enabled))


def profile_enabled() -> bool:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.profile_enabled()


def profile_last_json() -> str:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.profile_last_json()


def server_profile_json(query_id: str) -> str:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.server_profile_json(str(query_id))


def flight_recorder_set_enabled(enabled: bool) -> bool:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.flight_recorder_set_enabled(bool(enabled))


def flight_recorder_enabled() -> bool:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.flight_recorder_enabled()


def flight_recorder_configure(out_dir: str = "", max_bytes: int = 0,
                              min_interval_s: float = -1.0) -> None:
    from spark_rapids_tpu.shim import jni_api
    jni_api.flight_recorder_configure(str(out_dir), int(max_bytes),
                                      float(min_interval_s))


def incident_dump(reason: str = "manual") -> str:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.incident_dump(str(reason))


def incident_list() -> str:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.incident_list()


def health_json() -> str:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.health_json()


def timeseries_set_enabled(enabled: bool) -> bool:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.timeseries_set_enabled(bool(enabled))


def timeseries_enabled() -> bool:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.timeseries_enabled()


def timeseries_snapshot_json() -> str:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.timeseries_snapshot_json()


def slo_set_enabled(enabled: bool) -> bool:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.slo_set_enabled(bool(enabled))


def slo_enabled() -> bool:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.slo_enabled()


def slo_status_json() -> str:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.slo_status_json()


def slo_evaluate_json() -> str:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.slo_evaluate_json()


def attribution_set_enabled(enabled: bool) -> bool:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.attribution_set_enabled(bool(enabled))


def attribution_enabled() -> bool:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.attribution_enabled()


def attribution_last_json() -> str:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.attribution_last_json()


def fault_injection_install(config_path: str = "", watch: bool = True,
                            interval_ms: int = 0) -> int:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.fault_injection_install(str(config_path),
                                           bool(watch),
                                           int(interval_ms))


def fault_injection_uninstall() -> None:
    from spark_rapids_tpu.shim import jni_api
    jni_api.fault_injection_uninstall()


def fault_injection_config_path() -> str:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.fault_injection_config_path()


def fault_injection_rules_json() -> str:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.fault_injection_rules_json()


def jit_cache_stats() -> str:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.jit_cache_stats()


def jit_cache_clear(reset_stats: bool = False) -> int:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.jit_cache_clear(bool(reset_stats))


def result_cache_stats() -> str:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.result_cache_stats()


def result_cache_clear(reset_stats: bool = False) -> int:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.result_cache_clear(bool(reset_stats))


def result_cache_bump_epoch(source: str) -> int:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.result_cache_bump_epoch(str(source))


def stats_set_enabled(enabled: bool) -> bool:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.stats_set_enabled(bool(enabled))


def stats_enabled() -> bool:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.stats_enabled()


def stats_snapshot_json() -> str:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.stats_snapshot_json()


def stats_store_clear() -> None:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.stats_store_clear()


def kudo_set_crc_enabled(enabled: bool) -> bool:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.kudo_set_crc_enabled(bool(enabled))


def kudo_crc_enabled() -> bool:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.kudo_crc_enabled()


# --------------------------------------------------------- query server
# (primitive-only twins of jni_api's server entries)


def server_start(max_concurrency: int = 0, max_queue: int = 0,
                 socket_path: str = "") -> bool:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.server_start(int(max_concurrency), int(max_queue),
                                str(socket_path))


def server_stop() -> None:
    from spark_rapids_tpu.shim import jni_api
    jni_api.server_stop()


def server_set_tenant_quota(tenant: str, max_inflight: int = -1,
                            max_device_bytes: int = -1,
                            weight: float = -1.0) -> None:
    from spark_rapids_tpu.shim import jni_api
    jni_api.server_set_tenant_quota(str(tenant), int(max_inflight),
                                    int(max_device_bytes),
                                    float(weight))


def server_submit(tenant: str, query: str,
                  params_json: str = "",
                  deadline_s: float = -1.0) -> str:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.server_submit(str(tenant), str(query),
                                 str(params_json), float(deadline_s))


def server_poll(query_id: str, timeout_s: float = -1.0) -> str:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.server_poll(str(query_id), float(timeout_s))


def server_cancel(query_id: str) -> bool:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.server_cancel(str(query_id))


def server_stats_json() -> str:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.server_stats_json()


def server_drain(deadline_s: float = -1.0, flush_dir: str = "") -> str:
    from spark_rapids_tpu.shim import jni_api
    return jni_api.server_drain(float(deadline_s), str(flush_dir))


# --------------------------------------------------------- HostTable
# (spilled buffers are handles too: same lock-protected allocate/free
# discipline as the column registry — concurrent query-server callers
# must not be able to race the id counter or double-free an entry)


_HOST_TABLES = {}
_HOST_TABLE_NEXT = [1]
_HOST_TABLES_LOCK = make_lock("shim.host_tables")


def _host_table_get(handle: int):
    with _HOST_TABLES_LOCK:
        try:
            return _HOST_TABLES[handle]
        except KeyError:
            raise ShimArgumentError(
                f"invalid or released host-table handle {handle}")


def host_table_from_table(handles: Sequence[int]) -> int:
    """HostTable.fromTableAsync (HostTable.java:46): copy a device
    table into one contiguous host buffer; returns a host-table
    handle."""
    from spark_rapids_tpu.columns.table import Table
    from spark_rapids_tpu.memory.host_table import HostTable
    from spark_rapids_tpu.shim import jni_api
    ht = HostTable.from_table(Table(jni_api._cols(handles)))
    with _HOST_TABLES_LOCK:
        h = _HOST_TABLE_NEXT[0]
        _HOST_TABLE_NEXT[0] += 1
        _HOST_TABLES[h] = ht
    return h


def host_table_size_bytes(handle: int) -> int:
    return _host_table_get(handle).size_bytes


def host_table_to_device(handle: int) -> List[int]:
    """HostTable.toDeviceColumnViews: upload back; returns column
    handles."""
    from spark_rapids_tpu.shim.handles import REGISTRY
    table = _host_table_get(handle).to_table()
    return [REGISTRY.register(c) for c in table.columns]


def host_table_free(handle: int) -> None:
    """Free exactly once; a double free raises cleanly like the
    column registry's (HandleRegistry.release contract)."""
    with _HOST_TABLES_LOCK:
        if _HOST_TABLES.pop(handle, None) is None:
            raise ShimArgumentError(
                f"double free or invalid host-table handle {handle}")


# ----------------------------------------------------- kudo over JNI


# per-handle-tuple memo for the legacy write path: partition loops
# call kudo_write repeatedly on the SAME handles; one export serves
# them all.  Entries are PURGED when any of their handles is released
# (free() above) and on shutdown — the memo never outlives the
# columns' ownership (handles.py: every handle released exactly once).
# All access is under _KUDO_CACHE_LOCK, and an insert re-validates
# that every handle is still live: a free() racing a kudo_write can
# therefore never park an export of already-released columns in the
# memo (free releases FIRST, so this liveness check is authoritative).
_KUDO_WRITE_CACHE: dict = {}
_KUDO_WRITE_CACHE_MAX = 4
_KUDO_CACHE_LOCK = make_lock("shim.kudo_cache")


def _kudo_cache_purge(handle: int) -> None:
    with _KUDO_CACHE_LOCK:
        for key in [k for k in _KUDO_WRITE_CACHE if handle in k]:
            del _KUDO_WRITE_CACHE[key]


def kudo_write(handles: Sequence[int], row_offset: int,
               num_rows: int) -> bytes:
    """KudoSerializer.writeToStreamWithMetrics: serialize a row slice
    of a table to one kudo block (bytes cross the JNI boundary as
    jbyteArray).  Routes through the byte-identical C++ engine when
    built (the GIL releases for the duration of the native write);
    the Python spec engine is the fallback and the oracle."""
    import io

    from spark_rapids_tpu.shim import jni_api
    from spark_rapids_tpu.shuffle import kudo, kudo_native
    cols = jni_api._cols(handles)
    # KCRC trailers are a Python-engine feature: with CRC on, write AND
    # merge stay on the spec engine so the trailer round-trips
    if kudo_native.available() and not kudo.crc_enabled():
        from spark_rapids_tpu.shim.handles import REGISTRY
        key = tuple(handles)
        with _KUDO_CACHE_LOCK:
            nt = _KUDO_WRITE_CACHE.get(key)
        if nt is None:
            nt = kudo_native.table_from_columns(cols)
            with _KUDO_CACHE_LOCK:
                # only memoize while every handle is still live: a
                # concurrent free() has already purged this key and
                # must not have a stale export re-inserted behind it
                if all(REGISTRY.is_live(h) for h in key):
                    _KUDO_WRITE_CACHE[key] = nt
                    while len(_KUDO_WRITE_CACHE) > \
                            _KUDO_WRITE_CACHE_MAX:
                        del _KUDO_WRITE_CACHE[
                            next(iter(_KUDO_WRITE_CACHE))]
        return nt.write(row_offset, num_rows)
    out = io.BytesIO()
    kudo.write_to_stream(cols, out, row_offset, num_rows)
    return out.getvalue()


def export_kudo_host(handles: Sequence[int]) -> list:
    """ONE-crossing export of a table's host buffers for the pure-C++
    kudo engine (native/kudo_native.hpp): after this, every partition
    write / merge runs without the GIL (VERDICT r4 #1 — the
    reference's kudo hot path is pure JVM, kudo/KudoSerializer.java).

    Returns the flat list
      [num_rows, n_flat,
       then 8 entries per flat column (depth-first pre-order):
       kudo_kind:int, item_size:int, num_children:int,
       type_id:str, scale:int,
       data:bytes|None, validity:bytes|None, offsets:bytes|None]
    """
    import numpy as np

    from spark_rapids_tpu.columns.dtypes import Kind
    from spark_rapids_tpu.shim import jni_api
    from spark_rapids_tpu.shuffle.kudo import prepare_host_columns
    cols = jni_api._cols(handles)
    views = prepare_host_columns(cols)
    out: list = [int(cols[0].length) if cols else 0, 0]

    def rec(v):
        out[1] += 1
        kind = v.dtype.kind
        if kind == Kind.STRING:
            kkind, item = 1, 0
        elif kind == Kind.LIST:
            kkind, item = 2, 0
        elif kind == Kind.STRUCT:
            kkind, item = 3, 0
        else:
            kkind = 0
            item = 16 if kind == Kind.DECIMAL128 else v.dtype.size_bytes
        out.extend([
            kkind, item, len(v.children) if kkind != 1 else 0,
            str(v.dtype.kind), int(getattr(v.dtype, "scale", 0) or 0),
            None if v.data is None or kkind in (2, 3)
            else np.ascontiguousarray(v.data).tobytes(),
            None if v.validity is None else v.validity.tobytes(),
            None if v.offsets is None
            else np.ascontiguousarray(v.offsets, "<i4").tobytes(),
        ])
        for ch in v.children:
            rec(ch)

    for v in views:
        rec(v)
    return out


def columns_from_kudo_host(num_rows: int, flat: Sequence) -> List[int]:
    """Inverse of export_kudo_host: rebuild device Columns from the
    C++ engine's merged host buffers (one crossing on the merge side)
    and register them, returning root-column handles."""
    import jax.numpy as jnp
    import numpy as np

    from spark_rapids_tpu.columns.column import Column
    from spark_rapids_tpu.columns.dtypes import DType, Kind
    from spark_rapids_tpu.shim.handles import REGISTRY
    flat = list(flat)
    pos = [0]

    def read_col(rows: int) -> Column:
        (kkind, item, nch, type_id, scale, data, validity,
         offsets) = flat[pos[0]: pos[0] + 8]
        pos[0] += 8
        dtype = DType(type_id, scale)
        mask = None
        if validity is not None:
            bits = np.unpackbits(np.frombuffer(validity, np.uint8),
                                 bitorder="little")[:rows]
            mask = jnp.asarray(bits.astype(np.uint8))
        if kkind == 1:  # string: shared buffer->Column assembly
            offs = np.frombuffer(offsets, "<i4") if offsets \
                is not None else np.zeros(rows + 1, np.int32)
            return _string_column_from_buffers(
                np.frombuffer(data or b"", np.uint8), offs, validity,
                rows)
        if kkind == 2:  # list
            offs = np.frombuffer(offsets, "<i4").copy() if offsets \
                is not None else np.zeros(rows + 1, np.int32)
            child = read_col(int(offs[-1]) if len(offs) else 0)
            return Column(dtype, rows, validity=mask,
                          offsets=jnp.asarray(offs), children=(child,))
        if kkind == 3:  # struct
            children = tuple(read_col(rows) for _ in range(nch))
            return Column(dtype, rows, validity=mask, children=children)
        raw = data or b""
        if dtype.kind == Kind.DECIMAL128:
            arr = np.frombuffer(raw, "<i4").reshape(rows, 4).copy()
        else:
            arr = np.frombuffer(raw, dtype.np_dtype).copy()
            if dtype.kind == Kind.FLOAT64:
                arr = arr.view(np.uint64)  # f64-as-raw-bits convention
        return Column(dtype, rows, data=jnp.asarray(arr), validity=mask)

    roots = []
    while pos[0] < len(flat):
        roots.append(read_col(int(num_rows)))
    return [REGISTRY.register(c) for c in roots]


def kudo_merge(blob: bytes, type_ids: Sequence[str],
               scales: Sequence[int]) -> List[int]:
    """KudoSerializer.mergeToTable over a concatenated stream of kudo
    blocks (flat schemas; the Python API handles nested).  Routes
    through the C++ engine when built (GIL released for the native
    merge); the Python spec engine is fallback and oracle."""
    import io

    from spark_rapids_tpu.columns.dtypes import DType
    from spark_rapids_tpu.shim.handles import REGISTRY
    from spark_rapids_tpu.shuffle import kudo, kudo_native
    from spark_rapids_tpu.shuffle.schema import Field
    fields = [Field(DType(k, s)) for k, s in zip(type_ids, scales)]
    blob = bytes(blob)
    # the native engine doesn't understand KCRC trailers, and a PEER
    # process may have written them regardless of the local CRC
    # setting — gate on stream STRUCTURE (record-walk, so payload
    # bytes containing "KCRC" can't misroute the fast path)
    if kudo_native.available() and not kudo.crc_enabled() \
            and not kudo.stream_has_crc_trailers(blob):
        table = kudo_native.merge_to_table(blob, fields)
        return [REGISTRY.register(c) for c in table.columns]
    kts = kudo.read_tables(io.BytesIO(blob))
    table = kudo.merge_to_table(kts, fields)
    return [REGISTRY.register(c) for c in table.columns]


# compiled mesh steps are cached so repeated JVM calls never re-jit
_Q5_MESH_STEPS: dict = {}


def flagship_q5_mesh(n_devices: int, rows: int,
                     stores: int) -> List[int]:
    """Run the q5-shape flagship as ONE shard_map program over an
    n-device mesh and return the live group rows flattened as
    [store_id, sales, returns, profit, ...] — the multi-chip SPMD
    path driven END TO END from the JVM (north star: GpuExec-shaped
    callers reach distributed execution through this binding).
    Raises when fewer devices exist than requested: a silent
    single-device run would fake the distribution being proven."""
    import jax as _jax
    import numpy as np
    from jax.sharding import Mesh

    from spark_rapids_tpu.models import tpcds
    devs = _jax.devices()
    n = int(n_devices)
    if len(devs) < n:
        raise ShimStateError(
            f"mesh wants {n} devices, backend has {len(devs)} "
            f"(set SPARK_RAPIDS_TPU_CPU_DEVICES before init)")
    mesh = Mesh(np.array(devs[:n]), ("data",))
    d = tpcds.q5_mesh_data(int(rows), int(stores), n)
    key = (n, int(stores))
    step = _Q5_MESH_STEPS.get(key)
    if step is None:
        step = tpcds.make_q5_multichip(mesh, int(stores),
                                       join_capacity=1 << 12)
        _Q5_MESH_STEPS[key] = step
    key_s, sales, rets, profit, overflow = step(
        d.s_date, d.s_store, d.s_price, d.s_profit, d.r_date,
        d.r_store, d.r_amt, d.r_loss, d.d_date, d.st_id)
    if bool(np.asarray(overflow)):
        raise ShimStateError("q5 mesh overflow")
    key = np.asarray(key_s)
    live = key != 2**31 - 1
    out: List[int] = []
    for k, a, b, c in zip(key[live], np.asarray(sales)[live],
                          np.asarray(rets)[live],
                          np.asarray(profit)[live]):
        out.extend([int(k), int(a), int(b), int(c)])
    return out


_Q72_MESH_STEPS: dict = {}


def flagship_q72_mesh(n_devices: int, cs_rows: int,
                      items: int) -> List[int]:
    """q72-shape (fact-fact join chain) over an n-device mesh from
    the JVM; returns live (item, week, count) triples flattened."""
    import jax as _jax
    import numpy as np
    from jax.sharding import Mesh

    from spark_rapids_tpu.models import tpcds
    devs = _jax.devices()
    n = int(n_devices)
    if len(devs) < n:
        raise ShimStateError(
            f"mesh wants {n} devices, backend has {len(devs)}")
    mesh = Mesh(np.array(devs[:n]), ("data",))
    week0 = 11_000 // 7
    d = tpcds.q72_mesh_data(int(cs_rows), int(items), n)
    key = (n, int(items))
    step = _Q72_MESH_STEPS.get(key)
    if step is None:
        step = tpcds.make_q72_multichip(mesh, int(items), 16,
                                        join_capacity=1 << 12,
                                        week0=week0)
        _Q72_MESH_STEPS[key] = step
    ti, tw, tc, ovf = step(d.cs_item, d.cs_date, d.cs_qty, d.inv_item,
                           d.inv_date, d.inv_qty, d.item_id)
    if bool(np.asarray(ovf)):
        raise ShimStateError("q72 mesh overflow")
    cnts = np.asarray(tc)
    live = cnts > 0
    out: List[int] = []
    for i, w, c in zip(np.asarray(ti)[live], np.asarray(tw)[live],
                       cnts[live]):
        out.extend([int(i), int(w), int(c)])
    return out


# ---------------------------------------------------------- RmmSpark


def rmm_set_event_handler(limit_bytes: int) -> None:
    from spark_rapids_tpu.memory import rmm_spark
    rmm_spark.set_event_handler(limit_bytes)


def rmm_clear_event_handler() -> None:
    from spark_rapids_tpu.memory import rmm_spark
    rmm_spark.clear_event_handler()


def rmm_start_dedicated_task_thread(thread_id: int, task_id: int) -> None:
    from spark_rapids_tpu.memory import rmm_spark
    rmm_spark.start_dedicated_task_thread(thread_id, task_id)


def rmm_task_done(task_id: int) -> None:
    from spark_rapids_tpu.memory import rmm_spark
    rmm_spark.task_done(task_id)


def rmm_force_retry_oom(thread_id: int, num_ooms: int) -> None:
    from spark_rapids_tpu.memory import rmm_spark
    rmm_spark.force_retry_oom(thread_id, num_ooms)


def rmm_get_state_of(thread_id: int) -> str:
    from spark_rapids_tpu.memory import rmm_spark
    return rmm_spark.get_state_of(thread_id)


def rmm_current_thread_id() -> int:
    """The calling JVM thread's runtime-side id (stable per OS thread:
    PyGILState attaches the same interpreter thread state)."""
    from spark_rapids_tpu.memory import rmm_spark
    return rmm_spark.current_thread_id()


def rmm_register_current_thread(task_id: int) -> None:
    from spark_rapids_tpu.memory import rmm_spark
    rmm_spark.current_thread_is_dedicated_to_task(task_id)


def rmm_force_split_and_retry_oom(thread_id: int, num_ooms: int) -> None:
    from spark_rapids_tpu.memory import rmm_spark
    rmm_spark.force_split_and_retry_oom(thread_id, num_ooms)


def rmm_block_thread_until_ready() -> None:
    from spark_rapids_tpu.memory import rmm_spark
    rmm_spark.block_thread_until_ready()


def rmm_alloc(nbytes: int) -> None:
    """Device-allocation notification for the calling thread; forced
    OOMs (forceRetryOOM / forceSplitAndRetryOOM) fire here and cross
    JNI as the matching typed Java exceptions."""
    from spark_rapids_tpu.memory import rmm_spark
    rmm_spark.get_adaptor().allocate(nbytes)


def rmm_dealloc(nbytes: int) -> None:
    from spark_rapids_tpu.memory import rmm_spark
    rmm_spark.get_adaptor().deallocate(nbytes)


def rmm_shuffle_thread_working_on_tasks(task_ids: Sequence[int]
                                        ) -> None:
    """RmmSpark.shuffleThreadWorkingOnTasks for the calling JVM
    thread (pool/shuffle thread registration — shuffle threads take
    priority in the BUFN victim selection)."""
    from spark_rapids_tpu.memory import rmm_spark
    rmm_spark.shuffle_thread_working_on_tasks(
        [int(t) for t in task_ids])


def rmm_pool_thread_finished_for_tasks(task_ids: Sequence[int]
                                       ) -> None:
    from spark_rapids_tpu.memory import rmm_spark
    rmm_spark.pool_thread_finished_for_tasks(
        rmm_spark.current_thread_id(), [int(t) for t in task_ids])


# ------------------------------------------- list/map utils over JNI


def list_slice(handle: int, start, length, start_is_col: bool,
               length_is_col: bool, check: bool) -> int:
    """GpuListSliceUtils.listSlice (4 scalar/column overloads folded
    into one entry: *_is_col picks handle vs scalar operands)."""
    from spark_rapids_tpu.ops.strings_misc import list_slice as LS
    from spark_rapids_tpu.shim.handles import REGISTRY
    col = REGISTRY.get(handle)
    s = REGISTRY.get(int(start)) if start_is_col else int(start)
    ln = REGISTRY.get(int(length)) if length_is_col else (
        None if length is None else int(length))
    return REGISTRY.register(LS(col, s, ln, bool(check)))


def map_is_valid(handle: int, throw_on_null_key: bool) -> bool:
    from spark_rapids_tpu.ops.map_utils import is_valid_map
    from spark_rapids_tpu.shim.handles import REGISTRY
    return bool(is_valid_map(REGISTRY.get(handle),
                             bool(throw_on_null_key)))


def map_from_entries_jni(handle: int, throw_on_null_key: bool) -> int:
    from spark_rapids_tpu.ops.map_utils import map_from_entries
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(map_from_entries(
        REGISTRY.get(handle), bool(throw_on_null_key)))


def map_zip_jni(h1: int, h2: int) -> int:
    from spark_rapids_tpu.ops.map_utils import map_zip_full
    from spark_rapids_tpu.shim.handles import REGISTRY
    return REGISTRY.register(map_zip_full(REGISTRY.get(h1),
                                          REGISTRY.get(h2)))


# --------------------------------------- ORC timezone info over JNI


def orc_timezone_packed(zone_id: str) -> List[int]:
    """OrcDstRuleExtractor packing: [rawOffsetMillis, hasDst, n,
    transitions_ms.., offsets_ms..]."""
    from spark_rapids_tpu.ops.orc_timezones import (
        get_orc_timezone_info, has_daylight_saving_time)
    info = get_orc_timezone_info(zone_id)
    trans = ([] if info.transitions is None
             else [int(x) for x in info.transitions])
    offs = ([] if info.offsets is None
            else [int(x) for x in info.offsets])
    has_dst = 1 if has_daylight_saving_time(zone_id) else 0
    return ([int(info.raw_offset), has_dst, len(trans)]
            + trans + offs)


def all_timezone_ids() -> List[str]:
    import os

    from spark_rapids_tpu.utils.tzdb import TZDIR
    base = TZDIR   # honors $TZDIR like every other zone lookup
    out = []
    for root, _dirs, names in os.walk(base):
        for n in names:
            p = os.path.relpath(os.path.join(root, n), base)
            if "/" in p or p[0].isupper():
                if not p.endswith(".tab") and "posix" not in p \
                        and "right" not in p:
                    out.append(p)
    return sorted(set(out))


# ----------------------------------------- device telemetry over JNI


def telemetry_device_count() -> int:
    from spark_rapids_tpu.utils import telemetry
    return telemetry.get_device_count()


def telemetry_snapshot_packed(index: int) -> List[int]:
    """NVML.getSnapshotPacked: [memTotal, memUsed, memFree, util%,
    powerW, clockMhz, tempC]; -1 = metric not supported here."""
    from spark_rapids_tpu.utils import telemetry
    out = [-1] * 7
    try:
        mem = telemetry.get_memory_info(index)
        out[0] = int(mem.get("total", -1))
        out[1] = int(mem.get("used", -1))
        out[2] = int(mem.get("free", -1))
    except Exception:
        pass
    try:
        # utilization is a [0,1] fraction; the packed slot is percent
        out[3] = int(telemetry.get_device_utilization(index) * 100)
    except Exception:
        pass
    for slot, fn in ((4, telemetry.get_power_usage_watts),
                     (5, telemetry.get_clock_mhz)):
        try:
            out[slot] = int(fn(index))
        except Exception:
            pass
    return out


def telemetry_device_name(index: int) -> str:
    from spark_rapids_tpu.utils import telemetry
    info = telemetry.get_device_info(index)
    return f"{info.platform}:{info.kind}"


# ------------------------------------------------------- test support
# (comparison happens Python-side so the emitted JVM test bytecode can
# stay straight-line: a native assert throws on failure)


def make_list_of_ints(offsets: Sequence[int],
                      values: Sequence[int]) -> int:
    """Test helper: LIST<INT64> column from offsets + flat values
    (drives the GpuListSliceUtils smoke — the JVM has no list
    builder of its own)."""
    import numpy as np

    from spark_rapids_tpu.columns import dtypes
    from spark_rapids_tpu.columns.column import Column
    from spark_rapids_tpu.shim.handles import REGISTRY
    child = Column.from_pylist(list(values), dtypes.INT64)
    return REGISTRY.register(Column.make_list(
        np.asarray(list(offsets), np.int32), child))


def make_map_column(offsets: Sequence[int], keys: Sequence[str],
                    values: Sequence[str]) -> int:
    """Test helper: MAP-shaped LIST<STRUCT<key,value>> column (drives
    the MapUtils / GpuMapZipWithUtils smoke)."""
    import numpy as np

    from spark_rapids_tpu.columns.column import Column
    from spark_rapids_tpu.shim.handles import REGISTRY
    n = len(keys)
    entry = Column.make_struct(n, [Column.from_strings(list(keys)),
                                   Column.from_strings(list(values))])
    return REGISTRY.register(Column.make_list(
        np.asarray(list(offsets), np.int32), entry))


def check_int_column(handle: int, expected: Sequence[int]) -> int:
    from spark_rapids_tpu.shim.handles import REGISTRY
    got = REGISTRY.get(handle).to_pylist()
    return 1 if got == list(expected) else 0


def check_long_column(handle: int, expected: Sequence[int]) -> int:
    return check_int_column(handle, expected)


def check_string_column(handle: int, expected: Sequence[str]) -> int:
    from spark_rapids_tpu.shim.handles import REGISTRY
    got = REGISTRY.get(handle).to_pylist()
    return 1 if got == list(expected) else 0


def check_columns_equal(h1: int, h2: int) -> int:
    from spark_rapids_tpu.shim.handles import REGISTRY
    a = REGISTRY.get(h1).to_pylist()
    b = REGISTRY.get(h2).to_pylist()
    return 1 if a == b else 0


def describe_column(handle: int) -> str:
    from spark_rapids_tpu.shim.handles import REGISTRY
    col = REGISTRY.get(handle)
    return f"{col.dtype.kind}[{col.length}]"
