"""Project-typed exceptions for the JNI shim boundary (srt-lint
SRT004).

The embedded-interpreter entry points (shim/jni_entry.py) used to
raise bare ``ValueError``/``RuntimeError`` — which the JVM side can
only map to a generic RuntimeException, losing the
argument-vs-state distinction the reference's typed Java exceptions
(CudfException, ExceptionWithRowIndex, ...) preserve.  These two
types keep that distinction AND subclass the builtins they replace,
so every existing ``except ValueError`` / test expectation holds.
"""


class ShimArgumentError(ValueError):
    """Caller handed the shim malformed arguments (bad offsets,
    unknown component names, missing handles) — maps to
    IllegalArgumentException on the JVM side."""


class ShimStateError(RuntimeError):
    """The shim was driven in an illegal state (mesh overflow, op on
    a shut-down runtime) — maps to IllegalStateException on the JVM
    side."""
