"""Java handle model (SURVEY.md §7.1): the cudf-java surface works on
`long` native pointers; here a process-global registry maps opaque int64
handles to device Column/Table objects so the JNI layer (or any FFI) can
round-trip them without marshalling data.

Mirrors the reference ownership rules: every handle returned to the
caller must be released exactly once (ColumnVector.close); leaks are
observable via live_count for tests/sanitizers."""

from __future__ import annotations

import itertools
import threading
from typing import Any, Dict, Optional


class HandleRegistry:
    def __init__(self):
        self._objects: Dict[int, Any] = {}
        self._next = itertools.count(1)
        self._lock = threading.Lock()

    def register(self, obj: Any) -> int:
        with self._lock:
            h = next(self._next)
            self._objects[h] = obj
            return h

    def get(self, handle: int) -> Any:
        with self._lock:
            try:
                return self._objects[handle]
            except KeyError:
                raise ValueError(f"invalid or released handle {handle}")

    def release(self, handle: int) -> None:
        with self._lock:
            if self._objects.pop(handle, None) is None:
                raise ValueError(
                    f"double release or invalid handle {handle}")

    def live_count(self) -> int:
        with self._lock:
            return len(self._objects)

    def clear(self) -> None:
        with self._lock:
            self._objects.clear()


REGISTRY = HandleRegistry()
