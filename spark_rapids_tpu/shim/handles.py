"""Java handle model (SURVEY.md §7.1): the cudf-java surface works on
`long` native pointers; here a process-global registry maps opaque int64
handles to device Column/Table objects so the JNI layer (or any FFI) can
round-trip them without marshalling data.

Mirrors the reference ownership rules: every handle returned to the
caller must be released exactly once (ColumnVector.close); leaks are
observable via live_count for tests/sanitizers.

Concurrency contract (audited for the multi-tenant query server,
ISSUE 6): every operation holds the registry lock, ids are issued by
a monotonically increasing counter and NEVER reused, and releasing a
handle twice (or releasing a handle that never existed) raises
``ValueError`` cleanly without touching any other entry — concurrent
callers can race register/get/release freely and the worst outcome is
that typed error on the loser."""

from __future__ import annotations

import itertools
import threading

from spark_rapids_tpu.analysis.lockdep import make_lock
from typing import Any, Dict, Optional

_MISSING = object()   # registered objects may legitimately be falsy


class HandleRegistry:
    def __init__(self):
        self._objects: Dict[int, Any] = {}
        self._next = itertools.count(1)
        self._lock = make_lock("shim.handles")

    def register(self, obj: Any) -> int:
        with self._lock:
            h = next(self._next)
            self._objects[h] = obj
            return h

    def get(self, handle: int) -> Any:
        with self._lock:
            try:
                return self._objects[handle]
            except KeyError:
                raise ValueError(f"invalid or released handle {handle}")

    def release(self, handle: int) -> Any:
        """Release exactly once; returns the released object so
        callers can run post-release cleanup on it.  A second release
        of the same handle raises — it never corrupts the table."""
        with self._lock:
            obj = self._objects.pop(handle, _MISSING)
            if obj is _MISSING:
                raise ValueError(
                    f"double release or invalid handle {handle}")
            return obj

    def is_live(self, handle: int) -> bool:
        with self._lock:
            return handle in self._objects

    def live_count(self) -> int:
        with self._lock:
            return len(self._objects)

    def clear(self) -> None:
        with self._lock:
            self._objects.clear()


REGISTRY = HandleRegistry()
