"""ctypes binding over the pure-C++ kudo engine
(native/kudo_native.hpp via native/libkudo_native.so).

The Python engine in shuffle/kudo.py is the golden-validated SPEC; this
binding routes the shuffle hot path through C++ so that (a) JVM
executor threads crossing via JNI never touch the GIL (the reference's
kudo is pure JVM for exactly this reason —
kudo/KudoSerializer.java:48-170), and (b) Python callers get true
multi-thread scaling: ctypes releases the GIL for the duration of each
C call, so concurrent writes on one immutable native table run in
parallel.

Differential contract: byte-identical output to shuffle/kudo.py on
every input (tests/test_kudo_native.py drives both over the golden
fixtures and randomized nested tables).
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional, Sequence

import numpy as np

from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import Kind
from spark_rapids_tpu.columns.table import Table
from spark_rapids_tpu.shuffle.kudo import HostColumnView, prepare_host_columns
from spark_rapids_tpu.shuffle.schema import Field

_LIB_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native",
    "libkudo_native.so")

_lib = None


def available() -> bool:
    return _load() is not None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH):
        return None
    lib = ctypes.CDLL(_LIB_PATH)
    lib.kudo_last_error.restype = ctypes.c_char_p
    lib.kudo_table_create.restype = ctypes.c_void_p
    lib.kudo_table_create.argtypes = [
        ctypes.c_int64, ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32)]
    lib.kudo_col_set_data.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p, ctypes.c_int64]
    lib.kudo_col_set_validity.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p, ctypes.c_int64]
    lib.kudo_col_set_offsets.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p, ctypes.c_int64]
    lib.kudo_table_free.argtypes = [ctypes.c_void_p]
    lib.kudo_write.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.kudo_write.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64)]
    lib.kudo_write_row_count_only.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.kudo_write_row_count_only.argtypes = [
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int64)]
    lib.kudo_buf_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    lib.kudo_merge.restype = ctypes.c_void_p
    lib.kudo_merge.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32)]
    lib.kudo_table_num_rows.restype = ctypes.c_int64
    lib.kudo_table_num_rows.argtypes = [ctypes.c_void_p]
    lib.kudo_table_n_flat.restype = ctypes.c_int32
    lib.kudo_table_n_flat.argtypes = [ctypes.c_void_p]
    for name in ("kudo_col_data_len", "kudo_col_validity_len",
                 "kudo_col_offsets_len"):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_int64
        fn.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    for name in ("kudo_col_has_validity", "kudo_col_has_offsets"):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_int32
        fn.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.kudo_col_get_data.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p]
    lib.kudo_col_get_validity.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p]
    lib.kudo_col_get_offsets.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p]
    _lib = lib
    return lib


KIND_FIXED, KIND_STRING, KIND_LIST, KIND_STRUCT = 0, 1, 2, 3


def _flat_schema(fields: Sequence[Field]):
    """Flatten a Field tree to (kinds, item_sizes, num_children) in
    depth-first pre-order — the C++ engine's schema encoding."""
    kinds: List[int] = []
    items: List[int] = []
    nch: List[int] = []

    def rec(f: Field):
        kind = f.dtype.kind
        if kind == Kind.STRING:
            kinds.append(KIND_STRING)
            items.append(0)
            nch.append(0)
        elif kind == Kind.LIST:
            kinds.append(KIND_LIST)
            items.append(0)
            nch.append(1)
            rec(f.children[0])
        elif kind == Kind.STRUCT:
            kinds.append(KIND_STRUCT)
            items.append(0)
            nch.append(len(f.children))
            for ch in f.children:
                rec(ch)
        else:
            kinds.append(KIND_FIXED)
            items.append(16 if kind == Kind.DECIMAL128
                         else f.dtype.size_bytes)
            nch.append(0)

    for f in fields:
        rec(f)
    return kinds, items, nch


def _i32_arr(values: List[int]):
    return (ctypes.c_int32 * len(values))(*values)


class NativeKudoTable:
    """Owns a C++ kudo::Table handle.  Immutable once built; concurrent
    write() calls are safe and GIL-free."""

    def __init__(self, handle: int, fields: List[Field]):
        self._handle = handle
        self.fields = fields

    def __del__(self):
        # interpreter teardown may have cleared module globals; the
        # OS reclaims the native memory then anyway
        try:
            lib = _lib
            if lib is not None and self._handle:
                lib.kudo_table_free(self._handle)
        except Exception:
            pass
        self._handle = 0

    @property
    def num_rows(self) -> int:
        return int(_load().kudo_table_num_rows(self._handle))

    def write(self, row_offset: int, num_rows: int) -> bytes:
        lib = _load()
        n = ctypes.c_int64()
        buf = lib.kudo_write(self._handle, row_offset, num_rows,
                             ctypes.byref(n))
        if not buf or n.value < 0:
            raise ValueError(lib.kudo_last_error().decode())
        try:
            return ctypes.string_at(buf, n.value)
        finally:
            lib.kudo_buf_free(buf)

    def to_table(self) -> Table:
        """Import the native host table back as device Columns (one
        crossing; used on the merge side)."""
        lib = _load()
        idx = [0]

        import jax.numpy as jnp

        def read_col(f: Field, rows: int) -> Column:
            i = idx[0]
            idx[0] += 1
            validity = None
            if lib.kudo_col_has_validity(self._handle, i):
                vlen = lib.kudo_col_validity_len(self._handle, i)
                vbuf = ctypes.create_string_buffer(max(int(vlen), 1))
                lib.kudo_col_get_validity(self._handle, i, vbuf)
                bits = np.unpackbits(
                    np.frombuffer(vbuf.raw[:vlen], np.uint8),
                    bitorder="little")[:rows]
                validity = jnp.asarray(bits.astype(np.uint8))
            kind = f.dtype.kind
            if kind in (Kind.STRING, Kind.LIST):
                olen = lib.kudo_col_offsets_len(self._handle, i)
                obuf = ctypes.create_string_buffer(max(int(olen) * 4, 1))
                lib.kudo_col_get_offsets(self._handle, i, obuf)
                offsets = np.frombuffer(obuf.raw[:olen * 4], "<i4").copy()
                child_rows = int(offsets[-1]) if len(offsets) else 0
                if kind == Kind.STRING:
                    dlen = lib.kudo_col_data_len(self._handle, i)
                    dbuf = ctypes.create_string_buffer(max(int(dlen), 1))
                    lib.kudo_col_get_data(self._handle, i, dbuf)
                    chars = np.frombuffer(dbuf.raw[:dlen], np.uint8).copy()
                    return Column(f.dtype, rows, data=jnp.asarray(chars),
                                  validity=validity,
                                  offsets=jnp.asarray(offsets))
                child = read_col(f.children[0], child_rows)
                return Column(f.dtype, rows, validity=validity,
                              offsets=jnp.asarray(offsets),
                              children=(child,))
            if kind == Kind.STRUCT:
                children = tuple(read_col(ch, rows) for ch in f.children)
                return Column(f.dtype, rows, validity=validity,
                              children=children)
            dlen = lib.kudo_col_data_len(self._handle, i)
            dbuf = ctypes.create_string_buffer(max(int(dlen), 1))
            lib.kudo_col_get_data(self._handle, i, dbuf)
            raw = dbuf.raw[:dlen]
            if kind == Kind.DECIMAL128:
                data = np.frombuffer(raw, "<i4").reshape(rows, 4).copy()
            else:
                data = np.frombuffer(raw, f.dtype.np_dtype).copy()
                if kind == Kind.FLOAT64:
                    # columns convention: f64 carried as raw bits
                    data = data.view(np.uint64)
            return Column(f.dtype, rows, data=jnp.asarray(data),
                          validity=validity)

        rows = self.num_rows
        return Table([read_col(f, rows) for f in self.fields])


def table_from_columns(columns: Sequence[Column]) -> NativeKudoTable:
    """One-time host materialization + export into the C++ engine.
    After this, every write() is pure C++ (no GIL, no numpy)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("libkudo_native.so not built")
    views = prepare_host_columns(list(columns))
    fields = [_field_of_view(v) for v in views]
    kinds, items, nch = _flat_schema(fields)
    num_rows = columns[0].length if columns else 0
    handle = lib.kudo_table_create(
        num_rows, len(kinds), _i32_arr(kinds), _i32_arr(items),
        _i32_arr(nch))
    if not handle:
        raise MemoryError(lib.kudo_last_error().decode())
    nt = NativeKudoTable(handle, fields)
    idx = [0]

    def load(v: HostColumnView):
        i = idx[0]
        idx[0] += 1
        if v.validity is not None:
            b = v.validity.tobytes()
            lib.kudo_col_set_validity(handle, i, b, len(b))
        if v.offsets is not None:
            b = np.ascontiguousarray(v.offsets, "<i4").tobytes()
            lib.kudo_col_set_offsets(handle, i, b, len(b) // 4)
        if v.data is not None and v.dtype.kind not in (Kind.LIST,
                                                       Kind.STRUCT):
            b = np.ascontiguousarray(v.data).tobytes()
            lib.kudo_col_set_data(handle, i, b, len(b))
        for ch in v.children:
            load(ch)

    for v in views:
        load(v)
    return nt


def _field_of_view(v: HostColumnView) -> Field:
    return Field(v.dtype, tuple(_field_of_view(c) for c in v.children))


def write_to_bytes(columns: Sequence[Column], row_offset: int,
                   num_rows: int) -> bytes:
    """Convenience one-shot: export + single partition write.  For
    per-partition loops, hold a NativeKudoTable (or go through the
    JNI path, whose handle-keyed memo amortizes the export and is
    purged when handles are released)."""
    return table_from_columns(columns).write(row_offset, num_rows)


def merge_blob(blob: bytes, fields: Sequence[Field]) -> NativeKudoTable:
    """Merge a concatenated stream of kudo blocks natively
    (KudoTableMerger analog; byte-semantics of kudo.merge_to_table)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("libkudo_native.so not built")
    kinds, items, nch = _flat_schema(fields)
    handle = lib.kudo_merge(blob, len(blob), len(kinds), _i32_arr(kinds),
                            _i32_arr(items), _i32_arr(nch))
    if not handle:
        raise ValueError(lib.kudo_last_error().decode())
    return NativeKudoTable(handle, list(fields))


def merge_to_table(blob: bytes, fields: Sequence[Field]) -> Table:
    return merge_blob(blob, fields).to_table()
