"""Partition split/assemble behind the reference's KudoGpuSerializer API
(shuffle_split.cu:797 / shuffle_assemble.cu; Java KudoGpuSerializer.java).

The reference's device variant packs per-partition kudo-like blobs into one
GPU buffer because its network path consumes opaque bytes from device
memory.  On TPU the equivalents diverge by transport:

  * host/Spark-network transport: partitions serialize through the byte-
    exact Kudo writer (shuffle/kudo.py) — split_and_serialize /
    assemble_from_blobs here.
  * chip-to-chip (ICI) transport: no byte blobs at all — sharded columns
    move as arrays through jax collectives (parallel/exchange.py), which
    is the TPU-native fast path the reference's NVLink story maps to.
"""

from __future__ import annotations

import io
from typing import List, Sequence, Tuple

import numpy as np

from spark_rapids_tpu.columns.table import Table
from spark_rapids_tpu.shuffle import kudo
from spark_rapids_tpu.shuffle.schema import Field


def _use_device() -> bool:
    import os

    import jax

    if os.environ.get("SPARK_RAPIDS_TPU_FORCE_DEVICE_SHUFFLE") == "1":
        return True
    if os.environ.get("SPARK_RAPIDS_TPU_FORCE_DEVICE_SHUFFLE") == "0":
        return False
    return jax.default_backend() != "cpu"


def shuffle_split(table: Table, splits: Sequence[int]
                  ) -> Tuple[bytes, np.ndarray]:
    """Split at row boundaries and serialize every partition as a kudo
    blob; returns (packed buffer, int64 offsets per partition) — the same
    (data, offsets) pair shape as KudoGpuSerializer.splitAndSerializeToDevice
    (KudoGpuSerializer.java:50).  On accelerator backends the bytes are
    packed by the device blob kernels (shuffle/device_split.py) and read
    back once; the host writer remains the differential oracle."""
    if _use_device():
        from spark_rapids_tpu.shuffle.device_split import \
            device_shuffle_split

        blob, offsets = device_shuffle_split(table, splits)
        return bytes(np.asarray(blob)), offsets
    bounds = [0] + list(splits) + [table.num_rows]
    out = io.BytesIO()
    offsets = np.zeros(len(bounds), np.int64)
    views = kudo.prepare_host_columns(table.columns)  # one device sync
    for i in range(len(bounds) - 1):
        start, end = bounds[i], bounds[i + 1]
        kudo.write_to_stream(views, out, start, end - start)
        offsets[i + 1] = out.tell()
    return out.getvalue(), offsets


def shuffle_assemble(fields: Sequence[Field], buffer: bytes,
                     offsets: np.ndarray) -> Table:
    """Reassemble partitions into one device table
    (shuffle_split.hpp:183 shuffle_assemble).  On accelerator backends
    the body bytes are gathered into columns by device kernels; the
    host parse/concat path is the oracle and the fallback.

    Note: this entry point accepts one kudo table per partition slot
    (the device writer's layout).  Multi-table-per-slot streams take
    the host path."""
    if _use_device() and len(offsets) > 1 and fields:
        try:
            from spark_rapids_tpu.shuffle.device_split import \
                device_shuffle_assemble
            import jax.numpy as jnp

            blob = jnp.asarray(np.frombuffer(buffer, np.uint8))
            return device_shuffle_assemble(fields, blob, offsets)
        except ValueError:
            pass  # e.g. multi-table partitions: host path below
    kts: List[kudo.KudoTable] = []
    for i in range(len(offsets) - 1):
        stream = io.BytesIO(buffer[offsets[i]:offsets[i + 1]])
        while True:
            kt = kudo.read_one_table(stream)
            if kt is None:
                break
            kts.append(kt)
    return kudo.merge_to_table(kts, fields)
