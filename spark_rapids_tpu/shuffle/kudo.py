"""Kudo shuffle wire format — byte-compatible with the reference
(format spec: kudo/KudoSerializer.java:48-170 javadoc; writer:
KudoTableHeaderCalc + SlicedBufferSerializer; merge: KudoTableMerger).

Layout of one serialized table partition:

  header:  "KUD0" | rowOffset | numRows | validityLen | offsetLen |
           totalLen | numFlatCols   (all 4-byte big-endian)
           hasValidityBuffer bitset ((numFlatCols+7)/8 bytes, LSB-first,
           depth-first schema order, struct/list before children)
  body:    [validity buffers][offset buffers][data buffers]
           - validity: sloppy byte-slices of the packed null masks starting
             at rowOffset/8 (bit offset rowOffset%8 resolved at merge);
             section padded so header+validity is 4-byte aligned
             (padForValidityAlignment, KudoSerializer.java:497)
           - offsets: raw int32 offset values (NOT rebased), rowCount+1 per
             string/list column with rows
           - data: char/fixed-width payload slices; section padded to 4B

Writes are pure memcpy of host buffers; all bit realignment and offset
rebasing happens in merge_to_table (the read side), matching the
reference's write-cheap/merge-once design.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import observability as _obs
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import Kind
from spark_rapids_tpu.columns.table import Table
from spark_rapids_tpu.shuffle.schema import Field

MAGIC = b"KUD0"
# Optional trace-context header extension: when span tracing is on, the
# writer prefixes a table with "KTRX" + big-endian u64 trace_id + u64
# span_id (20 bytes) so the read side can re-parent its merge spans
# under the writing task's span.  The extension precedes the standard
# "KUD0" header, so the byte-compatible format is untouched whenever
# tracing is off (golden-file and native interop tests see identical
# streams) and readers need no look-ahead: the next 4 bytes of a stream
# are always EOF, "KUD0", or "KTRX".
TRACE_MAGIC = b"KTRX"


def _pad4(n: int) -> int:
    return (n + 3) // 4 * 4


def _pad_validity(n: int, header_size: int) -> int:
    """Pad validity section so header+validity is 4-byte aligned."""
    return _pad4(n + header_size) - header_size


def _validity_slice(row_offset: int, num_rows: int) -> Tuple[int, int]:
    """(byte offset, byte length) of the sloppy validity slice."""
    begin_byte = row_offset // 8
    begin_bit = row_offset % 8
    nbytes = (begin_bit + num_rows + 7) // 8 if num_rows > 0 else 0
    return begin_byte, nbytes


@dataclass
class KudoTableHeader:
    offset: int
    num_rows: int
    validity_len: int
    offset_len: int
    total_len: int
    num_columns: int
    has_validity: bytes
    # (trace_id, span_id) carried by a "KTRX" extension, else None;
    # never serialized by `write` (the extension is the WRITER's
    # concern, see write_to_stream) so header bytes stay golden
    trace_ctx: Optional[Tuple[int, int]] = None

    @property
    def serialized_size(self) -> int:
        return 4 + 6 * 4 + len(self.has_validity)

    def has_validity_buffer(self, col_idx: int) -> bool:
        return (self.has_validity[col_idx // 8] >> (col_idx % 8)) & 1 != 0

    def write(self, out) -> int:
        out.write(MAGIC)
        out.write(struct.pack(">iiiiii", self.offset, self.num_rows,
                              self.validity_len, self.offset_len,
                              self.total_len, self.num_columns))
        out.write(self.has_validity)
        return self.serialized_size

    @staticmethod
    def read(stream) -> Optional["KudoTableHeader"]:
        magic = stream.read(4)
        if len(magic) == 0:
            return None  # clean EOF
        trace_ctx = None
        if magic == TRACE_MAGIC:
            raw = stream.read(16)
            if len(raw) != 16:
                raise EOFError("truncated kudo trace extension")
            trace_ctx = struct.unpack(">QQ", raw)
            magic = stream.read(4)
            if len(magic) == 0:
                raise EOFError("kudo trace extension without a table")
        if magic != MAGIC:
            raise ValueError(f"bad kudo magic {magic!r}")
        raw = stream.read(24)
        if len(raw) != 24:
            raise EOFError("truncated kudo header")
        fields = struct.unpack(">iiiiii", raw)
        nbitset = (fields[5] + 7) // 8
        bitset = stream.read(nbitset)
        if len(bitset) != nbitset:
            raise EOFError("truncated kudo header bitset")
        return KudoTableHeader(*fields, bitset, trace_ctx)


@dataclass
class KudoTable:
    header: KudoTableHeader
    buffer: bytes  # body, length == header.total_len


# ------------------------------------------------------------------ write


class _Slice:
    __slots__ = ("offset", "row_count")

    def __init__(self, offset: int, row_count: int):
        self.offset = offset
        self.row_count = row_count


class HostColumnView:
    """Host-materialized view of one column (data bytes view, packed
    validity, offsets), built ONCE so repeated partition writes don't
    re-sync the device buffers (shuffle_split calls the writer per
    partition)."""

    __slots__ = ("dtype", "data", "validity", "offsets", "children")

    def __init__(self, col: Column):
        self.dtype = col.dtype
        self.children = [HostColumnView(ch) for ch in col.children]
        self.offsets = np.asarray(col.offsets) if col.offsets is not None \
            else None
        if col.validity is not None:
            bits = np.asarray(col.validity).astype(np.uint8)
            self.validity = np.packbits(bits, bitorder="little")
        else:
            self.validity = None
        kind = col.dtype.kind
        if kind in (Kind.LIST, Kind.STRUCT):
            self.data = None
        elif kind == Kind.STRING:
            self.data = np.asarray(col.data) if col.data is not None \
                else np.zeros(0, np.uint8)
        elif kind == Kind.DECIMAL128:
            self.data = np.asarray(col.data).astype("<i4")
        else:
            self.data = col.to_numpy()


def prepare_host_columns(columns: Sequence[Column]) -> List[HostColumnView]:
    """One-time device->host materialization for repeated kudo writes."""
    return [HostColumnView(c) for c in columns]


def _flat_count(views: Sequence[HostColumnView]) -> int:
    return sum(1 + _flat_count(v.children) for v in views)


def _walk_columns(cols: Sequence[HostColumnView], root: _Slice, visit):
    """Depth-first walk calling visit(view, slice) pre-order; list children
    get the child slice derived from raw offset values."""
    def rec(c: HostColumnView, sl: _Slice):
        visit(c, sl)
        if c.dtype.kind == Kind.LIST:
            if c.offsets is not None and sl.row_count > 0:
                start = int(c.offsets[sl.offset])
                end = int(c.offsets[sl.offset + sl.row_count])
                child = _Slice(start, end - start)
            else:
                child = _Slice(0, 0)
            rec(c.children[0], child)
        elif c.dtype.kind == Kind.STRUCT:
            for ch in c.children:
                rec(ch, sl)
    for c in cols:
        rec(c, root)


def write_to_stream(columns: Sequence[Column], out, row_offset: int,
                    num_rows: int) -> int:
    """Serialize rows [row_offset, row_offset+num_rows) of the columns as
    one kudo table (KudoSerializer.writeToStreamWithMetrics:249).  Returns
    bytes written (header + body)."""
    if num_rows < 0 or row_offset < 0:
        raise ValueError("row_offset/num_rows must be non-negative")
    ntrace = _write_trace_extension(out)
    views = list(columns)
    if views and isinstance(views[0], Column):
        views = prepare_host_columns(views)
    root = _Slice(row_offset, num_rows)
    nflat = _flat_count(views)
    bitset = bytearray((nflat + 7) // 8)

    validity_parts: List[bytes] = []
    offset_parts: List[bytes] = []
    data_parts: List[bytes] = []
    col_idx = [0]

    def visit(c: HostColumnView, sl: _Slice):
        i = col_idx[0]
        col_idx[0] += 1
        include_validity = c.validity is not None and sl.row_count > 0
        if include_validity:
            bitset[i // 8] |= 1 << (i % 8)
            bo, bl = _validity_slice(sl.offset, sl.row_count)
            sliced = c.validity[bo:bo + bl]
            if len(sliced) < bl:  # packed mask may be short; zero-extend
                sliced = np.concatenate(
                    [sliced, np.zeros(bl - len(sliced), np.uint8)])
            validity_parts.append(sliced.tobytes())
        kind = c.dtype.kind
        if kind in (Kind.STRING, Kind.LIST):
            if c.offsets is not None and sl.row_count > 0:
                offset_parts.append(
                    c.offsets[sl.offset: sl.offset + sl.row_count + 1]
                    .astype("<i4").tobytes())
                if kind == Kind.STRING:
                    start = int(c.offsets[sl.offset])
                    end = int(c.offsets[sl.offset + sl.row_count])
                    if end > start:
                        data_parts.append(c.data[start:end].tobytes())
        elif kind == Kind.STRUCT:
            pass
        else:  # fixed width (incl. decimal128 as (rows, 4) LE limbs)
            if sl.row_count > 0:
                data_parts.append(
                    c.data[sl.offset: sl.offset + sl.row_count].tobytes())

    _walk_columns(views, root, visit)

    validity = b"".join(validity_parts)
    offsets_b = b"".join(offset_parts)
    data_b = b"".join(data_parts)
    header_size = 4 + 24 + len(bitset)
    vlen = _pad_validity(len(validity), header_size)
    olen = _pad4(len(offsets_b))
    dlen = _pad4(len(data_b))
    header = KudoTableHeader(row_offset, num_rows, vlen, olen,
                             vlen + olen + dlen, nflat, bytes(bitset))
    header.write(out)
    out.write(validity)
    out.write(b"\0" * (vlen - len(validity)))
    out.write(offsets_b)
    out.write(b"\0" * (olen - len(offsets_b)))
    out.write(data_b)
    out.write(b"\0" * (dlen - len(data_b)))
    return ntrace + header.serialized_size + header.total_len


def _write_trace_extension(out) -> int:
    """Prefix the next table with the active trace context when span
    tracing is on (see TRACE_MAGIC).  Returns bytes written (0 when
    tracing is off or no span is open — the stream stays reference
    byte-compatible)."""
    tracer = _obs.TRACER
    if not tracer.enabled:
        return 0
    ctx = tracer.current_context()
    if ctx is None:
        return 0
    out.write(TRACE_MAGIC)
    out.write(struct.pack(">QQ", ctx.trace_id, ctx.span_id))
    return 20


def write_row_count_only(out, num_rows: int) -> int:
    """Degenerate zero-column table (KudoSerializer rows-only path)."""
    ntrace = _write_trace_extension(out)
    header = KudoTableHeader(0, num_rows, 0, 0, 0, 0, b"")
    return ntrace + header.write(out)


def read_one_table(stream) -> Optional[KudoTable]:
    header = KudoTableHeader.read(stream)
    if header is None:
        return None
    body = stream.read(header.total_len)
    if len(body) != header.total_len:
        raise EOFError("truncated kudo body")
    return KudoTable(header, body)


# ------------------------------------------------------------------ merge


class _HostCol:
    __slots__ = ("dtype", "rows", "mask", "data", "offsets", "children")

    def __init__(self, dtype, rows, mask=None, data=None, offsets=None,
                 children=()):
        self.dtype = dtype
        self.rows = rows
        self.mask = mask          # np bool array or None (all valid)
        self.data = data          # np array (values / chars / limb bytes)
        self.offsets = offsets    # np int32, rebased to 0
        self.children = list(children)


def _parse_table(kt: KudoTable, fields: Sequence[Field]) -> List[_HostCol]:
    """Decode one kudo body into logical host columns (bit offsets and raw
    offsets resolved here, as KudoTableMerger does)."""
    h = kt.header
    body = kt.buffer
    vcur = [0]
    ocur = [h.validity_len]
    dcur = [h.validity_len + h.offset_len]
    col_idx = [0]

    def read_validity(sl: _Slice) -> Optional[np.ndarray]:
        i = col_idx[0]
        has = h.has_validity_buffer(i)
        if not has or sl.row_count <= 0:
            return None
        begin_bit = sl.offset % 8
        nbytes = (begin_bit + sl.row_count + 7) // 8
        raw = np.frombuffer(body, np.uint8, nbytes, vcur[0])
        vcur[0] += nbytes
        bits = np.unpackbits(raw, bitorder="little")
        return bits[begin_bit: begin_bit + sl.row_count].astype(bool)

    def rec(f: Field, sl: _Slice) -> _HostCol:
        mask = read_validity(sl)
        col_idx[0] += 1
        kind = f.dtype.kind
        if kind in (Kind.STRING, Kind.LIST):
            if sl.row_count > 0:
                n = sl.row_count + 1
                raw = np.frombuffer(body, "<i4", n, ocur[0]).copy()
                ocur[0] += 4 * n
                child_sl = _Slice(int(raw[0]), int(raw[-1] - raw[0]))
                offsets = raw - raw[0]
            else:
                child_sl = _Slice(0, 0)
                offsets = np.zeros(1, np.int32)
            if kind == Kind.STRING:
                nchars = child_sl.row_count
                data = np.frombuffer(body, np.uint8, nchars, dcur[0]).copy()
                dcur[0] += nchars
                return _HostCol(f.dtype, sl.row_count, mask, data, offsets)
            child = rec(f.children[0], child_sl)
            return _HostCol(f.dtype, sl.row_count, mask, None, offsets,
                            [child])
        if kind == Kind.STRUCT:
            children = [rec(ch, sl) for ch in f.children]
            return _HostCol(f.dtype, sl.row_count, mask, None, None,
                            children)
        # fixed width
        item = 16 if kind == Kind.DECIMAL128 else f.dtype.size_bytes
        nbytes = sl.row_count * item
        raw = body[dcur[0]: dcur[0] + nbytes]
        dcur[0] += nbytes
        if kind == Kind.DECIMAL128:
            data = np.frombuffer(raw, "<i4").reshape(sl.row_count, 4).copy()
        else:
            data = np.frombuffer(raw, f.dtype.np_dtype).copy()
        return _HostCol(f.dtype, sl.row_count, mask, data, None)

    root = _Slice(h.offset, h.num_rows)
    return [rec(f, root) for f in fields]


def _concat_host_cols(parts: List[_HostCol], f: Field) -> Column:
    rows = sum(p.rows for p in parts)
    if any(p.mask is not None for p in parts):
        mask = np.concatenate([
            p.mask if p.mask is not None else np.ones(p.rows, bool)
            for p in parts]).astype(np.uint8)
    else:
        mask = None
    kind = f.dtype.kind
    if kind == Kind.STRING:
        data = np.concatenate([p.data for p in parts]) if parts else \
            np.zeros(0, np.uint8)
        sizes = [int(p.offsets[-1]) for p in parts]
        offs = [np.zeros(1, np.int32)]
        base = 0
        for p, sz in zip(parts, sizes):
            offs.append((p.offsets[1:] + base).astype(np.int32))
            base += sz
        offsets = np.concatenate(offs)
        import jax.numpy as jnp
        return Column(f.dtype, rows, data=jnp.asarray(data),
                      validity=None if mask is None else jnp.asarray(mask),
                      offsets=jnp.asarray(offsets))
    if kind == Kind.LIST:
        child = _concat_host_cols([p.children[0] for p in parts],
                                  f.children[0])
        offs = [np.zeros(1, np.int32)]
        base = 0
        for p in parts:
            offs.append((p.offsets[1:] + base).astype(np.int32))
            base += int(p.offsets[-1])
        import jax.numpy as jnp
        return Column(f.dtype, rows,
                      validity=None if mask is None else jnp.asarray(mask),
                      offsets=jnp.asarray(np.concatenate(offs)),
                      children=(child,))
    if kind == Kind.STRUCT:
        children = tuple(
            _concat_host_cols([p.children[i] for p in parts], ch)
            for i, ch in enumerate(f.children))
        import jax.numpy as jnp
        return Column(f.dtype, rows,
                      validity=None if mask is None else jnp.asarray(mask),
                      children=children)
    if parts:
        data = np.concatenate([p.data for p in parts])
    elif kind == Kind.DECIMAL128:
        data = np.zeros((0, 4), np.int32)
    else:
        data = np.zeros(0, f.dtype.np_dtype)
    import jax.numpy as jnp
    if kind == Kind.FLOAT64:
        data = data.view(np.uint64)
    return Column(f.dtype, rows, data=jnp.asarray(data),
                  validity=None if mask is None else jnp.asarray(mask))


def merge_to_table(kudo_tables: Sequence[KudoTable],
                   fields: Sequence[Field]) -> Table:
    """Concatenate N kudo tables into one device Table
    (KudoSerializer.mergeToTable:407 / KudoTableMerger)."""
    table, _ = merge_to_table_with_metrics(kudo_tables, fields)
    return table


# ------------------------------------------------------- metrics & dump


@dataclass
class WriteMetrics:
    """KudoSerializer WriteMetrics analog: bytes written + copy time."""
    written_bytes: int = 0
    copy_time_ns: int = 0


@dataclass
class MergeMetrics:
    """KudoTableMerger MergeMetrics analog."""
    parse_time_ns: int = 0
    concat_time_ns: int = 0
    total_rows: int = 0


def write_to_stream_with_metrics(columns, out, row_offset: int,
                                 num_rows: int) -> "WriteMetrics":
    """writeToStreamWithMetrics (KudoSerializer.java:249).  Opens a
    shuffle_write span; its context is what the trace extension embeds
    in the wire bytes, so the read side links back to THIS write."""
    import time as _time
    with _obs.TRACER.span("kudo_write", kind="shuffle_write",
                          attrs={"rows": num_rows}) as sp:
        t0 = _time.monotonic_ns()
        n = write_to_stream(columns, out, row_offset, num_rows)
        dur = _time.monotonic_ns() - t0
        sp.set_attr("bytes", n)
    # fold into the process metrics spine (shuffle byte counters +
    # per-task attribution + journal event); no-op when disabled
    _obs.record_shuffle_write(n, dur, num_rows)
    return WriteMetrics(written_bytes=n, copy_time_ns=dur)


def merge_to_table_with_metrics(kudo_tables, fields):
    import time as _time
    span = _open_merge_span(kudo_tables)
    try:
        t0 = _time.monotonic_ns()
        parsed = [_parse_table(kt, fields) for kt in kudo_tables]
        t1 = _time.monotonic_ns()
        cols = [_concat_host_cols([p[i] for p in parsed], f)
                for i, f in enumerate(fields)]
        t2 = _time.monotonic_ns()
        table = Table(cols)
        span.set_attr("rows", table.num_rows)
    finally:
        span.end()
    _obs.record_shuffle_merge(table.num_rows, t1 - t0, t2 - t1,
                              len(kudo_tables))
    return table, MergeMetrics(parse_time_ns=t1 - t0,
                               concat_time_ns=t2 - t1,
                               total_rows=table.num_rows)


def _open_merge_span(kudo_tables):
    """Open the shuffle_merge span with writer-side causality: every
    distinct trace context carried by the incoming tables' "KTRX"
    extensions becomes a span link, and when the merging thread has no
    open span of its own (a remote reader), the span is RE-PARENTED
    under the first writer's context so the read side joins the writing
    task's trace instead of starting an orphan one."""
    tracer = _obs.TRACER
    if not tracer.enabled:
        return _obs.NOOP_SPAN
    ctxs = []
    seen = set()
    for kt in kudo_tables:
        ctx = kt.header.trace_ctx
        if ctx is not None and ctx not in seen:
            seen.add(ctx)
            ctxs.append(_obs.SpanContext(*ctx))
    parent = None
    if ctxs and tracer.current_context() is None:
        parent = ctxs[0]
    span = tracer.start_span("kudo_merge", kind="shuffle_merge",
                             attrs={"tables": len(kudo_tables)},
                             parent=parent)
    for c in ctxs:
        span.add_link(c)
    return span


def dump_tables(kudo_tables, path_prefix: str) -> List[str]:
    """Debug dump of shuffle blocks to files (kudo/DumpOption.java /
    WriteInput dump support): one file per kudo table, header+body."""
    paths = []
    for i, kt in enumerate(kudo_tables):
        p = f"{path_prefix}{i:05d}.kudo"
        with open(p, "wb") as f:
            kt.header.write(f)
            f.write(kt.buffer)
        paths.append(p)
    return paths
