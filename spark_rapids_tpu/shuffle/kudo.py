"""Kudo shuffle wire format — byte-compatible with the reference
(format spec: kudo/KudoSerializer.java:48-170 javadoc; writer:
KudoTableHeaderCalc + SlicedBufferSerializer; merge: KudoTableMerger).

Layout of one serialized table partition:

  header:  "KUD0" | rowOffset | numRows | validityLen | offsetLen |
           totalLen | numFlatCols   (all 4-byte big-endian)
           hasValidityBuffer bitset ((numFlatCols+7)/8 bytes, LSB-first,
           depth-first schema order, struct/list before children)
  body:    [validity buffers][offset buffers][data buffers]
           - validity: sloppy byte-slices of the packed null masks starting
             at rowOffset/8 (bit offset rowOffset%8 resolved at merge);
             section padded so header+validity is 4-byte aligned
             (padForValidityAlignment, KudoSerializer.java:497)
           - offsets: raw int32 offset values (NOT rebased), rowCount+1 per
             string/list column with rows
           - data: char/fixed-width payload slices; section padded to 4B

Writes are pure memcpy of host buffers; all bit realignment and offset
rebasing happens in merge_to_table (the read side), matching the
reference's write-cheap/merge-once design.
"""

from __future__ import annotations

import os
import struct
import zlib
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import observability as _obs
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import Kind
from spark_rapids_tpu.columns.table import Table
from spark_rapids_tpu.shuffle.schema import Field

MAGIC = b"KUD0"
# Optional trace-context header extension: when span tracing is on, the
# writer prefixes a table with "KTRX" + big-endian u64 trace_id + u64
# span_id (20 bytes) so the read side can re-parent its merge spans
# under the writing task's span.  The extension precedes the standard
# "KUD0" header, so the byte-compatible format is untouched whenever
# tracing is off (golden-file and native interop tests see identical
# streams) and readers need no look-ahead: the next 4 bytes of a stream
# are always EOF, "KUD0", or "KTRX".
TRACE_MAGIC = b"KTRX"
# Optional integrity trailer: when CRC mode is on, every table is
# FOLLOWED by "KCRC" + big-endian u32 CRC32 of (header bytes + body).
# The stream is byte-compatible when disabled (golden fixtures and the
# native engine see identical bytes); readers verify any trailer they
# encounter regardless of the local write-side setting, so a CRC'd
# stream is checked even by a process that writes without CRC.  The
# KTRX trace extension is NOT covered — corrupting it already fails
# loudly at magic dispatch.
CRC_MAGIC = b"KCRC"
CRC_TRAILER_LEN = 8

_CRC_ENABLED = [os.environ.get("SPARK_RAPIDS_TPU_KUDO_CRC", "")
                not in ("", "0")]


class KudoCorruptException(ValueError):
    """A kudo table failed integrity verification (CRC mismatch or a
    structurally impossible record).  Carries enough to drive a
    re-fetch or a resync: ``reason`` in {'crc', 'magic',
    'truncated'}.  ``deferred=True`` marks a NON-seekable stream's
    late-trailer verification failure: the corrupt table was already
    handed to the caller one read earlier, and the stream itself is
    positioned cleanly at the next record (see read_one_table).

    For SPILL FILES (memory/spill.py) a stream offset alone is
    useless triage — the operator needs to know WHICH file on disk
    went bad and which spill generation wrote it, so re-reads of
    kudo spill files carry ``path`` + ``generation`` (None for wire
    streams, where the link peer/offset is the address)."""

    def __init__(self, msg: str, reason: str = "crc",
                 deferred: bool = False,
                 path: Optional[str] = None,
                 generation: Optional[int] = None):
        if path is not None:
            msg = (f"{msg} [spill file {path}"
                   + (f", generation {generation}"
                      if generation is not None else "") + "]")
        super().__init__(msg)
        self.reason = reason
        self.deferred = deferred
        self.path = path
        self.generation = generation


def annotate_spill_corruption(e: "KudoCorruptException", path: str,
                              generation: Optional[int] = None
                              ) -> "KudoCorruptException":
    """Rebuild a corruption error with the spill-file address (file
    path + spill generation) folded into the message — the read path
    only knows stream offsets; the spill store knows the file."""
    return KudoCorruptException(
        str(e.args[0]) if e.args else "kudo corruption",
        reason=e.reason, deferred=e.deferred, path=path,
        generation=generation)


def set_crc_enabled(enabled: bool) -> bool:
    """Flip CRC-trailer writing for this process; returns the prior
    setting.  Read-side verification is always on when a trailer is
    present."""
    prior = _CRC_ENABLED[0]
    _CRC_ENABLED[0] = bool(enabled)
    return prior


def crc_enabled() -> bool:
    return _CRC_ENABLED[0]


def _pad4(n: int) -> int:
    return (n + 3) // 4 * 4


def _pad_validity(n: int, header_size: int) -> int:
    """Pad validity section so header+validity is 4-byte aligned."""
    return _pad4(n + header_size) - header_size


def _stream_read(stream, n: int) -> bytes:
    """stream.read honoring any pushback left by a trailer peek."""
    buf = getattr(stream, "_kudo_pushback", b"")
    if buf:
        take = buf[:n]
        stream._kudo_pushback = buf[n:]
        if len(take) < n:
            take += stream.read(n - len(take))
        return take
    return stream.read(n)


def _stream_unread(stream, data: bytes) -> None:
    """Give peeked bytes back: seek when possible, else stash them on
    the stream object (read_one_table must peek past a table to see
    whether a CRC trailer follows)."""
    if not data:
        return
    try:
        stream.seek(-len(data), 1)
        return
    except (OSError, ValueError, AttributeError):
        pass  # unseekable (or mid-pushback): fall through to the stash
    stream._kudo_pushback = data + getattr(stream, "_kudo_pushback", b"")


def _validity_slice(row_offset: int, num_rows: int) -> Tuple[int, int]:
    """(byte offset, byte length) of the sloppy validity slice."""
    begin_byte = row_offset // 8
    begin_bit = row_offset % 8
    nbytes = (begin_bit + num_rows + 7) // 8 if num_rows > 0 else 0
    return begin_byte, nbytes


@dataclass
class KudoTableHeader:
    offset: int
    num_rows: int
    validity_len: int
    offset_len: int
    total_len: int
    num_columns: int
    has_validity: bytes
    # (trace_id, span_id) carried by a "KTRX" extension, else None;
    # never serialized by `write` (the extension is the WRITER's
    # concern, see write_to_stream) so header bytes stay golden
    trace_ctx: Optional[Tuple[int, int]] = None

    @property
    def serialized_size(self) -> int:
        return 4 + 6 * 4 + len(self.has_validity)

    def has_validity_buffer(self, col_idx: int) -> bool:
        return (self.has_validity[col_idx // 8] >> (col_idx % 8)) & 1 != 0

    def to_bytes(self) -> bytes:
        """The exact wire bytes `write` emits — also what the KCRC
        trailer's checksum covers on both sides."""
        return (MAGIC
                + struct.pack(">iiiiii", self.offset, self.num_rows,
                              self.validity_len, self.offset_len,
                              self.total_len, self.num_columns)
                + self.has_validity)

    def write(self, out) -> int:
        out.write(self.to_bytes())
        return self.serialized_size

    @staticmethod
    def read(stream) -> Optional["KudoTableHeader"]:
        magic = _stream_read(stream, 4)
        while magic == CRC_MAGIC:
            # a trailer the previous read could not peek at (non-
            # seekable stream): verify it now against the checksum
            # read_one_table stashed for exactly this moment; without
            # a stash (C stream that refuses attributes) skip it
            raw = _stream_read(stream, 4)
            if len(raw) != 4:
                raise EOFError("truncated kudo crc trailer")
            pending = getattr(stream, "_kudo_pending_crc", None)
            if pending is not None:
                stream._kudo_pending_crc = None
                want = struct.unpack(">I", raw)[0]
                if want != pending:
                    _obs.record_kudo_corruption(
                        "crc", detail=f"deferred: want {want:08x} "
                                      f"got {pending:08x}")
                    _obs.trigger_incident(
                        "kudo_corrupt", reason="crc",
                        detail=f"deferred trailer mismatch want "
                               f"{want:08x} got {pending:08x}")
                    raise KudoCorruptException(
                        f"kudo crc mismatch (want {want:08x} got "
                        f"{pending:08x})", deferred=True)
            magic = _stream_read(stream, 4)
        if len(magic) == 0:
            return None  # clean EOF
        trace_ctx = None
        if magic == TRACE_MAGIC:
            raw = _stream_read(stream, 16)
            if len(raw) != 16:
                raise EOFError("truncated kudo trace extension")
            trace_ctx = struct.unpack(">QQ", raw)
            magic = _stream_read(stream, 4)
            if len(magic) == 0:
                raise EOFError("kudo trace extension without a table")
        if magic != MAGIC:
            raise ValueError(f"bad kudo magic {magic!r}")
        raw = _stream_read(stream, 24)
        if len(raw) != 24:
            raise EOFError("truncated kudo header")
        fields = struct.unpack(">iiiiii", raw)
        off, rows, vlen, olen, tlen, ncols = fields
        if (min(off, rows, vlen, olen, tlen, ncols) < 0
                or vlen + olen > tlen):
            _obs.trigger_incident(
                "kudo_corrupt", reason="magic",
                detail=f"impossible header rows={rows} "
                       f"total_len={tlen} cols={ncols}")
            raise KudoCorruptException(
                f"impossible kudo header (offset={off} rows={rows} "
                f"validity_len={vlen} offset_len={olen} "
                f"total_len={tlen} cols={ncols})", reason="magic")
        nbitset = (fields[5] + 7) // 8
        bitset = _stream_read(stream, nbitset)
        if len(bitset) != nbitset:
            raise EOFError("truncated kudo header bitset")
        return KudoTableHeader(*fields, bitset, trace_ctx)


@dataclass
class KudoTable:
    header: KudoTableHeader
    buffer: bytes  # body, length == header.total_len


# ------------------------------------------------------------------ write


class _Slice:
    __slots__ = ("offset", "row_count")

    def __init__(self, offset: int, row_count: int):
        self.offset = offset
        self.row_count = row_count


class HostColumnView:
    """Host-materialized view of one column (data bytes view, packed
    validity, offsets), built ONCE so repeated partition writes don't
    re-sync the device buffers (shuffle_split calls the writer per
    partition)."""

    __slots__ = ("dtype", "data", "validity", "offsets", "children")

    def __init__(self, col: Column):
        self.dtype = col.dtype
        self.children = [HostColumnView(ch) for ch in col.children]
        self.offsets = np.asarray(col.offsets) if col.offsets is not None \
            else None
        if col.validity is not None:
            bits = np.asarray(col.validity).astype(np.uint8)
            self.validity = np.packbits(bits, bitorder="little")
        else:
            self.validity = None
        kind = col.dtype.kind
        if kind in (Kind.LIST, Kind.STRUCT):
            self.data = None
        elif kind == Kind.STRING:
            self.data = np.asarray(col.data) if col.data is not None \
                else np.zeros(0, np.uint8)
        elif kind == Kind.DECIMAL128:
            self.data = np.asarray(col.data).astype("<i4")
        else:
            self.data = col.to_numpy()


def prepare_host_columns(columns: Sequence[Column]) -> List[HostColumnView]:
    """One-time device->host materialization for repeated kudo writes."""
    return [HostColumnView(c) for c in columns]


def _flat_count(views: Sequence[HostColumnView]) -> int:
    return sum(1 + _flat_count(v.children) for v in views)


def _walk_columns(cols: Sequence[HostColumnView], root: _Slice, visit):
    """Depth-first walk calling visit(view, slice) pre-order; list children
    get the child slice derived from raw offset values."""
    def rec(c: HostColumnView, sl: _Slice):
        visit(c, sl)
        if c.dtype.kind == Kind.LIST:
            if c.offsets is not None and sl.row_count > 0:
                start = int(c.offsets[sl.offset])
                end = int(c.offsets[sl.offset + sl.row_count])
                child = _Slice(start, end - start)
            else:
                child = _Slice(0, 0)
            rec(c.children[0], child)
        elif c.dtype.kind == Kind.STRUCT:
            for ch in c.children:
                rec(ch, sl)
    for c in cols:
        rec(c, root)


def write_to_stream(columns: Sequence[Column], out, row_offset: int,
                    num_rows: int, *,
                    crc: Optional[bool] = None) -> int:
    """Serialize rows [row_offset, row_offset+num_rows) of the columns as
    one kudo table (KudoSerializer.writeToStreamWithMetrics:249).  Returns
    bytes written (header + body).  ``crc`` overrides the process CRC
    setting for THIS table (the spill store forces trailers on so
    spilled bytes are always corruption-checked on read-back, without
    racing the global flag against concurrent shuffle writers)."""
    if num_rows < 0 or row_offset < 0:
        raise ValueError("row_offset/num_rows must be non-negative")
    ntrace = _write_trace_extension(out)
    views = list(columns)
    if views and isinstance(views[0], Column):
        views = prepare_host_columns(views)
    root = _Slice(row_offset, num_rows)
    nflat = _flat_count(views)
    bitset = bytearray((nflat + 7) // 8)

    validity_parts: List[bytes] = []
    offset_parts: List[bytes] = []
    data_parts: List[bytes] = []
    col_idx = [0]

    def visit(c: HostColumnView, sl: _Slice):
        i = col_idx[0]
        col_idx[0] += 1
        include_validity = c.validity is not None and sl.row_count > 0
        if include_validity:
            bitset[i // 8] |= 1 << (i % 8)
            bo, bl = _validity_slice(sl.offset, sl.row_count)
            sliced = c.validity[bo:bo + bl]
            if len(sliced) < bl:  # packed mask may be short; zero-extend
                sliced = np.concatenate(
                    [sliced, np.zeros(bl - len(sliced), np.uint8)])
            validity_parts.append(sliced.tobytes())
        kind = c.dtype.kind
        if kind in (Kind.STRING, Kind.LIST):
            if c.offsets is not None and sl.row_count > 0:
                offset_parts.append(
                    c.offsets[sl.offset: sl.offset + sl.row_count + 1]
                    .astype("<i4").tobytes())
                if kind == Kind.STRING:
                    start = int(c.offsets[sl.offset])
                    end = int(c.offsets[sl.offset + sl.row_count])
                    if end > start:
                        data_parts.append(c.data[start:end].tobytes())
        elif kind == Kind.STRUCT:
            pass
        else:  # fixed width (incl. decimal128 as (rows, 4) LE limbs)
            if sl.row_count > 0:
                data_parts.append(
                    c.data[sl.offset: sl.offset + sl.row_count].tobytes())

    _walk_columns(views, root, visit)

    validity = b"".join(validity_parts)
    offsets_b = b"".join(offset_parts)
    data_b = b"".join(data_parts)
    header_size = 4 + 24 + len(bitset)
    vlen = _pad_validity(len(validity), header_size)
    olen = _pad4(len(offsets_b))
    dlen = _pad4(len(data_b))
    header = KudoTableHeader(row_offset, num_rows, vlen, olen,
                             vlen + olen + dlen, nflat, bytes(bitset))
    hb = header.to_bytes()
    body = (validity, b"\0" * (vlen - len(validity)),
            offsets_b, b"\0" * (olen - len(offsets_b)),
            data_b, b"\0" * (dlen - len(data_b)))
    out.write(hb)
    for part in body:
        out.write(part)
    n = ntrace + header.serialized_size + header.total_len
    return n + _write_crc_trailer(out, hb, body, crc=crc)


def _write_crc_trailer(out, header_bytes: bytes, body_parts, *,
                       crc: Optional[bool] = None) -> int:
    """Append the KCRC trailer when CRC mode is on; returns the bytes
    written (0 when off — the stream stays reference
    byte-compatible).  ``crc`` overrides the process flag per table."""
    if not (_CRC_ENABLED[0] if crc is None else crc):
        return 0
    crc = zlib.crc32(header_bytes)
    for part in body_parts:
        crc = zlib.crc32(part, crc)
    out.write(CRC_MAGIC + struct.pack(">I", crc & 0xFFFFFFFF))
    return CRC_TRAILER_LEN


def _write_trace_extension(out) -> int:
    """Prefix the next table with the active trace context when span
    tracing is on (see TRACE_MAGIC).  Returns bytes written (0 when
    tracing is off or no span is open — the stream stays reference
    byte-compatible)."""
    tracer = _obs.TRACER
    if not tracer.enabled:
        return 0
    ctx = tracer.current_context()
    if ctx is None:
        return 0
    out.write(TRACE_MAGIC)
    out.write(struct.pack(">QQ", ctx.trace_id, ctx.span_id))
    return 20


def write_row_count_only(out, num_rows: int) -> int:
    """Degenerate zero-column table (KudoSerializer rows-only path)."""
    ntrace = _write_trace_extension(out)
    header = KudoTableHeader(0, num_rows, 0, 0, 0, 0, b"")
    hb = header.to_bytes()
    out.write(hb)
    return ntrace + header.serialized_size + _write_crc_trailer(
        out, hb, ())


def read_one_table(stream) -> Optional[KudoTable]:
    """Read one table; when a KCRC trailer follows it is consumed and
    VERIFIED (a mismatch raises :class:`KudoCorruptException`) —
    regardless of the local write-side CRC setting.  On a
    NON-seekable stream (a live socket/pipe) the trailer peek is
    skipped so an incremental reader never blocks waiting for bytes
    past the table; verification is DEFERRED instead — the table's
    checksum is stashed on the stream and checked when the next
    header read encounters the trailer (a C stream that refuses
    attribute stashes skips verification)."""
    header = KudoTableHeader.read(stream)
    if header is None:
        return None
    body = _stream_read(stream, header.total_len)
    if len(body) != header.total_len:
        raise EOFError("truncated kudo body")
    seekable = getattr(stream, "seekable", None)
    if seekable is not None and not seekable():
        try:
            stream._kudo_pending_crc = zlib.crc32(
                body, zlib.crc32(header.to_bytes())) & 0xFFFFFFFF
        except AttributeError:
            pass
        return KudoTable(header, body)
    peek = _stream_read(stream, 4)
    if peek == CRC_MAGIC:
        raw = _stream_read(stream, 4)
        if len(raw) != 4:
            raise EOFError("truncated kudo crc trailer")
        want = struct.unpack(">I", raw)[0]
        got = zlib.crc32(body, zlib.crc32(header.to_bytes())) \
            & 0xFFFFFFFF
        if got != want:
            _obs.record_kudo_corruption(
                "crc", detail=f"want {want:08x} got {got:08x} "
                              f"rows={header.num_rows}")
            _obs.trigger_incident(
                "kudo_corrupt", reason="crc",
                detail=f"trailer mismatch want {want:08x} got "
                       f"{got:08x} rows={header.num_rows}")
            raise KudoCorruptException(
                f"kudo crc mismatch (want {want:08x} got {got:08x})")
    else:
        _stream_unread(stream, peek)
    return KudoTable(header, body)


def stream_has_crc_trailers(blob: bytes) -> bool:
    """Structured scan of a concatenated table stream: walk records by
    their header lengths and report whether any KCRC trailer is
    present.  Payload bytes are never pattern-matched, so a payload
    that happens to contain b"KCRC" cannot false-positive (the shim
    uses this to decide whether the trailer-unaware native engine may
    parse the blob).  An unparseable structure returns False — the
    real reader will raise its precise error."""
    pos, n = 0, len(blob)
    while pos + 4 <= n:
        magic = blob[pos:pos + 4]
        if magic == CRC_MAGIC:
            return True
        if magic == TRACE_MAGIC:
            pos += 20
            continue
        if magic != MAGIC or pos + 28 > n:
            return False
        tlen = int.from_bytes(blob[pos + 20:pos + 24], "big",
                              signed=True)
        ncols = int.from_bytes(blob[pos + 24:pos + 28], "big",
                               signed=True)
        if tlen < 0 or ncols < 0:
            return False
        pos += 28 + (ncols + 7) // 8 + tlen
    return False


def _is_seekable(stream) -> bool:
    """Mirror read_one_table's convention: a stream without a
    ``seekable`` method is treated as seekable (plain BytesIO-likes)."""
    probe = getattr(stream, "seekable", None)
    return True if probe is None else bool(probe())


def resync_to_magic(stream, chunk_size: int = 1 << 16) -> int:
    """Scan forward to the next table magic ("KUD0"/"KTRX"), leaving
    the stream positioned AT it; returns the bytes skipped.  At EOF
    the stream is left there (the caller's next read sees a clean
    EOF).  On a seekable stream the scan rewinds with ``seek``; on a
    NON-seekable one (a live socket wrapped by
    shuffle/socket_io.SocketStream) the unconsumed tail is given back
    through the pushback stash, so resync works mid-stream without
    random access.  Chunked bytes.find scan (a 3-byte carry covers
    magics straddling chunk edges) — a multi-MB corrupt partition
    resyncs at memchr speed, not per-byte Python."""
    can_seek = _is_seekable(stream)
    carry = b""
    consumed = 0          # bytes read from the stream by this scan
    while True:
        chunk = _stream_read(stream, chunk_size)
        if not chunk:
            return consumed
        buf = carry + chunk
        consumed += len(chunk)
        hits = [p for p in (buf.find(MAGIC), buf.find(TRACE_MAGIC))
                if p >= 0]
        if hits:
            pos = min(hits)
            back = len(buf) - pos
            if can_seek:
                stream.seek(-back, 1)
            else:
                _stream_unread(stream, buf[pos:])
            return consumed - back
        carry = buf[-3:]


def read_tables(stream, *, resync: bool = False) -> List[KudoTable]:
    """Read every table in a stream.  With ``resync=False`` any
    detected corruption raises: CRC mismatch, bad magic, truncation,
    or a structurally impossible header — without CRC those
    magic/length/structure checks are the loud-failure floor, while
    payload bit-flips (the silent kind) need the CRC trailer.  With
    ``resync=True`` the reader skips to the next table magic after a
    corrupt record and keeps going — the multi-table salvage mode for
    streams whose remaining tables are still good.  Resync works on
    seekable streams (rewind + scan) AND on non-seekable socket
    streams: there a deferred late-trailer CRC failure drops the
    PREVIOUS table (the one the stashed checksum covered — the stream
    itself already sits cleanly at the next record), and a bad-magic
    failure scans forward through the pushback stash."""
    tables: List[KudoTable] = []
    can_seek = _is_seekable(stream)
    while True:
        start = stream.tell() if (resync and can_seek) else None
        try:
            kt = read_one_table(stream)
        except (ValueError, EOFError) as e:
            if not resync:
                raise
            if isinstance(e, KudoCorruptException):
                reason = e.reason
            elif isinstance(e, EOFError):
                reason = "truncated"
            else:
                reason = "magic"
            if getattr(e, "deferred", False):
                # non-seekable late-trailer verification: the corrupt
                # table is the LAST one handed back (its trailer
                # immediately follows it on the wire); drop it — the
                # stream needs no repositioning
                skipped = 0
                if tables:
                    bad = tables.pop()
                    skipped = (bad.header.serialized_size
                               + bad.header.total_len + CRC_TRAILER_LEN
                               + (20 if bad.header.trace_ctx is not None
                                  else 0))
                _obs.record_kudo_corruption(
                    "resync", skipped_bytes=skipped,
                    detail=f"{reason}(deferred): {e}")
                continue
            if not can_seek:
                if isinstance(e, EOFError):
                    # mid-record EOF on a live stream: nothing past it
                    # to salvage — return what survived
                    _obs.record_kudo_corruption(
                        "resync", skipped_bytes=0,
                        detail=f"{reason}: {e}")
                    return tables
                skipped = resync_to_magic(stream)
                _obs.record_kudo_corruption(
                    "resync", skipped_bytes=skipped,
                    detail=f"{reason}: {e}")
                continue
            if reason == "crc" and stream.tell() > start:
                # the record's full extent is known (header, body, and
                # trailer were all consumed before the mismatch):
                # resume AFTER it — rescanning the corrupt body could
                # resurrect a phantom table from payload bytes that
                # merely look like a kudo record
                skipped = stream.tell() - start
            else:
                # rewind to one past the failed record's start and
                # scan; progress is monotonic, so a corrupt tail
                # terminates at EOF instead of looping
                stream.seek(start + 1)
                skipped = 1 + resync_to_magic(stream)
            # one "resync" record per skip (the crc mismatch itself
            # was already counted at the verify site)
            _obs.record_kudo_corruption("resync", skipped_bytes=skipped,
                                        detail=f"{reason}: {e}")
            continue
        if kt is None:
            return tables
        tables.append(kt)


# ------------------------------------------------------------------ merge


class _HostCol:
    __slots__ = ("dtype", "rows", "mask", "data", "offsets", "children")

    def __init__(self, dtype, rows, mask=None, data=None, offsets=None,
                 children=()):
        self.dtype = dtype
        self.rows = rows
        self.mask = mask          # np bool array or None (all valid)
        self.data = data          # np array (values / chars / limb bytes)
        self.offsets = offsets    # np int32, rebased to 0
        self.children = list(children)


def _parse_table(kt: KudoTable, fields: Sequence[Field]) -> List[_HostCol]:
    """Decode one kudo body into logical host columns (bit offsets and raw
    offsets resolved here, as KudoTableMerger does)."""
    h = kt.header
    body = kt.buffer
    vcur = [0]
    ocur = [h.validity_len]
    dcur = [h.validity_len + h.offset_len]
    col_idx = [0]

    def read_validity(sl: _Slice) -> Optional[np.ndarray]:
        i = col_idx[0]
        has = h.has_validity_buffer(i)
        if not has or sl.row_count <= 0:
            return None
        begin_bit = sl.offset % 8
        nbytes = (begin_bit + sl.row_count + 7) // 8
        raw = np.frombuffer(body, np.uint8, nbytes, vcur[0])
        vcur[0] += nbytes
        bits = np.unpackbits(raw, bitorder="little")
        return bits[begin_bit: begin_bit + sl.row_count].astype(bool)

    def rec(f: Field, sl: _Slice) -> _HostCol:
        mask = read_validity(sl)
        col_idx[0] += 1
        kind = f.dtype.kind
        if kind in (Kind.STRING, Kind.LIST):
            if sl.row_count > 0:
                n = sl.row_count + 1
                raw = np.frombuffer(body, "<i4", n, ocur[0]).copy()
                ocur[0] += 4 * n
                child_sl = _Slice(int(raw[0]), int(raw[-1] - raw[0]))
                offsets = raw - raw[0]
            else:
                child_sl = _Slice(0, 0)
                offsets = np.zeros(1, np.int32)
            if kind == Kind.STRING:
                nchars = child_sl.row_count
                data = np.frombuffer(body, np.uint8, nchars, dcur[0]).copy()
                dcur[0] += nchars
                return _HostCol(f.dtype, sl.row_count, mask, data, offsets)
            child = rec(f.children[0], child_sl)
            return _HostCol(f.dtype, sl.row_count, mask, None, offsets,
                            [child])
        if kind == Kind.STRUCT:
            children = [rec(ch, sl) for ch in f.children]
            return _HostCol(f.dtype, sl.row_count, mask, None, None,
                            children)
        # fixed width
        item = 16 if kind == Kind.DECIMAL128 else f.dtype.size_bytes
        nbytes = sl.row_count * item
        raw = body[dcur[0]: dcur[0] + nbytes]
        dcur[0] += nbytes
        if kind == Kind.DECIMAL128:
            data = np.frombuffer(raw, "<i4").reshape(sl.row_count, 4).copy()
        else:
            data = np.frombuffer(raw, f.dtype.np_dtype).copy()
        return _HostCol(f.dtype, sl.row_count, mask, data, None)

    root = _Slice(h.offset, h.num_rows)
    return [rec(f, root) for f in fields]


def _concat_host_cols(parts: List[_HostCol], f: Field) -> Column:
    rows = sum(p.rows for p in parts)
    if any(p.mask is not None for p in parts):
        mask = np.concatenate([
            p.mask if p.mask is not None else np.ones(p.rows, bool)
            for p in parts]).astype(np.uint8)
    else:
        mask = None
    kind = f.dtype.kind
    if kind == Kind.STRING:
        data = np.concatenate([p.data for p in parts]) if parts else \
            np.zeros(0, np.uint8)
        sizes = [int(p.offsets[-1]) for p in parts]
        offs = [np.zeros(1, np.int32)]
        base = 0
        for p, sz in zip(parts, sizes):
            offs.append((p.offsets[1:] + base).astype(np.int32))
            base += sz
        offsets = np.concatenate(offs)
        import jax.numpy as jnp
        return Column(f.dtype, rows, data=jnp.asarray(data),
                      validity=None if mask is None else jnp.asarray(mask),
                      offsets=jnp.asarray(offsets))
    if kind == Kind.LIST:
        child = _concat_host_cols([p.children[0] for p in parts],
                                  f.children[0])
        offs = [np.zeros(1, np.int32)]
        base = 0
        for p in parts:
            offs.append((p.offsets[1:] + base).astype(np.int32))
            base += int(p.offsets[-1])
        import jax.numpy as jnp
        return Column(f.dtype, rows,
                      validity=None if mask is None else jnp.asarray(mask),
                      offsets=jnp.asarray(np.concatenate(offs)),
                      children=(child,))
    if kind == Kind.STRUCT:
        children = tuple(
            _concat_host_cols([p.children[i] for p in parts], ch)
            for i, ch in enumerate(f.children))
        import jax.numpy as jnp
        return Column(f.dtype, rows,
                      validity=None if mask is None else jnp.asarray(mask),
                      children=children)
    if parts:
        data = np.concatenate([p.data for p in parts])
    elif kind == Kind.DECIMAL128:
        data = np.zeros((0, 4), np.int32)
    else:
        data = np.zeros(0, f.dtype.np_dtype)
    import jax.numpy as jnp
    if kind == Kind.FLOAT64:
        data = data.view(np.uint64)
    return Column(f.dtype, rows, data=jnp.asarray(data),
                  validity=None if mask is None else jnp.asarray(mask))


def merge_to_table(kudo_tables: Sequence[KudoTable],
                   fields: Sequence[Field]) -> Table:
    """Concatenate N kudo tables into one device Table
    (KudoSerializer.mergeToTable:407 / KudoTableMerger)."""
    table, _ = merge_to_table_with_metrics(kudo_tables, fields)
    return table


# ------------------------------------------------------- metrics & dump


@dataclass
class WriteMetrics:
    """KudoSerializer WriteMetrics analog: bytes written + copy time."""
    written_bytes: int = 0
    copy_time_ns: int = 0


@dataclass
class MergeMetrics:
    """KudoTableMerger MergeMetrics analog."""
    parse_time_ns: int = 0
    concat_time_ns: int = 0
    total_rows: int = 0


def write_to_stream_with_metrics(columns, out, row_offset: int,
                                 num_rows: int) -> "WriteMetrics":
    """writeToStreamWithMetrics (KudoSerializer.java:249).  Opens a
    shuffle_write span; its context is what the trace extension embeds
    in the wire bytes, so the read side links back to THIS write."""
    import time as _time
    with _obs.TRACER.span("kudo_write", kind="shuffle_write",
                          attrs={"rows": num_rows}) as sp:
        t0 = _time.monotonic_ns()
        n = write_to_stream(columns, out, row_offset, num_rows)
        dur = _time.monotonic_ns() - t0
        sp.set_attr("bytes", n)
    # fold into the process metrics spine (shuffle byte counters +
    # per-task attribution + journal event); no-op when disabled
    _obs.record_shuffle_write(n, dur, num_rows)
    return WriteMetrics(written_bytes=n, copy_time_ns=dur)


def merge_to_table_with_metrics(kudo_tables, fields):
    import time as _time

    from spark_rapids_tpu.robustness import retry as _retry
    span = _open_merge_span(kudo_tables)
    try:
        t0 = _time.monotonic_ns()
        # split-and-retry over the TABLE LIST: a GpuSplitAndRetryOOM
        # mid-parse halves the batch and parses the halves (down to a
        # one-table floor); per-half results flatten back in order, so
        # the split merge is byte-identical to the unsplit one
        parsed = _retry.split_and_retry(
            lambda kts: [_parse_table(kt, fields) for kt in kts],
            list(kudo_tables),
            combine=lambda chunks: [p for chunk in chunks
                                    for p in chunk],
            name="kudo_merge")
        t1 = _time.monotonic_ns()
        cols = [_concat_host_cols([p[i] for p in parsed], f)
                for i, f in enumerate(fields)]
        t2 = _time.monotonic_ns()
        table = Table(cols)
        span.set_attr("rows", table.num_rows)
    finally:
        span.end()
    _obs.record_shuffle_merge(table.num_rows, t1 - t0, t2 - t1,
                              len(kudo_tables))
    return table, MergeMetrics(parse_time_ns=t1 - t0,
                               concat_time_ns=t2 - t1,
                               total_rows=table.num_rows)


def _open_merge_span(kudo_tables):
    """Open the shuffle_merge span with writer-side causality: every
    distinct trace context carried by the incoming tables' "KTRX"
    extensions becomes a span link, and when the merging thread has no
    open span of its own (a remote reader), the span is RE-PARENTED
    under the first writer's context so the read side joins the writing
    task's trace instead of starting an orphan one."""
    tracer = _obs.TRACER
    if not tracer.enabled:
        return _obs.NOOP_SPAN
    ctxs = []
    seen = set()
    for kt in kudo_tables:
        ctx = kt.header.trace_ctx
        if ctx is not None and ctx not in seen:
            seen.add(ctx)
            ctxs.append(_obs.SpanContext(*ctx))
    parent = None
    if ctxs and tracer.current_context() is None:
        parent = ctxs[0]
    span = tracer.start_span("kudo_merge", kind="shuffle_merge",
                             attrs={"tables": len(kudo_tables)},
                             parent=parent)
    for c in ctxs:
        span.add_link(c)
    return span


def dump_tables(kudo_tables, path_prefix: str) -> List[str]:
    """Debug dump of shuffle blocks to files (kudo/DumpOption.java /
    WriteInput dump support): one file per kudo table, header+body."""
    paths = []
    for i, kt in enumerate(kudo_tables):
        p = f"{path_prefix}{i:05d}.kudo"
        with open(p, "wb") as f:
            kt.header.write(f)
            f.write(kt.buffer)
        paths.append(p)
    return paths
