"""Flattened-schema description + visitors (reference
src/main/java/.../schema/SchemaVisitor.java:81 — depth-first walk where a
struct/list column's own entry precedes its children)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import DType
from spark_rapids_tpu.columns.table import Table


@dataclass(frozen=True)
class Field:
    dtype: DType
    children: Tuple["Field", ...] = ()
    name: Optional[str] = None


def schema_of_table(table: Table) -> List[Field]:
    def of_col(c: Column) -> Field:
        return Field(c.dtype, tuple(of_col(ch) for ch in c.children))
    return [of_col(c) for c in table.columns]


def flattened_count(fields) -> int:
    """Number of columns in the flattened (depth-first) schema."""
    n = 0
    for f in fields:
        n += 1 + flattened_count(f.children)
    return n
