"""Socket endpoints for the kudo wire format (ISSUE 10).

The kudo reader already has a non-seekable mode: on a live stream the
trailer peek is skipped and CRC verification is DEFERRED one record
(the stashed-checksum path, PR 3).  That machinery stashes state as
attributes on the stream object — which a raw ``socket.makefile('rb')``
silently refuses (C-implemented io objects have no ``__dict__``), so a
bare socket file never verifies anything.  :class:`SocketStream` is the
fix: a small python-level file-like wrapper over a connected socket
that

  * loops ``recv`` until exactly ``n`` bytes arrive (or EOF) — kudo's
    framing assumes ``read(n)`` is all-or-short-at-EOF;
  * reports ``seekable() == False`` so the reader takes the deferred
    trailer path;
  * accepts arbitrary attributes, so ``_kudo_pushback`` (resync) and
    ``_kudo_pending_crc`` (late trailer verify) work as designed.

``read_tables(SocketStream(sock), resync=True)`` therefore streams
multiple KCRC-trailed tables off a live socket, drops a corrupted one
on its deferred trailer check, scans past garbage via the pushback
stash, and returns every intact table — the socket twin of the
seekable salvage mode.
"""

from __future__ import annotations

import socket
from typing import List

from spark_rapids_tpu.shuffle import kudo as _kudo


class SocketStream:
    """Non-seekable read adapter over a connected socket.

    ``read(n)`` returns exactly ``n`` bytes unless the peer closed the
    connection, in which case it returns what arrived (possibly
    ``b""``) — the contract kudo's ``_stream_read`` expects.  A recv
    timeout set on the socket surfaces as ``socket.timeout`` (an
    ``OSError``), which link-level retry treats as a transient failure.
    """

    def __init__(self, sock: socket.socket):
        self._sock = sock

    def read(self, n: int) -> bytes:
        if n <= 0:
            return b""
        parts: List[bytes] = []
        remaining = n
        while remaining > 0:
            chunk = self._sock.recv(remaining)
            if not chunk:
                break  # peer closed: short read signals EOF upstream
            parts.append(chunk)
            remaining -= len(chunk)
        return b"".join(parts)

    def seekable(self) -> bool:
        return False

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def send_tables(sock: socket.socket, payload: bytes) -> int:
    """Write an already-serialized kudo stream to a socket (the
    transport frames it first; this is the raw-stream endpoint for
    unframed peer links and the socketpair tests)."""
    sock.sendall(payload)
    return len(payload)


def recv_tables(sock: socket.socket, *,
                resync: bool = False) -> List[_kudo.KudoTable]:
    """Read kudo tables straight off a socket until the peer closes —
    the non-seekable read path: deferred CRC trailers, pushback-based
    resync.  Reading to EOF is what makes the LAST table's deferred
    trailer check fire (a bounded-count read would return before its
    checksum was ever compared); framed transports that know the
    payload length up front parse the buffered bytes instead
    (distributed/transport.py)."""
    return _kudo.read_tables(SocketStream(sock), resync=resync)
