"""Device-resident kudo blob split/assemble.

Reference: shuffle_split.cu:797 (spark-rapids-jni shuffle_split),
shuffle_assemble.cu (device assemble), shuffle_split_detail.hpp:46-60
(per-partition layout math), KudoGpuSerializer.java:50 (the
splitAndSerializeToDevice (data, offsets) contract).

The reference packs per-partition kudo blobs into ONE device buffer with
device kernels because its network path consumes opaque bytes straight
from GPU memory.  This module is the TPU-native equivalent: all row/byte
payload stays in device arrays end-to-end; the host only ever touches
O(partitions x columns) scalar geometry (section sizes, cursors,
headers).  The byte movement itself is one XLA gather program over a
concatenated source pool:

  blob[j] = pool[ src_start[sec(j)] + (j - dst_start[sec(j)]) ]

with sec(j) a vectorized searchsorted over the section start table —
the same inverted-copy trick the repo's device join uses for pair
expansion.  No per-row or per-partition Python on the data path.

Byte compatibility: the produced blob is bit-for-bit the concatenation
of shuffle/kudo.py host-writer tables (which is itself byte-compatible
with the reference KudoSerializer format) — tests/test_device_split.py
asserts equality against the host writer, and either side's output can
be consumed by the other's assembler.
"""

from __future__ import annotations

from functools import partial as _partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import Kind
from spark_rapids_tpu.columns.table import Table
from spark_rapids_tpu.shuffle.schema import Field

_HEADER_FIXED = 28  # magic + 6 big-endian int32 fields


def _pad4(n):
    return (n + 3) // 4 * 4


# ------------------------------------------------------------------ pool


def _byte_view(col: Column) -> Optional[jnp.ndarray]:
    """Device u8 view of a column's data payload (LE byte image,
    identical to the host writer's .tobytes())."""
    from jax import lax

    kind = col.dtype.kind
    if kind in (Kind.LIST, Kind.STRUCT):
        return None
    data = col.data
    if data is None:
        return jnp.zeros(0, jnp.uint8)
    if kind == Kind.STRING:
        if data.dtype == jnp.uint32:   # packed chars (bytesview)
            return lax.bitcast_convert_type(data, jnp.uint8).reshape(-1)
        return data.astype(jnp.uint8)
    if kind == Kind.DECIMAL128:
        b = lax.bitcast_convert_type(data.astype(jnp.int32), jnp.uint8)
        return b.reshape(-1)
    if kind == Kind.UINT8 and data.dtype == jnp.uint32:
        # packed byte column (columns/bytesview.py)
        b = lax.bitcast_convert_type(data, jnp.uint8).reshape(-1)
        return b[: col.length]
    if data.dtype.itemsize == 1:
        return data.astype(jnp.uint8)
    b = lax.bitcast_convert_type(data, jnp.uint8)
    return b.reshape(-1)


def _packed_validity(col: Column) -> Optional[jnp.ndarray]:
    """LSB-first bit-packed validity bytes on device, +1 trailing zero
    byte so sloppy slices can read one past the packed end."""
    if col.validity is None:
        return None
    v = col.validity.astype(jnp.uint8)
    n = col.length
    nb = (n + 7) // 8
    pad = nb * 8 - n
    v = jnp.concatenate([v[:n], jnp.zeros(pad, jnp.uint8)])
    weights = jnp.asarray([1, 2, 4, 8, 16, 32, 64, 128], jnp.uint8)
    packed = (v.reshape(nb, 8) * weights[None, :]).sum(
        axis=1, dtype=jnp.uint32).astype(jnp.uint8)
    return jnp.concatenate([packed, jnp.zeros(1, jnp.uint8)])


def _offsets_bytes(col: Column) -> Optional[jnp.ndarray]:
    from jax import lax

    if col.offsets is None:
        return None
    o = col.offsets.astype(jnp.int32)
    return lax.bitcast_convert_type(o, jnp.uint8).reshape(-1)


class _FlatCol:
    """One flat (depth-first) column with its per-partition slice bounds
    and device source buffers."""

    __slots__ = ("col", "kind", "width", "has_validity", "bounds",
                 "child_bounds", "vbytes", "obytes", "dbytes")

    def __init__(self, col: Column, bounds: np.ndarray):
        self.col = col
        self.kind = col.dtype.kind
        self.width = (16 if self.kind == Kind.DECIMAL128
                      else col.dtype.size_bytes
                      if self.kind not in (Kind.STRING, Kind.LIST,
                                           Kind.STRUCT) else 0)
        self.has_validity = col.validity is not None
        self.bounds = bounds            # (P+1,) int64 row bounds
        self.child_bounds = None        # (P+1,) for string/list
        self.vbytes = _packed_validity(col)
        self.obytes = _offsets_bytes(col)
        self.dbytes = _byte_view(col)


def _flatten_for_split(columns: Sequence[Column], bounds: np.ndarray
                       ) -> List[_FlatCol]:
    """Depth-first flatten with per-partition bounds per flat column;
    list/string child bounds come from one (P+1)-element device gather
    of the offsets array (the only host syncs on the split path)."""
    out: List[_FlatCol] = []

    def rec(col: Column, b: np.ndarray):
        fc = _FlatCol(col, b)
        out.append(fc)
        if fc.kind in (Kind.STRING, Kind.LIST):
            if col.offsets is not None and col.length > 0:
                idx = jnp.asarray(np.clip(b, 0, col.length))
                cb = np.asarray(jnp.take(col.offsets.astype(jnp.int64),
                                         idx)).astype(np.int64)
            else:
                cb = np.zeros_like(b)
            fc.child_bounds = cb
            if fc.kind == Kind.LIST:
                rec(col.children[0], cb)
        elif fc.kind == Kind.STRUCT:
            for ch in col.children:
                rec(ch, b)

    for c in columns:
        rec(c, bounds)
    return out


# --------------------------------------------------------------- kernels


def _pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1)).bit_length()


@_partial(jax.jit, static_argnames=("capacity",))
def _gather_sections_kernel(pool, dst_starts, src_starts, total,
                            capacity: int):
    j = jnp.arange(capacity, dtype=jnp.int64)
    k = jnp.searchsorted(dst_starts, j, side="right") - 1
    k = jnp.clip(k, 0, dst_starts.shape[0] - 1)
    src = jnp.clip(src_starts[k] + (j - dst_starts[k]), 0,
                   pool.shape[0] - 1)
    return jnp.where(j < total, pool[src], jnp.uint8(0))


def _gather_sections(pool: jnp.ndarray, dst_starts: np.ndarray,
                     src_starts: np.ndarray, total: int) -> jnp.ndarray:
    """Device bytes [0,total) copied section-wise from pool (pow2-padded
    compile capacity so repeated shuffles reuse the XLA program)."""
    if total == 0:
        return jnp.zeros(0, jnp.uint8)
    cap = _pow2(total)
    out = _gather_sections_kernel(
        pool, jnp.asarray(dst_starts, dtype=jnp.int64),
        jnp.asarray(src_starts, dtype=jnp.int64),
        jnp.int64(total), cap)
    return out[:total]


# ------------------------------------------------------------------ split


def device_shuffle_split(table: Table, splits: Sequence[int]
                         ) -> Tuple[jnp.ndarray, np.ndarray]:
    """Split at row boundaries and pack every partition's kudo table
    into ONE device u8 buffer; returns (device blob, int64 partition
    offsets) — the KudoGpuSerializer.splitAndSerializeToDevice contract
    (KudoGpuSerializer.java:50), byte-identical to the host
    shuffle_split (shuffle/split_assemble.py)."""
    bounds = np.asarray([0] + list(splits) + [table.num_rows], np.int64)
    P = len(bounds) - 1
    flats = _flatten_for_split(table.columns, bounds)
    C = len(flats)
    hs = _HEADER_FIXED + (C + 7) // 8

    ro = np.stack([f.bounds[:-1] for f in flats])          # (C, P)
    rc = np.stack([np.diff(f.bounds) for f in flats])      # (C, P)

    # --- per-section lengths (C, P), DFS col order -------------------
    vlen = np.zeros((C, P), np.int64)
    olen = np.zeros((C, P), np.int64)
    dlen = np.zeros((C, P), np.int64)
    for c, f in enumerate(flats):
        if f.has_validity:
            vlen[c] = np.where(rc[c] > 0, (ro[c] % 8 + rc[c] + 7) // 8, 0)
        if f.kind in (Kind.STRING, Kind.LIST):
            olen[c] = np.where(rc[c] > 0, (rc[c] + 1) * 4, 0)
            if f.kind == Kind.STRING:
                dlen[c] = np.diff(f.child_bounds)
        elif f.kind != Kind.STRUCT:
            dlen[c] = rc[c] * f.width

    vsum = vlen.sum(axis=0)
    osum = olen.sum(axis=0)
    dsum = dlen.sum(axis=0)
    # header+validity padded together to 4B (kudo._pad_validity)
    vpad = (4 - (vsum + hs) % 4) % 4
    opad = (4 - osum % 4) % 4
    dpad = (4 - dsum % 4) % 4
    total = (vsum + vpad) + (osum + opad) + (dsum + dpad)
    part_sizes = hs + total
    part_starts = np.zeros(P + 1, np.int64)
    np.cumsum(part_sizes, out=part_starts[1:])

    # --- headers (host: O(P) bytes) ----------------------------------
    headers = np.zeros((P, hs), np.uint8)
    fields_be = np.stack([bounds[:-1], np.diff(bounds), vsum + vpad,
                          osum + opad, total,
                          np.full(P, C, np.int64)]).astype(">i4")
    headers[:, 0:4] = np.frombuffer(b"KUD0", np.uint8)
    headers[:, 4:28] = fields_be.T.copy().view(np.uint8).reshape(P, 24)
    for c, f in enumerate(flats):
        if f.has_validity:
            headers[:, 28 + c // 8] |= (
                (rc[c] > 0).astype(np.uint8) << (c % 8))

    # --- source pool -------------------------------------------------
    parts = [jnp.zeros(8, jnp.uint8),
             jnp.asarray(headers.reshape(-1))]
    cursor = 8 + P * hs
    vbase = np.zeros(C, np.int64)
    obase = np.zeros(C, np.int64)
    dbase = np.zeros(C, np.int64)
    for c, f in enumerate(flats):
        for base, buf in ((vbase, f.vbytes), (obase, f.obytes),
                          (dbase, f.dbytes)):
            if buf is not None and buf.shape[0] > 0:
                base[c] = cursor
                parts.append(buf)
                cursor += buf.shape[0]
    pool = jnp.concatenate(parts)

    # --- section tables: order per partition = header, validity slices,
    # vpad, offset buffers, opad, data buffers, dpad ------------------
    sec_len: List[np.ndarray] = [np.full(P, hs, np.int64)]
    sec_src: List[np.ndarray] = [8 + np.arange(P, dtype=np.int64) * hs]
    for c, f in enumerate(flats):
        if f.has_validity:
            sec_len.append(vlen[c])
            sec_src.append(vbase[c] + ro[c] // 8)
    sec_len.append(vpad)
    sec_src.append(np.zeros(P, np.int64))
    for c, f in enumerate(flats):
        if f.kind in (Kind.STRING, Kind.LIST):
            sec_len.append(olen[c])
            sec_src.append(obase[c] + ro[c] * 4)
    sec_len.append(opad)
    sec_src.append(np.zeros(P, np.int64))
    for c, f in enumerate(flats):
        if f.kind == Kind.STRING:
            sec_len.append(dlen[c])
            sec_src.append(dbase[c] + f.child_bounds[:-1])
        elif f.width > 0:
            sec_len.append(dlen[c])
            sec_src.append(dbase[c] + ro[c] * f.width)
    sec_len.append(dpad)
    sec_src.append(np.zeros(P, np.int64))

    lens = np.stack(sec_len, axis=1).reshape(-1)       # (P*S,) in order
    srcs = np.stack(sec_src, axis=1).reshape(-1)
    dsts = np.zeros(lens.shape[0], np.int64)
    np.cumsum(lens[:-1], out=dsts[1:])
    blob_total = int(dsts[-1] + lens[-1])
    assert blob_total == int(part_starts[-1])

    blob = _gather_sections(pool, dsts, srcs, blob_total)
    return blob, part_starts


# --------------------------------------------------------------- assemble


@_partial(jax.jit, static_argnames=("capacity",))
def _gather_i32_kernel(blob, byte_pos, capacity: int):
    """int32 values from (unaligned) LE byte positions."""
    p = byte_pos[:capacity]
    b = [blob[jnp.clip(p + i, 0, blob.shape[0] - 1)].astype(jnp.uint32)
         for i in range(4)]
    v = b[0] | (b[1] << 8) | (b[2] << 16) | (b[3] << 24)
    return v.astype(jnp.int32)


def _gather_i32(blob: jnp.ndarray, byte_pos: np.ndarray) -> np.ndarray:
    if len(byte_pos) == 0:
        return np.zeros(0, np.int32)
    cap = _pow2(len(byte_pos))
    padded = np.concatenate(
        [byte_pos, np.zeros(cap - len(byte_pos), np.int64)])
    out = _gather_i32_kernel(blob, jnp.asarray(padded), cap)
    return np.asarray(out)[: len(byte_pos)]


class _AsmCol:
    """Per-flat-column assemble geometry (all O(P) host scalars)."""

    __slots__ = ("field", "kind", "width", "ro", "rc", "vstart", "has_v",
                 "ostart", "dstart", "dlen", "first", "last")

    def __init__(self, field, P):
        self.field = field
        self.kind = field.dtype.kind
        self.width = (16 if self.kind == Kind.DECIMAL128
                      else field.dtype.size_bytes
                      if self.kind not in (Kind.STRING, Kind.LIST,
                                           Kind.STRUCT) else 0)
        self.ro = np.zeros(P, np.int64)
        self.rc = np.zeros(P, np.int64)
        self.vstart = np.zeros(P, np.int64)
        self.has_v = np.zeros(P, bool)
        self.ostart = np.zeros(P, np.int64)
        self.dstart = np.zeros(P, np.int64)
        self.dlen = np.zeros(P, np.int64)
        self.first = np.zeros(P, np.int64)
        self.last = np.zeros(P, np.int64)


def _flat_fields(fields: Sequence[Field]) -> List[Field]:
    out: List[Field] = []

    def rec(f: Field):
        out.append(f)
        for ch in f.children:
            rec(ch)

    for f in fields:
        rec(f)
    return out


def device_shuffle_assemble(fields: Sequence[Field], blob: jnp.ndarray,
                            offsets: np.ndarray) -> Table:
    """Reassemble a packed device blob (from device_shuffle_split or a
    byte-identical host writer) into one device Table — the
    shuffle_assemble contract (shuffle_split.hpp:183).  Headers and
    section cursors are parsed host-side (O(P x C) scalars); every data
    byte moves device-to-device."""
    offsets = np.asarray(offsets, np.int64)
    P = len(offsets) - 1
    flat = _flat_fields(fields)
    C = len(flat)
    hs = _HEADER_FIXED + (C + 7) // 8
    blob = blob.astype(jnp.uint8)

    if P == 0 or not fields:
        # degenerate inputs: host stream reader directly (NOT the
        # split_assemble router, which would recurse back here)
        import io

        from spark_rapids_tpu.shuffle import kudo

        kts = []
        for i in range(P):
            stream = io.BytesIO(
                bytes(np.asarray(blob[offsets[i]:offsets[i + 1]])))
            while True:
                kt = kudo.read_one_table(stream)
                if kt is None:
                    break
                kts.append(kt)
        return kudo.merge_to_table(kts, fields)

    # --- headers: one small gather + readback ------------------------
    hidx = (offsets[:-1, None] + np.arange(hs)[None, :]).reshape(-1)
    hbytes = np.asarray(
        jnp.take(blob, jnp.asarray(hidx), mode="clip")).reshape(P, hs)
    if not (hbytes[:, 0:4] == np.frombuffer(b"KUD0", np.uint8)).all():
        raise ValueError("bad kudo magic in device blob")
    hdr = hbytes[:, 4:28].copy().view(">i4").reshape(P, 6).astype(np.int64)
    row_off, num_rows, validity_len, offset_len = (
        hdr[:, 0], hdr[:, 1], hdr[:, 2], hdr[:, 3])
    if not (hs + hdr[:, 4] == np.diff(offsets)).all():
        # partition slots holding multiple concatenated kudo tables (or
        # trailing bytes) need the host stream reader
        raise ValueError("partition is not a single kudo table")
    bitset = hbytes[:, 28:]
    body = offsets[:-1] + hs

    # --- DFS cursor walk (mirrors kudo._parse_table, vectorized over
    # partitions; list/string first+last raw offsets are one 2P-element
    # device gather per such column) ----------------------------------
    cols = [_AsmCol(f, P) for f in flat]
    vcur = np.zeros(P, np.int64)
    ocur = np.zeros(P, np.int64)
    dcur = np.zeros(P, np.int64)
    idx = [0]

    def walk(f: Field, ro: np.ndarray, rc: np.ndarray):
        c = idx[0]
        idx[0] += 1
        ac = cols[c]
        ac.ro, ac.rc = ro, rc
        ac.has_v = ((bitset[np.arange(P), c // 8] >> (c % 8)) & 1
                    ).astype(bool) & (rc > 0)
        nbytes = np.where(ac.has_v, (ro % 8 + rc + 7) // 8, 0)
        ac.vstart = body + vcur
        vcur[:] += nbytes
        if ac.kind in (Kind.STRING, Kind.LIST):
            has_o = rc > 0
            ac.ostart = body + validity_len + ocur
            pos = np.concatenate([ac.ostart, ac.ostart + rc * 4])
            vals = _gather_i32(blob, pos).astype(np.int64)
            ac.first = np.where(has_o, vals[:P], 0)
            ac.last = np.where(has_o, vals[P:], 0)
            ocur[:] += np.where(has_o, (rc + 1) * 4, 0)
            if ac.kind == Kind.STRING:
                ac.dstart = body + validity_len + offset_len + dcur
                ac.dlen = ac.last - ac.first
                dcur[:] += ac.dlen
            else:
                walk(f.children[0], ac.first, ac.last - ac.first)
        elif ac.kind == Kind.STRUCT:
            for ch in f.children:
                walk(ch, ro, rc)
        else:
            ac.dstart = body + validity_len + offset_len + dcur
            ac.dlen = rc * ac.width
            dcur[:] += ac.dlen

    for f in fields:
        walk(f, row_off.copy(), num_rows.copy())

    # --- device output buffers ---------------------------------------
    from jax import lax

    def out_validity(ac: _AsmCol) -> Optional[jnp.ndarray]:
        if not ac.has_v.any():
            return None
        R = int(ac.rc.sum())
        rowstart = np.zeros(P, np.int64)
        np.cumsum(ac.rc[:-1], out=rowstart[1:])
        return _validity_rows_kernel(
            blob, jnp.asarray(rowstart), jnp.asarray(ac.vstart),
            jnp.asarray(ac.ro % 8), jnp.asarray(ac.has_v),
            _pow2(R))[:R]

    def out_databytes(ac: _AsmCol) -> jnp.ndarray:
        dst = np.zeros(P, np.int64)
        np.cumsum(ac.dlen[:-1], out=dst[1:])
        return _gather_sections(blob, dst, ac.dstart,
                                int(ac.dlen.sum()))

    def out_offsets(ac: _AsmCol) -> jnp.ndarray:
        L = 1 + int(ac.rc.sum())
        starts = np.zeros(P, np.int64)
        np.cumsum(ac.rc[:-1], out=starts[1:])
        starts += 1                      # first value slot per partition
        charbase = np.zeros(P, np.int64)
        np.cumsum((ac.last - ac.first)[:-1], out=charbase[1:])
        return _offsets_rebase_kernel(
            blob, jnp.asarray(starts), jnp.asarray(ac.ostart),
            jnp.asarray(ac.first - charbase), jnp.int64(L),
            _pow2(L))[:L]

    def build(f: Field) -> Column:
        c = idx[0]
        idx[0] += 1
        ac = cols[c]
        rows = int(ac.rc.sum())
        mask = out_validity(ac)
        kind = ac.kind
        if kind == Kind.STRING:
            return Column(f.dtype, rows, data=out_databytes(ac),
                          validity=mask, offsets=out_offsets(ac))
        if kind == Kind.LIST:
            offs = out_offsets(ac)
            child = build(f.children[0])
            return Column(f.dtype, rows, validity=mask, offsets=offs,
                          children=(child,))
        if kind == Kind.STRUCT:
            children = tuple(build(ch) for ch in f.children)
            return Column(f.dtype, rows, validity=mask,
                          children=children)
        raw = out_databytes(ac)
        if kind == Kind.DECIMAL128:
            data = lax.bitcast_convert_type(
                raw.reshape(rows, 4, 4), jnp.int32).reshape(rows, 4)
        elif ac.width == 1:
            data = raw.astype(_np_to_jnp(f.dtype.np_dtype))
        else:
            data = lax.bitcast_convert_type(
                raw.reshape(rows, ac.width),
                _np_to_jnp(_storage_np(f.dtype)))
        return Column(f.dtype, rows, data=data, validity=mask)

    idx[0] = 0
    return Table([build(f) for f in fields])


def _storage_np(dtype) -> np.dtype:
    # FLOAT64 columns store raw bits as uint64 (columns/column.py)
    if dtype.kind == Kind.FLOAT64:
        return np.dtype(np.uint64)
    return dtype.np_dtype


def _np_to_jnp(npdt):
    return jnp.dtype(np.dtype(npdt))


@_partial(jax.jit, static_argnames=("capacity",))
def _validity_rows_kernel(blob, rowstart, vstart, bitoff, has_v,
                          capacity: int):
    r = jnp.arange(capacity, dtype=jnp.int64)
    p = jnp.clip(jnp.searchsorted(rowstart, r, side="right") - 1, 0,
                 rowstart.shape[0] - 1)
    local = r - rowstart[p]
    bitpos = bitoff[p] + local
    byte = blob[jnp.clip(vstart[p] + bitpos // 8, 0, blob.shape[0] - 1)]
    bit = (byte >> (bitpos % 8).astype(jnp.uint8)) & 1
    return jnp.where(has_v[p], bit, jnp.uint8(1))


@_partial(jax.jit, static_argnames=("capacity",))
def _offsets_rebase_kernel(blob, starts, ostart, base, L,
                           capacity: int):
    i = jnp.arange(capacity, dtype=jnp.int64)
    p = jnp.clip(jnp.searchsorted(starts, i, side="right") - 1, 0,
                 starts.shape[0] - 1)
    jloc = i - starts[p] + 1
    pos = ostart[p] + 4 * jloc
    b = [blob[jnp.clip(pos + k, 0, blob.shape[0] - 1)].astype(jnp.uint32)
         for k in range(4)]
    raw = (b[0] | (b[1] << 8) | (b[2] << 16) | (b[3] << 24)
           ).astype(jnp.int64)
    out = raw - base[p]
    return jnp.where(i == 0, jnp.int64(0), out).astype(jnp.int32)
