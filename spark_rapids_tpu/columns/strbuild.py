"""Shared flat-span STRING column builder.

Every device string engine ends the same way: per-row (start, len)
spans into some flat u8 source, materialized as one vectorized byte
gather.  This is THE single implementation (r4 review: four divergent
copies had grown in parse_uri_device / protobuf_device /
from_json_device / raw_map_device); per-row host fallback values splice
into the byte buffer directly — never a whole-column Python round-trip.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column


def build_string_column(src: np.ndarray, starts: np.ndarray,
                        lens: np.ndarray,
                        valid: Optional[np.ndarray] = None,
                        host_patch: Optional[Dict[int, Optional[str]]]
                        = None,
                        fill_rows: Optional[np.ndarray] = None,
                        fill_text: Optional[str] = None) -> Column:
    """STRING column from per-element spans into a flat u8 buffer.

    src:    flat uint8 source (flatten a padded matrix with
            starts = row * row_width + col for matrix sources).
    starts/lens: per-element spans; elements with valid=False (or a
            host_patch value of None) become null rows.
    host_patch: {index: str|None} — values produced by a host fallback
            path, written directly into the output bytes (per-row
            Python; for RARE fallback rows).
    fill_rows/fill_text: bool mask of rows that take the CONSTANT
            fill_text (vectorized tile — for schema defaults that may
            cover most of the column).
    """
    n = len(starts)
    lens = np.asarray(lens, np.int64)
    starts = np.asarray(starts, np.int64)
    validity = (np.ones(n, bool) if valid is None
                else np.asarray(valid).astype(bool).copy())

    byte_lens = np.where(validity, np.maximum(lens, 0), 0)
    fill_b = None
    if fill_rows is not None and fill_text is not None:
        fill_rows = np.asarray(fill_rows).astype(bool)
        fill_b = np.frombuffer(fill_text.encode("utf-8"), np.uint8)
        validity = validity | fill_rows
        byte_lens = np.where(fill_rows, len(fill_b), byte_lens)
    host_bytes: Dict[int, bytes] = {}
    if host_patch:
        for i, s in host_patch.items():
            if s is None:
                validity[i] = False
                byte_lens[i] = 0
            else:
                b = s.encode("utf-8")
                host_bytes[i] = b
                validity[i] = True
                byte_lens[i] = len(b)

    offs = np.concatenate([[0], np.cumsum(byte_lens)]).astype(np.int32)
    total = int(offs[-1])
    buf = np.zeros(total, np.uint8)
    if total:
        dev_mask = byte_lens > 0
        if fill_b is not None:
            dev_mask &= ~fill_rows
        for i in host_bytes:
            dev_mask[i] = False
        didx = np.nonzero(dev_mask)[0]
        if didx.size:
            seg_len = byte_lens[didx]
            cum = np.cumsum(seg_len)
            flat = np.arange(int(cum[-1]))
            seg = np.searchsorted(cum, flat, side="right")
            within = flat - np.concatenate([[0], cum[:-1]])[seg]
            buf[offs[didx][seg] + within] = src[
                np.minimum(starts[didx][seg] + within,
                           max(len(src) - 1, 0))]
        if fill_b is not None and len(fill_b):
            fidx = np.nonzero(fill_rows)[0]
            if fidx.size:
                pos = (np.repeat(offs[fidx].astype(np.int64),
                                 len(fill_b))
                       + np.tile(np.arange(len(fill_b)), fidx.size))
                buf[pos] = np.tile(fill_b, fidx.size)
        for i, b in host_bytes.items():
            buf[offs[i]:offs[i] + len(b)] = np.frombuffer(b, np.uint8)

    v = None if validity.all() else jnp.asarray(
        validity.astype(np.uint8))
    return Column(dtypes.STRING, n, data=jnp.asarray(buf),
                  validity=v, offsets=jnp.asarray(offs))
