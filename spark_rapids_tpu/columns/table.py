"""Table: an ordered collection of equal-length Columns (cudf::table_view
equivalent).  Registered as a pytree so tables flow through jit/shard_map."""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax

from spark_rapids_tpu.columns.column import Column


class Table:
    __slots__ = ("columns", "names")

    def __init__(self, columns: Sequence[Column],
                 names: Optional[Sequence[str]] = None):
        cols = list(columns)
        if cols:
            n = cols[0].length
            for c in cols:
                if c.length != n:
                    raise ValueError(
                        f"column lengths differ: {c.length} vs {n}")
        self.columns: List[Column] = cols
        self.names = list(names) if names is not None else None

    @property
    def num_rows(self) -> int:
        return self.columns[0].length if self.columns else 0

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def column(self, i) -> Column:
        if isinstance(i, str):
            if self.names is None:
                raise KeyError("table has no column names")
            i = self.names.index(i)
        return self.columns[i]

    def __getitem__(self, i) -> Column:
        return self.column(i)

    def __len__(self) -> int:
        return self.num_rows

    def __repr__(self) -> str:
        return (f"Table(rows={self.num_rows}, "
                f"cols=[{', '.join(c.dtype.kind for c in self.columns)}])")

    def to_pylist(self) -> list:
        cols = [c.to_pylist() for c in self.columns]
        return [tuple(c[i] for c in cols) for i in range(self.num_rows)]


def _tbl_flatten(t: Table):
    names = tuple(t.names) if t.names is not None else None
    return (tuple(t.columns),), (names,)


def _tbl_unflatten(aux, dyn):
    (names,) = aux
    (columns,) = dyn
    return Table(list(columns), list(names) if names is not None else None)


jax.tree_util.register_pytree_node(Table, _tbl_flatten, _tbl_unflatten)
