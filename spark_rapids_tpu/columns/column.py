"""Arrow-layout device columns on JAX arrays.

The reference operates on `cudf::column_view` (data ptr, packed validity bits,
int32 offsets, children).  Here a Column is an immutable pytree of jax arrays:

  data      fixed-width: (rows,) natural dtype — EXCEPT float64, which is
            stored as (rows,) uint64 raw IEEE754 bits: TPUs have no native
            f64 (the XLA X64 rewrite demotes f64 compute to f32, and
            f64<->u64 bitcasts don't lower at all), so the exact Spark
            DOUBLE bit patterns live in integer lanes and ops that need
            true f64 arithmetic decode explicitly (utils/floats.py).
            string:      (chars,) uint8 — the flattened char buffer
            decimal128:  (rows, 4) int32 little-endian limbs
  validity  (rows,) uint8, 1 = valid; None means all rows valid.  Unpacked on
            device (packed bits don't vectorize on 8x128 lanes); packed only at
            serialization boundaries (Kudo / Arrow interop).
  offsets   (rows+1,) int32 for STRING and LIST (CUDF_LARGE_STRINGS_DISABLED
            semantics: offsets are int32, <=2^31 chars per column).
  children  LIST: (element column,); STRUCT: field columns.

Columns are registered as jax pytrees, so whole Tables flow through jit /
shard_map unchanged.  Ops never mutate; they build new Columns.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.dtypes import DType, Kind


class Column:
    __slots__ = ("dtype", "length", "data", "validity", "offsets", "children")

    def __init__(
        self,
        dtype: DType,
        length: int,
        data: Optional[jnp.ndarray] = None,
        validity: Optional[jnp.ndarray] = None,
        offsets: Optional[jnp.ndarray] = None,
        children: Tuple["Column", ...] = (),
    ):
        self.dtype = dtype
        self.length = int(length)
        self.data = data
        self.validity = validity
        self.offsets = offsets
        self.children = tuple(children)

    # ------------------------------------------------------------------ misc

    def __len__(self) -> int:
        return self.length

    def __repr__(self) -> str:
        return f"Column({self.dtype!r}, length={self.length})"

    @property
    def has_validity(self) -> bool:
        return self.validity is not None

    def null_count(self) -> int:
        """Host-syncing null count (test/debug use; not for jitted paths)."""
        if self.validity is None:
            return 0
        return int(self.length - np.asarray(self.validity[: self.length]).sum())

    def valid_mask(self) -> jnp.ndarray:
        """(rows,) bool mask, materializing all-valid if validity is None."""
        if self.validity is None:
            return jnp.ones((self.length,), dtype=jnp.bool_)
        return self.validity.astype(jnp.bool_)

    # ---------------------------------------------------------- constructors

    @staticmethod
    def from_numpy(arr: np.ndarray, validity: Optional[np.ndarray] = None,
                   dtype: Optional[DType] = None) -> "Column":
        arr = np.asarray(arr)
        dt = dtype if dtype is not None else dtypes.from_numpy(arr.dtype)
        host = arr.astype(dt.np_dtype, copy=False)
        if dt.kind == Kind.FLOAT64:
            host = host.view(np.uint64)  # device buffer holds raw bits
        data = jnp.asarray(host)
        v = None
        if validity is not None:
            v = jnp.asarray(np.asarray(validity).astype(np.uint8))
        return Column(dt, arr.shape[0], data=data, validity=v)

    @staticmethod
    def from_pylist(values: Sequence, dtype: DType) -> "Column":
        """Build a column from a python list; None entries become nulls."""
        if dtype.is_string:
            return Column.from_strings(values)
        if dtype.kind == Kind.DECIMAL128:
            return Column._decimal128_from_pylist(values, dtype)
        n = len(values)
        has_null = any(v is None for v in values)
        np_dt = dtype.np_dtype
        fill = 0
        host = np.array([fill if v is None else v for v in values], dtype=np_dt)
        if dtype.kind == Kind.FLOAT64:
            host = host.view(np.uint64)
        v = None
        if has_null:
            v = jnp.asarray(
                np.array([0 if x is None else 1 for x in values], np.uint8))
        return Column(dtype, n, data=jnp.asarray(host), validity=v)

    @staticmethod
    def _decimal128_from_pylist(values: Sequence, dtype: DType) -> "Column":
        """(rows, 4) int32 little-endian limbs from python ints (the unscaled
        decimal value), two's complement across the 128-bit word."""
        n = len(values)
        limbs = np.zeros((n, 4), dtype=np.int32)
        vmask = np.ones(n, dtype=np.uint8)
        for i, v in enumerate(values):
            if v is None:
                vmask[i] = 0
                continue
            u = int(v) & ((1 << 128) - 1)
            for j in range(4):
                limbs[i, j] = np.uint32((u >> (32 * j)) & 0xFFFFFFFF).astype(
                    np.int32)
        validity = None if vmask.all() else jnp.asarray(vmask)
        return Column(dtype, n, data=jnp.asarray(limbs), validity=validity)

    @staticmethod
    def from_strings(values: Sequence[Optional[Union[str, bytes]]]) -> "Column":
        n = len(values)
        bufs: List[bytes] = []
        offs = np.zeros(n + 1, dtype=np.int32)
        vmask = np.ones(n, dtype=np.uint8)
        total = 0
        for i, s in enumerate(values):
            if s is None:
                vmask[i] = 0
                b = b""
            else:
                b = s.encode("utf-8") if isinstance(s, str) else bytes(s)
            bufs.append(b)
            total += len(b)
            offs[i + 1] = total
        chars = np.frombuffer(b"".join(bufs), dtype=np.uint8).copy()
        validity = None if vmask.all() else jnp.asarray(vmask)
        return Column(
            dtypes.STRING, n,
            data=jnp.asarray(chars),
            validity=validity,
            offsets=jnp.asarray(offs),
        )

    @staticmethod
    def make_list(offsets: np.ndarray, child: "Column",
                  validity: Optional[np.ndarray] = None) -> "Column":
        offs = jnp.asarray(np.asarray(offsets, dtype=np.int32))
        v = None if validity is None else jnp.asarray(
            np.asarray(validity).astype(np.uint8))
        return Column(dtypes.LIST, len(offsets) - 1, validity=v,
                      offsets=offs, children=(child,))

    @staticmethod
    def make_list_from_parts(offsets: jnp.ndarray, byte_data: jnp.ndarray,
                             validity: Optional[jnp.ndarray] = None,
                             nbytes: Optional[int] = None) -> "Column":
        """LIST<UINT8> column from device offsets + flat byte buffer (the
        shape JCUDF rows and kudo blobs take).  `byte_data` may be uint8 or
        packed uint32 LE words (columns/bytesview.py) — uint8 minor dims
        tile terribly on TPU, so bulk producers pass words."""
        if byte_data.dtype == jnp.uint32:
            if nbytes is None:
                raise ValueError(
                    "packed uint32 byte_data requires explicit nbytes (the "
                    "word buffer may carry up to 3 tail pad bytes)")
            child = Column(dtypes.UINT8, nbytes, data=byte_data)
        else:
            child = Column(dtypes.UINT8, int(byte_data.shape[0]),
                           data=byte_data.astype(jnp.uint8))
        return Column(dtypes.LIST, int(offsets.shape[0]) - 1,
                      validity=validity, offsets=offsets.astype(jnp.int32),
                      children=(child,))

    @staticmethod
    def make_struct(length: int, children: Sequence["Column"],
                    validity: Optional[np.ndarray] = None) -> "Column":
        v = None if validity is None else jnp.asarray(
            np.asarray(validity).astype(np.uint8))
        return Column(dtypes.STRUCT, length, validity=v,
                      children=tuple(children))

    # ------------------------------------------------------------- host view

    def to_numpy(self) -> np.ndarray:
        """Data buffer to host in the logical dtype (no null masking)."""
        if self.data is None:
            raise ValueError(f"{self.dtype} column has no data buffer")
        host = np.asarray(self.data)
        if self.dtype.kind == Kind.FLOAT64:
            return host.view(np.float64)
        if self.dtype.kind == Kind.UINT8 and host.dtype == np.uint32:
            # packed byte column (columns/bytesview.py)
            return host.view(np.uint8)[: self.length]
        return host

    def to_pylist(self) -> list:
        """Host round-trip with None for nulls (test/debug use)."""
        mask = (np.ones(self.length, bool) if self.validity is None
                else np.asarray(self.validity).astype(bool)[: self.length])
        if self.dtype.is_string:
            chars = np.asarray(self.data).tobytes()
            offs = np.asarray(self.offsets)
            out: list = []
            for i in range(self.length):
                if not mask[i]:
                    out.append(None)
                else:
                    out.append(chars[offs[i]: offs[i + 1]].decode(
                        "utf-8", errors="replace"))
            return out
        if self.dtype.kind == Kind.LIST:
            offs = np.asarray(self.offsets)
            child = self.children[0].to_pylist()
            return [child[offs[i]: offs[i + 1]] if mask[i] else None
                    for i in range(self.length)]
        if self.dtype.kind == Kind.STRUCT:
            cols = [c.to_pylist() for c in self.children]
            return [tuple(c[i] for c in cols) if mask[i] else None
                    for i in range(self.length)]
        host = self.to_numpy()
        if self.dtype.kind == Kind.BOOL8:
            return [bool(host[i]) if mask[i] else None
                    for i in range(self.length)]
        if self.dtype.kind == Kind.DECIMAL128:
            out = []
            limbs = host.astype(np.uint32).astype(object)
            for i in range(self.length):
                if not mask[i]:
                    out.append(None)
                    continue
                u = sum(int(limbs[i, j]) << (32 * j) for j in range(4))
                if u >= 1 << 127:
                    u -= 1 << 128
                out.append(u)  # unscaled value
            return out
        return [host[i].item() if mask[i] else None
                for i in range(self.length)]

    # ------------------------------------------------------- string helpers

    def string_lengths(self) -> jnp.ndarray:
        """(rows,) int32 byte length per string row."""
        assert self.dtype.is_string
        return self.offsets[1:] - self.offsets[:-1]

    def max_string_length(self) -> int:
        """Host-syncing max byte length (used to size padded kernels)."""
        assert self.dtype.is_string
        if self.length == 0:
            return 0
        return int(np.asarray(self.string_lengths()).max())

    def to_padded_chars(self, pad_to: Optional[int] = None,
                        fill: int = 0) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Dense (rows, pad_to) uint8 char matrix + (rows,) int32 lengths.

        The workhorse representation for TPU string kernels: fixed shape so
        XLA can tile it; `fill` bytes beyond each row's length.  Memory cost
        rows*pad_to — callers chunk via ops budgets for long tails (the
        reference's scratch-budget pattern, SURVEY.md §3.4).
        """
        assert self.dtype.is_string
        lens = self.string_lengths()
        if pad_to is None:
            pad_to = max(1, self.max_string_length())
        starts = self.offsets[:-1]
        idx = starts[:, None] + jnp.arange(pad_to, dtype=jnp.int32)[None, :]
        in_range = idx < self.offsets[1:, None]
        idx = jnp.clip(idx, 0, max(int(self.data.shape[0]) - 1, 0))
        chars = jnp.where(in_range,
                          self.data[idx] if self.data.shape[0] else
                          jnp.zeros_like(idx, dtype=jnp.uint8),
                          jnp.uint8(fill))
        return chars.astype(jnp.uint8), lens


def _col_flatten(c: Column):
    dyn = (c.data, c.validity, c.offsets, c.children)
    aux = (c.dtype, c.length)
    return dyn, aux


def _col_unflatten(aux, dyn):
    dtype, length = aux
    data, validity, offsets, children = dyn
    return Column(dtype, length, data=data, validity=validity,
                  offsets=offsets, children=children)


jax.tree_util.register_pytree_node(Column, _col_flatten, _col_unflatten)
