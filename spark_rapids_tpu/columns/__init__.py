from spark_rapids_tpu.columns.dtypes import DType  # noqa: F401
from spark_rapids_tpu.columns.column import Column  # noqa: F401
from spark_rapids_tpu.columns.table import Table  # noqa: F401
