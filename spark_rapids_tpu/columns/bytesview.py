"""Packed byte buffers: device byte semantics over uint32 word storage.

TPU tiling makes narrow uint8 shapes catastrophically expensive: a
bitcast_convert_type(u32) -> u8[N,4] output is laid out with the 4-wide
minor dim padded to 128 lanes (observed: 32x HBM expansion, OOM at 512MB
logical).  So big byte buffers (JCUDF rows, kudo blobs) are carried as
uint32 words in little-endian byte order, and byte-level access happens
through shifts — identical memory image to the u8 buffer when viewed on
host (np .view(np.uint8)).

Convention: a Column of dtype UINT8 whose `.data.dtype` is uint32 is a
"packed" byte column — `length` is the logical byte count and `data` has
ceil(length/4) words (tail bytes zero).  Helpers here are the only code
that needs to know.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

_U8 = jnp.uint8
_U32 = jnp.uint32
_I32 = jnp.int32


def is_packed(data) -> bool:
    return data is not None and data.dtype == jnp.uint32


def byte_gather(data: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """data[idx] for byte index arrays, whether data is u8 or packed u32.
    Out-of-range indices must be pre-clipped by the caller."""
    if not is_packed(data):
        return data[idx]
    w = data[idx // 4]
    return ((w >> ((idx % 4) * 8).astype(_U32)) & _U32(0xFF)).astype(_U8)


def to_host_bytes(data, nbytes: int) -> bytes:
    """Materialize the logical byte string on host."""
    if data is None:
        return b""
    host = np.asarray(data)
    if host.dtype == np.uint32:
        return host.view("<u4").astype("<u4").tobytes()[:nbytes]
    return host.tobytes()[:nbytes]


def pack_u8_array(host: np.ndarray) -> np.ndarray:
    """Host uint8 array -> host uint32 LE words (zero-padded tail)."""
    n = host.shape[0]
    pad = (-n) % 4
    if pad:
        host = np.concatenate([host, np.zeros(pad, np.uint8)])
    return host.view("<u4").copy()
