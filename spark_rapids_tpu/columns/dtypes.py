"""Column data types for the TPU columnar engine.

Mirrors the subset of cudf type ids the reference library actually operates on
(see SURVEY.md §2.3): fixed-width numerics, bool, strings (int32 offsets only,
per CUDF_LARGE_STRINGS_DISABLED in the reference build: build/buildcpp.sh:118),
timestamps/dates, decimal 32/64/128, and nested LIST/STRUCT.

TPU-first choices:
  * int64/float64 require jax x64 mode — enabled at import here because Spark
    semantics are 64-bit throughout (BIGINT, DOUBLE, timestamps in micros).
  * decimal128 has no hardware type; it is carried as a (rows, 4) int32 limb
    array (little-endian limbs, two's complement), per SURVEY.md §7 item 7.
  * validity is an unpacked per-row mask on device (packed Arrow bits are
    hostile to 8x128 vector lanes); packing happens only at serialization
    boundaries (shuffle/Kudo, host Arrow interop).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import numpy as np

jax.config.update("jax_enable_x64", True)


class Kind:
    """Type-kind tags, roughly cudf type_id equivalents."""

    BOOL8 = "bool8"
    INT8 = "int8"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    UINT8 = "uint8"
    UINT16 = "uint16"
    UINT32 = "uint32"
    UINT64 = "uint64"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    STRING = "string"
    TIMESTAMP_DAYS = "timestamp_days"      # Spark DATE: int32 days since epoch
    TIMESTAMP_MICROS = "timestamp_micros"  # Spark TIMESTAMP: int64 micros
    DECIMAL32 = "decimal32"
    DECIMAL64 = "decimal64"
    DECIMAL128 = "decimal128"
    LIST = "list"
    STRUCT = "struct"


_FIXED_WIDTH_NP = {
    Kind.BOOL8: np.dtype(np.uint8),
    Kind.INT8: np.dtype(np.int8),
    Kind.INT16: np.dtype(np.int16),
    Kind.INT32: np.dtype(np.int32),
    Kind.INT64: np.dtype(np.int64),
    Kind.UINT8: np.dtype(np.uint8),
    Kind.UINT16: np.dtype(np.uint16),
    Kind.UINT32: np.dtype(np.uint32),
    Kind.UINT64: np.dtype(np.uint64),
    Kind.FLOAT32: np.dtype(np.float32),
    Kind.FLOAT64: np.dtype(np.float64),
    Kind.TIMESTAMP_DAYS: np.dtype(np.int32),
    Kind.TIMESTAMP_MICROS: np.dtype(np.int64),
    Kind.DECIMAL32: np.dtype(np.int32),
    Kind.DECIMAL64: np.dtype(np.int64),
}

_SIZES = dict(
    {k: d.itemsize for k, d in _FIXED_WIDTH_NP.items()},
    **{Kind.DECIMAL128: 16},
)


@dataclasses.dataclass(frozen=True)
class DType:
    """A column data type. `scale` follows cudf convention for decimals
    (negative scale = digits after the decimal point is -scale)."""

    kind: str
    scale: int = 0

    @property
    def is_fixed_width(self) -> bool:
        return self.kind in _SIZES

    @property
    def is_decimal(self) -> bool:
        return self.kind in (Kind.DECIMAL32, Kind.DECIMAL64, Kind.DECIMAL128)

    @property
    def is_nested(self) -> bool:
        return self.kind in (Kind.LIST, Kind.STRUCT)

    @property
    def is_string(self) -> bool:
        return self.kind == Kind.STRING

    @property
    def size_bytes(self) -> int:
        """Fixed-width element size in bytes (JCUDF row layout size)."""
        if not self.is_fixed_width:
            raise ValueError(f"{self.kind} is not fixed-width")
        return _SIZES[self.kind]

    @property
    def np_dtype(self) -> np.dtype:
        """Natural numpy dtype of the device data buffer."""
        if self.kind in _FIXED_WIDTH_NP:
            return _FIXED_WIDTH_NP[self.kind]
        if self.kind == Kind.DECIMAL128:
            return np.dtype(np.int32)  # (rows, 4) limb layout
        if self.kind == Kind.STRING:
            return np.dtype(np.uint8)  # chars buffer
        raise ValueError(f"{self.kind} has no single buffer dtype")

    def __repr__(self) -> str:
        if self.is_decimal:
            return f"DType({self.kind}, scale={self.scale})"
        return f"DType({self.kind})"


BOOL8 = DType(Kind.BOOL8)
INT8 = DType(Kind.INT8)
INT16 = DType(Kind.INT16)
INT32 = DType(Kind.INT32)
INT64 = DType(Kind.INT64)
UINT8 = DType(Kind.UINT8)
UINT16 = DType(Kind.UINT16)
UINT32 = DType(Kind.UINT32)
UINT64 = DType(Kind.UINT64)
FLOAT32 = DType(Kind.FLOAT32)
FLOAT64 = DType(Kind.FLOAT64)
STRING = DType(Kind.STRING)
TIMESTAMP_DAYS = DType(Kind.TIMESTAMP_DAYS)
TIMESTAMP_MICROS = DType(Kind.TIMESTAMP_MICROS)
LIST = DType(Kind.LIST)
STRUCT = DType(Kind.STRUCT)


def decimal32(scale: int) -> DType:
    return DType(Kind.DECIMAL32, scale)


def decimal64(scale: int) -> DType:
    return DType(Kind.DECIMAL64, scale)


def decimal128(scale: int) -> DType:
    return DType(Kind.DECIMAL128, scale)


def from_numpy(dt: np.dtype) -> DType:
    dt = np.dtype(dt)
    for kind, nd in _FIXED_WIDTH_NP.items():
        if kind in (Kind.BOOL8, Kind.TIMESTAMP_DAYS, Kind.TIMESTAMP_MICROS,
                    Kind.DECIMAL32, Kind.DECIMAL64):
            continue
        if nd == dt:
            return DType(kind)
    if dt == np.dtype(np.bool_):
        return BOOL8
    raise ValueError(f"no column dtype for numpy {dt}")
