"""Multi-process launch harness (ISSUE 10): spawn N shuffle workers,
hand out the port map, seed ONE trace context so every process's spans
stitch into a single tree, babysit the processes, and collect results.

The launcher is a library (scripts/dist_launch.py is the CLI shim) so
the dist-smoke gate and the slow tests drive the same code path."""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def make_addresses(world: int, outdir: str,
                   transport: str = "unix") -> List[str]:
    """Per-rank listen addresses.  Unix sockets (default) live in the
    run directory — no port allocation races; TCP mode binds throwaway
    sockets to reserve free localhost ports (the map is then passed to
    every worker, so all peers agree)."""
    if transport == "unix":
        return [f"unix:{os.path.join(outdir, f'shuffle_{r}.sock')}"
                for r in range(world)]
    # hold every probe socket open until the whole map is built: a
    # closed never-listened port is immediately reusable, so closing
    # per-iteration could hand the SAME ephemeral port to two ranks
    probes = []
    addrs = []
    try:
        for _ in range(world):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.bind(("127.0.0.1", 0))
            probes.append(s)
            addrs.append(f"127.0.0.1:{s.getsockname()[1]}")
    finally:
        for s in probes:
            s.close()
    return addrs


def launch(world: int, outdir: str, *,
           ops: Sequence[str] = ("q5", "q72"),
           transport: str = "unix",
           params: Optional[dict] = None,
           fault: Optional[str] = None,
           fault_rank: int = 1,
           mesh: str = "0",
           timeout_s: float = 300.0) -> Dict:
    """Run ``world`` worker processes to completion.  Returns
    ``{"summaries": [...], "addresses": [...], "trace_id": hex,
    "outdir": ...}``.  ``fault`` is a transport fault spec (e.g.
    ``"corrupt:0:101"``) armed on ``fault_rank``'s environment — the
    injected corrupt/truncated link must be healed by the link retry
    for the run to succeed at all (results are still compared
    upstream)."""
    from spark_rapids_tpu import observability as obs

    os.makedirs(outdir, exist_ok=True)
    addrs = make_addresses(world, outdir, transport)

    # one trace for the whole fleet: the launcher owns the root span;
    # workers parent their process spans under it via the env context
    prior_tracing = obs.TRACER.enabled
    obs.enable_tracing()
    root = obs.TRACER.start_span(
        "dist_query", kind="query",
        attrs={"world": world, "ops": ",".join(ops),
               "transport": transport})
    trace_ctx = f"{root.trace_id:016x}:{root.span_id:016x}"

    procs = []
    logs = []
    failed = True
    try:
        for r in range(world):
            env = dict(os.environ)
            env.update({
                "JAX_PLATFORMS": "cpu",
                "SPARK_RAPIDS_TPU_KUDO_CRC": "1",
                "SPARK_RAPIDS_TPU_DIST_TRACE_CTX": trace_ctx,
                "SPARK_RAPIDS_TPU_DIST_MESH": mesh,
                "PYTHONPATH": _REPO_ROOT + os.pathsep
                + env.get("PYTHONPATH", ""),
            })
            if fault and r == fault_rank:
                env["SPARK_RAPIDS_TPU_DIST_FAULT"] = fault
            cmd = [sys.executable, "-m",
                   "spark_rapids_tpu.distributed.runner",
                   "--rank", str(r), "--world", str(world),
                   "--addresses", ",".join(addrs),
                   "--ops", ",".join(ops),
                   "--outdir", outdir,
                   "--params", json.dumps(params or {})]
            log = open(os.path.join(outdir, f"worker_rank{r}.log"),
                       "w")
            logs.append(log)
            procs.append(subprocess.Popen(
                cmd, cwd=_REPO_ROOT, env=env, stdout=log,
                stderr=subprocess.STDOUT))
        deadline = time.monotonic() + timeout_s
        for r, proc in enumerate(procs):
            left = deadline - time.monotonic()
            try:
                rc = proc.wait(timeout=max(left, 1.0))
            except subprocess.TimeoutExpired:
                raise RuntimeError(
                    f"worker rank {r} timed out after {timeout_s}s "
                    f"(log: {_tail(outdir, r)})")
            if rc != 0:
                raise RuntimeError(
                    f"worker rank {r} exited rc={rc}: "
                    f"{_tail(outdir, r)}")
        failed = False
    finally:
        if failed:
            # ANY error exit (spawn-loop failure included) must not
            # leak live workers holding sockets and CPU
            for p in procs:
                if p.poll() is None:
                    p.kill()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except Exception:  # noqa: BLE001 — best-effort reap
                    pass
        for log in logs:
            log.close()
        root.end()
        _dump_launcher_spans(outdir, f"{root.trace_id:016x}")
        if not prior_tracing:
            obs.disable_tracing()

    summaries = []
    for r in range(world):
        with open(os.path.join(outdir,
                               f"summary_rank{r}.json")) as f:
            summaries.append(json.load(f))
    return {"summaries": summaries, "addresses": addrs,
            "trace_id": f"{root.trace_id:016x}", "outdir": outdir,
            "world": world, "ops": list(ops)}


def _dump_launcher_spans(outdir: str, trace_id: str) -> None:
    """Write the launcher's OWN spans for this trace (the fleet root)
    so the cross-process merge has the tree's apex."""
    from spark_rapids_tpu import observability as obs
    from spark_rapids_tpu.observability.dumpio import dump_via

    recs = [r for r in obs.TRACER.records()
            if r.get("trace_id") == trace_id]

    def _write(f):
        for r in recs:
            f.write(json.dumps(r) + "\n")
        return len(recs)

    dump_via(os.path.join(outdir, "spans_launcher.jsonl"), _write)


def _tail(outdir: str, rank: int, n: int = 2000) -> str:
    try:
        with open(os.path.join(outdir,
                               f"worker_rank{rank}.log")) as f:
            return f.read()[-n:]
    except OSError:
        return "<no log>"


def span_files(outdir: str, world: int) -> List[str]:
    """Every per-process span dump of a finished run, launcher first."""
    paths = [os.path.join(outdir, "spans_launcher.jsonl")]
    paths += [os.path.join(outdir, f"spans_rank{r}.jsonl")
              for r in range(world)]
    return [p for p in paths if os.path.exists(p)]
