"""Multi-process launch harness (ISSUE 10): spawn N shuffle workers,
hand out the port map, seed ONE trace context so every process's spans
stitch into a single tree, babysit the processes, and collect results.

The launcher is a library (scripts/dist_launch.py is the CLI shim) so
the dist-smoke gate and the slow tests drive the same code path.

ISSUE 15: the babysitter POLLS the whole fleet — a worker exiting
nonzero kills the remaining ranks and propagates its exit code
IMMEDIATELY (:class:`WorkerFailed` carries rank + rc) instead of
leaving the survivors to ride out the full inbox deadline.  In
``elastic=True`` runs a death is an EXPECTED event: the launcher
respawns the dead rank once (``respawn=True``), with the SAME seeded
trace context (so the respawned incarnation's spans land in the same
stitched Perfetto tree) and without the injected-death env, and the
rejoined worker converges by rebalance + replay."""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional, Sequence

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class _DeferredSpawn:
    """A respawn scheduled for the future, shaped like a Popen so the
    babysitter polls it like any worker.  Real orchestrators take tens
    of seconds to reschedule a dead pod — the delay keeps the death
    window OBSERVABLE (survivors' sends must fail and trigger the
    membership barrier before the endpoint is resurrected)."""

    def __init__(self, delay_s: float, factory: Callable):
        self._due = time.monotonic() + delay_s
        self._factory = factory
        self._proc = None

    def _materialize(self):
        if self._proc is None and time.monotonic() >= self._due:
            self._proc = self._factory()
        return self._proc

    def poll(self):
        p = self._materialize()
        return None if p is None else p.poll()

    def kill(self) -> None:
        if self._proc is not None:
            self._proc.kill()
        self._due = float("inf")  # cancel a still-pending spawn

    def wait(self, timeout=None):
        if self._proc is not None:
            return self._proc.wait(timeout=timeout)
        return 0


class WorkerFailed(RuntimeError):
    """A worker exited nonzero (or the fleet timed out).  ``rank`` and
    ``rc`` let the CLI propagate the worker's own exit code."""

    def __init__(self, rank: int, rc: Optional[int], tail: str = ""):
        self.rank = int(rank)
        self.rc = rc
        if rc is None:
            msg = f"worker rank {rank} timed out ({tail})"
        else:
            msg = f"worker rank {rank} exited rc={rc}: {tail}"
        super().__init__(msg)


def babysit(procs: Dict[int, object], timeout_s: float, *,
            on_death: Optional[Callable] = None,
            poll_s: float = 0.2,
            clock=time.monotonic, sleep=time.sleep) -> None:
    """Poll every worker until all exit 0.  A nonzero exit consults
    ``on_death(rank, rc)`` — return a replacement process to keep
    going (elastic respawn), or None to fail the fleet NOW: every
    surviving process is killed and :class:`WorkerFailed` carries the
    dead rank's exit code out immediately (no waiting out the
    survivors' inbox deadlines)."""
    active = dict(procs)
    deadline = clock() + timeout_s
    try:
        while active:
            progressed = False
            for r in sorted(active):
                rc = active[r].poll()
                if rc is None:
                    continue
                progressed = True
                del active[r]
                if rc == 0:
                    continue
                repl = on_death(r, rc) if on_death is not None \
                    else None
                if repl is None:
                    raise WorkerFailed(r, rc)
                active[r] = repl
            if active:
                if clock() >= deadline:
                    raise WorkerFailed(min(active), None,
                                       tail=f"after {timeout_s}s")
                if not progressed:
                    sleep(poll_s)
    except WorkerFailed:
        for p in active.values():
            try:
                if p.poll() is None:
                    p.kill()
            except Exception:  # noqa: BLE001 — best-effort reap
                pass
        for p in active.values():
            try:
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001 — best-effort reap
                pass
        raise


def make_addresses(world: int, outdir: str,
                   transport: str = "unix") -> List[str]:
    """Per-rank listen addresses.  Unix sockets (default) live in the
    run directory — no port allocation races; TCP mode binds throwaway
    sockets to reserve free localhost ports (the map is then passed to
    every worker, so all peers agree)."""
    if transport == "unix":
        return [f"unix:{os.path.join(outdir, f'shuffle_{r}.sock')}"
                for r in range(world)]
    # hold every probe socket open until the whole map is built: a
    # closed never-listened port is immediately reusable, so closing
    # per-iteration could hand the SAME ephemeral port to two ranks
    probes = []
    addrs = []
    try:
        for _ in range(world):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.bind(("127.0.0.1", 0))
            probes.append(s)
            addrs.append(f"127.0.0.1:{s.getsockname()[1]}")
    finally:
        for s in probes:
            s.close()
    return addrs


def launch(world: int, outdir: str, *,
           ops: Sequence[str] = ("q5", "q72"),
           transport: str = "unix",
           params: Optional[dict] = None,
           fault: Optional[str] = None,
           fault_rank: int = 1,
           die: Optional[str] = None,
           die_rank: int = 2,
           mesh: str = "0",
           elastic: bool = False,
           respawn: bool = False,
           respawn_delay_s: float = 0.0,
           worker_env: Optional[Dict[str, str]] = None,
           timeout_s: float = 300.0) -> Dict:
    """Run ``world`` worker processes to completion.  Returns
    ``{"summaries": [...], "addresses": [...], "trace_id": hex,
    "outdir": ..., "deaths": [...], "respawns": [...]}``.

    ``fault`` is a transport fault spec (e.g. ``"corrupt:0:101"`` or
    ``"slow:-1:2000"``) armed on ``fault_rank``'s environment;
    ``die`` injects a worker death (``"q5:partials"`` — see
    runner._die_spec) on ``die_rank``.  With ``elastic`` the workers
    speak the elastic fleet protocol; ``respawn`` additionally
    restarts a dead rank ONCE (same trace context, injected death
    stripped) and tells workers to await it at the fleet barrier.  A
    worker dying outside the respawn budget kills the remaining ranks
    and raises :class:`WorkerFailed` with its exit code immediately."""
    from spark_rapids_tpu import observability as obs

    os.makedirs(outdir, exist_ok=True)
    addrs = make_addresses(world, outdir, transport)

    # one trace for the whole fleet: the launcher owns the root span;
    # workers parent their process spans under it via the env context
    prior_tracing = obs.TRACER.enabled
    obs.enable_tracing()
    root = obs.TRACER.start_span(
        "dist_query", kind="query",
        attrs={"world": world, "ops": ",".join(ops),
               "transport": transport, "elastic": elastic})
    trace_ctx = f"{root.trace_id:016x}:{root.span_id:016x}"

    def worker_cmd(r: int) -> List[str]:
        cmd = [sys.executable, "-m",
               "spark_rapids_tpu.distributed.runner",
               "--rank", str(r), "--world", str(world),
               "--addresses", ",".join(addrs),
               "--ops", ",".join(ops),
               "--outdir", outdir,
               "--params", json.dumps(params or {})]
        if elastic:
            cmd.append("--elastic")
        return cmd

    def worker_environ(r: int, *, respawned: bool = False) -> dict:
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "SPARK_RAPIDS_TPU_KUDO_CRC": "1",
            "SPARK_RAPIDS_TPU_DIST_TRACE_CTX": trace_ctx,
            "SPARK_RAPIDS_TPU_DIST_MESH": mesh,
            "PYTHONPATH": _REPO_ROOT + os.pathsep
            + env.get("PYTHONPATH", ""),
        })
        env.pop("SPARK_RAPIDS_TPU_DIST_DIE", None)
        env.pop("SPARK_RAPIDS_TPU_DIST_RESPAWN", None)
        env.update(worker_env or {})
        if elastic and respawn:
            # workers' elastic barrier awaits the full original world
            # (the dead rank is coming back)
            env["SPARK_RAPIDS_TPU_FLEET_RESPAWN"] = "1"
        if fault and r == fault_rank:
            env["SPARK_RAPIDS_TPU_DIST_FAULT"] = fault
        if die and r == die_rank and not respawned:
            env["SPARK_RAPIDS_TPU_DIST_DIE"] = die
        if respawned:
            env["SPARK_RAPIDS_TPU_DIST_RESPAWN"] = "1"
        return env

    procs: List[subprocess.Popen] = []
    logs = []
    deaths: List[dict] = []
    respawns: List[dict] = []
    failed = True

    def spawn(r: int, *, respawned: bool = False) -> subprocess.Popen:
        suffix = "_respawn" if respawned else ""
        log = open(os.path.join(
            outdir, f"worker_rank{r}{suffix}.log"), "w")
        logs.append(log)
        p = subprocess.Popen(
            worker_cmd(r), cwd=_REPO_ROOT,
            env=worker_environ(r, respawned=respawned),
            stdout=log, stderr=subprocess.STDOUT)
        procs.append(p)
        return p

    def on_death(r: int, rc: int):
        deaths.append({"rank": r, "rc": rc,
                       "t_mono": time.monotonic()})
        budget_left = elastic and respawn and not any(
            x["rank"] == r for x in respawns)
        if not budget_left:
            raise WorkerFailed(r, rc, tail=_tail(outdir, r))
        respawns.append({"rank": r, "t_mono": time.monotonic(),
                         "delay_s": respawn_delay_s})
        if respawn_delay_s > 0:
            return _DeferredSpawn(
                respawn_delay_s, lambda: spawn(r, respawned=True))
        return spawn(r, respawned=True)

    try:
        active = {r: spawn(r) for r in range(world)}
        try:
            babysit(active, timeout_s, on_death=on_death)
        except WorkerFailed as e:
            if e.rc is None:
                # re-raise the timeout with the hung worker's log
                # tail (babysit is outdir-blind)
                raise WorkerFailed(
                    e.rank, None,
                    tail=f"after {timeout_s}s; log: "
                         f"{_tail(outdir, e.rank)}") from None
            raise
        failed = False
    finally:
        if failed:
            # ANY error exit (spawn-loop failure included) must not
            # leak live workers holding sockets and CPU
            for p in procs:
                if p.poll() is None:
                    p.kill()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except Exception:  # noqa: BLE001 — best-effort reap
                    pass
        for log in logs:
            log.close()
        root.end()
        _dump_launcher_spans(outdir, f"{root.trace_id:016x}")
        if not prior_tracing:
            obs.disable_tracing()

    summaries = []
    for r in range(world):
        with open(os.path.join(outdir,
                               f"summary_rank{r}.json")) as f:
            summaries.append(json.load(f))
    return {"summaries": summaries, "addresses": addrs,
            "trace_id": f"{root.trace_id:016x}", "outdir": outdir,
            "world": world, "ops": list(ops),
            "deaths": deaths, "respawns": respawns}


def _dump_launcher_spans(outdir: str, trace_id: str) -> None:
    """Write the launcher's OWN spans for this trace (the fleet root)
    so the cross-process merge has the tree's apex."""
    from spark_rapids_tpu import observability as obs
    from spark_rapids_tpu.observability.dumpio import dump_via

    recs = [r for r in obs.TRACER.records()
            if r.get("trace_id") == trace_id]

    def _write(f):
        for r in recs:
            f.write(json.dumps(r) + "\n")
        return len(recs)

    dump_via(os.path.join(outdir, "spans_launcher.jsonl"), _write)


def _tail(outdir: str, rank: int, n: int = 2000) -> str:
    # a respawned incarnation logs to its own file — when it exists,
    # IT is the incarnation whose failure is being diagnosed (the
    # base log ends at the first incarnation's injected/real death)
    for suffix in ("_respawn", ""):
        try:
            with open(os.path.join(
                    outdir, f"worker_rank{rank}{suffix}.log")) as f:
                return f.read()[-n:]
        except OSError:
            continue
    return "<no log>"


def span_files(outdir: str, world: int) -> List[str]:
    """Every per-process span dump of a finished run, launcher first."""
    paths = [os.path.join(outdir, "spans_launcher.jsonl")]
    paths += [os.path.join(outdir, f"spans_rank{r}.jsonl")
              for r in range(world)]
    return [p for p in paths if os.path.exists(p)]
