"""Multi-process mesh formation with graceful degradation (ISSUE 10).

The ideal scale-out promotes the virtual single-process mesh to a
genuine multi-process ``jax.distributed`` mesh (SNIPPETS.md [1][2] —
pjit across TPU-pod processes with a call-site mesh).  On this image's
CPU backend (jax 0.4.37) cross-process CPU collectives are not
reliably available, so mesh formation is an ATTEMPT with a bounded
timeout, and the distributed runner degrades to the process-per-shard
harness: every rank computes its shard with plain local jit, and ALL
cross-rank movement rides the kudo shuffle service — which is the
contract under test anyway (shuffle bytes must cross the process
boundary regardless of how the local step was compiled).

``SPARK_RAPIDS_TPU_DIST_MESH``:
  * ``0`` (default) — don't attempt; harness mode.
  * ``auto``/``1``  — try ``jax.distributed.initialize`` against the
    coordinator; any failure (timeout, unsupported backend, version)
    falls back to harness mode and says so in the worker summary.
"""

from __future__ import annotations

import os
from typing import Optional


def mesh_mode() -> str:
    v = os.environ.get("SPARK_RAPIDS_TPU_DIST_MESH", "0").lower()
    return "attempt" if v in ("1", "auto", "true") else "harness"


def try_form_mesh(rank: int, world: int,
                  coordinator: Optional[str] = None,
                  timeout_s: float = 10.0) -> dict:
    """Attempt the jax.distributed mesh; never raises.  Returns
    ``{"mode": "mesh"|"harness", "detail": str, "local_devices": n}``.
    In harness mode callers must shard/reduce through the shuffle
    service; in mesh mode a caller MAY shard_map over
    ``jax.devices()`` — the shuffle service still carries the
    table-granularity exchanges either way."""
    import jax

    if mesh_mode() != "attempt":
        return {"mode": "harness",
                "detail": "mesh attempt disabled "
                          "(SPARK_RAPIDS_TPU_DIST_MESH=0)",
                "local_devices": jax.local_device_count()}
    if coordinator is None:
        return {"mode": "harness", "detail": "no coordinator address",
                "local_devices": jax.local_device_count()}
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator, num_processes=world,
            process_id=rank,
            initialization_timeout=int(max(1, timeout_s)))
        ndev = jax.device_count()
        if ndev < world:
            return {"mode": "harness",
                    "detail": f"mesh formed but only {ndev} global "
                              f"devices for {world} ranks",
                    "local_devices": jax.local_device_count()}
        return {"mode": "mesh",
                "detail": f"{ndev} global devices across {world} "
                          f"processes",
                "local_devices": jax.local_device_count()}
    except Exception as e:  # noqa: BLE001 — degradation is the contract
        return {"mode": "harness",
                "detail": f"mesh init failed: "
                          f"{type(e).__name__}: {e}",
                "local_devices": jax.local_device_count()}
