"""Multi-process scale-out: a distributed shuffle service with kudo as
the inter-host wire format (ISSUE 10).

Modules:
  * transport — framed kudo streams over TCP/unix sockets with
    ACK/NAK delivery, dedup, and RetryPolicy-driven link retry;
  * service   — :class:`ShuffleService`: rank-ordered all-to-all /
    allgather / barrier; plugs into ``parallel.exchange`` as the
    process's table transport;
  * mesh      — jax.distributed mesh attempt with graceful
    degradation to the process-per-shard harness;
  * runner    — distributed q5/q72 workers (the per-query entry
    points are importable for in-process tests);
  * launcher  — spawn/babysit N worker processes, seed one trace.

See docs/distributed.md for topology, the wire protocol, failure
semantics, and knobs.
"""

from spark_rapids_tpu.distributed.service import ShuffleService  # noqa: F401
from spark_rapids_tpu.distributed.transport import (  # noqa: F401
    Inbox, Listener, PeerLink, clear_link_faults, set_link_fault)
