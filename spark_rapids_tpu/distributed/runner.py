"""Distributed TPC-DS worker: q5/q72 promoted to real processes
(ISSUE 10 tentpole).

Execution plan per rank (the process-per-shard harness; mesh.py may
additionally form a jax.distributed mesh, but table movement ALWAYS
rides the shuffle service — that is the contract under test):

  1. scan      — every rank regenerates the seeded dataset and takes
                 its row shard (deterministic, no data files needed);
  2. partials  — the map side runs as ONE fused stage executable
                 through the stage IR (plan/catalog — ISSUE 11), AOT
                 in the process compile cache, under
                 ``exchange.with_capacity_retry`` (overflow doubles
                 the join budget, same as every other
                 capacity-bounded pipeline).
                 ``SPARK_RAPIDS_TPU_STAGE_FUSION=0`` falls back to
                 the legacy per-op jit of the SHARED models/tpcds
                 kernels (``_q5_partials`` / ``_q72_partials``) — the
                 byte-identity oracle of the fused path;
  3. reduce-scatter — the partial group table is sliced into
                 rank-owned chunks, each chunk shipped to its owner as
                 kudo tables over the socket shuffle
                 (partition -> kudo write -> transport -> kudo merge);
                 owners sum their received chunks (exact int64 — any
                 arrival order is byte-identical);
  4. allgather — owners re-share their summed chunks; every rank
                 reassembles the GLOBAL group table;
  5. finish    — the reduce side is the matching fused finish stage
                 (ONE executable again — a rank runs exactly one
                 program between kudo exchanges), or the SHARED
                 ``_q5_finish`` / ``_q72_finish`` jits under the
                 escape hatch; either way the output bytes are
                 identical to the single-process pipeline's by
                 construction.

Run as a module (``python -m spark_rapids_tpu.distributed.runner``)
by scripts/dist_launch.py; the per-query entry points are also
importable for in-process tests against any table transport.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import time
from typing import Dict, Optional

import numpy as np

# default query shapes — the launcher AND the single-process reference
# (smoke gate) import these so the comparison can never drift
Q5_PARAMS = dict(rows=4096, stores=32, days=60,
                 join_capacity=1 << 14)
Q72_PARAMS = dict(cs_rows=4096, inv_rows=64, items=64, max_week=16,
                  days=35, join_capacity=1 << 17, limit=100,
                  week0=11_000 // 7)


class OpIds:
    """Centralized op-id allocation: one id per (query, stage) so
    concurrent exchanges can never cross payloads."""

    Q5_REDUCE_SCATTER = 101
    Q5_ALLGATHER = 102
    Q72_REDUCE_SCATTER = 111
    Q72_ALLGATHER = 112
    EQ5_PARTS = 121       # elastic q5: per-shard partial broadcast
    BARRIER = 900
    ELASTIC_BARRIER = 901


def _die_spec() -> Optional[tuple]:
    """Injected worker death (chaos for the elastic gate):
    ``SPARK_RAPIDS_TPU_DIST_DIE="<where>[:<rc>]"`` with ``where`` in
    {'boot', 'q5:scan', 'q5:partials'} — boot exits immediately at
    worker start (the launcher fast-fail path); q5:scan exits after
    generating the dataset, BEFORE any partials exist (survivors'
    sends fail -> membership barrier -> the inheritor recomputes the
    dead shard); q5:partials exits AFTER computing this rank's
    partials but BEFORE broadcasting them (work genuinely lost)."""
    spec = os.environ.get("SPARK_RAPIDS_TPU_DIST_DIE", "")
    if not spec:
        return None
    parts = spec.split(":")
    if parts[-1].isdigit() and len(parts) > 1:
        return ":".join(parts[:-1]), int(parts[-1])
    return spec, 13


_DIE_POINTS = ("boot", "q5:scan", "q5:partials")


def _maybe_die(where: str) -> None:
    spec = _die_spec()
    if spec is not None and spec[0] == where:
        sys.stderr.write(f"injected death at {where} "
                         f"(rc={spec[1]})\n")
        sys.stderr.flush()
        os._exit(spec[1])


# ------------------------------------------------------------- helpers


def _int64_table(arrays):
    """Build an all-INT64 kudo-shuffleable Table from numpy vectors."""
    import jax.numpy as jnp

    from spark_rapids_tpu.columns import dtypes
    from spark_rapids_tpu.columns.column import Column
    from spark_rapids_tpu.columns.table import Table
    cols = [Column(dtypes.INT64, len(a),
                   data=jnp.asarray(np.asarray(a, dtype=np.int64)))
            for a in arrays]
    return Table(cols)


def _pad_to(vec: np.ndarray, n: int) -> np.ndarray:
    if len(vec) == n:
        return vec
    out = np.zeros(n, dtype=vec.dtype)
    out[: len(vec)] = vec
    return out


def _reduce_scatter_allgather(transport, op_rs: int, op_ag: int,
                              vecs, overflow: bool):
    """Steps 3+4 for a dense partial group table: slice ``vecs`` (all
    same length) into rank-owned chunks, shuffle chunks to owners,
    sum, allgather the owned sums back, return the global vectors +
    the OR of every rank's overflow flag.  The flag rides as one more
    int64 column so it crosses the same wire as the data."""
    world = transport.world
    n = len(vecs[0])
    chunk = -(-n // world)  # ceil: pad so every rank owns equal rows
    padded = [_pad_to(np.asarray(v, dtype=np.int64), chunk * world)
              for v in vecs]
    ofv = np.full(chunk, int(bool(overflow)), dtype=np.int64)
    parts = []
    for d in range(world):
        sl = slice(d * chunk, (d + 1) * chunk)
        parts.append(_int64_table([v[sl] for v in padded] + [ofv]))
    merged = transport.exchange(op_rs, parts)
    # merged rows = world * chunk, source-rank order: sum per owner
    stacked = [c.to_numpy().reshape(world, chunk)
               for c in merged.columns]
    owned = [s.sum(axis=0, dtype=np.int64) for s in stacked[:-1]]
    of_owned = int(stacked[-1].max(initial=0) > 0)
    gathered = transport.allgather(
        op_ag, _int64_table(
            owned + [np.full(chunk, of_owned, dtype=np.int64)]))
    full = [c.to_numpy() for c in gathered.columns]
    out = [v[:n] for v in full[:-1]]
    return out, bool(full[-1].max(initial=0) > 0)


def _shard(a, rank: int, world: int):
    n = (len(a) // world) * world
    per = n // world
    return a[rank * per: (rank + 1) * per]


def _fused() -> bool:
    """Stage fusion on for this rank?  (The env escape hatch —
    SPARK_RAPIDS_TPU_STAGE_FUSION=0 — restores the legacy per-op jit
    of the shared models/tpcds kernel halves.)"""
    from spark_rapids_tpu.plan.compiler import fusion_mode
    return fusion_mode() != "off"


@contextlib.contextmanager
def _profiled(op: str, rank: int, world: int):
    """Per-rank query-profile session (ISSUE 13): when
    SPARK_RAPIDS_TPU_PROFILE is on, each rank assembles its own
    EXPLAIN ANALYZE artifact — this process's registry scopes the
    shuffle-link byte deltas, so a rank's profile carries exactly its
    own per-peer traffic.  ``merge_profiles`` stitches the rank
    artifacts into ONE fleet profile via the launcher-seeded trace
    context.  One attribute read when profiling is off."""
    from spark_rapids_tpu import observability as _obs

    sess = _obs.PROFILER.begin(f"{op}-rank{rank}", query=f"dist_{op}",
                               rank=rank, world=world)
    try:
        yield sess
    finally:
        _obs.PROFILER.end(sess)


# ------------------------------------------------------------------ q5


def run_dist_q5(params: Optional[dict] = None, *, transport=None
                ) -> Dict[str, np.ndarray]:
    """Distributed q5 on this rank's shard.  Returns the FULL query
    result (every rank converges to the same bytes) as numpy arrays:
    key / sales / rets / profit / overflow."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_tpu import observability as _obs
    from spark_rapids_tpu.models import tpcds as T
    from spark_rapids_tpu.parallel import exchange as X

    p = dict(Q5_PARAMS, **(params or {}))
    if transport is None:
        transport = X.table_transport()
    rank, world = transport.rank, transport.world
    with _obs.TRACER.span("dist_q5", kind="query",
                          attrs={"rank": rank, "world": world}), \
            _profiled("q5", rank, world):
        rows = max(int(p["rows"]) // (8 * world), 1) * 8 * world
        d = T.gen_q5(rows=rows, stores=p["stores"], days=p["days"])
        shard_args = tuple(
            _shard(a, rank, world)
            for a in (d.s_date, d.s_store, d.s_price, d.s_profit,
                      d.r_date, d.r_store, d.r_amt, d.r_loss)
        ) + (d.d_date,)

        # one read per query: a mid-query env flip must not leave the
        # finish step without the partials step's import/engine
        fused = _fused()
        if fused:
            from spark_rapids_tpu.plan import catalog as C
            outs, _cap = C.run_q5_partials(
                shard_args, p["stores"], p["join_capacity"])
        else:
            def build(cap):
                return jax.jit(T._q5_partials(p["stores"], cap))

            outs, _cap = T.run_with_capacity_retry(
                build, shard_args, p["join_capacity"])
        sales, rets, profit, seen, of = outs
        (sales, rets, profit, seen), of_any = \
            _reduce_scatter_allgather(
                transport, OpIds.Q5_REDUCE_SCATTER,
                OpIds.Q5_ALLGATHER,
                [np.asarray(sales), np.asarray(rets),
                 np.asarray(profit), np.asarray(seen)],
                bool(np.asarray(of)))
        if fused:
            key_s, sales_s, ret_s, profit_s, _of = C.run_q5_finish(
                np.asarray(sales), np.asarray(rets),
                np.asarray(profit), np.asarray(seen), of_any,
                np.asarray(d.st_id), p["stores"])
        else:
            fin = jax.jit(T._q5_finish(p["stores"]))
            key_s, sales_s, ret_s, profit_s = fin(
                jnp.asarray(sales), jnp.asarray(rets),
                jnp.asarray(profit), jnp.asarray(seen), d.st_id)
        return {"key": np.asarray(key_s), "sales": np.asarray(sales_s),
                "rets": np.asarray(ret_s),
                "profit": np.asarray(profit_s),
                "overflow": np.asarray(of_any)}


def single_q5(params: Optional[dict] = None) -> Dict[str, np.ndarray]:
    """The single-process reference with the SAME shapes the
    distributed run uses (row count rounded identically)."""
    from spark_rapids_tpu.models import tpcds as T

    p = dict(Q5_PARAMS, **(params or {}))
    world = int(p.get("world", 1))
    rows = max(int(p["rows"]) // (8 * world), 1) * 8 * world
    d = T.gen_q5(rows=rows, stores=p["stores"], days=p["days"])
    run = T.make_q5(p["stores"], p["join_capacity"])
    key_s, sales_s, ret_s, profit_s, of = run(d)
    return {"key": np.asarray(key_s), "sales": np.asarray(sales_s),
            "rets": np.asarray(ret_s), "profit": np.asarray(profit_s),
            "overflow": np.asarray(bool(np.asarray(of)))}


# ---------------------------------------------------------- elastic q5


def run_elastic_q5(params: Optional[dict] = None, *, transport=None
                   ) -> Dict[str, np.ndarray]:
    """q5 on the ELASTIC fleet protocol (ISSUE 15): every shard's
    partial group table is a logical PARTITION broadcast to all live
    ranks; the global sums are local (exact int64, shard order).  A
    dead rank's shards are recomputed by the fleet-assigned inheritor
    (inputs are seeded-deterministic); a straggler's shard is
    speculatively re-executed by the least-loaded survivor with the
    first verified copy winning the (op, shard) dedup; a respawned
    worker recomputes its own shards and catches up on the rest by
    CRC'd replay — every rank, however it got here, converges to
    bytes identical to ``single_q5``."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_tpu import observability as _obs
    from spark_rapids_tpu.models import tpcds as T
    from spark_rapids_tpu.parallel import exchange as X
    from spark_rapids_tpu.shuffle import kudo as _kudo
    from spark_rapids_tpu.shuffle.schema import schema_of_table

    p = dict(Q5_PARAMS, **(params or {}))
    if transport is None:
        transport = X.table_transport()
    if getattr(transport, "fleet", None) is None:
        # degenerate path: no elastic fabric installed — the classic
        # reduce-scatter runner computes the same bytes
        return run_dist_q5(params, transport=transport)
    fleet = transport.fleet
    rank, world0 = transport.rank, fleet.world0
    with _obs.TRACER.span("elastic_q5", kind="query",
                          attrs={"rank": rank, "world": world0}), \
            _profiled("q5", rank, world0):
        rows = max(int(p["rows"]) // (8 * world0), 1) * 8 * world0
        d = T.gen_q5(rows=rows, stores=p["stores"], days=p["days"])
        _maybe_die("q5:scan")
        fused = _fused()

        def compute_part(shard: int, ctx=None):
            """Deterministic per-shard partials -> one int64 kudo
            table.  Runs for our own shards, for INHERITED shards
            after a rebalance, and (cancel-aware via ``ctx``) as a
            speculative re-execution of a straggler's shard."""
            t0 = time.monotonic_ns()
            args = tuple(
                _shard(a, shard, world0)
                for a in (d.s_date, d.s_store, d.s_price, d.s_profit,
                          d.r_date, d.r_store, d.r_amt, d.r_loss)
            ) + (d.d_date,)
            if fused:
                from spark_rapids_tpu.plan import catalog as C
                outs, _cap = C.run_q5_partials(
                    args, p["stores"], p["join_capacity"], ctx=ctx)
            else:
                def build(cap):
                    return jax.jit(T._q5_partials(p["stores"], cap))

                if ctx is not None:
                    ctx.check_cancel()
                outs, _cap = T.run_with_capacity_retry(
                    build, args, p["join_capacity"])
                if ctx is not None:
                    ctx.check_cancel()
            sales, rets, profit, seen, of = (np.asarray(o)
                                             for o in outs)
            n = len(sales)
            fleet.note_stage_wall("q5.partials",
                                  time.monotonic_ns() - t0)
            return _int64_table([
                sales, rets, profit, seen,
                np.full(n, int(bool(of)), dtype=np.int64)])

        view = fleet.view()
        for shard in view.shards_of(rank):
            t = compute_part(shard)
            _maybe_die("q5:partials")
            transport.broadcast_part(OpIds.EQ5_PARTS, shard, t)
        got = transport.gather_parts(
            OpIds.EQ5_PARTS, range(world0), compute=compute_part,
            deadline_s=transport.recv_timeout_s)
        fields = schema_of_table(_int64_table([[0]] * 5))
        vecs = None
        of_any = False
        for shard in range(world0):
            merged = _kudo.merge_to_table(got[shard], fields)
            cols = [c.to_numpy().astype(np.int64)
                    for c in merged.columns]
            of_any = of_any or bool(cols[-1].max(initial=0) > 0)
            if vecs is None:
                vecs = cols[:-1]
            else:
                vecs = [a + b for a, b in zip(vecs, cols[:-1])]
        sales, rets, profit, seen = vecs
        if fused:
            from spark_rapids_tpu.plan import catalog as C
            key_s, sales_s, ret_s, profit_s, _of = C.run_q5_finish(
                sales, rets, profit, seen, of_any,
                np.asarray(d.st_id), p["stores"])
        else:
            fin = jax.jit(T._q5_finish(p["stores"]))
            key_s, sales_s, ret_s, profit_s = fin(
                jnp.asarray(sales), jnp.asarray(rets),
                jnp.asarray(profit), jnp.asarray(seen), d.st_id)
        return {"key": np.asarray(key_s), "sales": np.asarray(sales_s),
                "rets": np.asarray(ret_s),
                "profit": np.asarray(profit_s),
                "overflow": np.asarray(of_any)}


# ----------------------------------------------------------------- q72


def run_dist_q72(params: Optional[dict] = None, *, transport=None
                 ) -> Dict[str, np.ndarray]:
    """Distributed q72: catalog_sales sharded row-parallel, inventory
    + item dim replicated (the same plan as the mesh variant), counts
    reduce-scattered/allgathered over the kudo shuffle."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_tpu import observability as _obs
    from spark_rapids_tpu.models import tpcds as T
    from spark_rapids_tpu.parallel import exchange as X

    p = dict(Q72_PARAMS, **(params or {}))
    if transport is None:
        transport = X.table_transport()
    rank, world = transport.rank, transport.world
    with _obs.TRACER.span("dist_q72", kind="query",
                          attrs={"rank": rank, "world": world}), \
            _profiled("q72", rank, world):
        cs_rows = max(int(p["cs_rows"]) // world, 1) * world
        d = T.gen_q72(cs_rows=cs_rows, inv_rows=p["inv_rows"],
                      items=p["items"], days=p["days"])
        shard_args = (
            _shard(d.cs_item, rank, world),
            _shard(d.cs_date, rank, world),
            _shard(d.cs_qty, rank, world),
            d.inv_item, d.inv_date, d.inv_qty, d.item_id)

        fused = _fused()
        if fused:
            from spark_rapids_tpu.plan import catalog as C
            outs, _cap = C.run_q72_partials(
                shard_args, p["items"], p["max_week"],
                p["join_capacity"], p["week0"])
        else:
            def build(cap):
                return jax.jit(T._q72_partials(
                    p["items"], p["max_week"], cap, p["week0"]))

            outs, _cap = T.run_with_capacity_retry(
                build, shard_args, p["join_capacity"])
        counts, of = outs
        (counts,), of_any = _reduce_scatter_allgather(
            transport, OpIds.Q72_REDUCE_SCATTER,
            OpIds.Q72_ALLGATHER, [np.asarray(counts)],
            bool(np.asarray(of)))
        if fused:
            item, week, cnt, _of = C.run_q72_finish(
                np.asarray(counts), of_any, p["items"],
                p["max_week"], p["limit"], p["week0"])
        else:
            fin = jax.jit(T._q72_finish(
                p["items"], p["max_week"], p["limit"], p["week0"]))
            item, week, cnt = fin(jnp.asarray(counts))
        return {"item": np.asarray(item), "week": np.asarray(week),
                "cnt": np.asarray(cnt),
                "overflow": np.asarray(of_any)}


def single_q72(params: Optional[dict] = None) -> Dict[str, np.ndarray]:
    from spark_rapids_tpu.models import tpcds as T

    p = dict(Q72_PARAMS, **(params or {}))
    world = int(p.get("world", 1))
    cs_rows = max(int(p["cs_rows"]) // world, 1) * world
    d = T.gen_q72(cs_rows=cs_rows, inv_rows=p["inv_rows"],
                  items=p["items"], days=p["days"])
    run = T.make_q72(p["items"], p["max_week"], p["join_capacity"],
                     limit=p["limit"], week0=p["week0"])
    item, week, cnt, of = run(d)
    return {"item": np.asarray(item), "week": np.asarray(week),
            "cnt": np.asarray(cnt),
            "overflow": np.asarray(bool(np.asarray(of)))}


DIST_QUERIES = {"q5": run_dist_q5, "q72": run_dist_q72}
ELASTIC_QUERIES = {"q5": run_elastic_q5, "q72": run_dist_q72}
SINGLE_QUERIES = {"q5": single_q5, "q72": single_q72}


# ---------------------------------------------------------- worker main


def _parse_trace_ctx():
    from spark_rapids_tpu.observability import SpanContext
    spec = os.environ.get("SPARK_RAPIDS_TPU_DIST_TRACE_CTX", "")
    if ":" not in spec:
        return None
    try:
        tid, sid = spec.split(":")
        return SpanContext(int(tid, 16), int(sid, 16))
    except ValueError:
        return None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="spark_rapids_tpu distributed shuffle worker")
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--world", type=int, required=True)
    ap.add_argument("--addresses", required=True,
                    help="comma-separated per-rank listen addresses "
                         "(unix:/path or host:port)")
    ap.add_argument("--ops", default="q5,q72")
    ap.add_argument("--outdir", required=True)
    ap.add_argument("--coordinator", default=None,
                    help="jax.distributed coordinator (mesh attempt)")
    ap.add_argument("--params", default="{}",
                    help="JSON dict of per-query param overrides "
                         "keyed by op name")
    ap.add_argument("--elastic", action="store_true",
                    help="elastic fleet protocol: membership epoch, "
                         "rebalance on peer death, speculation, "
                         "skew re-split")
    args = ap.parse_args(argv)
    _maybe_die("boot")

    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

    from spark_rapids_tpu import observability as obs
    from spark_rapids_tpu.distributed.mesh import try_form_mesh
    from spark_rapids_tpu.distributed.service import ShuffleService
    from spark_rapids_tpu.observability.dumpio import dump_via
    from spark_rapids_tpu.shuffle import kudo

    rank, world = args.rank, args.world
    outdir = args.outdir
    os.makedirs(outdir, exist_ok=True)
    overrides = json.loads(args.params)

    kudo.set_crc_enabled(True)
    obs.enable()
    obs.enable_tracing()

    mesh_info = try_form_mesh(rank, world,
                              coordinator=args.coordinator)
    service = ShuffleService(
        rank, world, args.addresses.split(","),
        elastic=args.elastic).start().install()
    respawned = os.environ.get(
        "SPARK_RAPIDS_TPU_DIST_RESPAWN", "") == "1"
    parent = _parse_trace_ctx()
    root = obs.TRACER.start_span(
        "dist_worker", kind="process", parent=parent,
        attrs={"rank": rank, "world": world,
               "mesh": mesh_info["mode"],
               "respawned": respawned})
    from spark_rapids_tpu.observability import SpanContext
    # control/replay daemon threads parent under this worker's
    # process span so the fleet trace stays ONE connected tree
    service.trace_ctx = SpanContext(root.trace_id, root.span_id)
    if args.elastic and respawned:
        # a respawned incarnation: announce ourselves so survivors
        # waiting at the elastic barrier learn we are back, and learn
        # their epoch/departed view before sending fenceable frames
        # (after the root span, so the join sends stitch into the
        # fleet trace instead of rooting orphans)
        service.join_fleet()
    queries = ELASTIC_QUERIES if args.elastic else DIST_QUERIES
    ops = [o for o in args.ops.split(",") if o]
    rc = 0
    try:
        for op in ops:
            result = queries[op](overrides.get(op),
                                 transport=service)
            np.savez(os.path.join(
                outdir, f"result_{op}_rank{rank}.npz"), **result)
            if obs.PROFILER.enabled:
                prof = obs.PROFILER.last()
                if prof is not None:
                    dump_via(
                        os.path.join(
                            outdir,
                            f"profile_{op}_rank{rank}.json"),
                        lambda f, p=prof: f.write(
                            json.dumps(p, sort_keys=True,
                                       default=str)))
                    # same-moment registry snapshot: the profile's
                    # link-byte deltas reconcile exactly against
                    # THIS dump (the final metrics_rank dump also
                    # counts post-query barrier traffic)
                    dump_via(
                        os.path.join(
                            outdir,
                            f"metrics_{op}_rank{rank}.json"),
                        lambda f: f.write(
                            obs.METRICS.snapshot_json()))
        if obs.TIMESERIES.enabled:
            # close the final window NOW and dump the same-moment pair
            # (ring + registry): the ring's summed counter deltas equal
            # the registry's cumulative values at this instant exactly,
            # which is the fleet-reconciliation gate's oracle (the
            # post-barrier metrics_rank dump also counts barrier
            # traffic, so it cannot be the comparison point)
            obs.TIMESERIES.tick()
            ts_snap = obs.timeseries_snapshot(
                rank=rank, epoch=(service.fleet.epoch
                                  if service.fleet is not None else 0))
            dump_via(os.path.join(outdir,
                                  f"timeseries_rank{rank}.json"),
                     lambda f: f.write(json.dumps(ts_snap,
                                                  sort_keys=True)))
            dump_via(os.path.join(outdir,
                                  f"metrics_ts_rank{rank}.json"),
                     lambda f: f.write(obs.METRICS.snapshot_json()))
            # publish to rank 0 while the links are still up: the send
            # blocks for the ACK, so after the barrier below rank 0
            # holds every rank's windows
            service.publish_timeseries(ts_snap)
        if args.elastic:
            # membership-tolerant: survives peers leaving AND waits
            # for a respawned peer when the launcher may send one
            service.elastic_barrier(OpIds.ELASTIC_BARRIER)
            # graceful leave: peers still gathering (a respawned
            # straggler) drop us from their barrier wants instead of
            # waiting out a death detection on our closed listener
            service.leave_fleet()
        else:
            service.barrier(OpIds.BARRIER)
    except Exception as e:  # noqa: BLE001 — report, then nonzero exit
        rc = 1
        with open(os.path.join(outdir, f"error_rank{rank}.txt"),
                  "w") as f:
            f.write(f"{type(e).__name__}: {e}\n")
        raise
    finally:
        root.end()
        obs.TRACER.dump_jsonl(
            os.path.join(outdir, f"spans_rank{rank}.jsonl"))
        dump_via(os.path.join(outdir, f"metrics_rank{rank}.json"),
                 lambda f: f.write(obs.METRICS.snapshot_json()))
        # the journal carries the fleet evidence spine
        # (fleet_membership / fleet_speculation / fleet_inherit /
        # shuffle_dup_dropped) the elastic gate and srt-doctor read
        obs.dump_journal_jsonl(
            os.path.join(outdir, f"journal_rank{rank}.jsonl"))
        if rank == 0 and obs.TIMESERIES.enabled:
            # rank 0's merged fleet timeseries (self + every publish
            # folded pre-barrier) — the srt-top file tier and the
            # reconciliation gate read this
            dump_via(os.path.join(outdir, "fleet_timeseries.json"),
                     lambda f: f.write(json.dumps(
                         service.fleet_timeseries.merged(),
                         sort_keys=True)))
        summary = {
            "rank": rank, "world": world, "ops": ops,
            "mesh": mesh_info, "elastic": bool(args.elastic),
            "respawned": respawned,
            "epoch": (service.fleet.epoch
                      if service.fleet is not None else 0),
            "trace_id": (f"{root.trace_id:016x}"
                         if root.trace_id else None),
            "rc": rc,
        }
        dump_via(os.path.join(outdir, f"summary_rank{rank}.json"),
                 lambda f: f.write(json.dumps(summary, indent=1)))
        service.uninstall()
        service.stop()
    return rc


if __name__ == "__main__":
    sys.exit(main())
