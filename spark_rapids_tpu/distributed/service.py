"""The distributed shuffle service: partition -> kudo write -> socket
-> kudo merge, rank-ordered and byte-deterministic (ISSUE 10 tentpole,
layer 2 of 2 over transport.py).

One :class:`ShuffleService` per worker process.  It implements the
pluggable table-transport interface from ``parallel/exchange.py``
(``exchange`` / ``allgather``), so pipeline code written against
``exchange.exchange_tables`` runs unchanged on one process (loopback)
or N (this service):

  * ``exchange(op_id, tables_by_dest)`` — all-to-all: partition d goes
    to rank d; returns the merge (in SOURCE-RANK ORDER — the
    determinism the byte-identity gates lean on) of every partition
    addressed to this rank.
  * ``allgather(op_id, table)`` — every rank contributes one table,
    every rank gets the rank-ordered concatenation.
  * ``barrier(op_id)`` — an allgather of a 1-row sentinel; used to
    keep listeners alive until every peer is done.

The wire bytes are the existing kudo format end to end: the write side
embeds the active span's context in the KTRX extension (so the
receiving merge links/re-parents across the process boundary) and the
KCRC trailer (the receiver's verify + NAK/resend loop needs it —
construction fails fast if CRC mode is off), and the merge side is the
stock ``merge_to_table_with_metrics``.

``op_id`` discipline: each logical exchange in a query plan gets a
distinct op id per (query, stage) — the service namespaces nothing.
Collisions across CONCURRENT exchanges would cross payloads; the
distributed runner allocates ids centrally (runner.OpIds).
"""

from __future__ import annotations

import io
import threading

from spark_rapids_tpu.analysis.lockdep import make_lock
from typing import Dict, List, Optional, Sequence

from spark_rapids_tpu import observability as _obs
from spark_rapids_tpu.parallel import exchange as _exchange
from spark_rapids_tpu.robustness.retry import RetryPolicy
from spark_rapids_tpu.shuffle import kudo as _kudo
from spark_rapids_tpu.shuffle.schema import schema_of_table
from spark_rapids_tpu.distributed.transport import (
    Inbox, Listener, PeerLink)


class ShuffleService:
    """N-rank shuffle fabric over TCP/unix sockets."""

    def __init__(self, rank: int, world: int,
                 addresses: Sequence[str], *,
                 policy: Optional[RetryPolicy] = None,
                 recv_timeout_s: float = 120.0):
        if len(addresses) != world:
            raise ValueError(
                f"need {world} addresses, got {len(addresses)}")
        if not _kudo.crc_enabled():
            raise RuntimeError(
                "ShuffleService requires KCRC trailers "
                "(kudo.set_crc_enabled(True) or "
                "SPARK_RAPIDS_TPU_KUDO_CRC=1): the link NAK/resend "
                "protocol verifies payloads by CRC")
        self.rank = int(rank)
        self.world = int(world)
        self.addresses = list(addresses)
        self.recv_timeout_s = recv_timeout_s
        self.inbox = Inbox()
        self.listener = Listener(self.rank,
                                 self.addresses[self.rank], self.inbox)
        self.links: Dict[int, PeerLink] = {
            r: PeerLink(self.rank, r, addresses[r], policy=policy)
            for r in range(world) if r != self.rank}
        self._started = False
        self._lock = make_lock("dist.service")

    # ------------------------------------------------------- lifecycle

    def start(self) -> "ShuffleService":
        with self._lock:
            if not self._started:
                self.listener.start()
                self._started = True
        return self

    def stop(self) -> None:
        with self._lock:
            if not self._started:
                return
            self._started = False
        for link in self.links.values():
            link.close()
        self.listener.stop()

    def __enter__(self) -> "ShuffleService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------- transport

    def _serialize(self, table) -> bytes:
        buf = io.BytesIO()
        _kudo.write_to_stream_with_metrics(
            table.columns, buf, 0, table.num_rows)
        return buf.getvalue()

    def exchange(self, op_id: int, tables_by_dest, fields=None):
        """All-to-all one round: returns the Table merged from every
        rank's partition addressed to this rank, sources concatenated
        in rank order."""
        if len(tables_by_dest) != self.world:
            raise ValueError(
                f"need {self.world} destination partitions, got "
                f"{len(tables_by_dest)}")
        if fields is None:
            fields = schema_of_table(tables_by_dest[self.rank])
        with _obs.TRACER.span("shuffle_exchange", kind="stage",
                              attrs={"op": op_id,
                                     "world": self.world}) as sp:
            # serialize once per DISTINCT table: allgather passes the
            # same object to every destination, so an N-rank gather
            # pays one kudo write, not N identical ones
            blob_cache: Dict[int, bytes] = {}
            payloads = []
            for t in tables_by_dest:
                blob = blob_cache.get(id(t))
                if blob is None:
                    blob = blob_cache[id(t)] = self._serialize(t)
                payloads.append(blob)
            # local partition loops back through the same parsed form
            # (read_tables verifies its CRC too — uniform path)
            local = _kudo.read_tables(io.BytesIO(payloads[self.rank]))
            sent = self._send_all(op_id, payloads)
            others = [r for r in range(self.world) if r != self.rank]
            received = self.inbox.wait(op_id, others,
                                       self.recv_timeout_s) \
                if others else {}
            received[self.rank] = local
            tables: List[_kudo.KudoTable] = []
            for src in range(self.world):
                tables.extend(received[src])
            sp.set_attr("bytes_sent", sent)
            return _kudo.merge_to_table_with_metrics(tables, fields)[0]

    def _send_all(self, op_id: int, payloads) -> int:
        """One send per peer link, all in flight CONCURRENTLY: every
        send blocks for its peer's verify+ACK (or its retry budget),
        so a sequential loop would serialize world-1 round trips and
        let one slow or NAKing peer delay delivery to every
        later-numbered one.  Joins all senders; the first failure
        (after every thread settled) escalates."""
        sent = [0] * self.world
        errs: List[Optional[BaseException]] = [None] * self.world
        # sender threads start with an EMPTY tracer context stack —
        # adopt the caller's open span so each link's shuffle_send
        # span parents under the exchange instead of rooting a new
        # (orphan) trace
        ctx = _obs.TRACER.current_context()

        def one(dst: int) -> None:
            holder = _obs.TRACER.activate(ctx)
            try:
                sent[dst] = self.links[dst].send(op_id, payloads[dst])
            # srt-lint: disable=SRT007 captured into errs and re-raised by the collector after every worker joins
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errs[dst] = e
            finally:
                holder.end()

        workers = [threading.Thread(
            target=one, args=(dst,),
            name=f"srt-shuffle-send-{self.rank}-{dst}", daemon=True)
            for dst in range(self.world) if dst != self.rank]
        for w in workers:
            w.start()
        for w in workers:
            w.join()
        for e in errs:
            if e is not None:
                raise e
        return sum(sent)

    def allgather(self, op_id: int, table, fields=None):
        """Every rank contributes ``table``; everyone receives the
        rank-ordered concatenation."""
        return self.exchange(op_id, [table] * self.world, fields)

    def barrier(self, op_id: int) -> None:
        """Block until every rank reached this op — an allgather of a
        one-row sentinel.  Run before teardown so no peer's listener
        disappears while another rank still owes/awaits payloads."""
        import jax.numpy as jnp

        from spark_rapids_tpu.columns import dtypes
        from spark_rapids_tpu.columns.column import Column
        from spark_rapids_tpu.columns.table import Table
        col = Column(dtypes.INT64, 1,
                     data=jnp.asarray([self.rank], dtype=jnp.int64))
        out = self.allgather(op_id, Table([col]))
        if out.num_rows != self.world:
            raise RuntimeError(
                f"barrier saw {out.num_rows} ranks, want {self.world}")

    # ---------------------------------------------------- installation

    def install(self) -> "ShuffleService":
        """Register as the process's table transport
        (parallel/exchange.exchange_tables routes here)."""
        _exchange.set_table_transport(self)
        return self

    def uninstall(self) -> None:
        if _exchange._TABLE_TRANSPORT[0] is self:
            _exchange.set_table_transport(None)
