"""The distributed shuffle service: partition -> kudo write -> socket
-> kudo merge, rank-ordered and byte-deterministic (ISSUE 10 tentpole,
layer 2 of 2 over transport.py).

One :class:`ShuffleService` per worker process.  It implements the
pluggable table-transport interface from ``parallel/exchange.py``
(``exchange`` / ``allgather``), so pipeline code written against
``exchange.exchange_tables`` runs unchanged on one process (loopback)
or N (this service):

  * ``exchange(op_id, tables_by_dest)`` — all-to-all: partition d goes
    to rank d; returns the merge (in SOURCE-RANK ORDER — the
    determinism the byte-identity gates lean on) of every partition
    addressed to this rank.
  * ``allgather(op_id, table)`` — every rank contributes one table,
    every rank gets the rank-ordered concatenation.
  * ``barrier(op_id)`` — an allgather of a 1-row sentinel; used to
    keep listeners alive until every peer is done.

The wire bytes are the existing kudo format end to end: the write side
embeds the active span's context in the KTRX extension (so the
receiving merge links/re-parents across the process boundary) and the
KCRC trailer (the receiver's verify + NAK/resend loop needs it —
construction fails fast if CRC mode is off), and the merge side is the
stock ``merge_to_table_with_metrics``.

``op_id`` discipline: each logical exchange in a query plan gets a
distinct op id per (query, stage) — the service namespaces nothing.
Collisions across CONCURRENT exchanges would cross payloads; the
distributed runner allocates ids centrally (runner.OpIds).

Elastic mode (ISSUE 15): constructed with ``elastic=True`` the service
additionally speaks the part-granular elastic protocol over the SAME
links — ``broadcast_part`` / ``gather_parts`` / ``elastic_barrier`` —
with an :class:`~spark_rapids_tpu.robustness.fleet.ElasticFleet`
deciding membership and policy:

  * a ``PeerDiedException`` on any link marks the peer departed, bumps
    the membership epoch, gossips a death notice to every survivor
    (the fleet-wide membership barrier: assignment is a pure function
    of the departed set, so agreement on WHO died is agreement on who
    inherits), and the inheritor recomputes the dead rank's partitions
    from the seeded inputs;
  * a partition still missing past the straggler signal is
    speculatively re-executed by the least-loaded survivor — first
    verified copy wins the (op, part) dedup, the loser's frames count
    into ``srt_shuffle_dup_dropped_total`` and an original arriving
    mid-speculation cancels the speculative task through the
    cooperative QueryContext machinery;
  * a hot partition (payload >> the op's median, cross-checked against
    the live per-link byte counters) re-splits into per-rank
    sub-frames stitched back in index order;
  * every verified part payload is retained (bounded) as a REPLAY
    store: a FETCH control message re-serves the original CRC'd bytes,
    which is how a respawned worker catches up to a round that
    finished without it.
"""

from __future__ import annotations

import io
import json
import os
import struct
import threading
import time

from spark_rapids_tpu.analysis.lockdep import make_lock
from typing import Callable, Dict, List, Optional, Sequence, Set

from spark_rapids_tpu import observability as _obs
from spark_rapids_tpu.observability.timeseries import FleetTimeseries
from spark_rapids_tpu.parallel import exchange as _exchange
from spark_rapids_tpu.robustness.fleet import (
    ElasticFleet, StaleEpochError)
from spark_rapids_tpu.robustness.links import PeerDiedException
from spark_rapids_tpu.robustness.retry import RetryPolicy
from spark_rapids_tpu.shuffle import kudo as _kudo
from spark_rapids_tpu.shuffle.schema import schema_of_table
from spark_rapids_tpu.distributed.transport import (
    ACK, KIND_CTRL, KIND_EDATA, MAX_RESPLIT_SUBS, STALE, Inbox,
    Listener, PartInbox, PeerLink, pack_resplit, unpack_resplit)


class ShuffleService:
    """N-rank shuffle fabric over TCP/unix sockets."""

    def __init__(self, rank: int, world: int,
                 addresses: Sequence[str], *,
                 policy: Optional[RetryPolicy] = None,
                 recv_timeout_s: float = 120.0,
                 elastic: bool = False,
                 fleet: Optional[ElasticFleet] = None):
        if len(addresses) != world:
            raise ValueError(
                f"need {world} addresses, got {len(addresses)}")
        if not _kudo.crc_enabled():
            raise RuntimeError(
                "ShuffleService requires KCRC trailers "
                "(kudo.set_crc_enabled(True) or "
                "SPARK_RAPIDS_TPU_KUDO_CRC=1): the link NAK/resend "
                "protocol verifies payloads by CRC")
        self.rank = int(rank)
        self.world = int(world)
        self.addresses = list(addresses)
        self.recv_timeout_s = recv_timeout_s
        self.inbox = Inbox()
        self.fleet = fleet or (ElasticFleet(rank, world)
                               if elastic else None)
        self.parts = PartInbox() if self.fleet is not None else None
        self.listener = Listener(
            self.rank, self.addresses[self.rank], self.inbox,
            sink=self if self.fleet is not None else None)
        self.links: Dict[int, PeerLink] = {
            r: PeerLink(self.rank, r, addresses[r], policy=policy)
            for r in range(world) if r != self.rank}
        self._started = False
        # fleet telemetry merger (ISSUE 16): every rank holds one
        # (cheap), but only rank 0 receives publishes — workers ship
        # their windowed snapshots here over the CTRL path and the
        # merged view becomes the srt-top fleet feed
        self.fleet_timeseries = FleetTimeseries()
        self._lock = make_lock("dist.service")
        # per-op first-touch monotonic ns: arrival gaps feed the
        # straggler window relative to when THIS rank engaged the op
        self._op_t0: Dict[int, int] = {}
        self._op_t0_lock = make_lock("dist.service.op_t0")
        # fallback trace context for control/replay daemon threads
        # (they have no ambient span: without this every replayed
        # shuffle_send would root a fresh orphan trace and break the
        # one-stitched-tree invariant across a worker respawn)
        self.trace_ctx = None

    # ------------------------------------------------------- lifecycle

    def start(self) -> "ShuffleService":
        with self._lock:
            if not self._started:
                self.listener.start()
                self._started = True
        return self

    def stop(self) -> None:
        with self._lock:
            if not self._started:
                return
            self._started = False
        for link in self.links.values():
            link.close()
        self.listener.stop()

    def __enter__(self) -> "ShuffleService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------- transport

    def _serialize(self, table) -> bytes:
        buf = io.BytesIO()
        _kudo.write_to_stream_with_metrics(
            table.columns, buf, 0, table.num_rows)
        return buf.getvalue()

    def exchange(self, op_id: int, tables_by_dest, fields=None):
        """All-to-all one round: returns the Table merged from every
        rank's partition addressed to this rank, sources concatenated
        in rank order."""
        if len(tables_by_dest) != self.world:
            raise ValueError(
                f"need {self.world} destination partitions, got "
                f"{len(tables_by_dest)}")
        if fields is None:
            fields = schema_of_table(tables_by_dest[self.rank])
        with _obs.TRACER.span("shuffle_exchange", kind="stage",
                              attrs={"op": op_id,
                                     "world": self.world}) as sp:
            # serialize once per DISTINCT table: allgather passes the
            # same object to every destination, so an N-rank gather
            # pays one kudo write, not N identical ones
            t_wire = time.monotonic_ns()
            blob_cache: Dict[int, bytes] = {}
            payloads = []
            for t in tables_by_dest:
                blob = blob_cache.get(id(t))
                if blob is None:
                    blob = blob_cache[id(t)] = self._serialize(t)
                payloads.append(blob)
            # local partition loops back through the same parsed form
            # (read_tables verifies its CRC too — uniform path)
            local = _kudo.read_tables(io.BytesIO(payloads[self.rank]))
            # wire vs wait are sequential, non-overlapping segments on
            # this thread (_send_all joins every sender before the
            # inbox wait starts) — the attribution ledger's
            # shuffle_wire / shuffle_wait split hangs off exactly that
            sent = self._send_all(op_id, payloads)
            t_wait = time.monotonic_ns()
            _obs.record_shuffle_wire(op_id, t_wait - t_wire)
            others = [r for r in range(self.world) if r != self.rank]
            received = self.inbox.wait(op_id, others,
                                       self.recv_timeout_s) \
                if others else {}
            _obs.record_shuffle_wait(
                op_id, time.monotonic_ns() - t_wait)
            received[self.rank] = local
            tables: List[_kudo.KudoTable] = []
            for src in range(self.world):
                tables.extend(received[src])
            sp.set_attr("bytes_sent", sent)
            return _kudo.merge_to_table_with_metrics(tables, fields)[0]

    def _send_all(self, op_id: int, payloads) -> int:
        """One send per peer link, all in flight CONCURRENTLY: every
        send blocks for its peer's verify+ACK (or its retry budget),
        so a sequential loop would serialize world-1 round trips and
        let one slow or NAKing peer delay delivery to every
        later-numbered one.  Joins all senders; the first failure
        (after every thread settled) escalates."""
        sent = [0] * self.world
        errs: List[Optional[BaseException]] = [None] * self.world
        # sender threads start with an EMPTY tracer context stack —
        # adopt the caller's open span so each link's shuffle_send
        # span parents under the exchange instead of rooting a new
        # (orphan) trace
        ctx = _obs.TRACER.current_context()

        def one(dst: int) -> None:
            holder = _obs.TRACER.activate(ctx)
            try:
                sent[dst] = self.links[dst].send(op_id, payloads[dst])
            # srt-lint: disable=SRT007 captured into errs and re-raised by the collector after every worker joins
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errs[dst] = e
            finally:
                holder.end()

        workers = [threading.Thread(
            target=one, args=(dst,),
            name=f"srt-shuffle-send-{self.rank}-{dst}", daemon=True)
            for dst in range(self.world) if dst != self.rank]
        for w in workers:
            w.start()
        for w in workers:
            w.join()
        for e in errs:
            if e is not None:
                raise e
        return sum(sent)

    def allgather(self, op_id: int, table, fields=None):
        """Every rank contributes ``table``; everyone receives the
        rank-ordered concatenation."""
        return self.exchange(op_id, [table] * self.world, fields)

    def barrier(self, op_id: int) -> None:
        """Block until every rank reached this op — an allgather of a
        one-row sentinel.  Run before teardown so no peer's listener
        disappears while another rank still owes/awaits payloads."""
        import jax.numpy as jnp

        from spark_rapids_tpu.columns import dtypes
        from spark_rapids_tpu.columns.column import Column
        from spark_rapids_tpu.columns.table import Table
        col = Column(dtypes.INT64, 1,
                     data=jnp.asarray([self.rank], dtype=jnp.int64))
        out = self.allgather(op_id, Table([col]))
        if out.num_rows != self.world:
            raise RuntimeError(
                f"barrier saw {out.num_rows} ranks, want {self.world}")

    # ------------------------------------------------- elastic: sink
    # (listener handler threads call these for EDATA/CTRL frames)

    def _op_start(self, op_id: int) -> int:
        with self._op_t0_lock:
            t0 = self._op_t0.get(op_id)
            if t0 is None:
                t0 = self._op_t0[op_id] = time.monotonic_ns()
                if len(self._op_t0) > 256:
                    self._op_t0.pop(next(iter(self._op_t0)))
            return t0

    def on_edata(self, src: int, op_id: int, seq: int, epoch: int,
                 part_field: int, payload: bytes) -> bytes:
        """Verify + deliver one elastic data frame; returns the
        verdict bytes.  Raises ValueError/EOFError on a corrupt
        payload (the listener answers NAK)."""
        fleet = self.fleet
        if fleet.is_stale(epoch):
            _obs.record_fleet_stale_nak(src, epoch, fleet.epoch)
            return STALE + struct.pack(">I", fleet.epoch)
        fleet.learn_epoch(epoch)
        # NOTE: a current-epoch frame from a departed rank is merged
        # (the data is fine) but does NOT resurrect its membership —
        # a respawned worker announces itself with an explicit join
        # CTRL (ordered before its data on the same link), while a
        # late in-flight frame from a peer that gracefully LEFT must
        # not pull it back into the live set and point fanouts at a
        # closed listener.
        tables = _kudo.read_tables(io.BytesIO(payload))
        t0 = self._op_start(op_id)
        sub = unpack_resplit(part_field)
        if sub is None:
            part = part_field
            status = self.parts.put(op_id, part, tables, payload)
        else:
            part, k, nsub = sub
            status = self.parts.put_sub(op_id, part, k, nsub, tables,
                                        payload)
        if status.startswith("dup"):
            _obs.record_shuffle_dup_dropped(
                src, op_id, part,
                None if status == "dup_framing"
                else status == "dup_identical")
        elif status == "new":
            fleet.note_arrival(op_id, part, src,
                               time.monotonic_ns() - t0)
            # received payloads feed the op's skew window too — the
            # per-link byte counters this mirrors are the live signal
            # the re-split decision reads
            fleet.note_part_bytes(op_id, len(payload))
        _obs.record_shuffle_link("recv", src, len(payload), op_id)
        return ACK

    def on_ctrl(self, src: int, epoch: int, payload: bytes) -> bytes:
        """Control dispatch: death notices, joins, replay fetches,
        membership-view answers.  Always ACKs (notices are
        idempotent); malformed JSON raises ValueError -> NAK."""
        obj = json.loads(payload.decode("utf-8"))
        fleet = self.fleet
        typ = obj.get("type")
        if typ == "death":
            if fleet.note_death(obj.get("dead", ()),
                                epoch_hint=int(obj.get("epoch", 0))):
                self.parts.wake()
        elif typ == "join":
            joiner = int(obj.get("rank", src))
            fleet.note_join(joiner)
            self.parts.wake()
            # answer the joiner with our view so it fast-forwards its
            # epoch + departed set without waiting to be fenced
            self._spawn(self._send_view, joiner)
        elif typ == "leave":
            if fleet.note_leave(int(obj.get("rank", src))):
                self.parts.wake()
        elif typ == "view":
            fleet.note_death(obj.get("departed", ()),
                             epoch_hint=int(obj.get("epoch", 0)))
            fleet.learn_epoch(int(obj.get("epoch", 0)))
            self.parts.wake()
        elif typ == "fetch":
            # byte-safe replay: re-serve the retained CRC'd payloads
            # for the op (off the handler thread — replay sends block
            # for ACKs and must not stall this connection's reads)
            self._spawn(self._replay, src, int(obj.get("op", -1)),
                        obj.get("parts"))
        elif typ == "timeseries":
            # windowed telemetry publish (ISSUE 16): fold into the
            # fleet merger — dup windows dedup by sequence, snapshots
            # from a stale membership epoch are fenced outright (the
            # frame-level epoch fence already rejected older CARRIER
            # epochs; this guards the snapshot's own claimed epoch)
            snap = obj.get("snap") or {}
            outcome = self.fleet_timeseries.offer(snap)
            _obs.record_timeseries_merge(
                outcome, int(snap.get("rank", src)))
        else:
            raise ValueError(f"unknown control type {typ!r}")
        return ACK

    def _spawn(self, fn, *args) -> None:
        ctx = _obs.TRACER.current_context() or self.trace_ctx

        def run() -> None:
            holder = _obs.TRACER.activate(ctx)
            try:
                fn(*args)
            finally:
                holder.end()

        threading.Thread(target=run, daemon=True,
                         name=f"srt-fleet-ctrl-{self.rank}").start()

    # ----------------------------------------- elastic: send helpers

    def _elastic_send(self, dst: int, op_id: int, part_field: int,
                      payload: bytes, *, kind: int = KIND_EDATA
                      ) -> int:
        """One elastic send with stale-epoch fast-forward: a fence
        verdict teaches us the peer's epoch and the frame replays
        under it (bounded — a peer that keeps advancing mid-send is
        still making progress, not failing)."""
        for _ in range(3):
            try:
                return self.links[dst].send(
                    op_id, payload, kind=kind,
                    epoch=self.fleet.epoch, part=part_field)
            except StaleEpochError as e:
                self.fleet.learn_epoch(e.epoch)
        return 0  # persistently fenced: the peer no longer needs us

    def _send_ctrl(self, dst: int, obj: dict) -> None:
        payload = json.dumps(obj, sort_keys=True).encode("utf-8")
        self._elastic_send(dst, 0, 0, payload, kind=KIND_CTRL)

    def _send_view(self, dst: int) -> None:
        view = self.fleet.view()
        try:
            self._send_ctrl(dst, {
                "type": "view", "epoch": view.epoch,
                "departed": sorted(view.departed)})
        except (PeerDiedException, OSError):
            pass  # the joiner died again; its next join retries

    def publish_timeseries(self, snap: Optional[dict] = None
                           ) -> Optional[str]:
        """Ship this rank's windowed telemetry snapshot to rank 0's
        fleet merger over the CTRL path (rank 0 folds locally).  The
        send blocks for the ACK, so a completed publish IS merged —
        callers sequencing publish-then-barrier get a fully folded
        rank-0 view after the barrier.  Returns the merge outcome
        ('merged'/'dup'/'stale_epoch') on rank 0, 'sent' elsewhere,
        None when there is no elastic fabric (the launcher dump-dir
        tier covers that case offline)."""
        if self.fleet is None:
            return None
        if snap is None:
            snap = _obs.timeseries_snapshot(rank=self.rank,
                                            epoch=self.fleet.epoch)
        if self.rank == 0:
            outcome = self.fleet_timeseries.offer(snap)
            _obs.record_timeseries_merge(outcome, self.rank)
            return outcome
        try:
            self._send_ctrl(0, {"type": "timeseries", "snap": snap})
            return "sent"
        except (PeerDiedException, OSError):
            return None  # rank 0 is gone; nothing to publish to

    def _replay(self, dst: int, op_id: int, parts=None) -> None:
        blobs = self.parts.payloads(op_id)
        want = None if parts is None else set(int(p) for p in parts)
        for part, blob in sorted(blobs.items()):
            if want is not None and part not in want:
                continue
            try:
                self._elastic_send(dst, op_id, part, blob)
            except (PeerDiedException, OSError):
                return  # requester gone; nothing to do

    def _report_death(self, dead_rank: int) -> None:
        """A link to ``dead_rank`` exhausted its budget: fold the
        death in and gossip the notice to every survivor — the
        fleet-wide membership barrier.  Survivors that also failed to
        reach the peer converge on the same (departed, epoch) facts;
        assignment being a pure function of those facts IS the
        agreement."""
        fleet = self.fleet
        pending = {int(dead_rank)}
        while pending:
            d = pending.pop()
            if not fleet.note_death([d]):
                continue
            self.parts.wake()
            view = fleet.view()
            notice = {"type": "death", "dead": sorted(view.departed),
                      "epoch": view.epoch}
            for peer in sorted(view.live):
                if peer == self.rank:
                    continue
                try:
                    self._send_ctrl(peer, notice)
                except (PeerDiedException, OSError):
                    pending.add(peer)  # it died too: fold + re-gossip

    # -------------------------------------------- elastic: broadcast

    def broadcast_part(self, op_id: int, part: int, table, *,
                       resplit: bool = True) -> int:
        """Deliver one logical partition to EVERY live rank (self
        included — the local copy seeds the replay store and wins the
        dedup race for our own work).  A payload flagged hot by the
        fleet's skew signal re-splits into per-rank sub-frames.  A
        peer dying mid-fanout triggers the membership barrier and the
        broadcast continues to the survivors — delivery to the dead
        rank is the INHERITOR's problem now, not ours."""
        if self.fleet is None:
            raise RuntimeError("broadcast_part requires elastic=True")
        self._op_start(op_id)
        payload = self._serialize(table)
        hot = self.fleet.hot_part(op_id, len(payload)) \
            if resplit else None
        self.fleet.note_part_bytes(op_id, len(payload))
        if hot and table.num_rows >= 2:
            return self._broadcast_resplit(op_id, part, table, hot)
        status = self.parts.put(
            op_id, part, _kudo.read_tables(io.BytesIO(payload)),
            payload)
        if status != "new":
            return len(payload)  # a copy already won: spare the wire
        self._fanout(op_id, [(part, payload)])
        return len(payload)

    def _broadcast_resplit(self, op_id: int, part: int, table,
                           hot: dict) -> int:
        """Second sub-partitioned exchange round for a hot partition:
        row-sliced into one sub-frame per live rank, stitched back in
        index order by every receiver (concatenation of row slices is
        byte-identical to the unsplit table)."""
        view = self.fleet.view()
        rows = int(table.num_rows)
        nsub = max(2, min(self.fleet.policy.resplit_factor(view),
                          rows, MAX_RESPLIT_SUBS))
        subs: List[tuple] = []
        for k in range(nsub):
            lo = k * rows // nsub
            hi = (k + 1) * rows // nsub
            buf = io.BytesIO()
            _kudo.write_to_stream_with_metrics(
                table.columns, buf, lo, hi - lo)
            blob = buf.getvalue()
            self.parts.put_sub(
                op_id, part, k, nsub,
                _kudo.read_tables(io.BytesIO(blob)), blob)
            subs.append((pack_resplit(part, k, nsub), blob))
        total = sum(len(b) for _, b in subs)
        _obs.record_fleet_resplit(
            op_id, part, nsub, total,
            evidence=dict(hot, link_skew=self.fleet.link_skew()))
        self._fanout(op_id, subs)
        return total

    def _fanout(self, op_id: int,
                frames: List[tuple]) -> None:
        """Send (part_field, payload) frames to every live peer
        concurrently (one thread per peer, frames in order on each
        link).  Peer deaths fold into the membership barrier instead
        of failing the broadcast."""
        view = self.fleet.view()
        peers = [r for r in sorted(view.live) if r != self.rank]
        if not peers:
            return
        t_wire = time.monotonic_ns()
        dead: List[int] = []  # list.append is GIL-atomic
        ctx = _obs.TRACER.current_context()

        def one(dst: int) -> None:
            holder = _obs.TRACER.activate(ctx)
            try:
                for part_field, payload in frames:
                    self._elastic_send(dst, op_id, part_field,
                                       payload)
            except (PeerDiedException, OSError):
                dead.append(dst)
            finally:
                holder.end()

        workers = [threading.Thread(
            target=one, args=(d,), daemon=True,
            name=f"srt-fleet-bcast-{self.rank}-{d}") for d in peers]
        for w in workers:
            w.start()
        for w in workers:
            w.join()
        _obs.record_shuffle_wire(op_id,
                                 time.monotonic_ns() - t_wire)
        for d in dead:
            self._report_death(d)

    # ----------------------------------------------- elastic: gather

    def gather_parts(self, op_id: int, want,
                     *,
                     owner_of: Optional[Callable[[int], int]] = None,
                     compute: Optional[Callable] = None,
                     deadline_s: Optional[float] = None,
                     fetch_after_s: Optional[float] = None,
                     drop_departed: bool = False) -> Dict[int, list]:
        """Collect logical partitions, elastically.

        ``want``: part ids, or a callable ``view -> part ids`` (the
        barrier's membership-sensitive want).  ``owner_of``: part ->
        ORIGINAL owner rank (default: the fleet assignment, i.e.
        part == shard).  ``compute``: ``(part, ctx) -> Table``
        deterministic recompute — enables rebalance inheritance and
        straggler speculation; ``ctx`` is a cancel-capable
        QueryContext (None for non-speculative recomputes).
        ``drop_departed``: on deadline, departed owners' parts are
        dropped from the want set instead of failing (barrier
        semantics).  Returns {part: [KudoTable...]}."""
        if self.fleet is None:
            raise RuntimeError("gather_parts requires elastic=True")
        fleet = self.fleet
        deadline = (deadline_s if deadline_s is not None
                    else self.recv_timeout_s)
        fetch_after = (fetch_after_s if fetch_after_s is not None
                       else min(2.0, fleet.spec_delay_s))
        t0 = time.monotonic()
        self._op_start(op_id)
        done: Set[int] = set()       # parts I computed/speculated
        spec_seen: Set[int] = set()  # parts with a resolved decision
        last_fetch = 0.0
        fetch_rr = 0
        # gather idle, split by cause: waits while any missing part is
        # under a live speculation decision are a straggler's story
        # (speculation_wait), the rest ordinary inbox idle
        wait_ns = 0
        spec_wait_ns = 0
        with _obs.TRACER.span("elastic_gather", kind="stage",
                              attrs={"op": op_id}) as sp:
            while True:
                view = fleet.view()
                want_now = set(want(view) if callable(want) else want)
                missing = sorted(want_now - self.parts.have(op_id))
                if not missing:
                    break
                elapsed = time.monotonic() - t0
                for p in missing:
                    if owner_of is not None:
                        orig = resp = owner_of(p)
                    elif 0 <= p < view.world0:
                        # shard gather: shard p started on rank p;
                        # the CURRENT assignment names who answers
                        # for it after any rebalance
                        orig, resp = p, view.owner(p)
                    else:
                        orig = resp = p
                    if compute is not None and resp == self.rank \
                            and p not in done:
                        # my part — mine originally, or inherited
                        # from a departed rank at this epoch
                        if orig != self.rank:
                            _obs.JOURNAL.emit(
                                "fleet_inherit", op=op_id, part=p,
                                dead_owner=orig, epoch=view.epoch)
                        done.add(p)
                        self.broadcast_part(op_id, p,
                                            compute(p, None))
                        continue
                    if compute is not None and p not in spec_seen \
                            and resp != self.rank \
                            and resp not in view.departed:
                        ev = fleet.should_speculate(
                            op_id, int(elapsed * 1e9))
                        if ev:
                            spec_seen.add(p)
                            if fleet.policy.speculator(
                                    view, resp) == self.rank:
                                done.add(p)
                                self._speculate(op_id, p, resp,
                                                compute, ev)
                if missing and elapsed - last_fetch >= fetch_after \
                        and elapsed >= fetch_after:
                    # replay fetch (periodic): covers silently-dropped
                    # frames, late joiners catching up on a finished
                    # round, and replays lost to a peer's death — a
                    # failed fetch IS the death detection.  One peer
                    # per interval, round-robin: every replayed part
                    # arrives once instead of world-1 dup-dropped
                    # copies on an already-degraded fleet (failover
                    # is the next interval's rotation).
                    last_fetch = elapsed
                    fetch_peers = [p for p in sorted(view.live)
                                   if p != self.rank]
                    if fetch_peers:
                        peer = fetch_peers[fetch_rr
                                           % len(fetch_peers)]
                        fetch_rr += 1
                        self._spawn(self._fetch_from, peer,
                                    op_id, list(missing))
                if elapsed >= deadline:
                    missing = sorted(
                        set(want_now) - self.parts.have(op_id))
                    if not missing:
                        break
                    if drop_departed:
                        live_missing = [
                            p for p in missing
                            if (owner_of(p) if owner_of else p)
                            not in view.departed]
                        if not live_missing:
                            break  # only ghosts missing: proceed
                        missing = live_missing
                    if compute is not None:
                        # terminal fallback: every input is seeded +
                        # deterministic, so local recompute always
                        # converges (the fleet may be unreachable,
                        # the answer is not)
                        for p in missing:
                            self.broadcast_part(op_id, p,
                                                compute(p, None))
                        continue
                    raise PeerDiedException(
                        ",".join(str(owner_of(p) if owner_of else p)
                                 for p in missing),
                        0, detail=f"elastic gather op {op_id}: parts "
                                  f"{missing} missing after "
                                  f"{deadline:.1f}s")
                t_w = time.monotonic_ns()
                self.parts.wait_any(op_id, missing, 0.1)
                dt = time.monotonic_ns() - t_w
                if spec_seen.intersection(missing):
                    spec_wait_ns += dt
                else:
                    wait_ns += dt
            _obs.record_shuffle_wait(op_id, wait_ns, spec_wait_ns)
            have = self.parts.get(op_id)
            want_final = set(want(fleet.view())
                             if callable(want) else want)
            sp.set_attr("parts", len(want_final))
            sp.set_attr("epoch", fleet.epoch)
            return {p: have[p] for p in want_final if p in have}

    def _fetch_from(self, peer: int, op_id: int,
                    parts=None) -> None:
        try:
            self._send_ctrl(peer, {"type": "fetch", "op": op_id,
                                   "parts": parts})
        except (PeerDiedException, OSError):
            self._report_death(peer)

    def _speculate(self, op_id: int, part: int, owner: int, compute,
                   evidence: dict) -> None:
        """Speculatively re-execute a straggler's partition.  First
        byte-identical result wins the (op, part) dedup; if the
        original arrives while we compute, the cancel event trips and
        the speculative task unwinds through the cooperative
        QueryContext machinery (outcome 'cancelled')."""
        from spark_rapids_tpu.models import QueryCancelled, \
            QueryContext
        cancel = threading.Event()
        done = threading.Event()

        def watch() -> None:
            # trip the cancel the moment the original lands
            while not done.is_set():
                if self.parts.wait_any(op_id, {part}, 0.2):
                    cancel.set()
                    return

        watcher = threading.Thread(
            target=watch, daemon=True,
            name=f"srt-fleet-spec-watch-{self.rank}")
        watcher.start()
        ctx = QueryContext(query_id=f"spec:{op_id}:{part}",
                           cancel_event=cancel)
        try:
            table = compute(part, ctx)
        except QueryCancelled:
            _obs.record_fleet_speculation(op_id, part, owner,
                                          self.rank, "cancelled",
                                          evidence)
            return
        finally:
            done.set()
        payload = self._serialize(table)
        status = self.parts.put(
            op_id, part, _kudo.read_tables(io.BytesIO(payload)),
            payload)
        if status == "new":
            _obs.record_fleet_speculation(op_id, part, owner,
                                          self.rank, "won", evidence)
            self._fanout(op_id, [(part, payload)])
        else:
            _obs.record_fleet_speculation(op_id, part, owner,
                                          self.rank, "lost", evidence)

    # ---------------------------------------------- elastic: barrier

    def elastic_barrier(self, op_id: int,
                        deadline_s: Optional[float] = None) -> None:
        """Membership-tolerant barrier: every rank broadcasts a
        sentinel part keyed by its RANK and waits for the sentinels of
        the ranks it owes waiting to — the live set, or (when the
        launcher may respawn the dead: SPARK_RAPIDS_TPU_FLEET_RESPAWN)
        the full original world, so a rejoining worker finds its peers
        still listening and can catch up by replay.  Departed ranks
        that never return are dropped at the deadline."""
        import jax.numpy as jnp

        from spark_rapids_tpu.columns import dtypes
        from spark_rapids_tpu.columns.column import Column
        from spark_rapids_tpu.columns.table import Table
        if deadline_s is None:
            try:
                deadline_s = float(os.environ.get(
                    "SPARK_RAPIDS_TPU_FLEET_BARRIER_S", "") or 120.0)
            except ValueError:
                deadline_s = 120.0
        await_all = os.environ.get(
            "SPARK_RAPIDS_TPU_FLEET_RESPAWN", "") == "1"
        col = Column(dtypes.INT64, 1,
                     data=jnp.asarray([self.rank], dtype=jnp.int64))
        self.broadcast_part(op_id, self.rank, Table([col]),
                            resplit=False)

        def want(view):
            return (set(range(view.world0)) if await_all
                    else set(view.live))

        self.gather_parts(op_id, want, owner_of=lambda p: p,
                          deadline_s=deadline_s, drop_departed=True)

    def leave_fleet(self) -> None:
        """Graceful departure: tell every live peer we are leaving so
        their barrier wants shrink NOW instead of waiting out a death
        detection — the worker sends this after it passed its own
        barrier, so a peer dropping us from its want set is provably
        safe.  Best-effort: peers already gone are skipped."""
        if self.fleet is None:
            return
        view = self.fleet.view()
        for peer in sorted(view.live):
            if peer == self.rank:
                continue
            try:
                self._send_ctrl(peer, {"type": "leave",
                                       "rank": self.rank})
            except (PeerDiedException, OSError):
                continue

    def join_fleet(self, timeout_s: float = 10.0) -> None:
        """(Re)join a running fleet: announce to every peer, then wait
        briefly for a view answer so our epoch + departed set are
        current before we start fencing/being fenced."""
        if self.fleet is None:
            raise RuntimeError("join_fleet requires elastic=True")
        base = self.fleet.epoch
        for peer in range(self.world):
            if peer == self.rank:
                continue
            try:
                self._send_ctrl(peer, {"type": "join",
                                       "rank": self.rank})
            except (PeerDiedException, OSError):
                continue
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout_s:
            if self.fleet.epoch > base:
                return
            time.sleep(0.05)

    # ---------------------------------------------------- installation

    def install(self) -> "ShuffleService":
        """Register as the process's table transport
        (parallel/exchange.exchange_tables routes here)."""
        _exchange.set_table_transport(self)
        return self

    def uninstall(self) -> None:
        if _exchange._TABLE_TRANSPORT[0] is self:
            _exchange.set_table_transport(None)
