"""Peer-to-peer shuffle transport: framed kudo streams over
TCP/unix sockets (ISSUE 10 tentpole, layer 1 of 2 — the
ShuffleService in service.py owns partitioning/merging; this module
owns bytes on wires).

Wire protocol (one directed link = one persistent connection from the
sending rank to the receiving rank's listener):

  frame:   "SRTS" | u8 kind | u32 src_rank | u32 op_id | u32 seq |
           u64 payload_len   (big-endian, 25 bytes)
  payload: a kudo table stream — the EXISTING inter-host wire format:
           optional KTRX trace-context extension + KUD0 header/body +
           KCRC integrity trailer per table.  The transport adds
           nothing to the bytes the shuffle already knows how to
           write, verify, and merge.

Delivery contract (push + ack):

  * DATA: sender transmits frame+payload, then blocks for a 1-byte
    verdict: b"A" (payload parsed AND CRC-verified by the receiving
    kudo reader) or b"N" (corrupt — the reader raised
    KudoCorruptException).  Anything else — EOF, reset, timeout — is a
    transient link failure.
  * Retries ride :func:`robustness.links.with_link_retry` (the shared
    RetryPolicy: bounded attempts, decorrelated-jitter backoff,
    wall-clock deadline); a NAK or link error resends the sender's
    INTACT copy of the payload over a fresh connection if needed.
    Budget exhaustion raises PeerDiedException.
  * Duplicates (an ACK lost in flight makes the sender resend a
    payload the receiver already accepted) are deduplicated by
    (src, op_id, seq) and re-ACKed without re-delivery.

Fault injection for the chaos/dist gates: set
``SPARK_RAPIDS_TPU_DIST_FAULT="corrupt:<dst>:<op>"`` (or
``trunc:<dst>:<op>``) in a worker's environment and its FIRST send to
that destination/op is corrupted (one payload byte XOR'd after CRC
computation) or truncated mid-payload with a hard close — the receiver
NAKs / the ack read fails, and the retry loop must recover with a
clean resend.  Programmatic twin: :func:`set_link_fault`.
"""

from __future__ import annotations

import os
import socket
import struct
import threading

from spark_rapids_tpu.analysis import lockdep
from spark_rapids_tpu.analysis.lockdep import make_lock
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu import observability as _obs
from spark_rapids_tpu.robustness.links import (
    PeerDiedException, ShuffleLinkError, with_link_retry)
from spark_rapids_tpu.robustness.retry import RetryPolicy
from spark_rapids_tpu.shuffle import kudo as _kudo
from spark_rapids_tpu.shuffle.socket_io import SocketStream

FRAME_MAGIC = b"SRTS"
FRAME_FMT = ">4sBIIIQ"
FRAME_LEN = struct.calcsize(FRAME_FMT)  # 25
KIND_DATA = 1
ACK = b"A"
NAK = b"N"
MAX_PAYLOAD = 1 << 30  # sanity bound: refuse absurd frame lengths


def _parse_addr(addr: str):
    """'unix:/path' -> (AF_UNIX, path); 'host:port' -> (AF_INET, ...)."""
    if addr.startswith("unix:"):
        return socket.AF_UNIX, addr[5:]
    host, _, port = addr.rpartition(":")
    return socket.AF_INET, (host or "127.0.0.1", int(port))


# ----------------------------------------------------- fault injection

_FAULT_LOCK = make_lock("dist.fault")
# {(mode, dst, op): remaining} — armed once from env or set_link_fault
_FAULTS: Dict[Tuple[str, int, int], int] = {}


def set_link_fault(mode: str, dst: int, op_id: int,
                   times: int = 1) -> None:
    """Arm a one-shot (default) send fault: ``mode`` 'corrupt' flips a
    payload byte after serialization; 'trunc' sends half the payload
    and hard-closes the connection."""
    with _FAULT_LOCK:
        _FAULTS[(mode, int(dst), int(op_id))] = int(times)


def clear_link_faults() -> None:
    with _FAULT_LOCK:
        _FAULTS.clear()


def _env_faults() -> None:
    spec = os.environ.get("SPARK_RAPIDS_TPU_DIST_FAULT", "")
    if not spec:
        return
    for one in spec.split(","):
        try:
            mode, dst, op = one.strip().split(":")
            set_link_fault(mode, int(dst), int(op))
        except ValueError:
            pass  # garbled spec: ignore, like the fault injector does


_env_faults()


def _take_fault(dst: int, op_id: int) -> Optional[str]:
    with _FAULT_LOCK:
        for mode in ("corrupt", "trunc"):
            key = (mode, dst, op_id)
            left = _FAULTS.get(key, 0)
            if left > 0:
                _FAULTS[key] = left - 1
                return mode
    return None


# -------------------------------------------------------------- inbox


class Inbox:
    """Received, CRC-verified payloads keyed by (op_id, src_rank).
    ``wait`` blocks until every listed source delivered (or the
    deadline lapses -> PeerDiedException naming the missing peers)."""

    def __init__(self):
        self._lock = make_lock("dist.inbox")
        self._cv = threading.Condition(self._lock)
        self._slots: Dict[Tuple[int, int], List[_kudo.KudoTable]] = {}
        # (op_id, src) keys whose round died in wait(): a handler
        # thread that was mid-verify when the deadline lapsed may
        # still put() AFTER the cleanup below — each tombstone absorbs
        # exactly that one late delivery (one-shot, so a genuinely new
        # round reusing the op id starts clean)
        self._dead: Dict[Tuple[int, int], bool] = {}

    def put(self, op_id: int, src: int,
            tables: List[_kudo.KudoTable]) -> None:
        with self._cv:
            if self._dead.pop((op_id, src), None):
                return  # late delivery for a timed-out round: drop
            self._slots[(op_id, src)] = tables
            self._cv.notify_all()

    def wait(self, op_id: int, srcs, timeout_s: float
             ) -> Dict[int, List[_kudo.KudoTable]]:
        want = set(int(s) for s in srcs)
        with self._cv:
            ok = self._cv.wait_for(
                lambda: all((op_id, s) in self._slots for s in want),
                timeout=timeout_s)
            if not ok:
                missing = sorted(s for s in want
                                 if (op_id, s) not in self._slots)
                # the round is dead: discard what DID arrive for it,
                # so a retried exchange reusing this op id can never
                # merge a previous attempt's partitions (and failed
                # ops don't accrete slots forever); missing peers get
                # a tombstone so an in-flight late delivery is
                # absorbed too (bounded: one entry per missing peer)
                for s in want:
                    if self._slots.pop((op_id, s), None) is None:
                        self._dead[(op_id, s)] = True
                        if len(self._dead) > 1024:
                            self._dead.pop(next(iter(self._dead)))
                raise PeerDiedException(
                    ",".join(map(str, missing)), 0,
                    detail=f"no payload for op {op_id} within "
                           f"{timeout_s:.1f}s")
            return {s: self._slots.pop((op_id, s)) for s in want}


# ----------------------------------------------------------- listener


class Listener:
    """This rank's receive side: a bounded accept loop; one handler
    thread per inbound connection reading DATA frames, verifying the
    kudo payload (CRC included) and answering A/N.  Short payloads
    (a truncated link) drop the partial bytes and close — the sender's
    ack read fails and its retry resends."""

    def __init__(self, rank: int, addr: str, inbox: Inbox):
        self.rank = rank
        self.addr = addr
        self.inbox = inbox
        self._sock: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self._conns: set = set()
        self._conns_lock = make_lock("dist.listener.conns")
        self._stop = threading.Event()
        # (src, op, seq) already delivered — a resend after a lost ACK
        # re-ACKs without re-inserting.  Recorded only AFTER a
        # successful verify+deliver (a NAKed payload was never
        # delivered, so its clean resend must not be deduped), which
        # also keeps _seen and its eviction order in lockstep.
        # Bounded: shuffle ops are short-lived, 4096 message ids
        # dwarf any in-flight window.
        self._seen: Dict[Tuple[int, int, int], bool] = {}
        self._seen_order: List[Tuple[int, int, int]] = []
        self._seen_lock = make_lock("dist.listener.seen")

    def start(self) -> "Listener":
        fam, target = _parse_addr(self.addr)
        if fam == socket.AF_UNIX:
            try:
                os.unlink(target)
            except OSError:
                pass
        s = socket.socket(fam, socket.SOCK_STREAM)
        if fam == socket.AF_INET:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(target)
        s.listen(16)
        s.settimeout(0.2)
        self._sock = s
        t = threading.Thread(target=self._accept_loop,
                             name=f"srt-shuffle-accept-{self.rank}",
                             daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        # close accepted connections so handler threads blocked in
        # stream.read unwind immediately instead of riding out their
        # 60s socket timeout past the join below
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2.0)
        fam, target = _parse_addr(self.addr)
        if fam == socket.AF_UNIX:
            try:
                os.unlink(target)
            except OSError:
                pass

    # ------------------------------------------------------- internals

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed
            with self._conns_lock:
                self._conns.add(conn)
            t = threading.Thread(
                target=self._serve, args=(conn,),
                name=f"srt-shuffle-recv-{self.rank}", daemon=True)
            t.start()
            # prune finished handlers so a fault-heavy soak (every
            # reconnect is a new connection) doesn't accrete dead
            # Thread objects
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _already_delivered(self, key: Tuple[int, int, int]) -> bool:
        with self._seen_lock:
            return key in self._seen

    def _mark_delivered(self, key: Tuple[int, int, int]) -> None:
        with self._seen_lock:
            if key in self._seen:
                return
            self._seen[key] = True
            self._seen_order.append(key)
            if len(self._seen_order) > 4096:
                old = self._seen_order.pop(0)
                self._seen.pop(old, None)

    def _serve(self, conn: socket.socket) -> None:
        conn.settimeout(60.0)
        stream = SocketStream(conn)
        try:
            while not self._stop.is_set():
                head = stream.read(FRAME_LEN)
                if len(head) < FRAME_LEN:
                    return  # clean close (or trailing garbage: drop)
                magic, kind, src, op_id, seq, length = struct.unpack(
                    FRAME_FMT, head)
                if (magic != FRAME_MAGIC or kind != KIND_DATA
                        or length > MAX_PAYLOAD):
                    return  # protocol violation: drop the connection
                payload = stream.read(length)
                if len(payload) < length:
                    # truncated link mid-payload: the partial bytes
                    # are unusable — drop them, close, let the
                    # sender's retry resend over a fresh connection
                    _obs.record_kudo_corruption(
                        "resync", skipped_bytes=len(payload),
                        detail=f"truncated link from rank {src} "
                               f"op {op_id}")
                    return
                self._answer(conn, src, op_id, seq, payload)
        except OSError:
            return
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _answer(self, conn, src: int, op_id: int, seq: int,
                payload: bytes) -> None:
        import io
        key = (src, op_id, seq)
        if self._already_delivered(key):
            conn.sendall(ACK)  # duplicate after a lost ACK
            return
        try:
            # the verify pass IS the normal kudo read: every KCRC
            # trailer present is checked, impossible headers raise
            tables = _kudo.read_tables(io.BytesIO(payload))
        except (ValueError, EOFError):
            # corrupt payload: NAK (corruption was already recorded at
            # the kudo verify site); nothing was delivered, so nothing
            # is remembered and the clean resend goes through
            conn.sendall(NAK)
            return
        self.inbox.put(op_id, src, tables)
        self._mark_delivered(key)
        _obs.record_shuffle_link("recv", src, len(payload), op_id)
        conn.sendall(ACK)


# ---------------------------------------------------------- peer link


class PeerLink:
    """The sending half of one directed link.  Lazily connects (with
    connect itself inside the retry loop so a slow-starting peer is a
    transient, not an error) and keeps the connection for subsequent
    sends."""

    def __init__(self, my_rank: int, peer_rank: int, addr: str, *,
                 policy: Optional[RetryPolicy] = None,
                 ack_timeout_s: float = 30.0):
        self.my_rank = my_rank
        self.peer_rank = peer_rank
        self.addr = addr
        self.policy = policy
        self.ack_timeout_s = ack_timeout_s
        self._sock: Optional[socket.socket] = None
        self._seq = 0
        self._lock = make_lock("dist.peer_link")

    # ------------------------------------------------------- plumbing

    def _connect(self) -> socket.socket:
        if self._sock is not None:
            return self._sock
        fam, target = _parse_addr(self.addr)
        s = socket.socket(fam, socket.SOCK_STREAM)
        s.settimeout(self.ack_timeout_s)
        s.connect(target)
        self._sock = s
        return s

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._drop()

    # ----------------------------------------------------------- send

    def send(self, op_id: int, payload: bytes) -> int:
        """Deliver one kudo payload; returns bytes sent.  Blocks until
        the peer ACKs (payload verified) or the retry budget dies."""
        with self._lock:
            self._seq += 1
            seq = self._seq
        head = struct.pack(FRAME_FMT, FRAME_MAGIC, KIND_DATA,
                           self.my_rank, op_id, seq, len(payload))

        def attempt() -> int:
            with self._lock:
                try:
                    s = self._connect()
                    # arm the injected fault only once a connection
                    # exists: a transient connect failure must not
                    # burn the one-shot injection before any faulty
                    # byte could hit the wire (the chaos gate's
                    # "corrupt link healed" signal would go vacuous)
                    fault = _take_fault(self.peer_rank, op_id)
                    if fault == "trunc":
                        # inject a truncated link: half the payload,
                        # then a hard close mid-message
                        s.sendall(head + payload[: len(payload) // 2])
                        self._drop()
                        raise ShuffleLinkError(
                            "injected truncated link", reason="link")
                    wire = payload
                    if fault == "corrupt":
                        flip = len(payload) // 2
                        wire = (payload[:flip]
                                + bytes([payload[flip] ^ 0xFF])
                                + payload[flip + 1:])
                    # lockdep marker: this link mutex is held across
                    # the wire round-trip BY DESIGN (it serializes one
                    # peer's protocol); the evidence lets an operator
                    # see exactly how long-held it is
                    lockdep.note_blocking("transport.send")
                    s.sendall(head + wire)
                    verdict = s.recv(1)
                except OSError:
                    self._drop()
                    raise
                if verdict == ACK:
                    return len(payload)
                self._drop()
                if verdict == NAK:
                    raise ShuffleLinkError(
                        f"peer {self.peer_rank} NAKed op {op_id} "
                        f"seq {seq}", reason="nak")
                raise ShuffleLinkError(
                    f"link to peer {self.peer_rank} closed before "
                    f"verdict (op {op_id})", reason="link")

        with _obs.TRACER.span("shuffle_send", kind="shuffle_send",
                              attrs={"peer": self.peer_rank,
                                     "op": op_id,
                                     "bytes": len(payload)}):
            n = with_link_retry(attempt, peer=self.peer_rank,
                                policy=self.policy)
        _obs.record_shuffle_link("send", self.peer_rank, n, op_id)
        return n
