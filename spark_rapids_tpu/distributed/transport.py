"""Peer-to-peer shuffle transport: framed kudo streams over
TCP/unix sockets (ISSUE 10 tentpole, layer 1 of 2 — the
ShuffleService in service.py owns partitioning/merging; this module
owns bytes on wires).

Wire protocol (one directed link = one persistent connection from the
sending rank to the receiving rank's listener):

  frame:   "SRTS" | u8 kind | u32 src_rank | u32 op_id | u32 seq |
           u64 payload_len   (big-endian, 25 bytes)
  payload: a kudo table stream — the EXISTING inter-host wire format:
           optional KTRX trace-context extension + KUD0 header/body +
           KCRC integrity trailer per table.  The transport adds
           nothing to the bytes the shuffle already knows how to
           write, verify, and merge.

Delivery contract (push + ack):

  * DATA: sender transmits frame+payload, then blocks for a 1-byte
    verdict: b"A" (payload parsed AND CRC-verified by the receiving
    kudo reader) or b"N" (corrupt — the reader raised
    KudoCorruptException).  Anything else — EOF, reset, timeout — is a
    transient link failure.
  * Retries ride :func:`robustness.links.with_link_retry` (the shared
    RetryPolicy: bounded attempts, decorrelated-jitter backoff,
    wall-clock deadline); a NAK or link error resends the sender's
    INTACT copy of the payload over a fresh connection if needed.
    Budget exhaustion raises PeerDiedException.
  * Duplicates (an ACK lost in flight makes the sender resend a
    payload the receiver already accepted) are deduplicated by
    (src, op_id, seq) and re-ACKed without re-delivery.

Elastic extension (ISSUE 15): frames of kind EDATA carry an 8-byte
extension — ``u32 epoch | u32 part`` — between the base header and the
payload.  ``epoch`` is the sender's fleet-membership epoch
(robustness/fleet.py): a receiver whose view is AHEAD answers the
``E`` verdict (1 byte + its current u32 epoch) instead of merging, so
a zombie rank cannot push partitions into a round that already
rebalanced away from it.  ``part`` names the logical partition; the
receive side dedups by (op, part) — the FIRST verified copy wins,
later copies (speculation losers, rebalance replays) are byte-compared
and dropped into ``srt_shuffle_dup_dropped_total``.  A re-split hot
partition travels as sub-frames whose part field packs
(part, sub-index, sub-count); the :class:`PartInbox` stitches them
back in index order.  CTRL frames (same extension) carry small JSON
control payloads: death notices, joins, replay fetches.

Fault injection for the chaos/dist/elastic gates: set
``SPARK_RAPIDS_TPU_DIST_FAULT="corrupt:<dst>:<op>"`` (or
``trunc:<dst>:<op>``) in a worker's environment and its FIRST send to
that destination/op is corrupted (one payload byte XOR'd after CRC
computation) or truncated mid-payload with a hard close — the receiver
NAKs / the ack read fails, and the retry loop must recover with a
clean resend.  ``drop:<dst>:<op>`` silently drops the frame (the
sender forges local success, the receiver never sees it — the
speculation path's chaos mode), and ``slow:<dst>:<ms>`` injects a
PERSISTENT per-frame delay of ``ms`` milliseconds on every send to
``dst`` (the straggler chaos mode).  ``<dst>``/``<op>`` accept ``-1``
as a wildcard.  Programmatic twin: :func:`set_link_fault`.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time

from spark_rapids_tpu.analysis import lockdep
from spark_rapids_tpu.analysis.lockdep import make_lock
from typing import Dict, List, Optional, Set, Tuple

from spark_rapids_tpu import observability as _obs
from spark_rapids_tpu.robustness.fleet import StaleEpochError
from spark_rapids_tpu.robustness.links import (
    PeerDiedException, ShuffleLinkError, with_link_retry)
from spark_rapids_tpu.robustness.retry import RetryPolicy
from spark_rapids_tpu.shuffle import kudo as _kudo
from spark_rapids_tpu.shuffle.socket_io import SocketStream

FRAME_MAGIC = b"SRTS"
FRAME_FMT = ">4sBIIIQ"
FRAME_LEN = struct.calcsize(FRAME_FMT)  # 25
# elastic extension: u32 epoch | u32 part, between header and payload
EXT_FMT = ">II"
EXT_LEN = struct.calcsize(EXT_FMT)  # 8
KIND_DATA = 1
KIND_EDATA = 2   # elastic data: epoch-fenced, (op, part)-deduped
KIND_CTRL = 3    # elastic control: JSON payload (death/join/fetch)
ACK = b"A"
NAK = b"N"
STALE = b"E"     # stale-epoch fence; followed by the receiver's u32 epoch
MAX_PAYLOAD = 1 << 30  # sanity bound: refuse absurd frame lengths

# re-split part-field packing: flag | part(15b) | sub k (8b) | nsub (8b)
RESPLIT_FLAG = 0x80000000
MAX_RESPLIT_PART = (1 << 15) - 1
MAX_RESPLIT_SUBS = (1 << 8) - 1


def pack_resplit(part: int, k: int, nsub: int) -> int:
    if not (0 <= part <= MAX_RESPLIT_PART
            and 0 <= k < nsub <= MAX_RESPLIT_SUBS):
        raise ValueError(f"resplit out of range: part={part} k={k} "
                         f"nsub={nsub}")
    return RESPLIT_FLAG | (part << 16) | (k << 8) | nsub


def unpack_resplit(field: int) -> Optional[Tuple[int, int, int]]:
    """(part, k, nsub) when ``field`` is a re-split sub-frame, else
    None (a plain part id)."""
    if not field & RESPLIT_FLAG:
        return None
    return (field >> 16) & MAX_RESPLIT_PART, (field >> 8) & 0xFF, \
        field & 0xFF


def _parse_addr(addr: str):
    """'unix:/path' -> (AF_UNIX, path); 'host:port' -> (AF_INET, ...)."""
    if addr.startswith("unix:"):
        return socket.AF_UNIX, addr[5:]
    host, _, port = addr.rpartition(":")
    return socket.AF_INET, (host or "127.0.0.1", int(port))


# ----------------------------------------------------- fault injection

_FAULT_LOCK = make_lock("dist.fault")
# {(mode, dst, op): remaining} — armed once from env or set_link_fault
_FAULTS: Dict[Tuple[str, int, int], int] = {}
# {dst: delay_ms} — PERSISTENT per-frame injected delay (dst -1 = any)
_SLOW: Dict[int, int] = {}


def set_link_fault(mode: str, dst: int, op_id: int,
                   times: int = 1) -> None:
    """Arm a one-shot (default) send fault: ``mode`` 'corrupt' flips a
    payload byte after serialization; 'trunc' sends half the payload
    and hard-closes the connection; 'drop' silently discards the frame
    (the sender forges success — the receiver must recover by
    speculation or rebalance, not resend).  ``mode`` 'slow' is
    different: the third argument is a PER-FRAME delay in
    milliseconds, applied to every send to ``dst`` until cleared (the
    injected-straggler mode).  ``dst``/``op_id`` of -1 match any."""
    with _FAULT_LOCK:
        if mode == "slow":
            _SLOW[int(dst)] = int(op_id)
        else:
            _FAULTS[(mode, int(dst), int(op_id))] = int(times)


def clear_link_faults() -> None:
    with _FAULT_LOCK:
        _FAULTS.clear()
        _SLOW.clear()


def _env_faults() -> None:
    spec = os.environ.get("SPARK_RAPIDS_TPU_DIST_FAULT", "")
    if not spec:
        return
    for one in spec.split(","):
        try:
            mode, dst, op = one.strip().split(":")
            set_link_fault(mode, int(dst), int(op))
        except ValueError:
            pass  # garbled spec: ignore, like the fault injector does


_env_faults()


def _take_fault(dst: int, op_id: int) -> Optional[str]:
    with _FAULT_LOCK:
        for mode in ("corrupt", "trunc", "drop"):
            for key in ((mode, dst, op_id), (mode, -1, op_id),
                        (mode, dst, -1), (mode, -1, -1)):
                left = _FAULTS.get(key, 0)
                if left > 0:
                    _FAULTS[key] = left - 1
                    return mode
    return None


def _slow_ms(dst: int) -> int:
    with _FAULT_LOCK:
        return _SLOW.get(dst, _SLOW.get(-1, 0))


# -------------------------------------------------------------- inbox


class Inbox:
    """Received, CRC-verified payloads keyed by (op_id, src_rank).
    ``wait`` blocks until every listed source delivered (or the
    deadline lapses -> PeerDiedException naming the missing peers)."""

    def __init__(self):
        self._lock = make_lock("dist.inbox")
        self._cv = threading.Condition(self._lock)
        self._slots: Dict[Tuple[int, int], List[_kudo.KudoTable]] = {}
        # (op_id, src) keys whose round died in wait(): a handler
        # thread that was mid-verify when the deadline lapsed may
        # still put() AFTER the cleanup below — each tombstone absorbs
        # exactly that one late delivery (one-shot, so a genuinely new
        # round reusing the op id starts clean)
        self._dead: Dict[Tuple[int, int], bool] = {}

    def put(self, op_id: int, src: int,
            tables: List[_kudo.KudoTable]) -> None:
        with self._cv:
            if self._dead.pop((op_id, src), None):
                return  # late delivery for a timed-out round: drop
            self._slots[(op_id, src)] = tables
            self._cv.notify_all()

    def wait(self, op_id: int, srcs, timeout_s: float
             ) -> Dict[int, List[_kudo.KudoTable]]:
        want = set(int(s) for s in srcs)
        with self._cv:
            ok = self._cv.wait_for(
                lambda: all((op_id, s) in self._slots for s in want),
                timeout=timeout_s)
            if not ok:
                missing = sorted(s for s in want
                                 if (op_id, s) not in self._slots)
                # the round is dead: discard what DID arrive for it,
                # so a retried exchange reusing this op id can never
                # merge a previous attempt's partitions (and failed
                # ops don't accrete slots forever); missing peers get
                # a tombstone so an in-flight late delivery is
                # absorbed too (bounded: one entry per missing peer)
                for s in want:
                    if self._slots.pop((op_id, s), None) is None:
                        self._dead[(op_id, s)] = True
                        if len(self._dead) > 1024:
                            self._dead.pop(next(iter(self._dead)))
                raise PeerDiedException(
                    ",".join(map(str, missing)), 0,
                    detail=f"no payload for op {op_id} within "
                           f"{timeout_s:.1f}s")
            return {s: self._slots.pop((op_id, s)) for s in want}


# --------------------------------------------------------- part inbox


class PartInbox:
    """Elastic receive state: verified tables keyed by (op, part),
    FIRST verified copy wins.  Also stitches re-split sub-frames back
    into whole parts (index order) and keeps the verified payload
    bytes per part — the byte-safe replay store a FETCH control
    message re-serves (kudo frames are CRC'd end to end, so a replayed
    payload is provably the original bytes)."""

    MAX_OPS = 32  # replay store bound: oldest op evicted past this

    def __init__(self):
        self._lock = make_lock("dist.part_inbox")
        self._cv = threading.Condition(self._lock)
        # op -> {part: [KudoTable]}; payloads keyed (op, part)
        self._parts: Dict[int, Dict[int, list]] = {}
        self._payloads: Dict[Tuple[int, int], bytes] = {}
        # in-flight re-split assembly: (op, part) -> {k: (tables, payload)}
        self._subs: Dict[Tuple[int, int], Dict[int, tuple]] = {}
        # parts stitched from sub-frames: their stored payload is a
        # sub-blob concatenation, NOT byte-comparable against a
        # whole-table serialization of the same rows
        self._assembled: Set[Tuple[int, int]] = set()
        self._order: List[int] = []

    def _op_slot(self, op_id: int) -> Dict[int, list]:
        cur = self._parts.get(op_id)
        if cur is None:
            cur = self._parts[op_id] = {}
            self._order.append(op_id)
            while len(self._order) > self.MAX_OPS:
                old = self._order.pop(0)
                for p in self._parts.pop(old, {}):
                    self._payloads.pop((old, p), None)
                    self._assembled.discard((old, p))
                for key in [k for k in self._subs if k[0] == old]:
                    self._subs.pop(key, None)
        return cur

    def put(self, op_id: int, part: int, tables: list,
            payload: bytes) -> str:
        """Deliver one whole part.  Returns 'new' when this copy won,
        'dup_identical' / 'dup_mismatch' when a copy already merged
        (the byte compare is the speculative-winner contract:
        deterministic recomputes MUST collide byte-identically), or
        'dup_framing' when the winning copy was stitched from
        re-split sub-frames — same rows, different framing, so the
        byte compare is inapplicable (NOT corruption evidence)."""
        with self._cv:
            return self._put_locked(op_id, part, tables, payload)

    def _put_locked(self, op_id: int, part: int, tables, payload,
                    assembled: bool = False):
        cur = self._op_slot(op_id)
        if part in cur:
            if assembled or (op_id, part) in self._assembled:
                return "dup_framing"
            same = payload == self._payloads.get((op_id, part))
            return "dup_identical" if same else "dup_mismatch"
        cur[part] = tables
        self._payloads[(op_id, part)] = payload
        if assembled:
            self._assembled.add((op_id, part))
        self._subs.pop((op_id, part), None)
        self._cv.notify_all()
        return "new"

    def put_sub(self, op_id: int, part: int, k: int, nsub: int,
                tables: list, payload: bytes) -> str:
        """One re-split sub-frame.  When the last sub arrives the part
        assembles in index order (row-slice concatenation — the merged
        table is byte-identical to the unsplit original).  Returns
        'sub' (still assembling), 'new' (assembled just now),
        'dup_identical'/'dup_mismatch' for a duplicate sub-frame, or
        'dup_framing' when the whole part already merged (a sub
        colliding with a whole-table copy differs by framing alone)."""
        with self._cv:
            cur = self._op_slot(op_id)
            if part in cur:
                return "dup_framing"  # whole part already won
            entry = self._subs.setdefault((op_id, part), {})
            if k in entry:
                return ("dup_identical" if payload == entry[k][1]
                        else "dup_mismatch")
            entry[k] = (tables, payload)
            if len(entry) < nsub:
                return "sub"
            all_tables: list = []
            blobs: List[bytes] = []
            for i in range(nsub):
                t, b = entry[i]
                all_tables.extend(t)
                blobs.append(b)
            return self._put_locked(op_id, part, all_tables,
                                    b"".join(blobs), assembled=True)

    def have(self, op_id: int) -> Set[int]:
        with self._cv:
            return set(self._parts.get(op_id, ()))

    def get(self, op_id: int) -> Dict[int, list]:
        with self._cv:
            return dict(self._parts.get(op_id, {}))

    def payloads(self, op_id: int) -> Dict[int, bytes]:
        """The replay store for one op (FETCH serves these)."""
        with self._cv:
            return {p: self._payloads[(op_id, p)]
                    for p in self._parts.get(op_id, ())}

    def wait_any(self, op_id: int, want, timeout_s: float) -> bool:
        """Block until any part in ``want`` is present (or any
        membership wake poke) — the gather loop re-evaluates policy on
        every wake, so spurious wakes are cheap."""
        want = set(want)
        with self._cv:
            return self._cv.wait_for(
                lambda: bool(want & set(self._parts.get(op_id, ()))),
                timeout=timeout_s)

    def wake(self) -> None:
        """Membership changed: poke every waiter so gather loops
        re-read the fleet view immediately instead of riding out
        their poll timeout."""
        with self._cv:
            self._cv.notify_all()


# ----------------------------------------------------------- listener


class Listener:
    """This rank's receive side: a bounded accept loop; one handler
    thread per inbound connection reading DATA frames, verifying the
    kudo payload (CRC included) and answering A/N.  Short payloads
    (a truncated link) drop the partial bytes and close — the sender's
    ack read fails and its retry resends."""

    def __init__(self, rank: int, addr: str, inbox: Inbox,
                 sink=None):
        self.rank = rank
        self.addr = addr
        self.inbox = inbox
        # elastic sink (the ShuffleService in elastic mode): receives
        # EDATA/CTRL frames and returns the verdict bytes; without one
        # those kinds are protocol violations (plain PR-10 fleets)
        self.sink = sink
        self._sock: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self._conns: set = set()
        self._conns_lock = make_lock("dist.listener.conns")
        self._stop = threading.Event()
        # (src, op, seq) already delivered — a resend after a lost ACK
        # re-ACKs without re-inserting.  Recorded only AFTER a
        # successful verify+deliver (a NAKed payload was never
        # delivered, so its clean resend must not be deduped), which
        # also keeps _seen and its eviction order in lockstep.
        # Bounded: shuffle ops are short-lived, 4096 message ids
        # dwarf any in-flight window.
        self._seen: Dict[Tuple[int, int, int], bool] = {}
        self._seen_order: List[Tuple[int, int, int]] = []
        self._seen_lock = make_lock("dist.listener.seen")

    def start(self) -> "Listener":
        fam, target = _parse_addr(self.addr)
        if fam == socket.AF_UNIX:
            try:
                os.unlink(target)
            except OSError:
                pass
        s = socket.socket(fam, socket.SOCK_STREAM)
        if fam == socket.AF_INET:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(target)
        s.listen(16)
        s.settimeout(0.2)
        self._sock = s
        t = threading.Thread(target=self._accept_loop,
                             name=f"srt-shuffle-accept-{self.rank}",
                             daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        # close accepted connections so handler threads blocked in
        # stream.read unwind immediately instead of riding out their
        # 60s socket timeout past the join below
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2.0)
        fam, target = _parse_addr(self.addr)
        if fam == socket.AF_UNIX:
            try:
                os.unlink(target)
            except OSError:
                pass

    # ------------------------------------------------------- internals

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed
            with self._conns_lock:
                self._conns.add(conn)
            t = threading.Thread(
                target=self._serve, args=(conn,),
                name=f"srt-shuffle-recv-{self.rank}", daemon=True)
            t.start()
            # prune finished handlers so a fault-heavy soak (every
            # reconnect is a new connection) doesn't accrete dead
            # Thread objects
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _already_delivered(self, key: Tuple[int, int, int]) -> bool:
        with self._seen_lock:
            return key in self._seen

    def _mark_delivered(self, key: Tuple[int, int, int]) -> None:
        with self._seen_lock:
            if key in self._seen:
                return
            self._seen[key] = True
            self._seen_order.append(key)
            if len(self._seen_order) > 4096:
                old = self._seen_order.pop(0)
                self._seen.pop(old, None)

    def _serve(self, conn: socket.socket) -> None:
        conn.settimeout(60.0)
        stream = SocketStream(conn)
        try:
            while not self._stop.is_set():
                head = stream.read(FRAME_LEN)
                if len(head) < FRAME_LEN:
                    return  # clean close (or trailing garbage: drop)
                magic, kind, src, op_id, seq, length = struct.unpack(
                    FRAME_FMT, head)
                elastic = kind in (KIND_EDATA, KIND_CTRL)
                if (magic != FRAME_MAGIC or length > MAX_PAYLOAD
                        or not (kind == KIND_DATA
                                or (elastic
                                    and self.sink is not None))):
                    return  # protocol violation: drop the connection
                epoch = part = 0
                if elastic:
                    ext = stream.read(EXT_LEN)
                    if len(ext) < EXT_LEN:
                        return
                    epoch, part = struct.unpack(EXT_FMT, ext)
                payload = stream.read(length)
                if len(payload) < length:
                    # truncated link mid-payload: the partial bytes
                    # are unusable — drop them, close, let the
                    # sender's retry resend over a fresh connection
                    _obs.record_kudo_corruption(
                        "resync", skipped_bytes=len(payload),
                        detail=f"truncated link from rank {src} "
                               f"op {op_id}")
                    return
                if kind == KIND_DATA:
                    self._answer(conn, src, op_id, seq, payload)
                else:
                    self._answer_elastic(conn, kind, src, op_id, seq,
                                         epoch, part, payload)
        except OSError:
            return
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _answer(self, conn, src: int, op_id: int, seq: int,
                payload: bytes) -> None:
        import io
        key = (src, op_id, seq)
        if self._already_delivered(key):
            conn.sendall(ACK)  # duplicate after a lost ACK
            return
        try:
            # the verify pass IS the normal kudo read: every KCRC
            # trailer present is checked, impossible headers raise
            tables = _kudo.read_tables(io.BytesIO(payload))
        except (ValueError, EOFError):
            # corrupt payload: NAK (corruption was already recorded at
            # the kudo verify site); nothing was delivered, so nothing
            # is remembered and the clean resend goes through
            conn.sendall(NAK)
            return
        self.inbox.put(op_id, src, tables)
        self._mark_delivered(key)
        _obs.record_shuffle_link("recv", src, len(payload), op_id)
        conn.sendall(ACK)

    def _answer_elastic(self, conn, kind: int, src: int, op_id: int,
                        seq: int, epoch: int, part: int,
                        payload: bytes) -> None:
        """EDATA/CTRL dispatch to the elastic sink.  The sink returns
        the verdict bytes (ACK, NAK, or STALE + its current epoch);
        the (src, op, seq) link-level dedup still short-circuits
        exact resends after a lost ACK — logical (op, part) dedup of
        DISTINCT copies (speculation, replay) is the sink's job."""
        key = (src, op_id, seq)
        if kind == KIND_EDATA and self._already_delivered(key):
            conn.sendall(ACK)
            return
        try:
            if kind == KIND_CTRL:
                verdict = self.sink.on_ctrl(src, epoch, payload)
            else:
                verdict = self.sink.on_edata(src, op_id, seq, epoch,
                                             part, payload)
        except (ValueError, EOFError):
            conn.sendall(NAK)  # corrupt payload: sender resends clean
            return
        if kind == KIND_EDATA and verdict[:1] == ACK:
            self._mark_delivered(key)
        conn.sendall(verdict)


# ---------------------------------------------------------- peer link


class PeerLink:
    """The sending half of one directed link.  Lazily connects (with
    connect itself inside the retry loop so a slow-starting peer is a
    transient, not an error) and keeps the connection for subsequent
    sends."""

    def __init__(self, my_rank: int, peer_rank: int, addr: str, *,
                 policy: Optional[RetryPolicy] = None,
                 ack_timeout_s: float = 30.0):
        self.my_rank = my_rank
        self.peer_rank = peer_rank
        self.addr = addr
        self.policy = policy
        self.ack_timeout_s = ack_timeout_s
        self._sock: Optional[socket.socket] = None
        # seq namespace is per-INCARNATION: peers keep a persistent
        # (src, op, seq) dedup table, so a respawned worker whose
        # links restarted at 0 would collide with its predecessor's
        # entries and have fresh frames falsely re-ACKed without
        # delivery — the pid offset keeps incarnations disjoint
        self._seq = (os.getpid() & 0x7FFF) << 16
        self._lock = make_lock("dist.peer_link")

    # ------------------------------------------------------- plumbing

    def _connect(self) -> socket.socket:
        if self._sock is not None:
            return self._sock
        fam, target = _parse_addr(self.addr)
        s = socket.socket(fam, socket.SOCK_STREAM)
        s.settimeout(self.ack_timeout_s)
        s.connect(target)
        self._sock = s
        return s

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._drop()

    # ----------------------------------------------------------- send

    def send(self, op_id: int, payload: bytes, *,
             kind: int = KIND_DATA, epoch: int = 0,
             part: int = 0) -> int:
        """Deliver one payload; returns bytes sent.  Blocks until the
        peer ACKs (payload verified) or the retry budget dies.  Kinds
        EDATA/CTRL prepend the elastic (epoch, part) extension; a
        peer whose membership view is ahead answers the stale-epoch
        fence, surfaced as :class:`StaleEpochError` (NOT retried —
        resending the same stale frame can never merge)."""
        with self._lock:
            self._seq += 1
            seq = self._seq
        head = struct.pack(FRAME_FMT, FRAME_MAGIC, kind,
                           self.my_rank, op_id, seq, len(payload))
        if kind != KIND_DATA:
            head += struct.pack(EXT_FMT, epoch, part)

        def attempt() -> int:
            with self._lock:
                try:
                    s = self._connect()
                    # arm the injected fault only once a connection
                    # exists: a transient connect failure must not
                    # burn the one-shot injection before any faulty
                    # byte could hit the wire (the chaos gate's
                    # "corrupt link healed" signal would go vacuous)
                    fault = _take_fault(self.peer_rank, op_id)
                    if fault == "drop":
                        # injected silent frame loss: forge local
                        # success — the receiver never sees the frame
                        # and must recover by speculation/rebalance
                        return len(payload)
                    if fault == "trunc":
                        # inject a truncated link: half the payload,
                        # then a hard close mid-message
                        s.sendall(head + payload[: len(payload) // 2])
                        self._drop()
                        raise ShuffleLinkError(
                            "injected truncated link", reason="link")
                    wire = payload
                    if fault == "corrupt":
                        flip = len(payload) // 2
                        wire = (payload[:flip]
                                + bytes([payload[flip] ^ 0xFF])
                                + payload[flip + 1:])
                    delay_ms = _slow_ms(self.peer_rank)
                    if delay_ms > 0:
                        # injected per-frame straggler delay
                        time.sleep(delay_ms / 1000.0)
                    # lockdep marker: this link mutex is held across
                    # the wire round-trip BY DESIGN (it serializes one
                    # peer's protocol); the evidence lets an operator
                    # see exactly how long-held it is
                    lockdep.note_blocking("transport.send")
                    s.sendall(head + wire)
                    verdict = s.recv(1)
                    peer_epoch = b""
                    if verdict == STALE:
                        while len(peer_epoch) < 4:
                            chunk = s.recv(4 - len(peer_epoch))
                            if not chunk:
                                break
                            peer_epoch += chunk
                except OSError:
                    self._drop()
                    raise
                if verdict == ACK:
                    return len(payload)
                if verdict == STALE and len(peer_epoch) == 4:
                    # the connection stays healthy: the peer answered
                    # a complete fence verdict, it just refuses this
                    # epoch — the ELASTIC layer fast-forwards and
                    # replays, the link layer must not resend
                    raise StaleEpochError(
                        self.peer_rank, struct.unpack(">I",
                                                      peer_epoch)[0])
                self._drop()
                if verdict == NAK:
                    raise ShuffleLinkError(
                        f"peer {self.peer_rank} NAKed op {op_id} "
                        f"seq {seq}", reason="nak")
                raise ShuffleLinkError(
                    f"link to peer {self.peer_rank} closed before "
                    f"verdict (op {op_id})", reason="link")

        with _obs.TRACER.span("shuffle_send", kind="shuffle_send",
                              attrs={"peer": self.peer_rank,
                                     "op": op_id,
                                     "bytes": len(payload)}):
            n = with_link_retry(attempt, peer=self.peer_rank,
                                policy=self.policy)
        _obs.record_shuffle_link("send", self.peer_rank, n, op_id)
        return n
