"""Performance layer: process-wide kernel compile cache + shape
bucketing (ISSUE 4 tentpole).

The row-conversion / hash / exchange hot paths build one XLA program
per (kernel, schema layout, row count) they see.  Row counts vary batch
to batch, so without bucketing every batch recompiles; and an eager
212-column conversion dispatches thousands of tiny ops.  This package
centralizes the fix:

  * :mod:`spark_rapids_tpu.perf.jit_cache` — a registry of
    AOT-compiled kernels keyed by (kernel name, schema-layout digest,
    row bucket), with power-of-two row bucketing + pad/slice wrappers,
    buffer donation on the padded operands (TPU), and LRU eviction
    under a byte/entry budget.

Consumers: ops/row_conversion.py (to-rows / from-rows),
ops/row_assembly_pallas.py (tile kernels), ops/hash.py (row hashes),
parallel/exchange.py (capacity-retry step builders).  Stats surface
through srt_jit_cache_* metrics (observability), the shim
(jit_cache_stats / jit_cache_clear), and tools/metrics_report.py.

Env knobs (read dynamically; docs/performance.md):
  SPARK_RAPIDS_TPU_JIT_CACHE=0          disable (eager fallback paths)
  SPARK_RAPIDS_TPU_JIT_CACHE_ENTRIES=N  LRU entry budget (default 256)
  SPARK_RAPIDS_TPU_JIT_CACHE_BYTES=N    LRU byte budget (default 8 GiB
                                        of estimated operand footprint)
"""

from spark_rapids_tpu.perf.jit_cache import (  # noqa: F401
    CACHE, JitCache, bucket_rows, pad_axis0, schema_digest)
