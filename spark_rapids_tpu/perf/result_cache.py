"""Semantic result + subplan cache with incremental aggregation.

The serving tier's traffic is wildly redundant — the same dashboards
and aggregates re-requested as each micro-batch lands — yet every
submit used to recompute the whole query from scratch.  This module
makes a repeated query re-serve in O(delta) instead of O(total):

  * **Result cache** — a finished catalog query's host rows, keyed by
    (query name, parameter-binding digest, ingest-epoch vector).  The
    server answers a warm hit BEFORE admission (no pool slot, no
    scheduler charge) with a distinct ``cache_hit`` outcome.
  * **Subplan cache** — reusable intermediate outputs at two grains:
    content-keyed stage outputs (``plan/compiler.py`` consults per
    stage, so an unchanged upstream stage short-circuits while only
    the delta recomputes) and resident partial-aggregate states at
    the q5/q72 ``ShuffleBoundary`` seam, which new batches FOLD into
    via the exact-int64 merge property of segment sums (additive;
    overflow flags merge by OR) instead of recomputing history.
  * **Ingest epochs** — a registry Parquet/Arrow ingest and the
    catalog data generators bump.  Epoch vectors ride every result
    key, so new data invalidates results naturally while the resident
    partial states keep their second life.

Residency: payloads are encoded as column batches and registered in
the PR-17 tiered :class:`~spark_rapids_tpu.memory.spill.SpillStore`
at priority ``CACHE_PRIORITY`` (0) — strictly below every task
priority, so memory pressure evicts cached results BEFORE it demotes
live queries, and an evicted entry demotes device->host->disk for a
byte-identical disk second life instead of vanishing.  With no store
installed the payload stays a plain host array under this module's
own LRU byte budget.

Cross-tenant safety gate: a result entry is shared across tenants
only when its query's :class:`CacheSpec` says ``shared`` (pure
functions of their parameter binding over shared sources); otherwise
the tenant rides the key and tenant A's private binding can never
serve tenant B.  Stage-scope entries are keyed by the CONTENT digest
of their inputs — identical digests over identical bytes — which is
the only sharing the safety gate permits.

Everything is observable: ``srt_result_cache_{hits,misses,evictions,
bytes,incremental_folds}_total``, a ``cache`` section in query
profiles, and a ``cache_lookup`` attribution bucket.  Off by default
(``SPARK_RAPIDS_TPU_RESULT_CACHE=1`` opts in) so byte-level serving
semantics never change under anyone's feet.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from collections import OrderedDict
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu.analysis.lockdep import make_rlock

# SpillStore priority for cache residents: strictly below
# task_priority() of ANY live task (those are huge positive numbers),
# so ensure_headroom victimizes cached results first — results are
# recomputable luxuries, queries are work in flight
CACHE_PRIORITY = 0

SCOPE_RESULT = "result"
SCOPE_STAGE = "stage"
SCOPE_SUBPLAN = "subplan"


def cache_enabled() -> bool:
    """Dynamic env check (``SPARK_RAPIDS_TPU_RESULT_CACHE=1`` opts
    in).  Off by default: a semantic cache changes server outcomes
    (``cache_hit`` instead of a recompute), which operators must ask
    for, never discover."""
    return os.environ.get("SPARK_RAPIDS_TPU_RESULT_CACHE", "0") == "1"


# --------------------------------------------------------- ingest epochs
# source name -> (epoch, last fingerprint).  A fingerprint-carrying
# note (parquet reads pass size+mtime) bumps only when the fingerprint
# CHANGES — re-reading an unchanged file must not invalidate warm
# results; a fingerprint-less bump (arrow ingest, arriving stream
# batches) always advances.

_EPOCH_LOCK = make_rlock("perf.result_cache.epochs")
_EPOCHS: Dict[str, Tuple[int, Optional[str]]] = {}


def ingest_epoch(source: str) -> int:
    with _EPOCH_LOCK:
        return _EPOCHS.get(str(source), (0, None))[0]


def bump_ingest_epoch(source: str, n: int = 1) -> int:
    """Advance ``source``'s epoch (new data arrived): every result
    keyed over it goes stale; resident partial states survive and
    fold the delta."""
    source = str(source)
    with _EPOCH_LOCK:
        epoch = _EPOCHS.get(source, (0, None))[0] + max(int(n), 1)
        _EPOCHS[source] = (epoch, None)
        return epoch


def note_ingest(source: str, fingerprint: Optional[str] = None) -> int:
    """Ingest-door hook (parquet/arrow readers): records that
    ``source`` was read with ``fingerprint`` identifying its bytes
    (size+mtime for files).  The epoch bumps only when the
    fingerprint changes; ``None`` always bumps."""
    source = str(source)
    with _EPOCH_LOCK:
        epoch, last = _EPOCHS.get(source, (0, None))
        if fingerprint is None or fingerprint != last:
            epoch += 1
            _EPOCHS[source] = (epoch, fingerprint)
        return epoch


def epoch_vector(sources: Sequence[str]) -> Tuple[int, ...]:
    with _EPOCH_LOCK:
        return tuple(_EPOCHS.get(str(s), (0, None))[0]
                     for s in sources)


def reset_ingest_epochs() -> None:
    """Drop every recorded epoch (tests)."""
    with _EPOCH_LOCK:
        _EPOCHS.clear()


# ----------------------------------------------------------- cache specs
# Only queries with a registered spec are result-cacheable: the spec
# is the declaration that the query is a pure function of (binding,
# source epochs), and whether its results may be shared across
# tenants.  The built-in catalog queries register theirs in
# models/__init__.py.


class CacheSpec:
    """Result-cacheability declaration for one catalog query."""

    __slots__ = ("query", "shared", "sources", "source_param")

    def __init__(self, query: str, *, shared: bool = False,
                 sources: Tuple[str, ...] = (),
                 source_param: str = ""):
        self.query = query
        self.shared = bool(shared)
        self.sources = tuple(sources)
        self.source_param = source_param

    def sources_for(self, params: dict) -> Tuple[str, ...]:
        """The epoch sources this binding reads: a ``source_param``
        value in the binding overrides the spec's static list (the
        incremental queries name their stream per submit)."""
        if self.source_param:
            s = (params or {}).get(self.source_param)
            if s:
                return (str(s),)
        return self.sources


_SPEC_LOCK = make_rlock("perf.result_cache.specs")
_SPECS: Dict[str, CacheSpec] = {}


def register_cache_spec(query: str, *, shared: bool = False,
                        sources: Sequence[str] = (),
                        source_param: str = "") -> CacheSpec:
    spec = CacheSpec(str(query), shared=shared,
                     sources=tuple(sources),
                     source_param=source_param)
    with _SPEC_LOCK:
        _SPECS[spec.query] = spec
    return spec


def unregister_cache_spec(query: str) -> None:
    with _SPEC_LOCK:
        _SPECS.pop(str(query), None)


def cache_spec(query: str) -> Optional[CacheSpec]:
    with _SPEC_LOCK:
        return _SPECS.get(str(query))


# --------------------------------------------------------------- digests


def binding_digest(params: Optional[dict]) -> str:
    """Stable digest of a parameter binding (canonical JSON, sorted
    keys — dict order must not fork cache identities)."""
    s = json.dumps(params or {}, sort_keys=True, default=str,
                   separators=(",", ":"))
    return hashlib.sha1(s.encode()).hexdigest()[:16]


def data_digest(arrays: Sequence) -> str:
    """Content digest of operand arrays: dtype + shape + raw bytes.
    This is the subplan safety gate — stage outputs are shared ONLY
    between runs whose input bytes are identical, which makes
    cross-tenant reuse of a private binding structurally impossible
    (different data, different key)."""
    h = hashlib.sha1()
    for a in arrays:
        a = np.asarray(a)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()[:16]


def _encode_json(value) -> np.ndarray:
    """A JSON-able result as a uint8 host array (the spillable
    payload form; byte-identity is by construction — same bytes in,
    same bytes out)."""
    raw = json.dumps(value, separators=(",", ":")).encode()
    return np.frombuffer(raw, dtype=np.uint8).copy()


def _decode_json(arr: np.ndarray):
    return json.loads(np.asarray(arr, dtype=np.uint8).tobytes())


# ----------------------------------------------------------------- cache


class _Entry:
    __slots__ = ("arrays", "handle", "meta", "nbytes", "scope", "hits")

    def __init__(self, arrays, handle, meta, nbytes, scope):
        self.arrays = arrays        # host payload when no store
        self.handle = handle        # SpillHandle when a store holds it
        self.meta = meta
        self.nbytes = int(nbytes)
        self.scope = scope
        self.hits = 0


class ResultCache:
    """LRU semantic cache over (scope, key) with SpillStore-backed
    residency.  Same locking discipline as perf/jit_cache.py: store
    round trips (register/materialize/close) run OUTSIDE the cache
    lock, so a blocked restore never serializes unrelated lookups and
    the lock order against the store lock stays one-way."""

    def __init__(self, max_entries: Optional[int] = None,
                 max_bytes: Optional[int] = None):
        self._lock = make_rlock("perf.result_cache")
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._max_entries = max_entries
        self._max_bytes = max_bytes
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.puts = 0
        self.folds = 0
        self.lookup_ns_total = 0

    # ------------------------------------------------------------ budgets

    def max_entries(self) -> int:
        if self._max_entries is not None:
            return self._max_entries
        try:
            return int(os.environ.get(
                "SPARK_RAPIDS_TPU_RESULT_CACHE_ENTRIES", "256"))
        except ValueError:
            return 256

    def max_bytes(self) -> int:
        if self._max_bytes is not None:
            return self._max_bytes
        try:
            return int(os.environ.get(
                "SPARK_RAPIDS_TPU_RESULT_CACHE_BYTES", str(256 << 20)))
        except ValueError:
            return 256 << 20

    def enabled(self) -> bool:
        return cache_enabled()

    # ------------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._lock:
            by_scope: Dict[str, int] = {}
            for e in self._entries.values():
                by_scope[e.scope] = by_scope.get(e.scope, 0) + 1
            return {
                "enabled": self.enabled(),
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_entries": self.max_entries(),
                "max_bytes": self.max_bytes(),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "puts": self.puts,
                "folds": self.folds,
                "lookup_ns_total": self.lookup_ns_total,
                "by_scope": by_scope,
            }

    def clear(self, reset_stats: bool = False) -> int:
        """Drop every entry (spill handles are closed); returns the
        number dropped.  Cumulative stats survive unless
        ``reset_stats``."""
        with self._lock:
            dropped = list(self._entries.values())
            n = len(dropped)
            self._entries.clear()
            self._bytes = 0
            if reset_stats:
                self.hits = self.misses = self.evictions = 0
                self.puts = self.folds = self.lookup_ns_total = 0
        for e in dropped:
            self._close_entry(e)
        return n

    @staticmethod
    def _close_entry(e: _Entry) -> None:
        h, e.handle, e.arrays = e.handle, None, None
        if h is not None:
            try:
                h.close()
            except Exception:
                pass   # a torn-down store must not fail cache cleanup

    # ------------------------------------------------------- raw get/put

    def _get(self, key: tuple):
        """(arrays, meta) or None.  The spill-store materialize (a
        possible disk restore) runs outside the cache lock."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return None
            self._entries.move_to_end(key)
            e.hits += 1
            handle, arrays, meta = e.handle, e.arrays, e.meta
        if handle is not None:
            try:
                cols = handle.get()
            except Exception:
                # the store lost the payload (torn down, corrupt past
                # recovery): drop the entry and report a miss upstream
                self.invalidate(key)
                return None
            # payloads travel the store as ONE uint8 byte blob (kudo
            # serialization needs equal column lengths, which mixed
            # dtypes/shapes would violate); slice the original arrays
            # back out by dtype/shape from the meta so a bool/float64
            # state restores bit-exact
            blob = np.asarray(cols[0].to_numpy(), np.uint8)
            arrays, off = [], 0
            for dt, shape in zip(meta["_dtypes"], meta["_shapes"]):
                dt = np.dtype(dt)
                n = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
                arrays.append(blob[off:off + n].view(dt)
                              .reshape(shape))
                off += n
        return arrays, meta

    def _put(self, key: tuple, arrays, meta, scope: str,
             nbytes: int) -> None:
        from spark_rapids_tpu import observability as _obs
        from spark_rapids_tpu.memory.spill import installed_store

        handle = None
        store = installed_store()
        if store is not None:
            try:
                from spark_rapids_tpu.columns.column import Column
                # store-side form is ONE raw uint8 byte blob (the
                # store serializes registrations as a table, so the
                # columns must share a length — mixed dtypes/shapes
                # would violate that); _get slices the arrays back
                # out by dtype/shape from the meta (BOOL8's device
                # form is uint8, so dtype would not survive a Column
                # round trip on its own)
                meta = dict(meta)
                meta["_dtypes"] = [str(np.asarray(a).dtype)
                                   for a in arrays]
                meta["_shapes"] = [tuple(np.asarray(a).shape)
                                   for a in arrays]
                views = [np.ascontiguousarray(a).reshape(-1)
                         .view(np.uint8) for a in arrays]
                blob = (np.concatenate(views) if views
                        else np.zeros(0, np.uint8))
                cols = [Column.from_numpy(blob)]
                handle = store.register(
                    cols, device_bytes=nbytes,
                    name=f"result_cache:{scope}",
                    stage="result_cache", priority=CACHE_PRIORITY)
                arrays = None   # the store owns the payload now
            except Exception:
                handle = None   # unsupported payload: keep it in-proc
        entry = _Entry(arrays, handle, meta, nbytes, scope)
        evicted = []
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
                evicted.append((None, old))
            self._entries[key] = entry
            self._bytes += entry.nbytes
            self.puts += 1
            max_e, max_b = self.max_entries(), self.max_bytes()
            while len(self._entries) > max(1, max_e) or \
                    (self._bytes > max_b and len(self._entries) > 1):
                k, e = self._entries.popitem(last=False)
                self._bytes -= e.nbytes
                self.evictions += 1
                evicted.append((e.scope, e))
        for scope_ev, e in evicted:
            self._close_entry(e)
            if scope_ev is not None:
                _obs.record_result_cache("eviction", scope_ev)
        _obs.record_result_cache("put", scope, nbytes=nbytes)

    def invalidate(self, key: tuple) -> bool:
        with self._lock:
            e = self._entries.pop(key, None)
            if e is not None:
                self._bytes -= e.nbytes
        if e is None:
            return False
        self._close_entry(e)
        return True

    # ------------------------------------------------------ result scope

    def _result_key(self, spec: CacheSpec, tenant: str, query: str,
                    params: Optional[dict]) -> tuple:
        return (SCOPE_RESULT, query, binding_digest(params),
                epoch_vector(spec.sources_for(params or {})),
                "" if spec.shared else str(tenant))

    def lookup_result(self, tenant: str, query: str,
                      params: Optional[dict]):
        """(value, lookup_ns) — value is None on a miss or for a
        query with no cache spec (uncacheable queries count nothing).
        The hit/miss lands in metrics with per-tenant attribution."""
        from spark_rapids_tpu import observability as _obs

        spec = cache_spec(query)
        if spec is None:
            return None, 0
        t0 = time.monotonic_ns()
        got = self._get(self._result_key(spec, tenant, query, params))
        ns = time.monotonic_ns() - t0
        with self._lock:
            self.lookup_ns_total += ns
            if got is None:
                self.misses += 1
            else:
                self.hits += 1
        if got is None:
            _obs.record_result_cache("miss", SCOPE_RESULT,
                                     tenant=tenant, query=query, ns=ns)
            return None, ns
        arrays, _meta = got
        try:
            value = _decode_json(arrays[0])
        except Exception:
            return None, ns   # corrupt past the store's own recovery
        _obs.record_result_cache("hit", SCOPE_RESULT, tenant=tenant,
                                 query=query, ns=ns)
        return value, ns

    def store_result(self, tenant: str, query: str,
                     params: Optional[dict], value) -> bool:
        """Cache one finished query's JSON-able result; no-op for
        queries without a spec (never silently cache a query nobody
        declared pure)."""
        spec = cache_spec(query)
        if spec is None or value is None:
            return False
        try:
            payload = _encode_json(value)
        except (TypeError, ValueError):
            return False   # non-JSON-able result: not cacheable
        self._put(self._result_key(spec, tenant, query, params),
                  [payload], {"encoding": "json"}, SCOPE_RESULT,
                  int(payload.nbytes))
        return True

    # ----------------------------------------------------- subplan scope

    def get_subplan(self, key_parts: Sequence):
        """(meta, arrays) for a resident partial-aggregate state, or
        None.  Keys are caller-composed tuples (query shape +
        binding); states are shared only through identical keys."""
        from spark_rapids_tpu import observability as _obs
        t0 = time.monotonic_ns()
        got = self._get((SCOPE_SUBPLAN,) + tuple(key_parts))
        ns = time.monotonic_ns() - t0
        with self._lock:
            self.lookup_ns_total += ns
            if got is None:
                self.misses += 1
            else:
                self.hits += 1
        _obs.record_result_cache("hit" if got else "miss",
                                 SCOPE_SUBPLAN, ns=ns)
        if got is None:
            return None
        arrays, meta = got
        return meta, arrays

    def put_subplan(self, key_parts: Sequence, arrays,
                    meta: Optional[dict] = None) -> None:
        arrays = [np.asarray(a) for a in arrays]
        nbytes = sum(int(a.nbytes) for a in arrays)
        self._put((SCOPE_SUBPLAN,) + tuple(key_parts), arrays,
                  dict(meta or {}), SCOPE_SUBPLAN, nbytes)

    def record_fold(self, query: str, ns: int = 0) -> None:
        """One arriving batch folded into a resident partial state
        (the O(delta) event the bench counts).  Disarmed runs fold
        into a throwaway state — that is a full recompute, not an
        incremental serve, so it does not count."""
        from spark_rapids_tpu import observability as _obs
        if not self.enabled():
            return
        with self._lock:
            self.folds += 1
        _obs.record_result_cache("fold", SCOPE_SUBPLAN, query=query,
                                 ns=ns)

    # ------------------------------------------------------- stage scope

    def stage_run(self, cs, stage_inputs):
        """Content-keyed short-circuit for one compiled stage: inputs
        whose bytes were seen before return the cached outputs without
        executing (reported as an engine-``cached`` stage record so
        srt-explain shows the short-circuit); anything else runs and
        is cached.  Byte-identical by the data_digest contract."""
        import jax.numpy as jnp

        from spark_rapids_tpu import observability as _obs

        t0 = time.monotonic_ns()
        try:
            flat = [a for inp in cs.plan.inputs
                    for a in stage_inputs[inp.name]]
            key = (SCOPE_STAGE, cs.plan.digest, data_digest(flat))
        except Exception:
            return cs.run(stage_inputs)   # undigestable inputs: run
        got = self._get(key)
        ns = time.monotonic_ns() - t0
        with self._lock:
            self.lookup_ns_total += ns
            if got is None:
                self.misses += 1
            else:
                self.hits += 1
        if got is not None:
            arrays, _meta = got
            _obs.record_result_cache("hit", SCOPE_STAGE,
                                     query=cs.plan.name, ns=ns)
            if _obs.PROFILER.active():
                t_end = time.monotonic_ns()
                _obs.PROFILER.note_stage({
                    "stage": cs.plan.name, "digest": key[2],
                    "engine": "cached", "compiled": False,
                    "compile_ns": 0, "wall_ns": ns,
                    "t_start_ns": t_end - ns, "t_end_ns": t_end,
                    "dispatches": 0,
                    "nodes_total": cs.dispatch_count,
                    "nodes": [], "inputs": []})
            return tuple(jnp.asarray(a) for a in arrays)
        _obs.record_result_cache("miss", SCOPE_STAGE,
                                 query=cs.plan.name, ns=ns)
        out = cs.run(stage_inputs)
        host = [np.asarray(o) for o in out]
        self._put(key, host, {}, SCOPE_STAGE,
                  sum(int(a.nbytes) for a in host))
        return out


# ---------------------------------------------------------- fold helpers


def fold_partials(state: Sequence[np.ndarray],
                  delta: Sequence[np.ndarray],
                  or_indices: Sequence[int] = ()) -> list:
    """Merge one batch's partial-aggregate outputs into the resident
    state via the exact-int64 property: segment sums are additive
    across batches (bit-exact, no float reassociation), overflow
    flags merge by OR.  ``or_indices`` name the flag positions."""
    ors = {i % len(state) for i in or_indices}
    out = []
    for i, (s, d) in enumerate(zip(state, delta)):
        s, d = np.asarray(s), np.asarray(d)
        if i in ors:
            out.append(np.logical_or(s.astype(bool), d.astype(bool)))
        else:
            out.append(s + d)
    return out


CACHE = ResultCache()
