"""Measured kernel-path calibration (ISSUE 9).

The runtime used to hard-gate device kernels on the backend name
(``jax.default_backend() != "cpu"``): joins took the host rank path on
CPU, the JSON device scan was accelerator-only, and nobody ever
measured whether that was still true.  This module makes the choice a
*measurement*: the first large column of a given schema shape times
each candidate path on a small sample and the winner is cached per
``(op, digest, backend)`` — in-process for the steady state, and in a
small JSON file (the same verdict-cache shape bench_impl.py grew for
the Pallas row-conversion calibration) so repeated processes skip the
timing entirely.

Contract with callers:

  * every candidate path MUST be byte-identical on the same input (the
    fallback discipline each engine already enforces) — calibration
    picks for SPEED only, never for correctness;
  * candidates are thunks over a caller-built sample; a candidate that
    raises is simply excluded (and remembered as ``error:<Type>`` in
    the timing journal) — a missing/broken engine can never take down
    the op;
  * the whole calibration runs under a wall-clock budget
    (``SPARK_RAPIDS_TPU_CALIB_BUDGET_S``): when the budget trips
    mid-way the best candidate measured SO FAR wins (falling back to
    the caller's default when nothing finished).

Operators can pin a path per op with
``SPARK_RAPIDS_TPU_PATH_<OP>=<path>`` (op uppercased, non-alnum ->
``_``), bypassing measurement, and point the verdict file elsewhere
with ``SPARK_RAPIDS_TPU_CALIB_CACHE`` (shared with the rowconv
calibrator; empty string disables the file layer).
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import time
from typing import Callable, Dict, Mapping, Optional, Tuple

from spark_rapids_tpu.analysis.lockdep import make_rlock

_LOCK = make_rlock("perf.calibrate")
_PROC_CACHE: Dict[Tuple[str, str, str], str] = {}

DEFAULT_TTL_S = 86400.0


def _backend() -> str:
    import jax
    return jax.default_backend()


def _synced(out):
    """Fence async device work before the timer stops: an engine that
    returns unsynced device arrays would otherwise be measured as
    dispatch time only, and the too-fast verdict cached for a day.
    Opaque (non-pytree) results pass through — their engines are host
    code that already finished."""
    try:
        import jax
        jax.block_until_ready(out)
    except Exception:
        pass
    return out


def cache_path() -> str:
    """Verdict file (shared with bench_impl's rowconv calibrator).
    Empty string disables the file layer (process cache still works)."""
    return os.environ.get(
        "SPARK_RAPIDS_TPU_CALIB_CACHE",
        os.path.join(tempfile.gettempdir(), "srt_rowconv_calib.json"))


def _load(path: str) -> dict:
    if not path:
        return {}
    try:
        with open(path) as f:
            d = json.load(f)
        return d if isinstance(d, dict) else {}
    except (OSError, ValueError):
        return {}


def _store(path: str, d: dict) -> None:
    """Atomic tmp+replace write: a reader racing a plain truncate-write
    would see torn JSON, _load would answer {}, and the next store
    would persist that empty dict — wiping every cached verdict."""
    if not path:
        return
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(d, f)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _ttl() -> float:
    try:
        return float(os.environ.get(
            "SPARK_RAPIDS_TPU_CALIB_CACHE_TTL", DEFAULT_TTL_S))
    except ValueError:
        return DEFAULT_TTL_S


def _budget() -> float:
    try:
        return float(os.environ.get(
            "SPARK_RAPIDS_TPU_CALIB_BUDGET_S", "120"))
    except ValueError:
        return 120.0


def cached_verdict(key: str) -> Optional[str]:
    """Unexpired file-cache verdict for an opaque key (bench_impl's
    rowconv calibration rides this same helper)."""
    rec = _load(cache_path()).get(key)
    if not isinstance(rec, dict):
        return None
    v = rec.get("verdict")
    try:
        # srt-lint: disable=SRT005 wall-clock TTL of the on-disk verdict cache; expiry never folds into a digest or cache key
        fresh = time.time() - float(rec.get("t", 0)) < _ttl()
    except (TypeError, ValueError):
        fresh = False
    return v if isinstance(v, str) and fresh else None


def store_verdict(key: str, verdict: str) -> None:
    with _LOCK:
        path = cache_path()
        d = _load(path)
        # srt-lint: disable=SRT005 wall-clock stamp read back only by the TTL check above; never part of a digest
        d[key] = {"verdict": verdict, "t": time.time()}
        _store(path, d)


def operands_digest(parts, extra: str = "") -> str:
    """Stable digest folding EVERY operand of a multi-input op/stage:
    each part is ``(layout, rows)`` — a layout string (schema digest,
    dtype join, anything stable) plus a row count that folds as its
    power-of-two bucket class (``rows <= 0`` means the layout string
    already encodes the exact shape).

    This is the fix for the multi-input keying bug: a verdict keyed on
    ONE operand's digest could be reused for a stage whose OTHER side
    changed size class — e.g. a join whose build side grew past cache
    residency kept the probe-side verdict.  Folding all operands makes
    that reuse impossible; the regression test lives in
    tests/test_stage_fusion.py."""
    import hashlib

    from spark_rapids_tpu.perf.jit_cache import bucket_rows
    items = []
    for layout, rows in parts:
        bucket = bucket_rows(int(rows)) if rows and rows > 0 else 0
        items.append(f"{layout}@{bucket}")
    s = "|".join(items) + f"|{extra}"
    return hashlib.sha1(s.encode()).hexdigest()[:16]


def pinned_path(op: str) -> Optional[str]:
    env = "SPARK_RAPIDS_TPU_PATH_" + re.sub(r"[^A-Za-z0-9]", "_",
                                            op).upper()
    v = os.environ.get(env)
    return v or None


def forget(op: Optional[str] = None) -> None:
    """Drop process-cache verdicts (tests / operator resets).  The file
    layer keeps its entries — use SPARK_RAPIDS_TPU_CALIB_CACHE to point
    tests at a throwaway file."""
    with _LOCK:
        if op is None:
            _PROC_CACHE.clear()
        else:
            for k in [k for k in _PROC_CACHE if k[0] == op]:
                del _PROC_CACHE[k]


def pick_path(op: str, digest: str,
              candidates: Mapping[str, Callable[[], object]],
              default: str, *, repeats: int = 1) -> str:
    """Name of the winning candidate for (op, digest, backend).

    ``candidates`` maps path name -> thunk over a caller-built sample.
    Measurement: one warm call (compiles / caches), then ``repeats``
    timed calls, per candidate, under the calibration budget.  The
    verdict is cached process-wide and in the verdict file; an env pin
    (SPARK_RAPIDS_TPU_PATH_<OP>) short-circuits everything — even to a
    path the caller did not offer (callers validate membership)."""
    pin = pinned_path(op)
    if pin is not None:
        return pin
    backend = _backend()
    pkey = (op, digest, backend)
    with _LOCK:
        v = _PROC_CACHE.get(pkey)
    if v is not None:
        return v
    fkey = f"{op}:{digest}@{backend}"
    v = cached_verdict(fkey)
    if v is not None and v in candidates:
        with _LOCK:
            _PROC_CACHE[pkey] = v
        return v

    budget = _budget()
    t_start = time.perf_counter()
    timings: Dict[str, float] = {}
    errors: Dict[str, str] = {}
    for name, thunk in candidates.items():
        if time.perf_counter() - t_start > budget:
            errors[name] = "budget_exceeded"
            continue
        try:
            t_w = time.perf_counter()
            _synced(thunk())             # warm: compile + caches
            warm_s = time.perf_counter() - t_w
            if time.perf_counter() - t_start > budget:
                # the warm call alone tripped the budget: keep its wall
                # time as the measurement (compile-biased, but a path
                # this slow only needs to lose) and skip the repeats
                timings[name] = warm_s
                continue
            t0 = time.perf_counter()
            for _ in range(max(1, repeats)):
                _synced(thunk())
            timings[name] = (time.perf_counter() - t0) / max(1, repeats)
        except Exception as e:  # noqa: BLE001 — a broken engine is a
            # calibration datum, never an op failure
            errors[name] = f"error:{type(e).__name__}"
    if timings:
        verdict = min(timings, key=timings.get)
        if (timings[verdict] > budget
                and errors.get(default) == "budget_exceeded"):
            # every measured candidate alone blew the whole budget and
            # the default never got a turn: a path that slow must not
            # win just because it starved the competition — fall back
            # to the static default instead of crowning the least-awful
            # disaster (callers order expected-fast candidates first,
            # so this only fires on pathological shapes)
            verdict = default
    else:
        verdict = default
    with _LOCK:
        _PROC_CACHE[pkey] = verdict
    store_verdict(fkey, verdict)
    try:
        from spark_rapids_tpu import observability as _obs
        _obs.JOURNAL.emit(
            "kernel_calibrated", op=op, digest=digest, backend=backend,
            verdict=verdict,
            timings_us={k: round(v * 1e6, 1)
                        for k, v in sorted(timings.items())},
            errors=errors or None)
    except Exception:  # pragma: no cover - observability must not gate
        pass
    return verdict


def last_verdict(op: str, digest: str) -> Optional[str]:
    """Process-cache peek (bench labels / tests)."""
    with _LOCK:
        return _PROC_CACHE.get((op, digest, _backend()))
