"""Process-wide kernel compile cache with shape bucketing.

The cache answers one question for the hot paths: "I need THIS kernel
for THIS schema layout at THIS row count — give me an executable
without recompiling".  Three mechanisms make that cheap:

  * **Row bucketing** — row counts are rounded up to the next power of
    two before keying, and operands are zero-padded to the bucket, so
    repeated batches of nearby sizes share one compiled executable.
    Padded output rows are sliced off by the caller.
  * **AOT compilation** — a miss runs ``jax.jit(fn).lower(*args)
    .compile()`` once and stores the resulting executable; a hit calls
    it directly, so a hit can never trigger XLA compilation (the
    recompile-count tests and ``make perf-smoke`` assert on exactly
    this property via :meth:`JitCache.stats`).
  * **Buffer donation** — the padded operands are throwaway copies, so
    on backends that honor donation (TPU) they are donated to the
    executable and the pad cost is not also an HBM residency cost.

Eviction is LRU under two budgets: an entry count and an estimated
byte footprint (the sum of operand bytes per entry — a proxy for
executable + workspace size; XLA does not expose the true number
portably).  Every hit/miss/eviction also lands in the observability
registry (``srt_jit_cache_*``) when metrics are enabled.
"""

from __future__ import annotations

import hashlib
import os
import time
from collections import OrderedDict
from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.analysis.lockdep import make_rlock

_MIN_BUCKET = 8


def cache_enabled() -> bool:
    """Dynamic env check so operators can flip the cache off per run
    (``SPARK_RAPIDS_TPU_JIT_CACHE=0``) without code changes."""
    return os.environ.get("SPARK_RAPIDS_TPU_JIT_CACHE", "1") != "0"


def bucket_rows(n: int, min_bucket: int = _MIN_BUCKET) -> int:
    """Power-of-two row bucket: smallest 2^k >= n (floor min_bucket)."""
    if n <= min_bucket:
        return min_bucket
    return 1 << (int(n) - 1).bit_length()


def pad_axis0(arr: jnp.ndarray, bucket: int) -> jnp.ndarray:
    """Zero-pad the leading (rows) axis up to ``bucket``.  The copy is
    intentional: the padded array is a throwaway the compiled kernel
    may take by donation.  When the row count already equals the
    bucket, donation-active backends (TPU — the same condition
    cached_call uses) still get a copy: an executable compiled with
    donation donates whatever buffer it is handed, and handing it the
    CALLER'S live column buffer would invalidate the caller's data.
    Backends that ignore donation (CPU) keep the zero-copy fast path."""
    n = int(arr.shape[0])
    if n == bucket:
        if jax.default_backend() == "tpu":
            return jnp.array(arr, copy=True)
        return arr
    widths = [(0, bucket - n)] + [(0, 0)] * (arr.ndim - 1)
    return jnp.pad(arr, widths)


def schema_digest(schema: Sequence, nullable: Sequence[bool] = (),
                  extra: str = "") -> str:
    """Stable digest of a schema layout: one (kind, scale) pair per
    column plus the nullability pattern (validity presence changes the
    kernel's pytree signature) plus a free-form discriminator."""
    parts = ";".join(f"{dt.kind}:{dt.scale}" for dt in schema)
    nulls = "".join("1" if b else "0" for b in nullable)
    s = f"{parts}|{nulls}|{extra}"
    return hashlib.sha1(s.encode()).hexdigest()[:16]


def _tree_nbytes(tree) -> int:
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            total += int(nb)
    return total


class _Entry:
    __slots__ = ("fn", "cost_bytes", "owner", "compile_ns")

    def __init__(self, fn, cost_bytes, owner, compile_ns):
        self.fn = fn
        self.cost_bytes = int(cost_bytes)
        self.owner = owner
        self.compile_ns = int(compile_ns)


class JitCache:
    """LRU registry of compiled kernels keyed by
    (kernel name, digest, row bucket)."""

    def __init__(self, max_entries: Optional[int] = None,
                 max_bytes: Optional[int] = None):
        self._lock = make_rlock("perf.jit_cache")
        self._entries: "OrderedDict[Tuple[str, str, int], _Entry]" = \
            OrderedDict()
        self._max_entries = max_entries
        self._max_bytes = max_bytes
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.compiles = 0
        self.compile_ns_total = 0
        self._by_kernel: Dict[str, Dict[str, int]] = {}

    # ------------------------------------------------------------ budgets

    def max_entries(self) -> int:
        if self._max_entries is not None:
            return self._max_entries
        try:
            return int(os.environ.get(
                "SPARK_RAPIDS_TPU_JIT_CACHE_ENTRIES", "256"))
        except ValueError:
            return 256

    def max_bytes(self) -> int:
        if self._max_bytes is not None:
            return self._max_bytes
        try:
            return int(os.environ.get(
                "SPARK_RAPIDS_TPU_JIT_CACHE_BYTES", str(8 << 30)))
        except ValueError:
            return 8 << 30

    def enabled(self) -> bool:
        return cache_enabled()

    # ------------------------------------------------------------- stats

    def _kernel_stat(self, name: str) -> Dict[str, int]:
        return self._by_kernel.setdefault(
            name, {"hits": 0, "misses": 0, "evictions": 0})

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled(),
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_entries": self.max_entries(),
                "max_bytes": self.max_bytes(),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "compiles": self.compiles,
                "compile_ns_total": self.compile_ns_total,
                "kernels": {k: dict(v)
                            for k, v in sorted(self._by_kernel.items())},
            }

    def clear(self, reset_stats: bool = False) -> int:
        """Drop every entry (compiled executables are released);
        returns the number dropped.  Cumulative stats survive unless
        ``reset_stats``."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._bytes = 0
            if reset_stats:
                self.hits = self.misses = self.evictions = 0
                self.compiles = self.compile_ns_total = 0
                self._by_kernel.clear()
            return n

    # ------------------------------------------------------------ lookup

    def get_or_build(self, name: str, digest: str, bucket: int,
                     build: Callable[[], Callable], *,
                     cost_bytes: int = 0, owner=None,
                     counts_compile: bool = True) -> Callable:
        """Return the cached callable for (name, digest, bucket),
        invoking ``build()`` on a miss.  ``owner`` (optional) is held
        strongly in the entry and identity-checked on hits — callers
        keyed by object identity (exchange step factories) use it to
        make id-reuse collisions impossible."""
        from spark_rapids_tpu import observability as _obs

        key = (name, digest, int(bucket))
        with self._lock:
            e = self._entries.get(key)
            if e is not None and (owner is None or e.owner is owner):
                self._entries.move_to_end(key)
                self.hits += 1
                self._kernel_stat(name)["hits"] += 1
                _obs.record_jit_cache("hit", name)
                return e.fn

        # build outside the lock: compiles can take seconds and must
        # not serialize unrelated kernels.  A racing thread may build
        # the same entry twice; last insert wins (both are correct).
        # compile_begin marks the START too: a multi-second
        # lower+compile is the classic slow-but-alive window, and the
        # lifeguard's heartbeat hook must see a sign of life on BOTH
        # edges or a first-touch compile longer than the hang
        # threshold reads as a hung worker
        _obs.record_jit_cache("compile_begin", name)
        t0 = time.monotonic_ns()
        fn = build()
        dt = time.monotonic_ns() - t0

        with self._lock:
            self.misses += 1
            ks = self._kernel_stat(name)
            ks["misses"] += 1
            if counts_compile:
                self.compiles += 1
                self.compile_ns_total += dt
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.cost_bytes
            self._entries[key] = _Entry(fn, cost_bytes, owner, dt)
            self._bytes += int(cost_bytes)
            evicted = self._evict_over_budget()
        _obs.record_jit_cache("miss", name, compile_ns=dt)
        for ev_name in evicted:
            _obs.record_jit_cache("eviction", ev_name)
        return fn

    def _evict_over_budget(self):
        """Caller holds the lock.  Returns kernel names evicted."""
        evicted = []
        max_e, max_b = self.max_entries(), self.max_bytes()
        while len(self._entries) > max(1, max_e) or \
                (self._bytes > max_b and len(self._entries) > 1):
            key, e = self._entries.popitem(last=False)
            self._bytes -= e.cost_bytes
            self.evictions += 1
            self._kernel_stat(key[0])["evictions"] += 1
            evicted.append(key[0])
        return evicted

    # ------------------------------------------------------- cached call

    def cached_call(self, name: str, digest: str, fn: Callable,
                    args: tuple, *, bucket: int,
                    donate_argnums: Tuple[int, ...] = ()):
        """Run ``fn(*args)`` through an AOT-compiled executable cached
        under (name, digest, bucket).  ``args`` must already be padded
        to the bucket; every later call with the same key must pass the
        same pytree structure / shapes / dtypes (bucketing guarantees
        this for row-shaped operands).  Donation is applied only on
        backends that honor it (TPU) to avoid per-compile warnings."""
        donate = donate_argnums if jax.default_backend() == "tpu" else ()
        cost = _tree_nbytes(args)

        def build():
            return jax.jit(fn, donate_argnums=donate).lower(*args).compile()

        compiled = self.get_or_build(name, digest, bucket, build,
                                     cost_bytes=cost)
        return compiled(*args)


CACHE = JitCache()
