"""``srt-explain``: render per-query EXPLAIN ANALYZE profiles
(ISSUE 13 — the analyst-facing half of observability/profile.py; the
reference's counterpart is the profiler sidecar's
``profile_converter`` text mode).

Input: one or more profile JSON files — written by the query server's
``profile`` door, the distributed runner's ``profile_<op>_rank<r>.json``
dumps, or frozen into a flight-recorder bundle as ``profile.json`` (a
bundle directory is accepted directly).  MULTIPLE inputs merge into
ONE fleet profile via :func:`observability.profile.merge_profiles`:
per-stage wall is the max over ranks (the critical path) and the
per-rank walls render as a skew table.

Output: the plan tree with per-stage attribution — wall ns, engine
(fused/unfused), compile-vs-cache-hit, dispatch count, per-input
rows/bucket/pad-waste — with the hot path highlighted, plus the
task-scoped op deltas, retry/OOM episodes, per-peer shuffle-link
bytes and jit-cache activity the profiler folded in.

``--diff BASELINE`` compares per-stage mean walls against a baseline
profile and EXITS NONZERO when any stage regressed beyond
``--threshold`` — the per-node guardrail the bench-trajectory BENCH_*
files cannot give.

Usage:
    python -m spark_rapids_tpu.tools.srt_explain PROFILE.json \
        [more_rank_profiles.json ...] [--nodes] [--json] \
        [--diff BASELINE.profile.json] [--threshold 1.5]
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List

from spark_rapids_tpu.observability.profile import (diff_profiles,
                                                    merge_profiles)


def load_profiles(paths) -> List[dict]:
    """One profile dict per input path; a flight-recorder bundle
    directory stands in for its ``profile.json``."""
    from spark_rapids_tpu.tools import expand_bundle_input

    out: List[dict] = []
    for p0 in paths:
        for p in expand_bundle_input(p0, "profile"):
            with open(p) as f:
                prof = json.load(f)
            if not isinstance(prof, dict) or "stages" not in prof:
                raise ValueError(f"{p}: not a query profile "
                                 f"(no 'stages')")
            out.append(prof)
    return out


# ---------------------------------------------------------------- render


def _ms(ns) -> str:
    return f"{(ns or 0) / 1e6:.3f}"


def _kb(n) -> str:
    n = int(n)
    if n >= 1 << 20:
        return f"{n / (1 << 20):.1f}MiB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f}KiB"
    return f"{n}B"


def _node_summary(nodes: List[dict]) -> str:
    counts: Dict[str, int] = {}
    for n in nodes or ():
        k = str(n.get("kind", "?"))
        counts[k] = counts.get(k, 0) + 1
    return ", ".join(f"{k} x{v}" if v > 1 else k
                     for k, v in sorted(counts.items()))


def _input_summary(inputs: List[dict]) -> str:
    parts = []
    for i in inputs or ():
        s = f"{i.get('name', '?')} rows={i.get('rows', 0)}"
        pad = int(i.get("pad_rows", 0))
        if pad:
            s += f"/{i.get('bucket', 0)} pad={pad}"
        parts.append(s)
    return "; ".join(parts)


def render_profile(profile: dict, *, nodes: bool = False
                   ) -> List[str]:
    """The EXPLAIN ANALYZE tree as text lines.  Purely
    profile-derived (no "now" stamps): the same artifact always
    renders the same text — the golden test holds the CLI to that."""
    out: List[str] = []
    fleet = bool(profile.get("fleet"))
    head = (f"srt-explain: {profile.get('query') or '?'}"
            f"  (query_id {profile.get('query_id') or '?'}"
            + (f", tenant {profile['tenant']}"
               if profile.get("tenant") else "")
            + (f", trace {profile['trace_id']}"
               if profile.get("trace_id") else "") + ")")
    out.append(head)
    if fleet:
        out.append(
            f"fleet: world={profile.get('world')} "
            f"ranks={profile.get('ranks')}  trace "
            + ("consistent" if profile.get("trace_consistent")
               else "UNVERIFIED — inputs may be unrelated runs"))
    stages = profile.get("stages") or []
    out.append(f"wall {_ms(profile.get('wall_ns'))} ms"
               + (" (max over ranks)" if fleet else "")
               + f"   stages {len(stages)}"
               + (f"   hot {profile['hot_stage']}"
                  if profile.get("hot_stage") else ""))
    wall = max(int(profile.get("wall_ns") or 0), 1)
    hot = profile.get("hot_stage")
    out.append("plan tree (stage-IR attribution):")
    for s in stages:
        tags = [str(s.get("engine", "?"))]
        if s.get("compiled"):
            tags.append("compiled")
        else:
            tags.append("cache-hit")
        calls = int(s.get("calls", 1))
        tags.append(f"{int(s.get('dispatches', 0))} dispatch / "
                    f"{int(s.get('nodes_total', 0))} nodes")
        if calls > 1:
            tags.append(f"{calls} calls")
        pct = min(100 * int(s.get("wall_ns", 0)) // wall, 100)
        line = (f"  {s.get('stage', '?'):<16} "
                f"[{', '.join(tags)}]  "
                f"{_ms(s.get('wall_ns')):>9} ms  ({pct:>2}%)")
        if hot and s.get("stage") == hot:
            line += "  <-- HOT"
        out.append(line)
        ins = _input_summary(s.get("inputs"))
        if ins:
            out.append(f"      inputs: {ins}")
        summary = _node_summary(s.get("nodes"))
        if summary:
            out.append(f"      nodes: {summary}")
        if nodes:
            for n in s.get("nodes") or ():
                out.append(f"        {n.get('kind', '?'):<12} -> "
                           + ",".join(n.get("outs") or ()))
        prw = s.get("per_rank_wall_ns")
        if prw:
            ranks = " ".join(f"r{r}={_ms(w)}ms"
                             for r, w in sorted(
                                 prw.items(),
                                 key=lambda kv: int(kv[0])))
            out.append(f"      per-rank: {ranks}")
    # ---- skew table (fleet merges only) ----------------------------
    skew = profile.get("skew") or []
    worst = [r for r in skew if r.get("skew_ratio")
             and r["skew_ratio"] > 1.0]
    if worst:
        out.append("rank skew (max/min wall per stage):")
        for r in sorted(worst, key=lambda r: -r["skew_ratio"]):
            out.append(f"  {r.get('stage', '?'):<16} "
                       f"x{r['skew_ratio']:.2f}  "
                       f"(max {_ms(r.get('max_wall_ns'))} ms, "
                       f"min {_ms(r.get('min_wall_ns'))} ms)")
    # ---- cross-cutting sections ------------------------------------
    links = profile.get("shuffle_links") or {}
    if links.get("bytes"):
        parts = []
        for direction in ("send", "recv"):
            for peer, n in sorted(
                    (links["bytes"].get(direction) or {}).items()):
                parts.append(f"{direction}[{peer}]={_kb(n)}")
        if parts:
            out.append("shuffle links: " + "  ".join(parts))
    if links.get("per_rank"):
        for rank, rl in sorted(links["per_rank"].items(),
                               key=lambda kv: int(kv[0])):
            parts = []
            for direction in ("send", "recv"):
                for peer, n in sorted(
                        ((rl.get("bytes") or {}).get(direction)
                         or {}).items()):
                    parts.append(f"{direction}[{peer}]={_kb(n)}")
            if parts:
                out.append(f"shuffle links r{rank}: "
                           + "  ".join(parts))
    ops = profile.get("ops") or {}
    if ops:
        top = sorted(ops.items(),
                     key=lambda kv: -kv[1].get("time_ns", 0))[:8]
        out.append("task-scoped ops: " + "  ".join(
            f"{op}={_ms(o.get('time_ns'))}ms/{o.get('calls', 0)}"
            for op, o in top))
    r = profile.get("retries") or {}
    o = profile.get("oom") or {}
    if r.get("episodes") or o.get("retry") or o.get("split_retry") \
            or o.get("blocked_ns"):
        out.append(
            f"retries: {r.get('episodes', 0)} episodes "
            f"({r.get('attempts', 0)} attempts, "
            f"{r.get('splits', 0)} splits, "
            f"{_ms(r.get('lost_ns'))} ms lost)   "
            f"oom: {o.get('retry', 0)} retry / "
            f"{o.get('split_retry', 0)} split, blocked "
            f"{_ms(o.get('blocked_ns'))} ms")
    kp = profile.get("kernel_paths") or {}
    if kp:
        out.append("kernel paths: " + "  ".join(
            f"{k}={v}" for k, v in sorted(kp.items())))
    jit = profile.get("jit") or {}
    if jit:
        out.append("jit cache: " + "  ".join(
            f"{k}(hits={d.get('hits', 0)},misses={d.get('misses', 0)})"
            for k, d in sorted(jit.items())))
    spans = profile.get("spans") or {}
    if spans.get("count"):
        kinds = " ".join(f"{k}={v}" for k, v in
                         sorted(spans.get("by_kind", {}).items()))
        out.append(f"trace-scoped spans: {spans['count']} ({kinds})")
    return out


def render_diff(findings: List[dict], threshold: float) -> List[str]:
    out = []
    if not findings:
        out.append(f"diff: no per-stage regression beyond "
                   f"x{threshold}")
        return out
    out.append(f"diff: {len(findings)} stage(s) regressed beyond "
               f"x{threshold}:")
    for f in findings:
        out.append(f"  {f['stage']:<16} x{f['ratio']:.2f}  "
                   f"({f['base_mean_ms']} ms -> "
                   f"{f['cur_mean_ms']} ms)")
    return out


# ------------------------------------------------------------------ CLI


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="srt-explain",
        description="Render per-query EXPLAIN ANALYZE profiles "
                    "(multiple rank profiles merge into one fleet "
                    "profile)")
    ap.add_argument("inputs", nargs="+",
                    help="profile JSON files or flight-recorder "
                         "bundle dirs")
    ap.add_argument("--nodes", action="store_true",
                    help="list every plan node under its stage")
    ap.add_argument("--json", action="store_true",
                    help="emit the (merged) profile as JSON")
    ap.add_argument("--diff", metavar="BASELINE", default=None,
                    help="baseline profile (file or bundle dir); "
                         "exits 1 on any per-stage regression")
    ap.add_argument("--threshold", type=float, default=1.5,
                    help="regression ratio threshold (default 1.5)")
    ap.add_argument("--min-delta-ms", type=float, default=1.0,
                    help="ignore regressions smaller than this "
                         "absolute per-call delta (default 1 ms)")
    args = ap.parse_args(argv)

    try:
        profiles = load_profiles(args.inputs)
    except (OSError, ValueError) as e:
        print(f"srt-explain: {e}", file=sys.stderr)
        return 2
    profile = merge_profiles(profiles)

    if args.json:
        print(json.dumps(profile, indent=2, sort_keys=True,
                         default=str))
    else:
        print("\n".join(render_profile(profile, nodes=args.nodes)))

    if args.diff:
        try:
            baseline = merge_profiles(load_profiles([args.diff]))
        except (OSError, ValueError) as e:
            print(f"srt-explain: --diff {e}", file=sys.stderr)
            return 2
        findings = diff_profiles(
            baseline, profile, threshold=args.threshold,
            min_delta_ns=int(args.min_delta_ms * 1e6))
        print("\n".join(render_diff(findings, args.threshold)))
        return 1 if findings else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
