"""``srt-explain``: render per-query EXPLAIN ANALYZE profiles
(ISSUE 13 — the analyst-facing half of observability/profile.py; the
reference's counterpart is the profiler sidecar's
``profile_converter`` text mode).

Input: one or more profile JSON files — written by the query server's
``profile`` door, the distributed runner's ``profile_<op>_rank<r>.json``
dumps, or frozen into a flight-recorder bundle as ``profile.json`` (a
bundle directory is accepted directly).  MULTIPLE inputs merge into
ONE fleet profile via :func:`observability.profile.merge_profiles`:
per-stage wall is the max over ranks (the critical path) and the
per-rank walls render as a skew table.

Output: the plan tree with per-stage attribution — wall ns, engine
(fused/unfused), compile-vs-cache-hit, dispatch count, per-input
rows/bucket/pad-waste — with the hot path highlighted, plus the
task-scoped op deltas, retry/OOM episodes, per-peer shuffle-link
bytes and jit-cache activity the profiler folded in.

``--diff BASELINE`` compares per-stage mean walls against a baseline
profile and EXITS NONZERO when any stage regressed beyond
``--threshold`` — the per-node guardrail the bench-trajectory BENCH_*
files cannot give.  Stages present in the baseline but absent from the
current profile render as ``removed`` rows (informational: a vanished
stage is a plan change, not a regression, so it never fails the gate).
When both sides carry time-attribution ledgers the diff ALSO names
which bucket absorbed the extra wall (``diff_attribution``).

``--where`` (ISSUE 17) renders the time-attribution waterfall: the
admission-to-result wall split into the exhaustive bucket set from
``observability/attribution.py``, with the conservation verdict.
``--critical-path`` switches the inputs to per-rank span JSONL dumps
(or bundle dirs) and renders the cross-rank critical path — the chain
of spans the wall actually waited on — plus the exchange-edge
leaderboard with the hot link flagged.

Usage:
    python -m spark_rapids_tpu.tools.srt_explain PROFILE.json \
        [more_rank_profiles.json ...] [--nodes] [--json] [--where] \
        [--diff BASELINE.profile.json] [--threshold 1.5]
    python -m spark_rapids_tpu.tools.srt_explain --critical-path \
        spans_rank0.jsonl spans_rank1.jsonl [--json]
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List

from spark_rapids_tpu.observability.attribution import (
    OVERHEAD_BUCKETS, attribute_many, diff_attribution, hot_rank)
from spark_rapids_tpu.observability.critical_path import critical_path
from spark_rapids_tpu.observability.profile import (diff_profiles,
                                                    merge_profiles)


def load_profiles(paths) -> List[dict]:
    """One profile dict per input path; a flight-recorder bundle
    directory stands in for its ``profile.json``."""
    from spark_rapids_tpu.tools import expand_bundle_input

    out: List[dict] = []
    for p0 in paths:
        for p in expand_bundle_input(p0, "profile"):
            with open(p) as f:
                prof = json.load(f)
            if not isinstance(prof, dict) or "stages" not in prof:
                raise ValueError(f"{p}: not a query profile "
                                 f"(no 'stages')")
            out.append(prof)
    return out


def load_spans(paths) -> Dict[int, List[dict]]:
    """rank -> span records for ``--critical-path``.  Each input is a
    tracer/journal JSONL dump (or a bundle dir standing in for its
    spans.jsonl); the rank comes from the records themselves when
    stamped, else from the input ordinal — so both the distributed
    runner's ``spans_rank<r>.jsonl`` layout and anonymous dumps work."""
    from spark_rapids_tpu.tools import expand_bundle_input, read_jsonl

    by_rank: Dict[int, List[dict]] = {}
    for ordinal, p0 in enumerate(paths):
        for p in expand_bundle_input(p0, "spans"):
            records = read_jsonl(p)
            rank = ordinal
            for r in records:
                if isinstance(r.get("rank"), int):
                    rank = r["rank"]
                    break
            by_rank.setdefault(rank, []).extend(records)
    return by_rank


# ---------------------------------------------------------------- render


def _ms(ns) -> str:
    return f"{(ns or 0) / 1e6:.3f}"


def _kb(n) -> str:
    n = int(n)
    if n >= 1 << 20:
        return f"{n / (1 << 20):.1f}MiB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f}KiB"
    return f"{n}B"


def _node_summary(nodes: List[dict]) -> str:
    counts: Dict[str, int] = {}
    for n in nodes or ():
        k = str(n.get("kind", "?"))
        counts[k] = counts.get(k, 0) + 1
    return ", ".join(f"{k} x{v}" if v > 1 else k
                     for k, v in sorted(counts.items()))


def _input_summary(inputs: List[dict]) -> str:
    parts = []
    for i in inputs or ():
        s = f"{i.get('name', '?')} rows={i.get('rows', 0)}"
        pad = int(i.get("pad_rows", 0))
        if pad:
            s += f"/{i.get('bucket', 0)} pad={pad}"
        parts.append(s)
    return "; ".join(parts)


def render_profile(profile: dict, *, nodes: bool = False
                   ) -> List[str]:
    """The EXPLAIN ANALYZE tree as text lines.  Purely
    profile-derived (no "now" stamps): the same artifact always
    renders the same text — the golden test holds the CLI to that."""
    out: List[str] = []
    fleet = bool(profile.get("fleet"))
    head = (f"srt-explain: {profile.get('query') or '?'}"
            f"  (query_id {profile.get('query_id') or '?'}"
            + (f", tenant {profile['tenant']}"
               if profile.get("tenant") else "")
            + (f", trace {profile['trace_id']}"
               if profile.get("trace_id") else "") + ")")
    out.append(head)
    if fleet:
        out.append(
            f"fleet: world={profile.get('world')} "
            f"ranks={profile.get('ranks')}  trace "
            + ("consistent" if profile.get("trace_consistent")
               else "UNVERIFIED — inputs may be unrelated runs"))
    stages = profile.get("stages") or []
    out.append(f"wall {_ms(profile.get('wall_ns'))} ms"
               + (" (max over ranks)" if fleet else "")
               + f"   stages {len(stages)}"
               + (f"   hot {profile['hot_stage']}"
                  if profile.get("hot_stage") else ""))
    wall = max(int(profile.get("wall_ns") or 0), 1)
    hot = profile.get("hot_stage")
    out.append("plan tree (stage-IR attribution):")
    for s in stages:
        tags = [str(s.get("engine", "?"))]
        if s.get("compiled"):
            tags.append("compiled")
        else:
            tags.append("cache-hit")
        calls = int(s.get("calls", 1))
        tags.append(f"{int(s.get('dispatches', 0))} dispatch / "
                    f"{int(s.get('nodes_total', 0))} nodes")
        if calls > 1:
            tags.append(f"{calls} calls")
        pct = min(100 * int(s.get("wall_ns", 0)) // wall, 100)
        line = (f"  {s.get('stage', '?'):<16} "
                f"[{', '.join(tags)}]  "
                f"{_ms(s.get('wall_ns')):>9} ms  ({pct:>2}%)")
        if hot and s.get("stage") == hot:
            line += "  <-- HOT"
        out.append(line)
        ins = _input_summary(s.get("inputs"))
        if ins:
            out.append(f"      inputs: {ins}")
        summary = _node_summary(s.get("nodes"))
        if summary:
            out.append(f"      nodes: {summary}")
        if nodes:
            for n in s.get("nodes") or ():
                out.append(f"        {n.get('kind', '?'):<12} -> "
                           + ",".join(n.get("outs") or ()))
        prw = s.get("per_rank_wall_ns")
        if prw:
            ranks = " ".join(f"r{r}={_ms(w)}ms"
                             for r, w in sorted(
                                 prw.items(),
                                 key=lambda kv: int(kv[0])))
            out.append(f"      per-rank: {ranks}")
        # per-node cardinalities (ISSUE 20): est vs actual, sketch
        # NDV, selectivity, and the misestimate highlight
        st = s.get("stats")
        if st and st.get("nodes"):
            out.append("      stats (est vs actual rows):")
            for n in st["nodes"]:
                if n.get("est") is not None:
                    bits = [f"rows est={n['est']} "
                            f"actual={n.get('rows')}"]
                    if n.get("ratio"):
                        bits.append(f"(x{n['ratio']:g} off)")
                else:
                    bits = [f"rows actual={n.get('rows')}"]
                if n.get("selectivity") is not None:
                    bits.append(f"sel={n['selectivity']:.4f}")
                if n.get("ndv") is not None:
                    bits.append(f"ndv={n['ndv']}")
                if n.get("null_frac"):
                    bits.append(f"null={n['null_frac']:.3f}")
                prr = n.get("per_rank_rows")
                if prr:
                    bits.append("per-rank " + " ".join(
                        f"r{r}={v}" for r, v in sorted(
                            prr.items(),
                            key=lambda kv: int(kv[0]))))
                line = (f"        {n.get('node', '?'):<14} "
                        + "  ".join(bits))
                if n.get("misestimate"):
                    line += "  <-- MISESTIMATE"
                out.append(line)
    # ---- skew table (fleet merges only) ----------------------------
    skew = profile.get("skew") or []
    worst = [r for r in skew if r.get("skew_ratio")
             and r["skew_ratio"] > 1.0]
    if worst:
        out.append("rank skew (max/min wall per stage):")
        for r in sorted(worst, key=lambda r: -r["skew_ratio"]):
            out.append(f"  {r.get('stage', '?'):<16} "
                       f"x{r['skew_ratio']:.2f}  "
                       f"(max {_ms(r.get('max_wall_ns'))} ms, "
                       f"min {_ms(r.get('min_wall_ns'))} ms)")
    # ---- cross-cutting sections ------------------------------------
    links = profile.get("shuffle_links") or {}
    if links.get("bytes"):
        parts = []
        for direction in ("send", "recv"):
            for peer, n in sorted(
                    (links["bytes"].get(direction) or {}).items()):
                parts.append(f"{direction}[{peer}]={_kb(n)}")
        if parts:
            out.append("shuffle links: " + "  ".join(parts))
    if links.get("per_rank"):
        for rank, rl in sorted(links["per_rank"].items(),
                               key=lambda kv: int(kv[0])):
            parts = []
            for direction in ("send", "recv"):
                for peer, n in sorted(
                        ((rl.get("bytes") or {}).get(direction)
                         or {}).items()):
                    parts.append(f"{direction}[{peer}]={_kb(n)}")
            if parts:
                out.append(f"shuffle links r{rank}: "
                           + "  ".join(parts))
    ops = profile.get("ops") or {}
    if ops:
        top = sorted(ops.items(),
                     key=lambda kv: -kv[1].get("time_ns", 0))[:8]
        out.append("task-scoped ops: " + "  ".join(
            f"{op}={_ms(o.get('time_ns'))}ms/{o.get('calls', 0)}"
            for op, o in top))
    r = profile.get("retries") or {}
    o = profile.get("oom") or {}
    if r.get("episodes") or o.get("retry") or o.get("split_retry") \
            or o.get("blocked_ns"):
        out.append(
            f"retries: {r.get('episodes', 0)} episodes "
            f"({r.get('attempts', 0)} attempts, "
            f"{r.get('splits', 0)} splits, "
            f"{_ms(r.get('lost_ns'))} ms lost)   "
            f"oom: {o.get('retry', 0)} retry / "
            f"{o.get('split_retry', 0)} split, blocked "
            f"{_ms(o.get('blocked_ns'))} ms")
    kp = profile.get("kernel_paths") or {}
    if kp:
        out.append("kernel paths: " + "  ".join(
            f"{k}={v}" for k, v in sorted(kp.items())))
    jit = profile.get("jit") or {}
    if jit:
        out.append("jit cache: " + "  ".join(
            f"{k}(hits={d.get('hits', 0)},misses={d.get('misses', 0)})"
            for k, d in sorted(jit.items())))
    rc = profile.get("cache") or {}
    if any(rc.get(k) for k in ("hits", "misses", "puts", "folds")):
        out.append(
            f"result cache: hits={rc.get('hits', 0)} "
            f"misses={rc.get('misses', 0)} puts={rc.get('puts', 0)} "
            f"folds={rc.get('folds', 0)} "
            f"lookup={_ms(rc.get('lookup_ns'))}ms")
    spans = profile.get("spans") or {}
    if spans.get("count"):
        kinds = " ".join(f"{k}={v}" for k, v in
                         sorted(spans.get("by_kind", {}).items()))
        out.append(f"trace-scoped spans: {spans['count']} ({kinds})")
    return out


def render_where(ledger: dict) -> List[str]:
    """The time-attribution waterfall as text lines.  Like the plan
    tree, purely ledger-derived — same ledger, same text."""
    out: List[str] = []
    wall = max(int(ledger.get("wall_ns") or 0), 1)
    out.append(f"where did the time go: "
               f"{ledger.get('query') or '?'}"
               f"  (query_id {ledger.get('query_id') or '?'}"
               + (f", tenant {ledger['tenant']}"
                  if ledger.get("tenant") else "")
               + f", wall {_ms(ledger.get('wall_ns'))} ms"
               + (" over "
                  f"{len(ledger.get('per_rank') or ())} ranks"
                  if ledger.get("fleet") else "") + ")")
    buckets = ledger.get("buckets") or {}
    dom = ledger.get("dominant")
    for b, v in sorted(buckets.items(), key=lambda kv: -kv[1]):
        if v <= 0:
            continue
        pct = min(100 * int(v) // max(sum(buckets.values()), 1), 100)
        line = f"  {b:<16} {_ms(v):>10} ms  ({pct:>2}%)"
        if b == dom:
            line += "  <-- dominant"
        out.append(line)
    dov = ledger.get("dominant_overhead")
    if dov:
        hr = hot_rank(ledger, dov)
        out.append(f"  dominant overhead: {dov}"
                   + (f" (hot rank {hr})" if hr is not None else ""))
    if ledger.get("conserved"):
        out.append("  conservation: OK")
    else:
        oc = ledger.get("overcount_ns")
        if oc is None:  # fleet rollup: find the broken rank(s)
            oc = max((led.get("overcount_ns", 0) for led in
                      (ledger.get("per_rank") or {}).values()),
                     default=0)
        out.append(f"  conservation: BROKEN — buckets overcount the "
                   f"wall by {_ms(oc)} ms (double-counted seams)")
    return out


def render_critical_path(result: dict) -> List[str]:
    """The cross-rank critical path + exchange-edge leaderboard.  The
    hottest segment (largest dur + inbound gap) and the hottest
    exchange edge carry ``<-- HOT`` markers."""
    out: List[str] = []
    path = result.get("path") or []
    out.append(f"critical path: {len(path)} segment(s), "
               f"{_ms(result.get('total_ns'))} ms covered"
               + (f", {result['clamped_edges']} edge(s) clamped"
                  if result.get("clamped_edges") else ""))
    offs = result.get("clock_offsets") or {}
    if any(int(v) for v in offs.values()):
        out.append("  clock offsets: " + "  ".join(
            f"r{r}={int(v)}ns" for r, v in
            sorted(offs.items(), key=lambda kv: int(kv[0]))))
    for rk in result.get("truncated_ranks") or ():
        out.append(f"  WARNING: rank {rk} span dump truncated — "
                   f"path may be partial")
    hot_i = max(range(len(path)),
                key=lambda i: path[i]["dur_ns"] + path[i]["gap_in_ns"],
                default=None) if path else None
    for i, seg in enumerate(path):
        if seg["edge_in"] == "exchange":
            out.append(f"    ~~> exchange hop "
                       f"(wire+wait {_ms(seg['gap_in_ns'])} ms)")
        elif seg["gap_in_ns"] > 0:
            out.append(f"    ... lane idle {_ms(seg['gap_in_ns'])} ms")
        line = (f"  r{seg['rank']} {seg['name']:<24} "
                f"[{seg['span_kind']}/{seg['bucket']}]  "
                f"{_ms(seg['dur_ns']):>10} ms")
        if i == hot_i:
            line += "  <-- HOT"
        out.append(line)
    edges = result.get("exchange_edges") or []
    if edges:
        out.append("exchange edges (largest gap first):")
        for j, e in enumerate(edges):
            line = (f"  r{e['from_rank']}:{e['from']} -> "
                    f"r{e['to_rank']}:{e['to']}  "
                    f"gap {_ms(e['gap_ns'])} ms"
                    + ("  [on path]" if e.get("on_path") else ""))
            if j == 0:
                line += "  <-- HOT"
            out.append(line)
    return out


def render_diff(findings: List[dict], threshold: float,
                attribution_rows: List[dict] = None,
                hot: str = None) -> List[str]:
    out = []
    regressed = [f for f in findings
                 if f.get("kind", "regression") != "removed"]
    removed = [f for f in findings if f.get("kind") == "removed"]
    if not regressed:
        out.append(f"diff: no per-stage regression beyond "
                   f"x{threshold}")
    else:
        out.append(f"diff: {len(regressed)} stage(s) regressed "
                   f"beyond x{threshold}:")
        for f in regressed:
            out.append(f"  {f['stage']:<16} x{f['ratio']:.2f}  "
                       f"({f['base_mean_ms']} ms -> "
                       f"{f['cur_mean_ms']} ms)")
    for f in removed:
        out.append(f"  {f['stage']:<16} removed  "
                   f"(was {f['base_mean_ms']} ms x"
                   f"{f['base_calls']} calls in baseline)")
    if attribution_rows:
        out.append("where the delta went (per bucket):")
        for r in attribution_rows:
            share = (f"  ({r['share_of_delta'] * 100:.0f}% of "
                     f"wall delta)"
                     if r.get("share_of_delta") is not None else "")
            out.append(f"  {r['bucket']:<16} "
                       f"{r['base_ms']} ms -> {r['cur_ms']} ms  "
                       f"({r['delta_ms']:+} ms){share}")
        if hot is not None:
            out.append(f"  hot rank: {hot}")
    return out


# ------------------------------------------------------------------ CLI


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="srt-explain",
        description="Render per-query EXPLAIN ANALYZE profiles "
                    "(multiple rank profiles merge into one fleet "
                    "profile)")
    ap.add_argument("inputs", nargs="+",
                    help="profile JSON files or flight-recorder "
                         "bundle dirs")
    ap.add_argument("--nodes", action="store_true",
                    help="list every plan node under its stage")
    ap.add_argument("--json", action="store_true",
                    help="emit the (merged) profile as JSON")
    ap.add_argument("--where", action="store_true",
                    help="render the time-attribution waterfall "
                         "(where the admission-to-result wall went)")
    ap.add_argument("--critical-path", action="store_true",
                    help="treat inputs as per-rank span JSONL dumps "
                         "and solve the cross-rank critical path")
    ap.add_argument("--diff", metavar="BASELINE", default=None,
                    help="baseline profile (file or bundle dir); "
                         "exits 1 on any per-stage, whole-wall, or "
                         "overhead-bucket regression")
    ap.add_argument("--threshold", type=float, default=1.5,
                    help="regression ratio threshold (default 1.5)")
    ap.add_argument("--min-delta-ms", type=float, default=1.0,
                    help="ignore regressions smaller than this "
                         "absolute per-call delta (default 1 ms)")
    args = ap.parse_args(argv)

    if args.critical_path:
        try:
            spans_by_rank = load_spans(args.inputs)
        except (OSError, ValueError, KeyError) as e:
            print(f"srt-explain: {e}", file=sys.stderr)
            return 2
        result = critical_path(spans_by_rank)
        if args.json:
            print(json.dumps(result, indent=2, sort_keys=True,
                             default=str))
        else:
            print("\n".join(render_critical_path(result)))
        return 0

    try:
        profiles = load_profiles(args.inputs)
    except (OSError, ValueError) as e:
        print(f"srt-explain: {e}", file=sys.stderr)
        return 2
    profile = merge_profiles(profiles)
    ledger = None
    if args.where or args.diff:
        # recomputing from the artifact matches any embedded ledger
        # (attribution is a pure function of the profile), and also
        # serves profiles captured with the switch off
        ledger = attribute_many(profiles)

    if args.where:
        if args.json:
            print(json.dumps(ledger, indent=2, sort_keys=True,
                             default=str))
        else:
            print("\n".join(render_where(ledger)))
    elif args.json:
        print(json.dumps(profile, indent=2, sort_keys=True,
                         default=str))
    else:
        print("\n".join(render_profile(profile, nodes=args.nodes)))

    if args.diff:
        try:
            base_profiles = load_profiles([args.diff])
        except (OSError, ValueError) as e:
            print(f"srt-explain: --diff {e}", file=sys.stderr)
            return 2
        baseline = merge_profiles(base_profiles)
        min_delta_ns = int(args.min_delta_ms * 1e6)
        findings = diff_profiles(
            baseline, profile, threshold=args.threshold,
            min_delta_ns=min_delta_ns)
        base_ledger = attribute_many(base_profiles)
        rows = diff_attribution(base_ledger, ledger,
                                min_delta_ns=min_delta_ns)
        # regressions with no single guilty stage are still
        # regressions: time lost BETWEEN stages (exchange wire/wait,
        # retries, admission) lands in the overhead buckets, and a
        # compile-jitter swing in the wall can HIDE it — so the gate
        # also fails when the whole wall or any overhead bucket grows
        # past the threshold
        findings = list(findings)
        base_wall = int(base_ledger.get("wall_ns", 0))
        cur_wall = int(ledger.get("wall_ns", 0))
        if (base_wall > 0 and cur_wall >= base_wall * args.threshold
                and cur_wall - base_wall >= min_delta_ns):
            findings.append({
                "kind": "wall_regression", "stage": "(wall)",
                "ratio": round(cur_wall / base_wall, 3),
                "base_mean_ms": round(base_wall / 1e6, 3),
                "cur_mean_ms": round(cur_wall / 1e6, 3),
            })
        base_b = base_ledger.get("buckets") or {}
        cur_b = ledger.get("buckets") or {}
        for bucket in OVERHEAD_BUCKETS:
            bv = int(base_b.get(bucket, 0))
            cv = int(cur_b.get(bucket, 0))
            if cv >= bv * args.threshold and cv - bv >= min_delta_ns:
                findings.append({
                    "kind": "overhead_regression",
                    "stage": f"({bucket})",
                    "ratio": round(cv / max(bv, 1), 3),
                    "base_mean_ms": round(bv / 1e6, 3),
                    "cur_mean_ms": round(cv / 1e6, 3),
                })
        print("\n".join(render_diff(
            findings, args.threshold, attribution_rows=rows,
            hot=hot_rank(ledger) if ledger.get("fleet") else None)))
        # removed stages are informational (a plan change, not a
        # regression) — only true regressions fail the gate
        return 1 if any(f.get("kind", "regression") != "removed"
                        for f in findings) else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
