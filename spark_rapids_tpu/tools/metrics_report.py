"""Render a per-task / per-op summary from an observability journal
dump (the analyst-facing half of ISSUE 1's exposition story; the
reference's counterpart is the profile converter's text report mode
plus the task-level numbers Spark pulls through RmmSpark.getAndReset*).

Input: JSONL files written by
``spark_rapids_tpu.observability.dump_journal_jsonl`` (or the shim's
``metrics_journal_dump``): raw journal events interleaved with one
``task_rollup`` record per task and a final ``registry_snapshot``.
Unknown kinds are counted, never fatal — the journal schema is allowed
to grow ahead of this tool.

Usage:
    python -m spark_rapids_tpu.tools.metrics_report journal.jsonl
    python -m spark_rapids_tpu.tools.metrics_report journal.jsonl --json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, Iterable, List


def load_jsonl(paths: Iterable[str]) -> List[dict]:
    records: List[dict] = []
    for p in paths:
        with open(p) as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    print(f"{p}:{i + 1}: skipping unparseable line",
                          file=sys.stderr)
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
    return records


def split_records(records: List[dict]):
    """(task_rollups, registry_snapshot, events)."""
    rollups: Dict[int, dict] = {}
    registry = None
    events: List[dict] = []
    for r in records:
        kind = r.get("kind")
        if kind == "task_rollup":
            rollups[int(r.get("task", -1))] = r
        elif kind == "registry_snapshot":
            registry = r.get("registry")
        else:
            events.append(r)
    return rollups, registry, events


def _ms(ns: int) -> str:
    return f"{ns / 1e6:.3f}"


def render_task_table(rollups: Dict[int, dict]) -> List[str]:
    out = ["per-task summary", ""]
    hdr = (f"{'task':>6}  {'op_calls':>8}  {'op_ms':>10}  "
           f"{'shuf_wr_B':>10}  {'mrg_rows':>8}  {'retry':>5}  "
           f"{'split':>5}  {'blocked_ms':>10}  {'max_mem_B':>10}")
    out.append(hdr)
    out.append("-" * len(hdr))
    for task in sorted(rollups):
        r = rollups[task]
        ops = r.get("ops", {})
        calls = sum(o.get("calls", 0) for o in ops.values())
        op_ns = sum(o.get("time_ns", 0) for o in ops.values())
        name = "driver" if task == -1 else str(task)
        out.append(
            f"{name:>6}  {calls:>8}  {_ms(op_ns):>10}  "
            f"{r.get('shuffle_write_bytes', 0):>10}  "
            f"{r.get('shuffle_merge_rows', 0):>8}  "
            f"{r.get('retry_oom', 0):>5}  "
            f"{r.get('split_retry_oom', 0):>5}  "
            f"{_ms(r.get('blocked_time_ns', 0)):>10}  "
            f"{r.get('max_device_memory', 0):>10}")
    return out


def render_op_table(rollups: Dict[int, dict]) -> List[str]:
    """Per-op rows aggregated across tasks, busiest first."""
    agg: Dict[str, dict] = {}
    for r in rollups.values():
        for op, o in r.get("ops", {}).items():
            a = agg.setdefault(op, {"calls": 0, "time_ns": 0})
            a["calls"] += o.get("calls", 0)
            a["time_ns"] += o.get("time_ns", 0)
    out = ["", "per-op summary (all tasks)", ""]
    if not agg:
        out.append("(no op activity recorded)")
        return out
    w = max(len(op) for op in agg)
    out.append(f"{'op':<{w}}  {'calls':>6}  {'total_ms':>10}  {'avg_us':>8}")
    for op, a in sorted(agg.items(), key=lambda kv: -kv[1]["time_ns"]):
        avg_us = a["time_ns"] / max(a["calls"], 1) / 1e3
        out.append(f"{op:<{w}}  {a['calls']:>6}  "
                   f"{_ms(a['time_ns']):>10}  {avg_us:>8.1f}")
    return out


def render_event_table(events: List[dict]) -> List[str]:
    counts: Dict[str, int] = {}
    for e in events:
        k = e.get("kind", "?")
        counts[k] = counts.get(k, 0) + 1
    out = ["", "journal events", ""]
    if not counts:
        out.append("(journal empty)")
        return out
    w = max(len(k) for k in counts)
    for k in sorted(counts, key=lambda k: -counts[k]):
        out.append(f"{k:<{w}}  {counts[k]}")
    ooms = [e for e in events
            if e.get("kind") in ("oom_retry", "oom_split_retry")]
    if ooms:
        out.append("")
        out.append("oom events (most recent last):")
        for e in ooms[-10:]:
            out.append(
                f"  {e.get('kind')}: task={e.get('task')} "
                f"thread={e.get('thread')} device={e.get('device')}"
                f"{' injected' if e.get('injected') else ''}")
    return out


def build_report(records: List[dict]) -> dict:
    """Machine-readable report (the --json output)."""
    rollups, registry, events = split_records(records)
    counts: Dict[str, int] = {}
    for e in events:
        k = e.get("kind", "?")
        counts[k] = counts.get(k, 0) + 1
    return {
        "tasks": {str(t): {k: v for k, v in r.items() if k != "kind"}
                  for t, r in rollups.items()},
        "event_counts": counts,
        "has_registry_snapshot": registry is not None,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Per-task/per-op report from an observability "
                    "journal dump")
    ap.add_argument("inputs", nargs="+", help="journal JSONL files")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of tables")
    args = ap.parse_args(argv)

    records = load_jsonl(args.inputs)
    if args.json:
        print(json.dumps(build_report(records), indent=2, sort_keys=True))
        return 0
    rollups, registry, events = split_records(records)
    lines: List[str] = []
    if rollups:
        lines += render_task_table(rollups)
        lines += render_op_table(rollups)
    else:
        lines.append("(no task_rollup records in input)")
    lines += render_event_table(events)
    if registry is not None:
        lines.append("")
        lines.append(f"registry snapshot: {len(registry)} metric families")
    print("\n".join(lines))
    return 0


if __name__ == "__main__":
    sys.exit(main())
