"""Render a per-task / per-op summary from an observability journal
dump (the analyst-facing half of ISSUE 1's exposition story; the
reference's counterpart is the profile converter's text report mode
plus the task-level numbers Spark pulls through RmmSpark.getAndReset*).

Input: JSONL files written by
``spark_rapids_tpu.observability.dump_journal_jsonl`` (or the shim's
``metrics_journal_dump``): raw journal events interleaved with one
``task_rollup`` record per task and a final ``registry_snapshot``.
Unknown kinds are counted, never fatal — the journal schema is allowed
to grow ahead of this tool.

Usage:
    python -m spark_rapids_tpu.tools.metrics_report journal.jsonl
    python -m spark_rapids_tpu.tools.metrics_report journal.jsonl --json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, Iterable, List, Optional


def load_jsonl(paths: Iterable[str]) -> List[dict]:
    from spark_rapids_tpu.tools import expand_bundle_input, read_jsonl

    records: List[dict] = []
    for p0 in paths:
        # a flight-recorder incident bundle directory stands in for
        # its journal.jsonl — frozen incidents feed the same report
        for p in expand_bundle_input(p0, "journal"):
            records.extend(read_jsonl(p))
    return records


def split_records(records: List[dict]):
    """(task_rollups, registry_snapshot, events)."""
    rollups: Dict[int, dict] = {}
    registry = None
    events: List[dict] = []
    for r in records:
        kind = r.get("kind")
        if kind == "task_rollup":
            rollups[int(r.get("task", -1))] = r
        elif kind == "registry_snapshot":
            registry = r.get("registry")
        elif kind in ("timeseries_snapshot", "slo_status"):
            pass  # telemetry-plane records: extract_telemetry reads them
        else:
            events.append(r)
    return rollups, registry, events


def extract_telemetry(records: List[dict]):
    """(timeseries_snapshot, slo_status) from a journal dump — the
    ISSUE-16 records dump_journal_jsonl appends when the telemetry
    plane is armed.  Either may be None; the slo status embedded in a
    timeseries snapshot is honored when no standalone record exists."""
    timeseries = None
    slo = None
    for r in records:
        kind = r.get("kind")
        if kind == "timeseries_snapshot":
            timeseries = r
            if slo is None and r.get("slo"):
                slo = r["slo"]
        elif kind == "slo_status":
            slo = r.get("slo")
    return timeseries, slo


def _ms(ns: int) -> str:
    return f"{ns / 1e6:.3f}"


def histogram_quantile(buckets: List[float], bucket_counts: List[int],
                       q: float) -> float:
    """Estimate the q-quantile (0..1) from PER-BUCKET (non-cumulative)
    counts, the registry snapshot's `bucket_counts` format — NOT the
    cumulative `_bucket` values of Prometheus text exposition.  Same
    estimation rule as `histogram_quantile`: linear interpolation
    within the target bucket; the +Inf bucket clamps to the largest
    finite bound, an underestimate by construction."""
    total = sum(bucket_counts)
    if total <= 0:
        return 0.0
    target = q * total
    cum = 0.0
    for i, n in enumerate(bucket_counts):
        if cum + n >= target and n > 0:
            if i >= len(buckets):          # +Inf bucket
                return float(buckets[-1]) if buckets else 0.0
            lo = float(buckets[i - 1]) if i > 0 else 0.0
            hi = float(buckets[i])
            return lo + (hi - lo) * (target - cum) / n
        cum += n
    return float(buckets[-1]) if buckets else 0.0


def histogram_rows(registry: Optional[dict]) -> List[dict]:
    """Flatten every histogram family in a registry snapshot into rows
    with count/sum and p50/p95/p99 estimates (ns)."""
    rows: List[dict] = []
    for name, fam in sorted((registry or {}).items()):
        if fam.get("kind") != "histogram":
            continue
        buckets = fam.get("buckets", [])
        for s in fam.get("series", []):
            if not s.get("count"):
                continue
            bc = s.get("bucket_counts", [])
            rows.append({
                "family": name,
                "labels": dict(zip(fam.get("labels", []),
                                   s.get("labels", []))),
                "count": s["count"],
                "sum_ns": s.get("sum", 0),
                "p50_ns": histogram_quantile(buckets, bc, 0.50),
                "p95_ns": histogram_quantile(buckets, bc, 0.95),
                "p99_ns": histogram_quantile(buckets, bc, 0.99),
            })
    return rows


def empty_histogram_families(registry: Optional[dict]) -> List[str]:
    """Histogram families present in the snapshot with NO counted
    series (registered but never fired)."""
    out = []
    for name, fam in sorted((registry or {}).items()):
        if fam.get("kind") != "histogram":
            continue
        if not any(s.get("count") for s in fam.get("series", [])):
            out.append(name)
    return out


def render_histogram_table(registry: Optional[dict]) -> List[str]:
    """Latency-distribution table: one row per histogram series —
    op-latency and the span-duration families both land here.
    Families that exist but never fired render as '-' rows instead of
    vanishing, so a golden diff over two runs stays stable when a
    family is registered in one and fired only in the other."""
    rows = histogram_rows(registry)
    empty = empty_histogram_families(registry)
    out = ["", "latency histograms (p50/p95/p99 estimated from buckets)",
           ""]
    if not rows and not empty:
        out.append("(no histogram series recorded)")
        return out
    names = ["{}{{{}}}".format(
        r["family"],
        ",".join(f"{k}={v}" for k, v in r["labels"].items()))
        if r["labels"] else r["family"] for r in rows]
    w = max(len(n) for n in names + empty)
    out.append(f"{'series':<{w}}  {'count':>7}  {'p50_us':>9}  "
               f"{'p95_us':>9}  {'p99_us':>9}  {'total_ms':>10}")
    order = sorted(range(len(rows)),
                   key=lambda i: -rows[i]["sum_ns"])
    for i in order:
        r = rows[i]
        out.append(f"{names[i]:<{w}}  {r['count']:>7}  "
                   f"{r['p50_ns'] / 1e3:>9.1f}  "
                   f"{r['p95_ns'] / 1e3:>9.1f}  "
                   f"{r['p99_ns'] / 1e3:>9.1f}  "
                   f"{_ms(r['sum_ns']):>10}")
    for name in empty:   # stable alphabetical tail after live rows
        out.append(f"{name:<{w}}  {'-':>7}  {'-':>9}  {'-':>9}  "
                   f"{'-':>9}  {'-':>10}")
    return out


def render_task_table(rollups: Dict[int, dict]) -> List[str]:
    out = ["per-task summary", ""]
    hdr = (f"{'task':>6}  {'op_calls':>8}  {'op_ms':>10}  "
           f"{'shuf_wr_B':>10}  {'mrg_rows':>8}  {'retry':>5}  "
           f"{'split':>5}  {'blocked_ms':>10}  {'max_mem_B':>10}")
    out.append(hdr)
    out.append("-" * len(hdr))
    for task in sorted(rollups):
        r = rollups[task]
        ops = r.get("ops", {})
        calls = sum(o.get("calls", 0) for o in ops.values())
        op_ns = sum(o.get("time_ns", 0) for o in ops.values())
        name = "driver" if task == -1 else str(task)
        out.append(
            f"{name:>6}  {calls:>8}  {_ms(op_ns):>10}  "
            f"{r.get('shuffle_write_bytes', 0):>10}  "
            f"{r.get('shuffle_merge_rows', 0):>8}  "
            f"{r.get('retry_oom', 0):>5}  "
            f"{r.get('split_retry_oom', 0):>5}  "
            f"{_ms(r.get('blocked_time_ns', 0)):>10}  "
            f"{r.get('max_device_memory', 0):>10}")
    return out


def render_op_table(rollups: Dict[int, dict]) -> List[str]:
    """Per-op rows aggregated across tasks, busiest first."""
    agg: Dict[str, dict] = {}
    for r in rollups.values():
        for op, o in r.get("ops", {}).items():
            a = agg.setdefault(op, {"calls": 0, "time_ns": 0})
            a["calls"] += o.get("calls", 0)
            a["time_ns"] += o.get("time_ns", 0)
    out = ["", "per-op summary (all tasks)", ""]
    if not agg:
        out.append("(no op activity recorded)")
        return out
    w = max(len(op) for op in agg)
    out.append(f"{'op':<{w}}  {'calls':>6}  {'total_ms':>10}  {'avg_us':>8}")
    for op, a in sorted(agg.items(), key=lambda kv: -kv[1]["time_ns"]):
        avg_us = a["time_ns"] / max(a["calls"], 1) / 1e3
        out.append(f"{op:<{w}}  {a['calls']:>6}  "
                   f"{_ms(a['time_ns']):>10}  {avg_us:>8.1f}")
    return out


def jit_cache_rows(registry: Optional[dict]) -> List[dict]:
    """Per-kernel compile-cache counters (srt_jit_cache_*) from a
    registry snapshot, busiest kernel first, with a derived hit rate.
    Compile-time distributions live in the srt_jit_compile_ns rows of
    the histogram table."""
    agg: Dict[str, dict] = {}
    for metric, field in (("srt_jit_cache_hits_total", "hits"),
                          ("srt_jit_cache_misses_total", "misses"),
                          ("srt_jit_cache_evictions_total", "evictions")):
        fam = (registry or {}).get(metric)
        if not fam:
            continue
        for s in fam.get("series", []):
            kernel = s["labels"][0] if s.get("labels") else "?"
            a = agg.setdefault(kernel, {"kernel": kernel, "hits": 0,
                                        "misses": 0, "evictions": 0})
            a[field] = int(s.get("value", 0))
    rows = []
    for a in agg.values():
        total = a["hits"] + a["misses"]
        a["hit_rate"] = a["hits"] / total if total else 0.0
        rows.append(a)
    return sorted(rows, key=lambda a: -(a["hits"] + a["misses"]))


def render_jit_cache_table(registry: Optional[dict]) -> List[str]:
    """Kernel compile-cache summary: a cold cache (hit rate ~0) on a
    steady workload is the shape-bucketing regression signal."""
    rows = jit_cache_rows(registry)
    out = ["", "jit compile cache (srt_jit_cache_*)", ""]
    if not rows:
        out.append("(no compile-cache activity recorded)")
        return out
    w = max(len(r["kernel"]) for r in rows)
    hdr = (f"{'kernel':<{w}}  {'hits':>7}  {'misses':>7}  "
           f"{'evict':>6}  {'hit_rate':>8}")
    out.append(hdr)
    out.append("-" * len(hdr))
    for r in rows:
        out.append(f"{r['kernel']:<{w}}  {r['hits']:>7}  "
                   f"{r['misses']:>7}  {r['evictions']:>6}  "
                   f"{r['hit_rate']:>8.2f}")
    return out


def result_cache_rows(registry: Optional[dict]) -> List[dict]:
    """Per-(scope, tenant) semantic-cache counters
    (srt_result_cache_*) from a registry snapshot, busiest row first,
    with a derived hit rate.  Result-scope rows carry real tenants
    (the per-tenant warm-hit attribution the soak gate reads);
    stage/subplan rows aggregate under '-'."""
    agg: Dict[tuple, dict] = {}
    for metric, field in (("srt_result_cache_hits_total", "hits"),
                          ("srt_result_cache_misses_total", "misses")):
        fam = (registry or {}).get(metric)
        if not fam:
            continue
        for s in fam.get("series", []):
            labels = s.get("labels") or ("?", "?")
            scope = labels[0] if len(labels) > 0 else "?"
            tenant = labels[1] if len(labels) > 1 else "-"
            a = agg.setdefault((scope, tenant),
                               {"scope": scope, "tenant": tenant,
                                "hits": 0, "misses": 0})
            a[field] = int(s.get("value", 0))
    rows = []
    for a in agg.values():
        total = a["hits"] + a["misses"]
        a["hit_rate"] = a["hits"] / total if total else 0.0
        rows.append(a)
    rows.sort(key=lambda a: -(a["hits"] + a["misses"]))
    # cache-wide totals ride along so --json consumers see folds and
    # evictions without re-deriving them from other families
    folds = sum(int(s.get("value", 0)) for s in
                ((registry or {}).get(
                    "srt_result_cache_incremental_folds_total")
                 or {}).get("series", []))
    evictions = sum(int(s.get("value", 0)) for s in
                    ((registry or {}).get(
                        "srt_result_cache_evictions_total")
                     or {}).get("series", []))
    if rows or folds or evictions:
        rows.append({"scope": "(total)", "tenant": "-",
                     "hits": sum(r["hits"] for r in rows),
                     "misses": sum(r["misses"] for r in rows),
                     "hit_rate": 0.0, "folds": folds,
                     "evictions": evictions})
        t = rows[-1]
        tot = t["hits"] + t["misses"]
        t["hit_rate"] = t["hits"] / tot if tot else 0.0
    return rows


def render_result_cache_table(registry: Optional[dict]) -> List[str]:
    """Semantic result/subplan cache summary: per-tenant warm-hit
    rates plus the incremental-fold and eviction totals."""
    rows = result_cache_rows(registry)
    out = ["", "result cache (srt_result_cache_*)", ""]
    if not rows:
        out.append("(no result-cache activity recorded)")
        return out
    w = max(len(f"{r['scope']}/{r['tenant']}") for r in rows)
    hdr = (f"{'scope/tenant':<{w}}  {'hits':>7}  {'misses':>7}  "
           f"{'hit_rate':>8}")
    out.append(hdr)
    out.append("-" * len(hdr))
    for r in rows:
        name = f"{r['scope']}/{r['tenant']}"
        out.append(f"{name:<{w}}  {r['hits']:>7}  {r['misses']:>7}  "
                   f"{r['hit_rate']:>8.2f}")
    total = rows[-1]
    if "folds" in total:
        out.append(f"incremental folds: {total['folds']}  "
                   f"evictions: {total['evictions']}")
    return out


def kernel_path_rows(registry: Optional[dict]) -> List[dict]:
    """Per-op execution counts by the kernel path actually taken
    (srt_kernel_path_total) — the calibrated join/JSON routing
    evidence: an op stuck on ``host``/``host_rank`` at scale is the
    "dead calibration" regression signal."""
    rows: List[dict] = []
    fam = (registry or {}).get("srt_kernel_path_total")
    for s in (fam or {}).get("series", []):
        labels = s.get("labels") or ("?", "?")
        op = labels[0] if len(labels) > 0 else "?"
        path = labels[1] if len(labels) > 1 else "?"
        rows.append({"op": op, "path": path,
                     "count": int(s.get("value", 0))})
    return sorted(rows, key=lambda r: (r["op"], -r["count"], r["path"]))


def render_kernel_path_table(registry: Optional[dict]) -> List[str]:
    rows = kernel_path_rows(registry)
    out = ["", "kernel paths (srt_kernel_path_total)", ""]
    if not rows:
        out.append("(no calibrated kernel-path activity recorded)")
        return out
    w_op = max(len(r["op"]) for r in rows)
    w_p = max(max(len(r["path"]) for r in rows), len("path"))
    hdr = f"{'op':<{w_op}}  {'path':<{w_p}}  {'count':>8}"
    out.append(hdr)
    out.append("-" * len(hdr))
    for r in rows:
        out.append(f"{r['op']:<{w_op}}  {r['path']:<{w_p}}  "
                   f"{r['count']:>8}")
    return out


def stage_rows(events: List[dict]) -> List[dict]:
    """Whole-stage fusion accounting from ``stage_fusion`` journal
    events, one row per (stage, plan digest): executions by engine,
    fused-executable compiles vs cache hits, and the measured
    fused-vs-unfused wall ratio (>1 means fusion is winning).  The
    ``srt_stage_fusion_total{stage,outcome}`` counter carries the same
    outcomes to Prometheus."""
    agg: Dict[tuple, dict] = {}
    for e in events:
        if e.get("kind") != "stage_fusion":
            continue
        key = (str(e.get("stage", "?")), str(e.get("digest", "?")))
        a = agg.setdefault(key, {
            "stage": key[0], "digest": key[1], "nodes": 0,
            "fused": 0, "fused_timed": 0, "unfused": 0,
            "compiles": 0, "fused_ns": 0, "unfused_ns": 0})
        a["nodes"] = max(a["nodes"], int(e.get("nodes", 0)))
        outcome = str(e.get("outcome", "?"))
        if outcome == "fused":
            a["fused"] += 1
            # a run that BUILT its executable has lower+compile inside
            # its wall; folding that into the mean would make a 7x win
            # render as ratio << 1 — only steady-state walls count
            if not e.get("compiled"):
                a["fused_timed"] += 1
                a["fused_ns"] += int(e.get("wall_ns", 0))
        elif outcome == "unfused":
            a["unfused"] += 1
            a["unfused_ns"] += int(e.get("wall_ns", 0))
        if e.get("compiled"):
            a["compiles"] += 1
    rows = []
    for a in agg.values():
        a["cache_hits"] = max(a["fused"] - a["compiles"], 0)
        fused_mean = (a["fused_ns"] / a["fused_timed"]
                      if a["fused_timed"] else 0.0)
        unfused_mean = (a["unfused_ns"] / a["unfused"]
                        if a["unfused"] else 0.0)
        a["ratio"] = (unfused_mean / fused_mean
                      if fused_mean and unfused_mean else 0.0)
        rows.append(a)
    return sorted(rows, key=lambda a: (a["stage"], a["digest"]))


def render_stage_table(events: List[dict]) -> List[str]:
    """Stage-fusion table: one executable per stage, zero compiles on
    repeats, and unfused/fused wall ratio > 1 are the healthy signals;
    a stage stuck on the unfused engine at scale is the 'fusion went
    dead' regression signal."""
    rows = stage_rows(events)
    out = ["", "stage fusion (per stage digest)", ""]
    if not rows:
        out.append("(no stage-fusion activity recorded)")
        return out
    w = max(len(r["stage"]) for r in rows)
    hdr = (f"{'stage':<{w}}  {'digest':<16}  {'nodes':>5}  "
           f"{'fused':>5}  {'unfus':>5}  {'cmpl':>4}  {'hits':>4}  "
           f"{'fused_ms':>9}  {'unfus_ms':>9}  {'ratio':>6}")
    out.append(hdr)
    out.append("-" * len(hdr))
    for r in rows:
        fused_ms = (r["fused_ns"] / r["fused_timed"] / 1e6
                    if r["fused_timed"] else 0.0)
        unfused_ms = (r["unfused_ns"] / r["unfused"] / 1e6
                      if r["unfused"] else 0.0)
        # run digests are "plan|operands"; show a slice of BOTH
        # halves or same-plan rows at different buckets look identical
        dig = r["digest"]
        if "|" in dig:
            plan_d, ops_d = dig.split("|", 1)
            dig = f"{plan_d[:7]}|{ops_d[:8]}"
        out.append(
            f"{r['stage']:<{w}}  {dig[:16]:<16}  "
            f"{r['nodes']:>5}  {r['fused']:>5}  {r['unfused']:>5}  "
            f"{r['compiles']:>4}  {r['cache_hits']:>4}  "
            f"{fused_ms:>9.3f}  {unfused_ms:>9.3f}  "
            f"{r['ratio']:>6.2f}")
    return out


def retry_episode_rows(events: List[dict]) -> List[dict]:
    """Aggregate retry_episode journal events per driver name:
    episodes, attempts, splits, max split depth, time lost, and the
    outcome breakdown."""
    agg: Dict[str, dict] = {}
    for e in events:
        if e.get("kind") != "retry_episode":
            continue
        name = str(e.get("name", "?"))
        a = agg.setdefault(name, {
            "name": name, "episodes": 0, "attempts": 0, "splits": 0,
            "max_split_depth": 0, "lost_ns": 0, "outcomes": {}})
        a["episodes"] += 1
        a["attempts"] += int(e.get("attempts", 0))
        a["splits"] += int(e.get("splits", 0))
        a["max_split_depth"] = max(a["max_split_depth"],
                                   int(e.get("max_split_depth", 0)))
        a["lost_ns"] += int(e.get("lost_ns", 0))
        out = str(e.get("outcome", "?"))
        a["outcomes"][out] = a["outcomes"].get(out, 0) + 1
    return sorted(agg.values(), key=lambda a: -a["lost_ns"])


def render_retry_table(events: List[dict]) -> List[str]:
    """Retry-episode summary (robustness/retry.py drivers): how often
    sections retried/split, how deep, and what the failures cost."""
    rows = retry_episode_rows(events)
    out = ["", "retry episodes", ""]
    if not rows:
        out.append("(no retry episodes recorded)")
        return out
    w = max(len(r["name"]) for r in rows)
    hdr = (f"{'section':<{w}}  {'episodes':>8}  {'attempts':>8}  "
           f"{'splits':>6}  {'depth':>5}  {'lost_ms':>10}  outcomes")
    out.append(hdr)
    out.append("-" * len(hdr))
    for r in rows:
        outcomes = ",".join(f"{k}={v}"
                            for k, v in sorted(r["outcomes"].items()))
        out.append(
            f"{r['name']:<{w}}  {r['episodes']:>8}  "
            f"{r['attempts']:>8}  {r['splits']:>6}  "
            f"{r['max_split_depth']:>5}  {_ms(r['lost_ns']):>10}  "
            f"{outcomes}")
    return out


def server_rows(events: List[dict],
                registry: Optional[dict]) -> List[dict]:
    """Per-(tenant, query) query-server accounting from the
    ``server_*`` journal events, enriched with the registry's
    per-tenant queue-wait p95 and device-byte gauges.  A row with
    query '*' is the tenant rollup."""
    agg: Dict[tuple, dict] = {}

    def row(tenant: str, query: str) -> dict:
        return agg.setdefault((tenant, query), {
            "tenant": tenant, "query": query, "admitted": 0,
            "rejected": 0, "requeued": 0, "success": 0, "failed": 0,
            "cancelled": 0, "shed": 0, "hung": 0, "deadline": 0,
            "dur_ns": 0, "wait_ns": 0})

    for e in events:
        kind = e.get("kind")
        if kind not in ("server_admit", "server_reject",
                        "server_requeue", "server_complete"):
            continue
        tenant = str(e.get("tenant", "?"))
        query = str(e.get("query", "?"))
        targets = [row(tenant, "*")]
        if kind != "server_requeue":   # requeues carry no query name
            targets.append(row(tenant, query))
        for a in targets:
            if kind == "server_admit":
                a["admitted"] += 1
            elif kind == "server_reject":
                a["rejected"] += 1
            elif kind == "server_requeue":
                a["requeued"] += 1
            elif kind == "server_complete":
                outcome = str(e.get("outcome", "?"))
                if outcome in a:
                    a[outcome] += 1
                a["dur_ns"] += int(e.get("dur_ns", 0))
                a["wait_ns"] += int(e.get("wait_ns", 0))
    # registry enrichment: queue-wait p95 + live gauges per tenant
    reg = registry or {}
    waits = reg.get("srt_server_queue_wait_ns") or {}
    buckets = waits.get("buckets", [])
    for s in waits.get("series", []):
        tenant = s["labels"][0] if s.get("labels") else "?"
        a = row(tenant, "*")
        a["p95_wait_ns"] = histogram_quantile(
            buckets, s.get("bucket_counts", []), 0.95)
    for metric, field in (("srt_server_tenant_device_bytes",
                           "device_bytes"),
                          ("srt_server_running", "running"),
                          ("srt_server_queued", "queued")):
        fam = reg.get(metric) or {}
        for s in fam.get("series", []):
            tenant = s["labels"][0] if s.get("labels") else "?"
            row(tenant, "*")[field] = int(s.get("value", 0))
    return sorted(agg.values(),
                  key=lambda a: (a["tenant"], a["query"] != "*",
                                 a["query"]))


def render_server_table(events: List[dict],
                        registry: Optional[dict]) -> List[str]:
    """Query-server tenancy table: admission outcomes, fair-share
    wait, and held device bytes per tenant (rollup row '*') and per
    query — the 'is anyone starved / hogging' one-pager."""
    rows = server_rows(events, registry)
    out = ["", "query server (per tenant / per query)", ""]
    if not rows:
        out.append("(no server activity recorded)")
        return out
    w = max(len(f"{r['tenant']}:{r['query']}") for r in rows)
    hdr = (f"{'tenant:query':<{w}}  {'admit':>5}  {'rej':>4}  "
           f"{'requ':>4}  {'ok':>4}  {'fail':>4}  {'cncl':>4}  "
           f"{'shed':>4}  {'hung':>4}  {'ddl':>3}  {'run':>3}  "
           f"{'p95_wait_ms':>11}  {'dev_bytes':>10}")
    out.append(hdr)
    out.append("-" * len(hdr))
    for r in rows:
        name = f"{r['tenant']}:{r['query']}"
        p95 = r.get("p95_wait_ns")
        out.append(
            f"{name:<{w}}  {r['admitted']:>5}  {r['rejected']:>4}  "
            f"{r['requeued']:>4}  {r['success']:>4}  "
            f"{r['failed']:>4}  {r['cancelled']:>4}  {r['shed']:>4}  "
            f"{r.get('hung', 0):>4}  {r.get('deadline', 0):>3}  "
            f"{r.get('running', 0):>3}  "
            f"{(p95 / 1e6 if p95 is not None else 0.0):>11.3f}  "
            f"{r.get('device_bytes', 0):>10}")
    return out


def io_rows(events: List[dict],
            registry: Optional[dict]) -> List[dict]:
    """Per-source ingest accounting from ``io_file`` journal events
    (files, pages, rows, bytes, decode throughput), with the
    registry's ``srt_io_read_ns`` p95 on the total row.  A row with
    source '*' is the whole-process rollup."""
    agg: Dict[str, dict] = {}

    def row(source: str) -> dict:
        return agg.setdefault(source, {
            "source": source, "files": 0, "pages": 0, "rows": 0,
            "read_bytes": 0, "decode_ns": 0})

    for e in events:
        if e.get("kind") != "io_file":
            continue
        src = str(e.get("source", "?")).rsplit("/", 1)[-1]
        for a in (row("*"), row(src)):
            a["files"] += 1
            a["pages"] += int(e.get("pages", 0))
            a["rows"] += int(e.get("rows", 0))
            a["read_bytes"] += int(e.get("read_bytes", 0))
            a["decode_ns"] += int(e.get("decode_ns", 0))
    reads = (registry or {}).get("srt_io_read_ns") or {}
    for s in reads.get("series", []):
        a = row("*")
        a["p95_read_ns"] = histogram_quantile(
            reads.get("buckets", []), s.get("bucket_counts", []), 0.95)
        a["reads"] = s.get("count", 0)
    # derived AFTER every row exists (the registry loop above can
    # create the '*' rollup on its own when no io_file event landed)
    for a in agg.values():
        a["decode_mb_s"] = (a["read_bytes"] / 1e6
                            / (a["decode_ns"] / 1e9)
                            if a["decode_ns"] else 0.0)
    return sorted(agg.values(),
                  key=lambda a: (a["source"] != "*", a["source"]))


def render_io_table(events: List[dict],
                    registry: Optional[dict]) -> List[str]:
    """Ingest table: what storage cost per source file (rollup row
    '*') — files, pages, rows, bytes, read p95, decode throughput."""
    rows = io_rows(events, registry)
    out = ["", "io ingest (per source file)", ""]
    if not rows:
        out.append("(no io activity recorded)")
        return out
    w = max(len(r["source"]) for r in rows)
    hdr = (f"{'source':<{w}}  {'files':>5}  {'pages':>5}  "
           f"{'rows':>9}  {'MB':>8}  {'p95_read_ms':>11}  "
           f"{'decode_MB/s':>11}")
    out.append(hdr)
    out.append("-" * len(hdr))
    for r in rows:
        p95 = r.get("p95_read_ns")
        out.append(
            f"{r['source']:<{w}}  {r['files']:>5}  {r['pages']:>5}  "
            f"{r['rows']:>9}  {r['read_bytes'] / 1e6:>8.2f}  "
            f"{(p95 / 1e6 if p95 is not None else 0.0):>11.3f}  "
            f"{r['decode_mb_s']:>11.1f}")
    return out


def fleet_rows(events: List[dict],
               registry: Optional[dict]) -> dict:
    """Elastic-fleet accounting: per-peer link bytes (+ dup drops and
    observed deaths), the fleet skew ratio (max/median of per-peer
    recv bytes), speculation outcomes, rebalances, re-splits, and the
    membership epoch — the ISSUE-15 evidence surface."""
    reg = registry or {}

    def series(name: str) -> List[dict]:
        return (reg.get(name) or {}).get("series", [])

    peers: Dict[str, dict] = {}

    def peer(p: str) -> dict:
        return peers.setdefault(p, {
            "peer": p, "send_bytes": 0, "recv_bytes": 0,
            "dup_dropped": 0, "deaths": 0, "stale_naks": 0})

    for s in series("srt_shuffle_link_bytes_total"):
        d, p = (list(s.get("labels", ())) + ["?", "?"])[:2]
        key = "send_bytes" if d == "send" else "recv_bytes"
        peer(p)[key] += int(s.get("value", 0))
    for name, key in (("srt_shuffle_dup_dropped_total",
                       "dup_dropped"),
                      ("srt_fleet_deaths_total", "deaths"),
                      ("srt_fleet_stale_naks_total", "stale_naks")):
        for s in series(name):
            p = (list(s.get("labels", ())) + ["?"])[0]
            peer(p)[key] += int(s.get("value", 0))
    recv = sorted(r["recv_bytes"] for r in peers.values()
                  if r["recv_bytes"] > 0)
    med = recv[(len(recv) - 1) // 2] if recv else 0  # lower median
    skew = (round(recv[-1] / med, 2)
            if len(recv) >= 2 and med > 0 else None)
    spec = {"won": 0, "lost": 0, "cancelled": 0}
    for s in series("srt_fleet_speculations_total"):
        lab = (list(s.get("labels", ())) + ["?"])[0]
        if lab in spec:
            spec[lab] += int(s.get("value", 0))
    epoch_series = series("srt_fleet_epoch")
    epoch = int(epoch_series[0]["value"]) if epoch_series else 0
    rebalances = sum(int(s.get("value", 0)) for s in
                     series("srt_fleet_rebalances_total"))
    resplits = sum(int(s.get("value", 0)) for s in
                   series("srt_fleet_resplits_total"))
    memberships = [
        {"change": e.get("change"), "dead": e.get("dead"),
         "joined": e.get("joined"), "epoch": e.get("epoch"),
         "moved": e.get("moved")}
        for e in events if e.get("kind") == "fleet_membership"]
    return {
        "peers": sorted(peers.values(), key=lambda r: r["peer"]),
        "skew_ratio": skew,
        "speculations": spec,
        "rebalances": rebalances,
        "resplits": resplits,
        "epoch": epoch,
        "memberships": memberships,
    }


def render_fleet_table(events: List[dict],
                       registry: Optional[dict]) -> List[str]:
    """Fleet table: per-peer wire bytes + dedup/death evidence, then
    the one-line elasticity summary (epoch, rebalances, speculation
    won/lost, re-splits, skew)."""
    f = fleet_rows(events, registry)
    out = ["", "fleet (elastic shuffle)", ""]
    if not f["peers"] and not f["memberships"]:
        out.append("(no fleet activity recorded)")
        return out
    hdr = (f"{'peer':>4}  {'send_MB':>8}  {'recv_MB':>8}  "
           f"{'dup_drop':>8}  {'deaths':>6}  {'stale':>5}")
    out.append(hdr)
    out.append("-" * len(hdr))
    for r in f["peers"]:
        out.append(
            f"{r['peer']:>4}  {r['send_bytes'] / 1e6:>8.2f}  "
            f"{r['recv_bytes'] / 1e6:>8.2f}  {r['dup_dropped']:>8}  "
            f"{r['deaths']:>6}  {r['stale_naks']:>5}")
    spec = f["speculations"]
    out.append("")
    out.append(
        f"epoch {f['epoch']}  rebalances {f['rebalances']}  "
        f"speculations won/lost/cancelled "
        f"{spec['won']}/{spec['lost']}/{spec['cancelled']}  "
        f"resplits {f['resplits']}  "
        f"skew_ratio {f['skew_ratio'] if f['skew_ratio'] else '-'}")
    for m in f["memberships"][:8]:
        what = (f"dead={m['dead']}" if m["change"] == "death"
                else f"joined={m['joined']}")
        out.append(f"  membership: {m['change']} {what} "
                   f"epoch={m['epoch']} moved={m['moved'] or {}}")
    return out


def render_event_table(events: List[dict]) -> List[str]:
    counts: Dict[str, int] = {}
    for e in events:
        k = e.get("kind", "?")
        counts[k] = counts.get(k, 0) + 1
    out = ["", "journal events", ""]
    if not counts:
        out.append("(journal empty)")
        return out
    w = max(len(k) for k in counts)
    for k in sorted(counts, key=lambda k: -counts[k]):
        out.append(f"{k:<{w}}  {counts[k]}")
    ooms = [e for e in events
            if e.get("kind") in ("oom_retry", "oom_split_retry")]
    if ooms:
        out.append("")
        out.append("oom events (most recent last):")
        for e in ooms[-10:]:
            out.append(
                f"  {e.get('kind')}: task={e.get('task')} "
                f"thread={e.get('thread')} device={e.get('device')}"
                f"{' injected' if e.get('injected') else ''}")
    return out


def window_rows(timeseries: Optional[dict],
                registry: Optional[dict],
                n: int = 12) -> List[dict]:
    """Recent-rate rows: for every counter family that moved in the
    last ``n`` windows, the windowed delta + per-second rate NEXT TO
    the since-boot total (the distinction this PR exists to surface).
    Histogram families get windowed p50/p99 alongside the cumulative
    estimates — recent percentiles from per-window buckets, never the
    diluted since-boot distribution.  A registry histogram family with
    ZERO samples in the window still gets a row, with ``None``
    percentiles (rendered ``-``): quiet-right-now is a reading, and
    substituting the since-boot distribution would claim recency the
    data does not have."""
    if timeseries is None:
        return []
    windows = (timeseries.get("windows") or [])[-n:]
    if not windows:
        return []
    dur = max(sum(w.get("dur_s", 0.0) for w in windows), 1e-9)
    counters: Dict[str, float] = {}
    hists: Dict[str, dict] = {}
    for w in windows:
        for fam, vals in w.get("counters", {}).items():
            counters[fam] = counters.get(fam, 0) + sum(vals.values())
        for fam, h in w.get("histograms", {}).items():
            acc = hists.setdefault(fam, {
                "buckets": h["buckets"],
                "bucket_counts": [0] * (len(h["buckets"]) + 1),
                "sum": 0, "count": 0})
            for s in h["series"].values():
                for i, c in enumerate(s["bucket_counts"]):
                    acc["bucket_counts"][i] += c
                acc["sum"] += s["sum"]
                acc["count"] += s["count"]
    rows: List[dict] = []
    for fam in sorted(counters):
        total = None
        f = (registry or {}).get(fam)
        if f and f.get("kind") == "counter":
            total = sum(s.get("value", 0)
                        for s in f.get("series", []))
        rows.append({"family": fam, "kind": "counter",
                     "recent": counters[fam],
                     "rate_s": round(counters[fam] / dur, 3),
                     "since_boot": total})
    hist_fams = set(hists)
    for fam, f in (registry or {}).items():
        if f.get("kind") == "histogram":
            hist_fams.add(fam)
    for fam in sorted(hist_fams):
        h = hists.get(fam)
        cum_p99 = None
        f = (registry or {}).get(fam)
        if f and f.get("kind") == "histogram":
            bc = [0] * (len(f.get("buckets", [])) + 1)
            for s in f.get("series", []):
                for i, c in enumerate(s.get("bucket_counts", [])):
                    bc[i] += c
            if sum(bc):
                cum_p99 = histogram_quantile(f.get("buckets", []),
                                             bc, 0.99)
        count = h["count"] if h else 0
        rows.append({
            "family": fam, "kind": "histogram",
            "recent": count,
            # zero window samples -> None, NOT a since-boot stand-in
            "recent_p50_ns": (histogram_quantile(
                h["buckets"], h["bucket_counts"], 0.50)
                if h and count else None),
            "recent_p99_ns": (histogram_quantile(
                h["buckets"], h["bucket_counts"], 0.99)
                if h and count else None),
            "since_boot_p99_ns": cum_p99})
    return rows


def render_window_table(timeseries: Optional[dict],
                        registry: Optional[dict],
                        n: int = 12) -> List[str]:
    rows = window_rows(timeseries, registry, n)
    out = ["", f"recent window (last {n} windows of the timeseries "
               "ring; rates are per second)", ""]
    if not rows:
        out.append("(no timeseries_snapshot record in input — run "
                   "with SPARK_RAPIDS_TPU_TIMESERIES=1)")
        return out
    w = max(len(r["family"]) for r in rows)
    out.append(f"{'family':<{w}}  {'recent':>10}  {'rate/s':>10}  "
               f"{'since_boot':>12}  {'w_p50_us':>9}  {'w_p99_us':>9}  "
               f"{'boot_p99_us':>11}")
    for r in rows:
        if r["kind"] == "counter":
            boot = "-" if r["since_boot"] is None \
                else f"{r['since_boot']}"
            out.append(f"{r['family']:<{w}}  {r['recent']:>10}  "
                       f"{r['rate_s']:>10.2f}  {boot:>12}  "
                       f"{'-':>9}  {'-':>9}  {'-':>11}")
        else:
            boot99 = "-" if r["since_boot_p99_ns"] is None \
                else f"{r['since_boot_p99_ns'] / 1e3:.1f}"
            p50 = "-" if r["recent_p50_ns"] is None \
                else f"{r['recent_p50_ns'] / 1e3:.1f}"
            p99 = "-" if r["recent_p99_ns"] is None \
                else f"{r['recent_p99_ns'] / 1e3:.1f}"
            out.append(f"{r['family']:<{w}}  {r['recent']:>10}  "
                       f"{'-':>10}  {'-':>12}  "
                       f"{p50:>9}  "
                       f"{p99:>9}  "
                       f"{boot99:>11}")
    return out


def stats_rows(events: List[dict],
               registry: Optional[dict]) -> dict:
    """Data-statistics plane fold (ISSUE 20): per-(stage, node)
    misestimates (latest journal event wins) + per-tenant delivered
    rows from the registry."""
    latest: Dict[tuple, dict] = {}
    for e in events:
        if e.get("kind") != "cardinality_misestimate":
            continue
        latest[(str(e.get("stage", "?")), str(e.get("node", "?")))] = {
            "est": e.get("est"), "actual": e.get("actual"),
            "ratio": e.get("ratio")}
    fam = (registry or {}).get("srt_stats_rows_total") or {}
    tenant_rows = {s["labels"][0]: s.get("value", 0)
                   for s in fam.get("series", []) if s.get("labels")}
    return {
        "observations": sum(1 for e in events
                            if e.get("kind") == "node_stats"),
        "misestimates": [
            {"stage": k[0], "node": k[1], **v}
            for k, v in sorted(latest.items())],
        "tenant_rows": tenant_rows,
    }


def render_stats_table(events: List[dict],
                       registry: Optional[dict]) -> List[str]:
    d = stats_rows(events, registry)
    out = ["", "data statistics (cardinality est vs actual; rows past "
               "SPARK_RAPIDS_TPU_STATS_MISEST_RATIO are misestimates)",
           ""]
    mis = d["misestimates"]
    if mis:
        w = max(max(len(m["stage"]) for m in mis), len("stage"))
        wn = max(max(len(m["node"]) for m in mis), len("node"))
        hdr = (f"{'stage':<{w}}  {'node':<{wn}}  {'est':>12}  "
               f"{'actual':>12}  {'ratio':>8}")
        out.append(hdr)
        out.append("-" * len(hdr))
        for m in mis:
            out.append(f"{m['stage']:<{w}}  {m['node']:<{wn}}  "
                       f"{m.get('est', 0):>12}  "
                       f"{m.get('actual', 0):>12}  "
                       f"x{m.get('ratio', 0):>7}")
    else:
        out.append(f"(no misestimates; {d['observations']} "
                   f"node_stats observation event(s))")
    if d["tenant_rows"]:
        out.append("rows delivered: " + "  ".join(
            f"{t}={v}" for t, v in sorted(d["tenant_rows"].items())))
    return out


def render_slo_table(slo: Optional[dict]) -> List[str]:
    out = ["", "per-tenant SLO (burn = bad fraction / error budget; "
               "fires when fast AND slow exceed threshold)", ""]
    if not slo:
        out.append("(no SLO status in input — run with "
                   "SPARK_RAPIDS_TPU_SLO=1)")
        return out
    w = max(max(len(t) for t in slo), len("tenant"))
    hdr = (f"{'tenant':<{w}}  {'target_ms':>9}  {'objective':>9}  "
           f"{'events':>7}  {'attainment':>10}  {'burn_fast':>9}  "
           f"{'burn_slow':>9}  {'breaches':>8}")
    out.append(hdr)
    out.append("-" * len(hdr))
    for t in sorted(slo):
        r = slo[t]
        out.append(f"{t:<{w}}  {r.get('latency_target_ms', 0):>9.1f}  "
                   f"{r.get('objective', 0):>9.3f}  "
                   f"{r.get('events', 0):>7}  "
                   f"{r.get('attainment', 0):>10.4f}  "
                   f"{r.get('burn_fast', 0):>9.2f}  "
                   f"{r.get('burn_slow', 0):>9.2f}  "
                   f"{r.get('breaches', 0):>8}")
    return out


def build_report(records: List[dict]) -> dict:
    """Machine-readable report (the --json output)."""
    rollups, registry, events = split_records(records)
    timeseries, slo = extract_telemetry(records)
    counts: Dict[str, int] = {}
    for e in events:
        k = e.get("kind", "?")
        counts[k] = counts.get(k, 0) + 1
    return {
        "tasks": {str(t): {k: v for k, v in r.items() if k != "kind"}
                  for t, r in rollups.items()},
        "event_counts": counts,
        "has_registry_snapshot": registry is not None,
        "histograms": histogram_rows(registry),
        "retry_episodes": retry_episode_rows(events),
        "jit_cache": jit_cache_rows(registry),
        "cache": result_cache_rows(registry),
        "kernel_paths": kernel_path_rows(registry),
        "stages": stage_rows(events),
        "server": server_rows(events, registry),
        "io": io_rows(events, registry),
        "fleet": fleet_rows(events, registry),
        "stats": stats_rows(events, registry),
        "slo": slo,
        "window": window_rows(timeseries, registry),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Per-task/per-op report from an observability "
                    "journal dump")
    ap.add_argument("inputs", nargs="+", help="journal JSONL files")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of tables")
    ap.add_argument("--window", type=int, nargs="?", const=12,
                    default=None, metavar="N",
                    help="render recent-rate/windowed-percentile "
                         "columns from the timeseries ring (last N "
                         "windows, default 12)")
    args = ap.parse_args(argv)

    records = load_jsonl(args.inputs)
    if args.json:
        print(json.dumps(build_report(records), indent=2, sort_keys=True))
        return 0
    rollups, registry, events = split_records(records)
    timeseries, slo = extract_telemetry(records)
    lines: List[str] = []
    if rollups:
        lines += render_task_table(rollups)
        lines += render_op_table(rollups)
    else:
        lines.append("(no task_rollup records in input)")
    lines += render_event_table(events)
    lines += render_retry_table(events)
    if any(e.get("kind", "").startswith("server_") for e in events) \
            or (registry or {}).get("srt_server_queue_wait_ns"):
        lines += render_server_table(events, registry)
    if any(e.get("kind") == "io_file" for e in events):
        lines += render_io_table(events, registry)
    if any(e.get("kind", "").startswith("fleet_") for e in events) \
            or (registry or {}).get("srt_fleet_rebalances_total",
                                    {}).get("series") \
            or (registry or {}).get("srt_shuffle_dup_dropped_total",
                                    {}).get("series"):
        lines += render_fleet_table(events, registry)
    if any(e.get("kind") == "stage_fusion" for e in events):
        lines += render_stage_table(events)
    if any(e.get("kind") in ("node_stats", "cardinality_misestimate")
           for e in events) \
            or (registry or {}).get("srt_stats_rows_total",
                                    {}).get("series"):
        lines += render_stats_table(events, registry)
    if args.window is not None:
        lines += render_window_table(timeseries, registry,
                                     args.window)
    if slo is not None or args.window is not None:
        lines += render_slo_table(slo)
    if registry is not None:
        lines += render_jit_cache_table(registry)
        if (registry or {}).get("srt_result_cache_hits_total") \
                or (registry or {}).get(
                    "srt_result_cache_misses_total"):
            lines += render_result_cache_table(registry)
        if (registry or {}).get("srt_kernel_path_total"):
            lines += render_kernel_path_table(registry)
        lines += render_histogram_table(registry)
        lines.append("")
        lines.append(f"registry snapshot: {len(registry)} metric families")
    print("\n".join(lines))
    return 0


if __name__ == "__main__":
    sys.exit(main())
