"""bench-trend: the perf trajectory across bench rounds (ISSUE 20).

::

    python -m spark_rapids_tpu.tools.bench_trend [--dir REPO]
    ... --json     machine-readable, key-sorted, golden-stable

Folds every ``BENCH_r*.json`` / ``BENCH_serve_r*.json`` the bench
drivers left at the repo root into ONE table: per round, the headline
metric that round was about, its value/unit, the delta vs the previous
*comparable* round (same metric+unit — a round that switched headline
metrics starts a new series rather than faking a delta), and a
regression flag when a comparable headline dropped by more than
``--tolerance`` (default 5%).

The extractors mirror the writers: rounds r01–r05 are the row-conversion
bench (``parsed.metric/value/unit``), r06 the kernel+TPC-DS sweep
(headline: fused-pipeline q5 rows/s), r07 the whole-stage-fusion smoke
(headline: fused q5 speedup), r08 the out-of-core join bench, and the
``serve_*`` rounds the multi-tenant serving replays (throughput QPS;
r03 the cached-serving run).  Unknown/new round files degrade to a
"(no extractor)" row instead of failing the whole table, so the next
bench round does not break the trend until its extractor lands.

Exit status: 0 clean, 1 when any regression was flagged, 2 usage.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

# flag a drop bigger than this fraction vs the previous comparable
# round (bench noise on shared boxes sits well under it)
DEFAULT_TOLERANCE = 0.05


def repo_root() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def _load(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            d = json.load(f)
        return d if isinstance(d, dict) else None
    except (OSError, ValueError):
        return None


# --------------------------------------------------------- extractors
# one per bench-round schema; each returns the round's headline
# {metric, value, unit} plus whatever secondary numbers make the row
# readable.  Higher value = better for every headline emitted here,
# which is what the delta/regression logic assumes.


def _x_rowconv(parsed: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """r01–r05: ``{"metric", "value", "unit", "vs_baseline"}``."""
    if "value" not in parsed or "metric" not in parsed:
        return None
    out = {"metric": "rowconv_GBps", "value": float(parsed["value"]),
           "unit": str(parsed.get("unit", ""))}
    if "vs_baseline" in parsed:
        out["detail"] = f"x{parsed['vs_baseline']:g} vs baseline"
    return out


def _x_kernel_sweep(parsed: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """r06: bench_all kernel + TPC-DS sweep — headline q5 rows/s."""
    tp = parsed.get("tpcds_2e6")
    if not isinstance(tp, dict) or "q5_rows_per_s" not in tp:
        return None
    out = {"metric": "tpcds_q5_rows_per_s",
           "value": float(tp["q5_rows_per_s"]), "unit": "rows/s"}
    extras = []
    for q in ("q3", "q9", "q72_cs", "q7"):
        v = tp.get(f"{q}_rows_per_s")
        if v is not None:
            extras.append(f"{q} {float(v) / 1e6:.2f}M")
    if extras:
        out["detail"] = "also " + ", ".join(extras) + " rows/s"
    return out


def _x_fusion(parsed: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """r07: whole-stage fusion smoke — headline fused q5 speedup."""
    sf = parsed.get("stage_fusion")
    if not isinstance(sf, dict) or "q5" not in sf:
        return None
    q5 = sf["q5"]
    out = {"metric": "fused_q5_speedup",
           "value": float(q5["speedup"]), "unit": "x"}
    bits = [f"{q} x{sf[q]['speedup']:g}" for q in ("q3", "q72")
            if isinstance(sf.get(q), dict) and "speedup" in sf[q]]
    exe = parsed.get("executables") or {}
    if exe.get("second_same_bucket_query_compiles") == 0:
        bits.append("0 recompiles warm")
    if bits:
        out["detail"] = ", ".join(bits)
    return out


def _x_out_of_core(parsed: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """r08: tiered-spill out-of-core join bench."""
    ooc = parsed.get("out_of_core_join")
    if not isinstance(ooc, dict) or "probe_mrows_per_s" not in ooc:
        return None
    out = {"metric": "ooc_join_probe_Mrows_per_s",
           "value": float(ooc["probe_mrows_per_s"]), "unit": "Mrows/s"}
    if "spills" in ooc:
        out["detail"] = (f"{ooc['spills']} spills, "
                         f"{ooc.get('spill_gb_per_s', 0):g} GB/s out")
    return out


def _x_serve(parsed: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """serve_r01/r02/r03: serving replay / ramp / cached-serving."""
    if "throughput_qps" in parsed:
        return {"metric": "serve_qps",
                "value": float(parsed["throughput_qps"]), "unit": "qps",
                "detail": f"{parsed.get('requests', '?')} requests, "
                          f"concurrency {parsed.get('concurrency', '?')}"}
    steps = parsed.get("steps")
    if isinstance(steps, list) and steps:
        # achieved QPS at the top OFFERED step — load-following, not
        # capacity, so it gets its own series rather than a fake delta
        # vs the burst-throughput rounds
        last = steps[-1]
        if "qps_achieved" in last:
            return {"metric": "serve_ramp_qps",
                    "value": float(last["qps_achieved"]), "unit": "qps",
                    "detail": f"ramp {parsed.get('ramp', '?')}, top step "
                              f"offered {last.get('qps_offered', '?')}"}
    on = parsed.get("cache_on")
    if isinstance(on, dict) and "qps" in on:
        out = {"metric": "serve_cached_qps",
               "value": float(on["qps"]), "unit": "qps",
               "detail": f"hit ratio {on.get('hit_ratio', 0):.2%}"}
        sp = parsed.get("warm_vs_cold_median_speedup")
        if sp is not None:
            out["detail"] += f", warm x{sp:g} vs cold"
        return out
    return None


_EXTRACTORS = (_x_fusion, _x_kernel_sweep, _x_out_of_core, _x_serve,
               _x_rowconv)


def _round_label(path: str) -> str:
    name = os.path.basename(path)
    if name.startswith("BENCH_") and name.endswith(".json"):
        name = name[len("BENCH_"):-len(".json")]
    return name


def collect(paths: List[str]) -> List[Dict[str, Any]]:
    """One row per bench file, in the given order (the caller sorts
    paths so serve rounds trail the numbered kernel rounds)."""
    rows: List[Dict[str, Any]] = []
    for path in paths:
        d = _load(path)
        row: Dict[str, Any] = {"round": _round_label(path),
                               "file": os.path.basename(path)}
        if d is None:
            row["error"] = "unreadable"
            rows.append(row)
            continue
        parsed = d.get("parsed")
        head = None
        if isinstance(parsed, dict):
            for ex in _EXTRACTORS:
                head = ex(parsed)
                if head is not None:
                    break
        if head is None:
            row["error"] = "no extractor"
        else:
            row.update(head)
        rows.append(row)
    return rows


def annotate(rows: List[Dict[str, Any]],
             tolerance: float = DEFAULT_TOLERANCE) -> None:
    """Delta + regression flags, in place.  A delta only exists vs the
    most recent EARLIER row with the same metric+unit — new headline
    metrics start a new series at delta '-'."""
    last: Dict[str, float] = {}
    for row in rows:
        if "value" not in row:
            continue
        key = f"{row['metric']}|{row['unit']}"
        prev = last.get(key)
        if prev is not None and prev > 0:
            delta = (row["value"] - prev) / prev
            row["delta_pct"] = round(100.0 * delta, 1)
            row["regression"] = bool(delta < -tolerance)
        last[key] = row["value"]


def _fmt_value(row: Dict[str, Any]) -> str:
    v = row["value"]
    if abs(v) >= 1e6:
        return f"{v / 1e6:.2f}M"
    if abs(v) >= 1e4:
        return f"{v / 1e3:.1f}k"
    return f"{v:g}"


def render(rows: List[Dict[str, Any]]) -> str:
    out = ["bench trend (headline metric per round; delta vs previous "
           "comparable round)"]
    hdr = (f"{'round':<10}  {'metric':<26}  {'value':>9}  "
           f"{'unit':<8}  {'delta':>7}  {'flag':<4}  detail")
    out.append(hdr)
    out.append("-" * len(hdr))
    for row in rows:
        if "value" not in row:
            out.append(f"{row['round']:<10}  "
                       f"({row.get('error', 'empty')})")
            continue
        delta = ("-" if "delta_pct" not in row
                 else f"{row['delta_pct']:+.1f}%")
        flag = "REG" if row.get("regression") else ""
        out.append(f"{row['round']:<10}  {row['metric']:<26}  "
                   f"{_fmt_value(row):>9}  {row['unit']:<8}  "
                   f"{delta:>7}  {flag:<4}  {row.get('detail', '')}")
    regs = [r["round"] for r in rows if r.get("regression")]
    out.append("")
    out.append(f"{len([r for r in rows if 'value' in r])} rounds, "
               f"{len(regs)} regression(s)"
               + (f": {', '.join(regs)}" if regs else ""))
    return "\n".join(out)


def _default_paths(root: str) -> List[str]:
    # numbered kernel rounds first, then the serving rounds — each is
    # its own chronological series and the delta logic keys on metric
    # name anyway
    num = sorted(p for p in glob.glob(os.path.join(root, "BENCH_r*.json")))
    serve = sorted(
        p for p in glob.glob(os.path.join(root, "BENCH_serve_r*.json")))
    return num + serve


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench-trend",
        description="fold per-round BENCH_*.json files into one perf "
                    "trajectory table")
    ap.add_argument("files", nargs="*",
                    help="explicit bench files (default: BENCH_r*.json "
                         "+ BENCH_serve_r*.json under --dir)")
    ap.add_argument("--dir", default=repo_root(),
                    help="directory to glob bench files from")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="fractional drop vs previous comparable round "
                         "that flags a regression (default 0.05)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable, key-sorted, golden-stable")
    try:
        args = ap.parse_args(argv)
    except SystemExit:
        return 2
    paths = args.files or _default_paths(args.dir)
    if not paths:
        print(f"bench-trend: no BENCH_*.json files under {args.dir}",
              file=sys.stderr)
        return 2
    rows = collect(paths)
    annotate(rows, tolerance=args.tolerance)
    regressions = sum(1 for r in rows if r.get("regression"))
    if args.json:
        print(json.dumps({"rounds": rows, "regressions": regressions,
                          "tolerance": args.tolerance},
                         sort_keys=True, indent=1))
    else:
        print(render(rows))
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
