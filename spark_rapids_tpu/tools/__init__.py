"""Offline tooling (reference profiler/ converter + tools/ analogs)."""
