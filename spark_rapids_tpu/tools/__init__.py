"""Offline tooling (reference profiler/ converter + tools/ analogs)."""

from __future__ import annotations

import json
import os
import sys
from typing import List


def read_jsonl(path: str) -> List[dict]:
    """Tolerant JSONL load shared by the report/export tools: blank
    lines skipped, unparseable lines warned to stderr (never fatal —
    a truncated line must not hide the rest of a dump), non-dict
    records dropped."""
    records: List[dict] = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                print(f"{path}:{i + 1}: skipping unparseable line",
                      file=sys.stderr)
                continue
            if isinstance(rec, dict):
                records.append(rec)
    return records

# flight-recorder bundle layout (observability/flight_recorder.py)
_BUNDLE_FILES = {"spans": "spans.jsonl", "journal": "journal.jsonl",
                 "profile": "profile.json"}


def expand_bundle_input(path: str, prefer: str) -> List[str]:
    """Let every JSONL-eating tool accept a flight-recorder incident
    bundle directory directly: a directory input resolves to the
    bundle file matching ``prefer`` ("spans", "journal" or
    "profile").  Only the spans consumer may fall back to
    journal.jsonl (span records also ride the journal dump); the
    reverse would hand the metrics report a spans-only file it
    silently renders empty, and a bundle frozen before any query was
    profiled has no profile.json at all — both fail loudly instead.
    Non-directory inputs pass through untouched."""
    if not os.path.isdir(path):
        return [path]
    want = _BUNDLE_FILES[prefer]
    names = [want, _BUNDLE_FILES["journal"]] if prefer == "spans" \
        else [want]
    for name in names:
        cand = os.path.join(path, name)
        if os.path.isfile(cand):
            return [cand]
    raise FileNotFoundError(
        f"{path}: directory holds no {' or '.join(names)} "
        f"(not a flight-recorder incident bundle?)")
