"""Offline profile converter (reference:
profiler/src/spark_rapids_profile_converter.cpp:1-1356 — the tool that
turns the profiler's binary activity stream into analyst-facing
artifacts).

Input: one or more files containing the DataWriter stream of
length-prefixed JSON records emitted by utils/profiler.py.  Outputs:

  * Chrome trace-event JSON (``--chrome out.json``): op ranges as
    complete ("X") events on their thread track, alloc/free as a
    running counter track — loadable in chrome://tracing / Perfetto,
    the role nsys-ui plays for the reference's converted traces.
  * A per-op summary table (``--summary``): calls, total/avg/max ns —
    the converter's text report mode.

Usage:
    python -m spark_rapids_tpu.tools.profile_converter prof.bin \
        --chrome trace.json --summary
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, Iterable, List

from spark_rapids_tpu.utils.profiler import iter_records


def _iter_jsonl(blob: bytes):
    for line in blob.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue  # a journal dump may be torn mid-write
        if isinstance(rec, dict):
            yield rec


def _looks_like_jsonl(blob: bytes) -> bool:
    """A journal dump's first line is a complete JSON object; a
    DataWriter stream's first 'line' starts with a binary length prefix
    (which can itself look like '{' — 123 == 0x7b — so sniffing a byte
    is not enough) and never parses."""
    first = blob.split(b"\n", 1)[0].strip()
    if not first:
        return False
    try:
        return isinstance(json.loads(first), dict)
    except (json.JSONDecodeError, UnicodeDecodeError):
        return False


def load_records(paths: Iterable[str]) -> List[dict]:
    """Load profiler DataWriter streams AND observability journal JSONL
    dumps (spark_rapids_tpu.observability.dump_journal_jsonl) onto one
    timeline.  Format is sniffed per file by parsing the first line.
    Unknown record kinds pass through — downstream renderers skip or
    mark them instead of raising."""
    records: List[dict] = []
    for p in paths:
        with open(p, "rb") as f:
            blob = f.read()
        if _looks_like_jsonl(blob):
            records.extend(_iter_jsonl(blob))
        else:
            records.extend(iter_records(blob))
    records.sort(key=lambda r: r.get("t_ns", 0))
    return records


def to_chrome_trace(records: List[dict]) -> dict:
    """Chrome trace-event format (catapult spec): op_range -> "X"
    complete events; alloc/free -> a memory counter track."""
    events = []
    mem = 0
    for r in records:
        kind = r.get("kind")
        ts_us = r.get("t_ns", 0) / 1000.0
        if kind == "op_range":
            dur_us = r.get("dur_ns", 0) / 1000.0
            events.append({
                "name": r.get("name", "?"), "ph": "X", "cat": "op",
                "ts": ts_us - dur_us, "dur": dur_us,
                "pid": 1, "tid": r.get("thread", 0),
            })
        elif kind in ("alloc", "free"):
            mem += r.get("bytes", 0) * (1 if kind == "alloc" else -1)
            events.append({
                "name": "device_memory", "ph": "C", "ts": ts_us,
                "pid": 1, "args": {"bytes": mem},
            })
        elif kind in ("profiler_start", "profiler_stop"):
            events.append({
                "name": kind, "ph": "i", "ts": ts_us, "pid": 1,
                "tid": 0, "s": "g",
            })
        elif kind in ("task_rollup", "registry_snapshot"):
            pass  # journal-dump summary records: no timeline point
        elif "t_ns" in r:
            # journal events (oom_retry, shuffle_write, exchange
            # doublings, future kinds): instant events on the emitting
            # thread's track
            events.append({
                "name": kind or "?", "ph": "i", "ts": ts_us, "pid": 1,
                "tid": r.get("thread", 0), "s": "t",
                "args": {k: v for k, v in r.items()
                         if k not in ("kind", "t_ns", "thread")},
            })
    return {"traceEvents": events, "displayTimeUnit": "ns"}


def summarize(records: List[dict]) -> List[dict]:
    """Per-op aggregate rows, busiest first."""
    agg: Dict[str, dict] = {}
    for r in records:
        if r.get("kind") != "op_range":
            continue
        a = agg.setdefault(r.get("name", "?"),
                           {"calls": 0, "total_ns": 0, "max_ns": 0})
        d = r.get("dur_ns", 0)
        a["calls"] += 1
        a["total_ns"] += d
        a["max_ns"] = max(a["max_ns"], d)
    rows = [{"op": k, **v,
             "avg_ns": v["total_ns"] // max(v["calls"], 1)}
            for k, v in agg.items()]
    rows.sort(key=lambda r: -r["total_ns"])
    return rows


def alloc_stats(records: List[dict]) -> dict:
    cur = peak = total_allocs = 0
    for r in records:
        if r.get("kind") == "alloc":
            cur += r.get("bytes", 0)
            peak = max(peak, cur)
            total_allocs += 1
        elif r.get("kind") == "free":
            cur -= r.get("bytes", 0)
    return {"allocs": total_allocs, "peak_bytes": peak,
            "leaked_bytes": cur}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Convert spark_rapids_tpu profiler streams")
    ap.add_argument("inputs", nargs="+", help="profiler stream files")
    ap.add_argument("--chrome", metavar="OUT.json",
                    help="write Chrome trace-event JSON")
    ap.add_argument("--summary", action="store_true",
                    help="print per-op summary table")
    args = ap.parse_args(argv)

    records = load_records(args.inputs)
    if args.chrome:
        with open(args.chrome, "w") as f:
            json.dump(to_chrome_trace(records), f)
        print(f"wrote {args.chrome} ({len(records)} records)")
    if args.summary or not args.chrome:
        rows = summarize(records)
        if rows:
            w = max(len(r["op"]) for r in rows)
            print(f"{'op':<{w}}  calls  total_ms  avg_us  max_us")
            for r in rows:
                print(f"{r['op']:<{w}}  {r['calls']:>5}  "
                      f"{r['total_ns'] / 1e6:>8.3f}  "
                      f"{r['avg_ns'] / 1e3:>6.1f}  "
                      f"{r['max_ns'] / 1e3:>6.1f}")
        a = alloc_stats(records)
        if a["allocs"]:
            print(f"allocs: {a['allocs']}  peak: {a['peak_bytes']}B  "
                  f"leaked: {a['leaked_bytes']}B")
        known = {"op_range", "alloc", "free", "profiler_start",
                 "profiler_stop", "task_rollup", "registry_snapshot"}
        other: Dict[str, int] = {}
        for r in records:
            k = r.get("kind", "?")
            if k not in known:
                other[k] = other.get(k, 0) + 1
        if other:
            print("journal events: " + "  ".join(
                f"{k}={n}" for k, n in sorted(other.items())))
    return 0


if __name__ == "__main__":
    sys.exit(main())
