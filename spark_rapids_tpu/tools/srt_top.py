"""srt-top: live fleet telemetry dashboard over the windowed
timeseries plane (ISSUE 16 tentpole, subsystem 3 of 3).

Renders two tables from merged per-rank windowed snapshots:

  * tenants — inflight/queue depth, RECENT p50/p99 queue wait
    (windowed histogram deltas, never the since-boot cumulative),
    completion + retry rates, device bytes, SLO burn/attainment;
  * fleet ranks — link bytes/s, observed peer deaths, membership
    epoch, window lag.

Input tiers (first match wins):

  * explicit files — any mix of per-rank ``timeseries_rank*.json``
    snapshots and/or a pre-merged ``fleet_timeseries.json``;
  * ``--dump-dir DIR`` — poll a launcher outdir for those same files
    (the no-socket tier: workers dump, srt-top merges offline).

Live mode refreshes every ``--interval`` seconds by re-reading the
inputs; ``--once`` prints one frame and exits; ``--once --json``
emits a sorted-keys machine-readable frame with NO wall-clock
content, so back-to-back runs over the same inputs are byte-identical
(the CI digest gate).

Usage:
    python -m spark_rapids_tpu.tools.srt_top --dump-dir /tmp/out
    python -m spark_rapids_tpu.tools.srt_top out/timeseries_rank*.json \
        --once --json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from typing import Dict, List, Optional

from spark_rapids_tpu.observability.timeseries import (
    FleetTimeseries, histogram_quantile)

_QUEUE_WAIT = "srt_server_queue_wait_ns"
_COMPLETED = "srt_server_completed_total"
_REQUEUED = "srt_server_requeued_total"
_QUEUED = "srt_server_queued"
_RUNNING = "srt_server_running"
_TENANT_BYTES = "srt_server_tenant_device_bytes"
_LINK_BYTES = "srt_shuffle_link_bytes_total"
_DEATHS = "srt_fleet_deaths_total"
_EPOCH = "srt_fleet_epoch"
_SPECULATIONS = "srt_fleet_speculations_total"
_RETRIES = "srt_retry_episodes_total"
_ATTR_TIME = "srt_attribution_ns_total"
_STATS_ROWS = "srt_stats_rows_total"
_RC_HITS = "srt_result_cache_hits_total"
_RC_MISSES = "srt_result_cache_misses_total"


# ------------------------------------------------------------- loading


def discover_inputs(dump_dir: str) -> List[str]:
    """The dump-dir polling tier: per-rank snapshots plus the merged
    rank-0 view when present (offering both is fine — the merger
    dedups by window sequence)."""
    paths = sorted(glob.glob(
        os.path.join(dump_dir, "timeseries_rank*.json")))
    fleet = os.path.join(dump_dir, "fleet_timeseries.json")
    if os.path.isfile(fleet):
        paths.append(fleet)
    return paths


def load_fleet(paths: List[str]) -> FleetTimeseries:
    """Merge every input into one FleetTimeseries: per-rank snapshot
    files are offered directly; a pre-merged ``fleet_timeseries.json``
    is decomposed back into per-rank offers (same dedup/fencing
    rules either way)."""
    fleet = FleetTimeseries()
    for path in paths:
        try:
            with open(path) as f:
                obj = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"{path}: skipping unreadable input ({e})",
                  file=sys.stderr)
            continue
        if "ranks" in obj:  # a merged fleet view
            for rank, st in obj.get("ranks", {}).items():
                fleet.offer({"rank": int(rank),
                             "epoch": st.get("epoch", 0),
                             "windows": st.get("windows", []),
                             **st.get("meta", {})})
        else:               # one rank's own snapshot
            fleet.offer(obj)
    return fleet


# ----------------------------------------------------------- analysis


def _fold_windows(windows: List[dict], n: Optional[int]):
    """Counter totals + elapsed seconds + last-gauge values + summed
    histogram deltas over the last ``n`` windows of one rank."""
    ws = windows if n is None else windows[-n:]
    counters: Dict[str, Dict[str, float]] = {}
    gauges: Dict[str, Dict[str, float]] = {}
    hists: Dict[str, dict] = {}
    dur = 0.0
    for w in ws:
        dur += w.get("dur_s", 0.0)
        for fam, vals in w.get("counters", {}).items():
            tgt = counters.setdefault(fam, {})
            for k, v in vals.items():
                tgt[k] = tgt.get(k, 0) + v
        for fam, vals in w.get("gauges", {}).items():
            gauges.setdefault(fam, {}).update(vals)
        for fam, h in w.get("histograms", {}).items():
            tgt = hists.setdefault(
                fam, {"buckets": h["buckets"], "series": {}})
            for key, s in h["series"].items():
                acc = tgt["series"].setdefault(
                    key, {"bucket_counts":
                          [0] * len(s["bucket_counts"]),
                          "sum": 0, "count": 0})
                for i, c in enumerate(s["bucket_counts"]):
                    acc["bucket_counts"][i] += c
                acc["sum"] += s["sum"]
                acc["count"] += s["count"]
    return counters, gauges, hists, dur


def build_frame(fleet: FleetTimeseries, windows: int = 12) -> dict:
    """One dashboard frame: the tenant and rank tables as plain data.
    Purely input-derived (no clocks) — the --json golden leans on
    this."""
    merged = fleet.merged()
    tenants: Dict[str, dict] = {}
    ranks: Dict[str, dict] = {}
    for rank, st in merged["ranks"].items():
        counters, gauges, hists, dur = _fold_windows(
            st["windows"], windows)
        dur = max(dur, 1e-9)
        link = sum((counters.get(_LINK_BYTES) or {}).values())
        deaths = sum((counters.get(_DEATHS) or {}).values())
        spec = sum((counters.get(_SPECULATIONS) or {}).values())
        retry = sum((counters.get(_RETRIES) or {}).values())
        ranks[rank] = {
            "epoch": st["epoch"],
            "last_window": st["last_window"],
            "windows": len(st["windows"]),
            "link_bytes_s": round(link / dur, 1),
            "deaths": deaths,
            "speculations": spec,
            "fleet_epoch_gauge": (gauges.get(_EPOCH) or {}).get(""),
        }
        qw = hists.get(_QUEUE_WAIT)
        slo = st["meta"].get("slo") or {}
        tenant_names = set()
        for fam in (_COMPLETED, _QUEUED, _RUNNING, _TENANT_BYTES):
            for key in (counters.get(fam) or {}):
                tenant_names.add(key.split("|")[0])
            for key in (gauges.get(fam) or {}):
                tenant_names.add(key.split("|")[0])
        if qw:
            tenant_names.update(k.split("|")[0]
                                for k in qw["series"])
        attr = counters.get(_ATTR_TIME) or {}
        tenant_names.update(k.split("|")[0] for k in attr)
        tenant_names.update(counters.get(_STATS_ROWS) or {})
        # result-cache label order is (scope, tenant)
        for fam in (_RC_HITS, _RC_MISSES):
            for key in (counters.get(fam) or {}):
                parts = key.split("|")
                if len(parts) > 1 and parts[1] not in ("", "-"):
                    tenant_names.add(parts[1])
        tenant_names.update(slo)
        for t in tenant_names:
            row = tenants.setdefault(t, {
                "queued": 0, "running": 0, "device_bytes": 0,
                "completed_s": 0.0, "requeued_s": 0.0,
                "retry_s": 0.0, "rows_s": 0.0,
                "cache_hit_ratio": None, "recent_p50_ms": None,
                "recent_p99_ms": None, "recent_events": 0,
                "slo": None, "where": {}, "where_dominant": None,
                "_rc_hits": 0, "_rc_misses": 0})
            row["queued"] += int(
                (gauges.get(_QUEUED) or {}).get(t, 0))
            row["running"] += int(
                (gauges.get(_RUNNING) or {}).get(t, 0))
            row["device_bytes"] += int(
                (gauges.get(_TENANT_BYTES) or {}).get(t, 0))
            comp = sum(v for k, v in
                       (counters.get(_COMPLETED) or {}).items()
                       if k.split("|")[0] == t)
            row["completed_s"] = round(
                row["completed_s"] + comp / dur, 3)
            req = sum(v for k, v in
                      (counters.get(_REQUEUED) or {}).items()
                      if k.split("|")[0] == t)
            row["requeued_s"] = round(
                row["requeued_s"] + req / dur, 3)
            row["retry_s"] = round(row["retry_s"] + retry / dur, 3)
            # data-plane satellites (ISSUE 20): rows/s delivered +
            # result-cache hit ratio — both already in the registry,
            # now rendered
            rows = (counters.get(_STATS_ROWS) or {}).get(t, 0)
            row["rows_s"] = round(row["rows_s"] + rows / dur, 1)
            for fam, slot in ((_RC_HITS, "_rc_hits"),
                              (_RC_MISSES, "_rc_misses")):
                row[slot] += sum(
                    v for k, v in (counters.get(fam) or {}).items()
                    if len(k.split("|")) > 1
                    and k.split("|")[1] == t)
            if qw and t in qw["series"]:
                s = qw["series"][t]
                bc = s["bucket_counts"]
                row["recent_events"] += s["count"]
                row["recent_p50_ms"] = round(histogram_quantile(
                    qw["buckets"], bc, 0.50) / 1e6, 3)
                row["recent_p99_ms"] = round(histogram_quantile(
                    qw["buckets"], bc, 0.99) / 1e6, 3)
            for key, v in attr.items():
                parts = key.split("|")
                if parts[0] != t or len(parts) < 2:
                    continue
                bucket = parts[1]
                row["where"][bucket] = int(
                    row["where"].get(bucket, 0) + v)
            if row["where"]:
                row["where_dominant"] = max(row["where"],
                                            key=row["where"].get)
            if t in slo:
                row["slo"] = slo[t]
    for row in tenants.values():
        hits, misses = row.pop("_rc_hits"), row.pop("_rc_misses")
        if hits + misses > 0:
            row["cache_hit_ratio"] = round(hits / (hits + misses), 4)
    return {"epoch": merged["epoch"],
            "ranks": {k: ranks[k] for k in sorted(ranks)},
            "tenants": {k: tenants[k] for k in sorted(tenants)}}


# ---------------------------------------------------------- rendering


def render_frame(frame: dict) -> List[str]:
    out = [f"fleet epoch {frame['epoch']}  "
           f"ranks {len(frame['ranks'])}  "
           f"tenants {len(frame['tenants'])}", ""]
    tenants = frame["tenants"]
    out.append("tenants (recent percentiles from windowed buckets)")
    hdr = (f"{'tenant':<12}  {'run':>3}  {'qd':>3}  {'p50_ms':>8}  "
           f"{'p99_ms':>8}  {'cmpl/s':>7}  {'rows/s':>8}  "
           f"{'hit%':>5}  {'rq/s':>5}  "
           f"{'dev_MB':>7}  {'burn_f':>6}  {'burn_s':>6}  "
           f"{'attain':>6}  {'where':<15}")
    out.append(hdr)
    out.append("-" * len(hdr))
    if not tenants:
        out.append("(no tenant activity in the window)")
    for t, r in tenants.items():
        slo = r.get("slo") or {}

        def _n(v, fmt="{:.3f}"):
            return "-" if v is None else fmt.format(v)

        def _hit(v):
            return "-" if v is None else f"{100.0 * v:.1f}"

        out.append(
            f"{t[:12]:<12}  {r['running']:>3}  {r['queued']:>3}  "
            f"{_n(r['recent_p50_ms']):>8}  "
            f"{_n(r['recent_p99_ms']):>8}  "
            f"{r['completed_s']:>7.2f}  {r['rows_s']:>8.1f}  "
            f"{_hit(r.get('cache_hit_ratio')):>5}  "
            f"{r['requeued_s']:>5.2f}  "
            f"{r['device_bytes'] / 1e6:>7.1f}  "
            f"{_n(slo.get('burn_fast'), '{:.2f}'):>6}  "
            f"{_n(slo.get('burn_slow'), '{:.2f}'):>6}  "
            f"{_n(slo.get('attainment'), '{:.4f}'):>6}  "
            f"{(r.get('where_dominant') or '-')[:15]:<15}")
    out.append("")
    out.append("fleet ranks")
    hdr = (f"{'rank':>4}  {'epoch':>5}  {'windows':>7}  "
           f"{'link_B/s':>10}  {'deaths':>6}  {'spec':>5}")
    out.append(hdr)
    out.append("-" * len(hdr))
    for rank, r in frame["ranks"].items():
        out.append(f"{rank:>4}  {r['epoch']:>5}  {r['windows']:>7}  "
                   f"{r['link_bytes_s']:>10.1f}  {r['deaths']:>6}  "
                   f"{r['speculations']:>5}")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="srt-top",
        description="live fleet telemetry dashboard over windowed "
                    "timeseries snapshots")
    ap.add_argument("inputs", nargs="*",
                    help="timeseries_rank*.json and/or "
                         "fleet_timeseries.json files")
    ap.add_argument("--dump-dir", default=None,
                    help="poll a launcher outdir for snapshot files")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable frame (sorted keys, no "
                         "wall-clock content: byte-stable over "
                         "identical inputs)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="live-mode refresh seconds (default 2)")
    ap.add_argument("--windows", type=int, default=12,
                    help="recent windows folded per frame "
                         "(default 12)")
    args = ap.parse_args(argv)
    if not args.inputs and not args.dump_dir:
        ap.error("give snapshot files or --dump-dir")

    def frame_once() -> dict:
        paths = list(args.inputs)
        if args.dump_dir:
            paths += discover_inputs(args.dump_dir)
        if not paths:
            print(f"(no snapshot files in {args.dump_dir} yet)",
                  file=sys.stderr)
        return build_frame(load_fleet(paths), windows=args.windows)

    if args.once:
        frame = frame_once()
        if args.json:
            print(json.dumps(frame, sort_keys=True, indent=1))
        else:
            print("\n".join(render_frame(frame)))
        return 0
    try:
        while True:
            frame = frame_once()
            sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
            print("\n".join(render_frame(frame)))
            sys.stdout.flush()
            time.sleep(max(args.interval, 0.2))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
