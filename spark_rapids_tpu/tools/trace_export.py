"""Span -> Perfetto/Chrome trace-event exporter (the analyst-facing
half of the tracing story; the reference's counterpart is nsys-ui /
TensorBoard over the converted CUPTI stream).

Input: one or more JSONL files of span records — either pure span dumps
(``observability.dump_spans_jsonl`` / the shim's ``tracing_dump``) or
full journal dumps (``dump_journal_jsonl``; only ``kind == "span"``
records are used, everything else passes through as instant events).
Each FILE is treated as one process: files from different executors
merge onto one timeline keyed by trace_id, which is how a distributed
query's spans (query root on the driver, op spans on executors, merge
spans re-parented through the kudo trace extension) land in one
Perfetto view.

Output: Chrome trace-event JSON (the catapult format Perfetto and
chrome://tracing load):

  * spans            -> "X" complete events (pid = input file ordinal,
                        tid = emitting thread), args carry
                        trace/span/parent ids, task attribution, attrs;
  * span links       -> flow events ("s" at the linked span's end,
                        "f" at the linking span's start) — the shuffle
                        write->merge causality renders as arrows;
  * non-span journal -> "i" instant events on their thread track.

Timestamps are per-process monotonic clocks; cross-process alignment is
best-effort (the trace groups by pid, so skew shows as offset tracks,
never as wrong nesting).

Usage:
    python -m spark_rapids_tpu.tools.trace_export spans.jsonl \
        [more.jsonl ...] -o trace.json [--stats]
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, Iterable, List, Optional, Tuple


def load_files(paths: Iterable[str]) -> List[Tuple[str, List[dict]]]:
    """[(path, records)] — one entry per input file (= per process).
    A flight-recorder incident bundle directory stands in for its
    spans.jsonl, so existing viewers load frozen incidents as-is."""
    from spark_rapids_tpu.tools import expand_bundle_input, read_jsonl

    out = []
    for p0 in paths:
        for p in expand_bundle_input(p0, "spans"):
            out.append((p, read_jsonl(p)))
    return out


def spans_of(records: List[dict]) -> List[dict]:
    return [r for r in records if r.get("kind") == "span"
            and "span_id" in r]


# ------------------------------------------------------------ tree checks


def build_index(span_records: List[dict]) -> Dict[str, dict]:
    """span_id -> record across all processes (ids are 64-bit random,
    collision-free for any realistic trace)."""
    return {r["span_id"]: r for r in span_records}


def find_orphans(span_records: List[dict]) -> List[dict]:
    """Spans whose parent_id resolves to no known span — a broken tree
    (a root has parent_id None and is NOT an orphan)."""
    idx = build_index(span_records)
    return [r for r in span_records
            if r.get("parent_id") and r["parent_id"] not in idx]


def root_of(rec: dict, idx: Dict[str, dict],
            max_depth: int = 1000) -> Optional[dict]:
    """Walk parent links to the root span (None on a broken chain)."""
    seen = 0
    while rec.get("parent_id"):
        rec = idx.get(rec["parent_id"])
        if rec is None or seen > max_depth:
            return None
        seen += 1
    return rec


def fusion_counts(files: List[Tuple[str, List[dict]]]
                  ) -> Dict[str, Dict[str, int]]:
    """Per-stage fused/unfused/compile dispatch counts read back from
    ``srt_stage_fusion_total`` in any ``registry_snapshot`` record in
    the inputs (journal dumps carry one; pure span dumps don't) —
    a trace alone then shows whether fusion engaged.  Multiple input
    files (one per process) sum."""
    out: Dict[str, Dict[str, int]] = {}
    for _path, records in files:
        for r in records:
            if r.get("kind") != "registry_snapshot":
                continue
            fam = (r.get("registry") or {}).get(
                "srt_stage_fusion_total") or {}
            for s in fam.get("series", []):
                labels = s.get("labels") or ()
                stage = labels[0] if len(labels) > 0 else "?"
                outcome = labels[1] if len(labels) > 1 else "?"
                row = out.setdefault(stage, {})
                row[outcome] = row.get(outcome, 0) \
                    + int(s.get("value", 0))
    return out


def trace_summary(span_records: List[dict]) -> Dict[str, dict]:
    """Per-trace_id rollup: span counts by kind, root names, orphan
    count — the --stats view and the smoke gate's assertion surface."""
    idx = build_index(span_records)
    out: Dict[str, dict] = {}
    for r in span_records:
        t = out.setdefault(r.get("trace_id", "?"), {
            "spans": 0, "by_kind": {}, "roots": [], "orphans": 0})
        t["spans"] += 1
        k = r.get("span_kind", "?")
        t["by_kind"][k] = t["by_kind"].get(k, 0) + 1
        if not r.get("parent_id"):
            t["roots"].append(r.get("name", "?"))
        elif r["parent_id"] not in idx:
            t["orphans"] += 1
    return out


# ---------------------------------------------------------------- export


def to_chrome_trace(files: List[Tuple[str, List[dict]]]) -> dict:
    """Merge per-process record files into one Chrome trace-event JSON
    (loadable in Perfetto / chrome://tracing)."""
    events: List[dict] = []
    all_spans: List[dict] = []
    span_pid: Dict[str, int] = {}
    for pid0, (path, records) in enumerate(files):
        pid = pid0 + 1
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": path}})
        for r in records:
            if r.get("kind") == "span" and "span_id" in r:
                all_spans.append(r)
                span_pid[r["span_id"]] = pid
                args = {"trace_id": r.get("trace_id"),
                        "span_id": r.get("span_id"),
                        "parent_id": r.get("parent_id")}
                if "task" in r:
                    args["task"] = r["task"]
                if r.get("attrs"):
                    args.update(r["attrs"])
                events.append({
                    "name": r.get("name", "?"), "ph": "X",
                    "cat": r.get("span_kind", "span"),
                    "ts": r.get("t_ns", 0) / 1000.0,
                    "dur": max(r.get("dur_ns", 0) / 1000.0, 0.001),
                    "pid": pid, "tid": r.get("thread", 0),
                    "args": args,
                })
            elif "t_ns" in r and r.get("kind") not in (
                    "task_rollup", "registry_snapshot"):
                events.append({
                    "name": r.get("kind", "?"), "ph": "i",
                    "ts": r["t_ns"] / 1000.0, "pid": pid,
                    "tid": r.get("thread", 0), "s": "t",
                    "args": {k: v for k, v in r.items()
                             if k not in ("kind", "t_ns", "thread")},
                })
    # flow arrows for span links (shuffle write -> merge causality);
    # only drawable when the linked span is present in some input file
    idx = build_index(all_spans)
    for r in all_spans:
        for link in r.get("links", ()):
            src = idx.get(link.get("span_id"))
            if src is None:
                continue
            # flow id unique per (source, target): Perfetto binds flows
            # by (cat, id), so two merges linking the SAME writer span
            # must not share an id (they would chain into one arrow)
            fid = f"{link['span_id']}:{r['span_id']}"
            events.append({
                "name": "span_link", "ph": "s", "cat": "link",
                "id": fid,
                "ts": (src.get("t_ns", 0) + src.get("dur_ns", 0))
                / 1000.0,
                "pid": span_pid[src["span_id"]],
                "tid": src.get("thread", 0),
            })
            events.append({
                "name": "span_link", "ph": "f", "cat": "link",
                "id": fid, "bp": "e",
                "ts": r.get("t_ns", 0) / 1000.0,
                "pid": span_pid[r["span_id"]],
                "tid": r.get("thread", 0),
            })
    return {"traceEvents": events, "displayTimeUnit": "ns"}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Merge span JSONL dumps into a Perfetto-loadable "
                    "Chrome trace (one input file per process)")
    ap.add_argument("inputs", nargs="+", help="span/journal JSONL files")
    ap.add_argument("-o", "--output", metavar="TRACE.json",
                    help="write Chrome trace-event JSON here")
    ap.add_argument("--stats", action="store_true",
                    help="print per-trace span/tree summary")
    args = ap.parse_args(argv)

    files = load_files(args.inputs)
    all_spans = [r for _, recs in files for r in spans_of(recs)]
    if args.output:
        trace = to_chrome_trace(files)
        with open(args.output, "w") as f:
            json.dump(trace, f)
        print(f"wrote {args.output} ({len(trace['traceEvents'])} events, "
              f"{len(all_spans)} spans)")
    if args.stats or not args.output:
        summary = trace_summary(all_spans)
        if not summary:
            print("(no span records in input)")
        for tid_, t in sorted(summary.items(),
                              key=lambda kv: -kv[1]["spans"]):
            kinds = " ".join(f"{k}={n}"
                             for k, n in sorted(t["by_kind"].items()))
            roots = ",".join(t["roots"]) or "-"
            print(f"trace {tid_}: {t['spans']} spans  roots=[{roots}]  "
                  f"{kinds}  orphans={t['orphans']}")
        fusion = fusion_counts(files)
        if fusion:
            print("stage fusion (srt_stage_fusion_total):")
            for stage, row in sorted(fusion.items()):
                cells = "  ".join(f"{k}={row[k]}"
                                  for k in ("fused", "unfused",
                                            "compile") if k in row)
                print(f"  {stage}: {cells}")
        orphans = find_orphans(all_spans)
        if orphans:
            print(f"WARNING: {len(orphans)} orphan spans "
                  "(parent not in any input file)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
