"""``srt-doctor``: offline triage of flight-recorder incident bundles.

Loads a bundle written by ``observability/flight_recorder.py``,
cross-references its three evidence planes — spans (where time went),
journal (what happened), memory ledger (who holds what) — and prints a
ranked diagnosis, e.g.::

    1. [95] root cause: fault-injection rule match='exchange.step'
            (GpuRetryOOM) matches the exhausted section
    2. [90] task 7 exhausted retries in 'exchange.step' (attempts)
            after 4 failed attempts [GpuRetryOOM x4]
    3. [70] thread 3 (task 7, THREAD_BLOCKED) holds 1.2 GiB device
            memory (watermark 1.5 GiB)
    4. [60] stage 'exchange.step' p99 9.8x p50 over 42 tasks

Output is purely bundle-derived (no "now" stamps), so the same bundle
always prints the same diagnosis — the golden-output test in
tests/test_flight_recorder.py holds the CLI to that.

Usage:
    python -m spark_rapids_tpu.tools.doctor BUNDLE_DIR [--json]

``BUNDLE_DIR`` may also be the recorder's output directory (the one
holding ``incident-*`` subdirectories): the most recent complete
bundle is diagnosed and the rest are listed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from datetime import datetime, timezone
from typing import Dict, List, Optional

MANIFEST = "MANIFEST.json"

# how journal retry activity is judged a storm offline (mirrors the
# live RetryStormDetector defaults)
STORM_THRESHOLD = 10
STRAGGLER_RATIO = 5.0
STRAGGLER_MIN_SAMPLES = 8
# Monitor-thread sample age (s) at which the telemetry plane is
# declared stalled rather than idle
STALLED_SAMPLER_S = 15.0


def _fmt_bytes(n) -> str:
    n = int(n)
    for unit, width in (("GiB", 1 << 30), ("MiB", 1 << 20),
                        ("KiB", 1 << 10)):
        if n >= width:
            return f"{n / width:.1f} {unit}"
    return f"{n} B"


def _fmt_unix_ms(ms) -> str:
    return datetime.fromtimestamp(int(ms) / 1000.0, tz=timezone.utc) \
        .strftime("%Y-%m-%dT%H:%M:%SZ")


def _load_json(path: str, default):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return default


def _load_jsonl(path: str) -> List[dict]:
    out: List[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


class Bundle:
    """One loaded incident bundle; every file is optional (a partial
    bundle still gets a best-effort diagnosis)."""

    def __init__(self, path: str):
        self.path = path
        self.manifest = _load_json(os.path.join(path, MANIFEST), {})
        self.trigger = _load_json(os.path.join(path, "trigger.json"), {})
        self.metrics = _load_json(os.path.join(path, "metrics.json"), {})
        self.ledger = _load_json(
            os.path.join(path, "memory_ledger.json"), {})
        self.threads = _load_json(os.path.join(path, "threads.json"), {})
        self.fault_rules = _load_json(
            os.path.join(path, "fault_rules.json"), [])
        self.env = _load_json(os.path.join(path, "env.json"), {})
        records = _load_jsonl(os.path.join(path, "journal.jsonl"))
        self.journal = [r for r in records
                        if r.get("kind") not in ("task_rollup",
                                                 "registry_snapshot")]
        self.task_rollups = {r.get("task"): r for r in records
                             if r.get("kind") == "task_rollup"}
        self.spans = _load_jsonl(os.path.join(path, "spans.jsonl"))
        if not self.spans:  # fall back to span records in the journal
            self.spans = [r for r in self.journal
                          if r.get("kind") == "span"]
        # ISSUE 13: the most recent per-query EXPLAIN ANALYZE
        # artifact rides the bundle — the "slowest plan node"
        # evidence plane (absent in profiler-off processes)
        self.profile = _load_json(os.path.join(path, "profile.json"),
                                  {})
        # ISSUE 17: the last query's time-attribution ledger (absent
        # in attribution-off processes — findings built from it only
        # appear when the bundle carries it)
        self.attribution = _load_json(
            os.path.join(path, "attribution.json"), {})


def is_bundle_dir(path: str) -> bool:
    return os.path.isfile(os.path.join(path, MANIFEST)) \
        or os.path.isfile(os.path.join(path, "trigger.json"))


def find_bundles(root: str) -> List[str]:
    """Complete (manifest-bearing) bundle dirs under a recorder output
    directory, oldest first."""
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return []
    return [os.path.join(root, n) for n in names
            if not n.endswith(".tmp")  # half-written crash leftovers
            and os.path.isfile(os.path.join(root, n, MANIFEST))]


# ------------------------------------------------------------- analysis


def _retry_span_for(bundle: Bundle, name: str) -> Optional[dict]:
    for r in reversed(bundle.spans):
        if r.get("span_kind") == "retry" \
                and r.get("name") == f"retry_episode:{name}":
            return r
    return None


def _task_of_exhausted(bundle: Bundle, name: str):
    """Task attribution for an exhausted section: its retry span's
    task, else the task on the most recent OOM journal event, else the
    busiest task in the ledger."""
    span = _retry_span_for(bundle, name)
    if span is not None and span.get("task") is not None:
        return span["task"]
    for r in reversed(bundle.journal):
        if r.get("kind") in ("oom_retry", "oom_split_retry") \
                and r.get("task", -1) >= 0:
            return r["task"]
    tasks = bundle.ledger.get("tasks") or {}
    best = None
    for tid, row in tasks.items():
        if best is None or row.get("retry_oom", 0) > \
                tasks[best].get("retry_oom", 0):
            best = tid
    return best


def _err_counts(errors: List[str]) -> str:
    counts: Dict[str, int] = {}
    for e in errors:
        counts[e] = counts.get(e, 0) + 1
    return ", ".join(f"{e} x{n}" for e, n in sorted(counts.items()))


def analyze(bundle: Bundle) -> List[dict]:
    """Ranked findings (most severe first); each is
    {severity, kind, message}."""
    findings: List[dict] = []
    trig = bundle.trigger
    kind = trig.get("kind", "?")
    detail = trig.get("detail") or {}
    ledger_threads = bundle.ledger.get("threads") or {}

    # ---- the trigger itself, cross-referenced -----------------------
    if kind == "retry_exhausted":
        name = detail.get("name", "?")
        errors = [e for e in detail.get("errors", [])]
        task = _task_of_exhausted(bundle, name)
        task_txt = f"task {task}" if task is not None else "unknown task"
        msg = (f"{task_txt} exhausted retries in {name!r} "
               f"({detail.get('reason', '?')}) after "
               f"{len(errors) or detail.get('attempts', '?')} failed "
               f"attempts [{_err_counts(errors) or 'no history'}]")
        holders = [(tid, row) for tid, row in sorted(
            ledger_threads.items())
            if row.get("active_bytes", 0) > 0]
        if holders:
            tid, row = max(holders,
                           key=lambda kv: kv[1]["active_bytes"])
            msg += (f"; thread {tid} held "
                    f"{_fmt_bytes(row['active_bytes'])} at incident "
                    f"time")
        if detail.get("reason") == "split_floor":
            # ISSUE 18: the one-element split floor is a DIFFERENT
            # failure from a spent budget — the batch cannot shrink
            # further, so the fix is spilling / a bigger device, not
            # more retries
            msg += ("; SPLIT FLOOR: the batch is down to one element "
                    "and still does not fit — register the build side "
                    "with the spill store or raise the device budget")
        findings.append({"severity": 90, "kind": "retry_exhausted",
                         "message": msg})
        injected = [r for r in bundle.fault_rules
                    if r.get("match") in (name, "*")
                    or r.get("exception") in errors]
        for rule in injected:
            findings.append({
                "severity": 95, "kind": "fault_injection",
                "message": (f"root cause: fault-injection rule "
                            f"match={rule.get('match')!r} "
                            f"({rule.get('exception')}, "
                            f"remaining={rule.get('remaining')}) "
                            f"matches the exhausted section "
                            f"{name!r}")})
    elif kind == "memory_leak":
        findings.append({
            "severity": 88, "kind": "memory_leak",
            "message": (f"task {detail.get('task')} finished still "
                        f"holding "
                        f"{_fmt_bytes(detail.get('leaked_bytes', 0))} "
                        f"device memory")})
    elif kind == "kudo_corrupt":
        findings.append({
            "severity": 85, "kind": "kudo_corrupt",
            "message": (f"kudo stream corruption "
                        f"({detail.get('reason', '?')}): "
                        f"{detail.get('detail', '')}")})
    elif kind == "straggler":
        findings.append({
            "severity": 80, "kind": "straggler",
            "message": (f"stage {detail.get('stage')!r} task "
                        f"{detail.get('task')} ran "
                        f"{detail.get('dur_ns', 0) / 1e6:.1f} ms vs "
                        f"median "
                        f"{detail.get('median_ns', 0) / 1e6:.1f} ms "
                        f"(robust z {detail.get('robust_z')})")})
    elif kind == "retry_storm":
        findings.append({
            "severity": 80, "kind": "retry_storm",
            "message": (f"retry storm: "
                        f"{detail.get('episodes_in_window')} failed "
                        f"episodes in {detail.get('window_s')}s "
                        f"(sections: "
                        f"{', '.join(detail.get('recent_sections', []))}"
                        f")")})
    elif kind == "hbm_pressure":
        findings.append({
            "severity": 78, "kind": "hbm_pressure",
            "message": (f"device {detail.get('device')} HBM held "
                        f"{_fmt_bytes(detail.get('bytes_in_use', 0))} "
                        f">= threshold "
                        f"{_fmt_bytes(detail.get('threshold_bytes', 0))}"
                        f" for {detail.get('sustained_s')}s")})
    elif kind == "fleet_incident":
        dead = detail.get("dead", [])
        moved = detail.get("shards_moved") or {}
        heirs = sorted(set(moved.values()))
        findings.append({
            "severity": 84, "kind": "fleet_incident",
            "message": (f"fleet membership change on rank "
                        f"{detail.get('rank')}: dead rank(s) "
                        f"{dead} at epoch {detail.get('epoch')}; "
                        f"shard(s) {sorted(moved)} rebalanced to "
                        f"rank(s) {heirs}; live={detail.get('live')}"
                        )})
    elif kind == "query_hang":
        tenant = detail.get("tenant", "?")
        query = detail.get("query", "?")
        ident = detail.get("worker_ident")
        msg = (f"query server worker hung: tenant {tenant!r} query "
               f"{query!r} ({detail.get('query_id')}) silent "
               f"{detail.get('silent_ms', '?')} ms in op "
               f"{detail.get('last_op', '?')!r} "
               f"(worker thread {ident}, task "
               f"{detail.get('task_id')}, {detail.get('reason')})")
        findings.append({"severity": 92, "kind": "query_hang",
                         "message": msg})
        # where exactly it is stuck: the trigger's own stack capture,
        # else the bundle-wide python stack dump for that ident
        stack = detail.get("stack") or []
        if not stack and ident is not None:
            for t in (bundle.threads.get("python") or []):
                if t.get("ident") == ident:
                    stack = t.get("stack") or []
                    break
        if stack:
            findings.append({
                "severity": 74, "kind": "hung_stack",
                "message": ("hung worker's last frame: "
                            + str(stack[-1]).strip().splitlines()[0]
                            .strip())})
        q = detail.get("quarantine") or {}
        sig = detail.get("signature")
        if sig and q.get("quarantined"):
            findings.append({
                "severity": 88, "kind": "poison_query",
                "message": (f"poison query quarantined: signature "
                            f"{sig} after {q.get('strikes', '?')} "
                            f"death(s), retry after "
                            f"{q.get('retry_after_s', '?')}s")})
        elif sig:
            findings.append({
                "severity": 55, "kind": "poison_query",
                "message": (f"signature {sig} has "
                            f"{q.get('strikes', 0)} recent death(s) "
                            f"(quarantine not yet open)")})
    elif kind == "admission_stall":
        tenant = detail.get("tenant", "?")
        findings.append({
            "severity": 82, "kind": "admission_stall",
            "message": (f"query server admission stalled: tenant "
                        f"{tenant!r} query {detail.get('query_id')} "
                        f"waited {detail.get('queue_wait_ms', 0)} ms "
                        f"in queue (depth "
                        f"{detail.get('queue_depth', '?')})")})
        # name the tenant holding the device while others wait — the
        # per-tenant byte fold frozen at trigger time, else the ledger
        tenant_bytes = {str(t): int(b) for t, b in
                        (detail.get("tenant_device_bytes")
                         or {}).items() if int(b) > 0}
        if tenant_bytes:
            holder = max(tenant_bytes, key=lambda t: tenant_bytes[t])
            qualifier = "the stalled tenant itself" \
                if holder == tenant else f"while {tenant!r} waits"
            findings.append({
                "severity": 80, "kind": "tenant_memory",
                "message": (f"tenant {holder!r} holds "
                            f"{_fmt_bytes(tenant_bytes[holder])} "
                            f"device memory ({qualifier})")})
        else:
            held_tasks = [(tid, row) for tid, row in sorted(
                (bundle.ledger.get("tasks") or {}).items())
                if row.get("active_bytes", 0) > 0]
            if held_tasks:
                tid, row = max(held_tasks,
                               key=lambda kv: kv[1]["active_bytes"])
                findings.append({
                    "severity": 80, "kind": "tenant_memory",
                    "message": (f"task {tid} holds "
                                f"{_fmt_bytes(row['active_bytes'])} "
                                f"device memory while {tenant!r} "
                                f"admission stalls (no tenant map in "
                                f"bundle)")})
    elif kind == "lockdep_cycle":
        cycle = detail.get("cycle") or []
        findings.append({
            "severity": 86, "kind": "lockdep_cycle",
            "message": (f"lock-order cycle "
                        f"{' -> '.join(str(c) for c in cycle)} "
                        f"(ABBA deadlock potential — two threads "
                        f"taking these lock classes in opposite "
                        f"orders can wedge)")})
        fwd = (detail.get("evidence") or {}).get("forward") or {}
        stack = fwd.get("stack") or []
        # bundles are untrusted JSON off disk: a truncated/blank stack
        # entry must degrade to the top finding, not IndexError
        frame_lines = (str(stack[-1]).strip().splitlines()
                       if stack else [])
        if frame_lines:
            findings.append({
                "severity": 60, "kind": "lockdep_cycle",
                "message": ("reversing acquisition came from: "
                            + frame_lines[0].strip())})
    elif kind == "slo_burn":
        tenant = detail.get("tenant", "?")
        findings.append({
            "severity": 87, "kind": "slo_burn",
            "message": (f"tenant {tenant!r} is burning its error "
                        f"budget: burn {detail.get('burn_fast', '?')}x "
                        f"over the fast {detail.get('fast_window_s', '?')}s "
                        f"window and {detail.get('burn_slow', '?')}x "
                        f"over the slow "
                        f"{detail.get('slow_window_s', '?')}s window "
                        f"(threshold {detail.get('threshold', '?')}x; "
                        f"objective {detail.get('objective', '?')} at "
                        f"{detail.get('latency_target_ms', '?')} ms; "
                        f"attainment "
                        f"{detail.get('attainment', '?')})")})
        # the hot stage behind the burn: the profile frozen into this
        # bundle is the offending tenant's most recent EXPLAIN ANALYZE
        prof = bundle.profile or {}
        pstages = prof.get("stages") or []
        if pstages:
            hot = max(pstages, key=lambda s: int(s.get("wall_ns", 0)))
            findings.append({
                "severity": 70, "kind": "slo_hot_stage",
                "message": (f"hot stage behind the burn: "
                            f"{hot.get('stage')!r} "
                            f"[{hot.get('engine', '?')}] "
                            f"{int(hot.get('wall_ns', 0)) / 1e6:.1f} "
                            f"ms in query {prof.get('query_id')!r} "
                            f"(tenant {prof.get('tenant') or '?'})")})
        tail = detail.get("timeseries_tail") or []
        if tail:
            findings.append({
                "severity": 30, "kind": "slo_burn",
                "message": (f"ring tail frozen: {len(tail)} recent "
                            f"window(s) of telemetry in trigger.json "
                            f"(last window seq "
                            f"{tail[-1].get('window', '?')})")})
    elif kind == "cardinality_misestimate":
        node = detail.get("node", "?")
        stage = detail.get("stage", "?")
        findings.append({
            "severity": 55, "kind": "cardinality_misestimate",
            "message": (f"cardinality misestimate at node {node!r} of "
                        f"stage {stage!r}: estimated "
                        f"{detail.get('est', '?')} rows, observed "
                        f"{detail.get('actual', '?')} "
                        f"(x{detail.get('ratio', '?')} off; threshold "
                        f"SPARK_RAPIDS_TPU_STATS_MISEST_RATIO) — "
                        f"refresh the estimate source or re-plan: a "
                        f"cost-based choice keyed on this estimate is "
                        f"operating on wrong data")})
        ss = detail.get("stage_stats") or {}
        nodes = [n for n in (ss.get("nodes") or ())
                 if n.get("est") is not None]
        if nodes:
            split = ", ".join(
                f"{n['node']} est={n['est']} actual={n.get('rows')}"
                for n in nodes[:6])
            findings.append({
                "severity": 25, "kind": "cardinality_misestimate",
                "message": (f"stage {stage!r} est-vs-actual at "
                            f"trigger time: {split}")})
    elif kind == "manual":
        findings.append({
            "severity": 10, "kind": "manual",
            "message": (f"manual dump "
                        f"({detail.get('reason', 'no reason given')}) "
                        f"— no failure trigger")})

    # ---- time attribution (ISSUE 17) --------------------------------
    # on the latency-shaped triggers, name the dominant wall-clock
    # bucket of the last profiled query: "where the time went" is the
    # first question an operator asks a slo_burn/query_hang bundle
    if kind in ("slo_burn", "query_hang", "admission_stall") \
            and bundle.attribution:
        led = bundle.attribution
        buckets = {b: int(v) for b, v in
                   (led.get("buckets") or {}).items() if int(v) > 0}
        dom = led.get("dominant")
        if dom and buckets:
            wall = max(int(led.get("wall_ns", 0)), 1)
            top = sorted(buckets.items(), key=lambda kv: -kv[1])[:3]
            split = ", ".join(
                f"{b} {v / 1e6:.1f} ms ({100 * v / wall:.0f}%)"
                for b, v in top)
            msg = (f"where the wall went (query "
                   f"{led.get('query_id', '?')!r}, tenant "
                   f"{led.get('tenant', '?')!r}): dominant bucket "
                   f"{dom} — {split}")
            if not led.get("conserved", True):
                msg += (f"; CONSERVATION BROKEN (overcount "
                        f"{int(led.get('overcount_ns', 0)) / 1e6:.1f}"
                        f" ms) — bucket seams double-counted")
            findings.append({"severity": 71, "kind": "attribution",
                             "message": msg})

    # ---- memory-leak journal history --------------------------------
    for r in bundle.journal:
        if r.get("kind") == "memory_leak" and kind != "memory_leak":
            findings.append({
                "severity": 85, "kind": "memory_leak",
                "message": (f"task {r.get('task')} finished still "
                            f"holding "
                            f"{_fmt_bytes(r.get('leaked_bytes', 0))} "
                            f"device memory")})

    # ---- lifeguard journal history ----------------------------------
    opened = [r for r in bundle.journal
              if r.get("kind") == "server_quarantine"
              and r.get("event") in ("opened", "reopened")]
    if opened and kind != "query_hang":
        last = opened[-1]
        findings.append({
            "severity": 72, "kind": "poison_query",
            "message": (f"poison query quarantined earlier: signature "
                        f"{last.get('signature')} "
                        f"({last.get('reason', '?')} x"
                        f"{last.get('strikes', '?')})")})
    watchdog = [r for r in bundle.journal
                if r.get("kind") == "server_watchdog"]
    hangs = [r for r in watchdog if r.get("action") == "hang_release"]
    if hangs and kind != "query_hang":
        last = hangs[-1]
        findings.append({
            "severity": 70, "kind": "query_hang",
            "message": (f"{len(hangs)} hung worker(s) released by the "
                        f"lifeguard (last: tenant "
                        f"{last.get('tenant')!r} query "
                        f"{last.get('query')!r} silent "
                        f"{last.get('silent_ms', '?')} ms)")})

    # ---- blocked threads + held memory from the ledger --------------
    for tid, row in sorted(ledger_threads.items()):
        if row.get("state") in ("THREAD_BLOCKED", "THREAD_BUFN"):
            task = row.get("task")
            findings.append({
                "severity": 75, "kind": "blocked_thread",
                "message": (f"thread {tid} (task {task}) is "
                            f"{row['state']} in the OOM state machine "
                            f"holding "
                            f"{_fmt_bytes(row.get('active_bytes', 0))}"
                            )})
    held = [(tid, row) for tid, row in sorted(ledger_threads.items())
            if row.get("active_bytes", 0) > 0
            and row.get("state") not in ("THREAD_BLOCKED",
                                         "THREAD_BUFN")]
    for tid, row in sorted(held, key=lambda kv:
                           -kv[1]["active_bytes"])[:4]:
        findings.append({
            "severity": 70, "kind": "held_memory",
            "message": (f"thread {tid} (task {row.get('task')}, "
                        f"{row.get('state')}) holds "
                        f"{_fmt_bytes(row['active_bytes'])} device "
                        f"memory (watermark "
                        f"{_fmt_bytes(row.get('watermark_bytes', 0))}, "
                        f"{row.get('allocs', 0)} allocs / "
                        f"{row.get('frees', 0)} frees)")})

    # ---- lockdep journal history ------------------------------------
    ld_cycles = [r for r in bundle.journal
                 if r.get("kind") == "lockdep"
                 and r.get("event") == "cycle"]
    if ld_cycles and kind != "lockdep_cycle":
        last = ld_cycles[-1]
        path = " -> ".join(str(c) for c in (last.get("cycle") or []))
        findings.append({
            "severity": 76, "kind": "lockdep_cycle",
            "message": (f"{len(ld_cycles)} lock-order cycle(s) in the "
                        f"journal (last: {path}) — ABBA deadlock "
                        f"potential")})
    ld_blocking = [r for r in bundle.journal
                   if r.get("kind") == "lockdep"
                   and r.get("event") == "blocking"]
    if ld_blocking:
        ops: Dict[str, int] = {}
        for r in ld_blocking:
            ops[str(r.get("op", "?"))] = \
                ops.get(str(r.get("op", "?")), 0) + 1
        summary = ", ".join(f"{op} x{n}"
                            for op, n in sorted(ops.items()))
        held = sorted({str(h) for r in ld_blocking
                       for h in (r.get("held") or [])})
        findings.append({
            "severity": 55, "kind": "lockdep_blocking",
            "message": (f"{len(ld_blocking)} lock-held-across-"
                        f"blocking event(s) ({summary}; locks: "
                        f"{', '.join(held[:4])}) — contending "
                        f"threads stall behind I/O")})

    # ---- spill-store history (ISSUE 18) -----------------------------
    spills = [r for r in bundle.journal if r.get("kind") == "spill"]
    if spills:
        by_task: Dict[str, int] = {}
        tiers: Dict[str, int] = {}
        for r in spills:
            by_task[str(r.get("task"))] = \
                by_task.get(str(r.get("task")), 0) + \
                int(r.get("bytes", 0))
            tiers[str(r.get("tier", "?"))] = \
                tiers.get(str(r.get("tier", "?")), 0) + 1
        top_task, top_bytes = max(by_task.items(), key=lambda kv: kv[1])
        restores = sum(1 for r in bundle.journal
                       if r.get("kind") == "spill_restore")
        tier_s = ", ".join(f"{t} x{n}" for t, n in sorted(tiers.items()))
        findings.append({
            "severity": 60, "kind": "spill_pressure",
            "message": (f"{len(spills)} spill(s) through the tiered "
                        f"store ({tier_s}; {restores} restore(s)) — "
                        f"top spiller task {top_task} pushed "
                        f"{_fmt_bytes(top_bytes)} down-tier; the query "
                        f"ran THROUGH memory pressure (out-of-core), "
                        f"raise SPARK_RAPIDS_TPU_DEVICE_BUDGET_BYTES "
                        f"or add device memory to run in-core")})
    spill_corrupt = [r for r in bundle.journal
                     if r.get("kind") == "spill_corrupt"]
    if spill_corrupt:
        last = spill_corrupt[-1]
        findings.append({
            "severity": 78, "kind": "spill_corrupt",
            "message": (f"{len(spill_corrupt)} corrupt spill "
                        f"payload(s) on read-back (last: "
                        f"{last.get('path') or last.get('name', '?')} "
                        f"generation {last.get('generation', '?')}, "
                        f"outcome {last.get('outcome', '?')}) — "
                        f"recomputed from source when possible; check "
                        f"the spill volume for failing media")})

    # ---- kudo corruption history ------------------------------------
    corrupt = [r for r in bundle.journal
               if r.get("kind") == "kudo_corrupt"]
    if corrupt and kind != "kudo_corrupt":
        skipped = sum(r.get("skipped_bytes", 0) for r in corrupt)
        findings.append({
            "severity": 65, "kind": "kudo_corrupt",
            "message": (f"{len(corrupt)} kudo corruption event(s) in "
                        f"the journal ({_fmt_bytes(skipped)} resync-"
                        f"skipped)")})

    # ---- fleet journal history (dead / slow / hot) ------------------
    deaths = [r for r in bundle.journal
              if r.get("kind") == "fleet_membership"
              and r.get("change") == "death"]
    if deaths and kind != "fleet_incident":
        last = deaths[-1]
        findings.append({
            "severity": 72, "kind": "fleet_incident",
            "message": (f"{len(deaths)} fleet death event(s) in the "
                        f"journal — dead rank(s) "
                        f"{sorted({d for r in deaths for d in (r.get('dead') or [])})} "
                        f"(last at epoch {last.get('epoch')}, moved "
                        f"{last.get('moved') or {}})")})
    specs = [r for r in bundle.journal
             if r.get("kind") == "fleet_speculation"]
    if specs:
        by_owner: Dict[str, List[dict]] = {}
        for r in specs:
            by_owner.setdefault(str(r.get("owner")), []).append(r)
        slowest = max(by_owner.items(), key=lambda kv: len(kv[1]))
        won = sum(1 for r in specs if r.get("outcome") == "won")
        findings.append({
            "severity": 62, "kind": "fleet_straggler",
            "message": (f"slow rank {slowest[0]}: "
                        f"{len(slowest[1])} partition(s) "
                        f"speculatively re-executed ({won} "
                        f"speculation(s) won fleet-wide; evidence: "
                        f"{slowest[1][-1].get('evidence', {})})")})
    resplits = [r for r in bundle.journal
                if r.get("kind") == "fleet_resplit"]
    if resplits:
        last = resplits[-1]
        findings.append({
            "severity": 48, "kind": "fleet_skew",
            "message": (f"{len(resplits)} hot partition(s) re-split "
                        f"(last: op {last.get('op')} part "
                        f"{last.get('part')} -> {last.get('nsub')} "
                        f"sub-partitions, {last.get('bytes', 0)} "
                        f"bytes)")})

    # ---- stage stragglers from the span ring ------------------------
    stages: Dict[str, List[int]] = {}
    for r in bundle.spans:
        if r.get("span_kind") == "stage":
            stages.setdefault(r.get("name", "?"), []).append(
                int(r.get("dur_ns", 0)))
    for name, durs in sorted(stages.items()):
        if len(durs) < STRAGGLER_MIN_SAMPLES:
            continue
        xs = sorted(durs)
        p50 = xs[len(xs) // 2]
        p99 = xs[min(len(xs) - 1, int(len(xs) * 0.99))]
        if p50 > 0 and p99 / p50 >= STRAGGLER_RATIO:
            findings.append({
                "severity": 60, "kind": "straggler_stage",
                "message": (f"stage {name!r} p99 {p99 / p50:.1f}x p50 "
                            f"({p99 / 1e6:.1f} ms vs "
                            f"{p50 / 1e6:.1f} ms over {len(xs)} "
                            f"spans)")})

    # ---- slowest plan node from the frozen query profile ------------
    prof = bundle.profile or {}
    pstages = prof.get("stages") or []
    if pstages:
        hot = max(pstages, key=lambda s: int(s.get("wall_ns", 0)))
        wall = int(prof.get("wall_ns", 0))
        stage_ns = int(hot.get("wall_ns", 0))
        pct = (f" ({100 * stage_ns // wall}% of the "
               f"{wall / 1e6:.1f} ms query wall)" if wall else "")
        heavy = ""
        kinds = {}
        for n in hot.get("nodes") or ():
            k = str(n.get("kind", "?"))
            kinds[k] = kinds.get(k, 0) + 1
        if kinds:
            top = sorted(kinds.items(), key=lambda kv: -kv[1])[:3]
            heavy = ("; nodes: "
                     + ", ".join(f"{k} x{v}" for k, v in top))
        findings.append({
            "severity": 58, "kind": "slow_plan_node",
            "message": (f"slowest plan node: stage "
                        f"{hot.get('stage')!r} "
                        f"[{hot.get('engine', '?')}] "
                        f"{stage_ns / 1e6:.1f} ms{pct} in query "
                        f"{prof.get('query_id')!r} "
                        f"({prof.get('query') or '?'})"
                        f"{heavy}")})

    # ---- retry pressure short of the trigger ------------------------
    episodes = [r for r in bundle.journal
                if r.get("kind") == "retry_episode"]
    if len(episodes) >= STORM_THRESHOLD and kind != "retry_storm":
        sections = sorted({str(r.get("name", "?")) for r in episodes})
        findings.append({
            "severity": 50, "kind": "retry_pressure",
            "message": (f"{len(episodes)} failed retry episodes in "
                        f"the journal window (sections: "
                        f"{', '.join(sections[:6])})")})

    # ---- monitor sampler liveness -----------------------------------
    # srt_monitor_last_sample_age_s is recomputed at exposition time
    # (bundle freeze included), so a dead/stalled Monitor thread shows
    # a GROWING age here — stale gauges must not masquerade as a
    # healthy-but-idle system.  No series at all means no Monitor ran,
    # which is not itself a fault.
    reg = (bundle.metrics or {}).get("registry") or {}
    age_fam = reg.get("srt_monitor_last_sample_age_s") or {}
    for s in age_fam.get("series", []):
        age = float(s.get("value", 0.0))
        if age >= STALLED_SAMPLER_S:
            findings.append({
                "severity": 68, "kind": "stalled_sampler",
                "message": (f"telemetry sampler stalled: the Monitor "
                            f"thread last sampled {age:.1f}s before "
                            f"this freeze — every gauge and window "
                            f"after that is stale, not calm")})

    # ---- evidence-quality notes -------------------------------------
    jstats = (bundle.metrics or {}).get("journal") or {}
    if jstats.get("dropped", 0) > 0:
        findings.append({
            "severity": 15, "kind": "evidence",
            "message": (f"journal dropped {jstats['dropped']} events "
                        f"before the freeze — earliest history is "
                        f"incomplete")})

    findings.sort(key=lambda f: (-f["severity"], f["kind"],
                                 f["message"]))
    return findings


# -------------------------------------------------------------- render


def render(bundle: Bundle, findings: List[dict]) -> List[str]:
    out: List[str] = []
    trig = bundle.trigger
    out.append(f"srt-doctor: bundle {os.path.basename(bundle.path)}")
    t = trig.get("t_unix_ms")
    out.append(
        f"trigger : {trig.get('kind', '?')} "
        f"severity={trig.get('severity', '?')} "
        f"seq={trig.get('seq', '?')}"
        + (f" at {_fmt_unix_ms(t)}" if t else "")
        + (f" (pid {trig['pid']})" if trig.get("pid") else ""))
    chain = trig.get("cause_chain") or []
    for i, c in enumerate(chain):
        prefix = "cause   : " if i == 0 else "          <- "
        out.append(f"{prefix}{c.get('type')}: {c.get('message')}")
    files = bundle.manifest.get("files") or {}
    if files:
        out.append(f"files   : {len(files)} files, "
                   f"{bundle.manifest.get('total_bytes', 0)} bytes")
    out.append("")
    if not findings:
        out.append("diagnosis: nothing anomalous in this bundle")
        return out
    out.append("diagnosis (most severe first):")
    for i, f in enumerate(findings, 1):
        out.append(f"  {i}. [{f['severity']:>2}] {f['message']}")
    out.append("")
    out.append(f"summary: {findings[0]['message']}")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="srt-doctor",
        description="Diagnose a flight-recorder incident bundle")
    ap.add_argument("bundle",
                    help="incident bundle directory (or the recorder "
                         "output directory holding incident-* dirs)")
    ap.add_argument("--json", action="store_true",
                    help="emit machine-readable findings")
    args = ap.parse_args(argv)

    path = args.bundle
    if not os.path.isdir(path):
        print(f"srt-doctor: {path}: not a directory", file=sys.stderr)
        return 2
    if not is_bundle_dir(path):
        bundles = find_bundles(path)
        if not bundles:
            print(f"srt-doctor: {path}: no incident bundles found",
                  file=sys.stderr)
            return 2
        if len(bundles) > 1 and not args.json:
            print(f"({len(bundles)} bundles in {path}; diagnosing the "
                  f"most recent)")
        path = bundles[-1]

    bundle = Bundle(path)
    findings = analyze(bundle)
    if args.json:
        print(json.dumps({"bundle": path,
                          "trigger": bundle.trigger,
                          "findings": findings},
                         indent=2, sort_keys=True))
    else:
        print("\n".join(render(bundle, findings)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
