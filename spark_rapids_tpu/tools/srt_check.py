"""srt-check: the project static analyzer CLI (ISSUE 12).

::

    python -m spark_rapids_tpu.tools.srt_check [paths...]   # srt-lint
    python -m spark_rapids_tpu.tools.srt_check --diff BASE  # changed
    python -m spark_rapids_tpu.tools.srt_check --plan       # plan-IR
    python -m spark_rapids_tpu.tools.srt_check --list-rules
    ... --json     machine-readable, key-sorted, golden-stable

Default scope is the package + scripts + repo-root entry points
(tests excluded).  ``--diff BASE`` lints only the .py files changed
vs a git base ref (plus the working tree) — the fast local loop.
``--plan`` builds every plan in plan/catalog.py and runs the
plan-verify engine over it (this imports jax; plain linting does
not).  Exit status: 0 clean, 1 findings / verify failures, 2 usage.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional


def repo_root() -> str:
    """The repo checkout this module sits in (the CLI lints its own
    tree by default; ``--root`` overrides)."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def _changed_files(root: str, base: str) -> Optional[List[str]]:
    """Repo-relative .py files changed vs ``base`` (committed diff +
    working tree).  None when git itself fails (the caller falls back
    to a full lint rather than passing vacuously)."""
    files = set()
    for args in (["git", "diff", "--name-only", f"{base}...HEAD"],
                 ["git", "diff", "--name-only", "HEAD"],
                 ["git", "diff", "--name-only", "--cached"]):
        try:
            out = subprocess.run(
                args, cwd=root, capture_output=True, text=True,
                timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if out.returncode != 0:
            return None
        files.update(ln.strip() for ln in out.stdout.splitlines()
                     if ln.strip())
    return sorted(f for f in files
                  if f.endswith(".py")
                  and not f.startswith("tests/")
                  and os.path.isfile(os.path.join(root, f)))


# ------------------------------------------------------------- plan mode


def _catalog_plans():
    """(name, buildable) pairs over every plan/catalog.py shape — the
    same parameterizations the fusion smoke runs."""
    from spark_rapids_tpu.plan import catalog as pc
    return [
        ("q3", lambda: pc.q3_plan(base=1990, years=8, brands=16,
                                  manufact=8)),
        ("q9", pc.q9_plan),
        ("q67", lambda: pc.q67_plan(ncat=8, ncls=8)),
        ("cube", lambda: pc.cube_plan(ncat=8, ncls=8)),
        ("q89", lambda: pc.q89_plan(stores=8, items=16)),
        ("q5_pipeline", lambda: pc.q5_pipeline(stores=8,
                                               join_capacity=4096)),
        ("q72_pipeline", lambda: pc.q72_pipeline(
            items=64, max_week=16, join_capacity=4096, limit=100)),
    ]


def run_plan_verify(as_json: bool) -> int:
    from spark_rapids_tpu.analysis import plan_verify
    from spark_rapids_tpu.plan import ir
    results = []
    rc = 0
    for name, build in _catalog_plans():
        try:
            plan = build()
            if isinstance(plan, ir.Pipeline):
                plan_verify.verify_pipeline(plan)
            else:
                plan_verify.verify_stage(plan)
            results.append({"plan": name, "ok": True,
                            "digest": plan.digest})
        except plan_verify.PlanVerifyError as e:
            rc = 1
            results.append({"plan": name, "ok": False,
                            "node": e.node, "reason": e.reason})
    if as_json:
        print(json.dumps({"version": 1, "plans": results},
                         sort_keys=True, indent=2))
    else:
        for r in results:
            if r["ok"]:
                print(f"plan-verify: {r['plan']}: ok "
                      f"(digest {r['digest']})")
            else:
                print(f"plan-verify: {r['plan']}: FAIL at {r['node']}:"
                      f" {r['reason']}")
        print(f"plan-verify: {sum(r['ok'] for r in results)}/"
              f"{len(results)} plans verified")
    return rc


# ------------------------------------------------------------------ main


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="srt-check",
        description="project-invariant static analyzer "
                    "(srt-lint + plan-verify)")
    ap.add_argument("paths", nargs="*",
                    help="files to lint (default: the whole tree)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: this checkout)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--diff", metavar="BASE", default=None,
                    help="lint only .py files changed vs a git ref")
    ap.add_argument("--plan", action="store_true",
                    help="verify every plan/catalog.py stage plan")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--no-docs-check", action="store_true",
                    help="skip the catalog<->docs cross-check "
                         "(partial-scope runs)")
    args = ap.parse_args(argv)

    from spark_rapids_tpu.analysis import lint

    if args.list_rules:
        for rid, title in lint.RULE_TABLE:
            print(f"{rid}  {title}")
        return 0

    if args.plan:
        return run_plan_verify(args.json)

    root = os.path.abspath(args.root) if args.root else repo_root()
    paths = args.paths or None
    check_docs = not args.no_docs_check
    if args.diff is not None:
        changed = _changed_files(root, args.diff)
        if changed is None:
            print("srt-check: git diff failed, linting full tree",
                  file=sys.stderr)
        else:
            paths = changed
            check_docs = False      # partial scope: per-file rules only
            if not paths:
                print("srt-check: no changed python files")
                return 0
    res = lint.lint_paths(root, paths, check_docs=check_docs)
    print(res.to_json() if args.json else res.render_text())
    return 1 if res.findings else 0


if __name__ == "__main__":
    sys.exit(main())
