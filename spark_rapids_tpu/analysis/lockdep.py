"""Runtime lock-order detector (ISSUE 12 tentpole, engine 2).

The repo holds ~40 locks across the server, scheduler, lifeguard,
metrics registry, jit cache, shim handle registry, and shuffle
transport — with an *implied* acquisition order that nothing enforced.
This module is the enforcement: an opt-in instrumented Lock/RLock
wrapper (the linux-kernel lockdep idea, scaled to this process) that

  * records the per-thread held-lock stack on every acquire,
  * folds each (held -> acquired) pair into a process-wide
    acquisition-order graph keyed by *lock class* (the name passed to
    :func:`make_lock` — every ``metrics.series`` lock is one class,
    exactly like kernel lockdep keys on the lock's init site),
  * reports cycles in that graph (ABBA deadlock *potential* — the
    deadlock does not have to fire to be caught) with the acquisition
    stacks of both directions as flight-recorder-style JSON evidence,
  * flags locks held across known blocking calls (socket sends,
    storage range reads — the :func:`note_blocking` sites), which are
    latency bombs even when they never deadlock.

Cost model: ``make_lock``/``make_rlock`` return a *plain*
``threading.Lock``/``RLock`` unless ``SPARK_RAPIDS_TPU_LOCKDEP=1`` is
set when the lock is created — the off path costs one env read at
lock creation and NOTHING per acquire.  ``note_blocking`` costs one
module-bool read when no instrumented lock exists.  Because the env
var is read at creation time, it must be set before the instrumented
modules import (the analysis smoke does exactly that).

Evidence: every detected cycle / held-across-blocking event bumps
``srt_lockdep_*``, emits a ``lockdep`` journal event, and (cycles
only, when the recorder is armed) freezes a ``lockdep_cycle``
incident bundle that ``srt-doctor`` renders as a ranked finding.
The observability import is lazy and failure-isolated: lockdep is
adopted *by* the metrics registry, so it must never import it at
module scope.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, List, Optional, Tuple

ENV = "SPARK_RAPIDS_TPU_LOCKDEP"

# flipped true when the first instrumented lock is created: the
# note_blocking fast path in un-instrumented processes is one read of
# this bool (never an env read)
_INSTALLED = False

_MAX_CYCLES = 64          # distinct cycle reports kept (dedup by path)
_MAX_BLOCKING = 256       # held-across-blocking events kept
_MAX_STACK = 12           # frames kept per evidence stack


def enabled() -> bool:
    """Dynamic env read — governs what make_lock returns *now*."""
    return os.environ.get(ENV, "") not in ("", "0")


class _Graph:
    """Acquisition-order graph over lock classes.  One per process;
    its own internal lock is a plain threading.Lock (never
    instrumented — lockdep must not watch itself)."""

    def __init__(self):
        self.lock = threading.Lock()
        # (held_class, acquired_class) -> evidence
        self.edges: Dict[Tuple[str, str], dict] = {}
        self.cycles: List[dict] = []
        self._cycle_keys: set = set()
        self.blocking: List[dict] = []
        self.blocking_total = 0
        self.classes: Dict[str, int] = {}   # class -> instances created
        self.acquires = 0

    def reset(self):
        with self.lock:
            self.edges.clear()
            self.cycles.clear()
            self._cycle_keys.clear()
            self.blocking.clear()
            self.blocking_total = 0
            self.acquires = 0


_GRAPH = _Graph()

_TLS = threading.local()


def _held() -> list:
    """This thread's held-lock stack: list of [class, lock_id]."""
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def _stack_tail() -> List[str]:
    return [ln.strip() for ln in
            traceback.format_stack(limit=_MAX_STACK + 2)[:-2]][-_MAX_STACK:]


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """Caller holds _GRAPH.lock.  DFS path src -> dst over edges."""
    seen = {src}
    path = [src]

    def walk(node: str) -> bool:
        for (a, b) in _GRAPH.edges:
            if a != node or b in seen:
                continue
            path.append(b)
            if b == dst:
                return True
            seen.add(b)
            if walk(b):
                return True
            path.pop()
        return False

    return path if walk(src) else None


def _emit(kind: str, detail: dict) -> None:
    """Evidence fan-out (counters + journal + incident bundle for
    cycles).  Lazy, failure-isolated: lockdep is adopted by the
    metrics registry itself, so this must survive any observability
    state including mid-import.  Per-thread re-entrancy guard: the
    fan-out acquires instrumented metric locks of its own, and an
    edge detected WHILE emitting must not recurse back in here."""
    if getattr(_TLS, "emitting", False):
        return
    _TLS.emitting = True
    try:
        from spark_rapids_tpu import observability as _obs
        _obs.record_lockdep(kind, **detail)
    except Exception:
        pass
    finally:
        _TLS.emitting = False


def _note_attempt(cls: str, lock_id: int) -> None:
    """Record (held -> wanted) edges at acquisition ATTEMPT time —
    before the acquire can block.  An ABBA pair deadlocks on its
    second acquires; recording at attempt time reports the cycle even
    while both threads are still wedged (the kernel-lockdep
    discipline), instead of needing the deadlock to luckily miss."""
    held = _held()
    _GRAPH.acquires += 1        # racy but statistical — display only
    reentrant = any(i == lock_id for _c, i in held)
    if held and not reentrant:
        new_edges = []
        with _GRAPH.lock:
            for held_cls, held_id in held:
                if held_cls == cls and held_id == lock_id:
                    continue
                key = (held_cls, cls)
                ev = _GRAPH.edges.get(key)
                if ev is not None:
                    ev["count"] += 1
                    continue
                new_edges.append(key)
                _GRAPH.edges[key] = {
                    "count": 1,
                    "thread": threading.current_thread().name,
                    "stack": _stack_tail(),
                }
            cycles = []
            for (a, b) in new_edges:
                if a == b:
                    path = [a, b]      # same-class nesting across
                    #                    instances: ordered only by luck
                else:
                    back = _find_path(b, a)
                    if back is None:
                        continue
                    path = back + [b]
                ck = "->".join(path)
                if ck in _GRAPH._cycle_keys:
                    continue
                _GRAPH._cycle_keys.add(ck)
                cyc = {
                    "cycle": path,
                    "forward": {"edge": [a, b],
                                **_GRAPH.edges[(a, b)]},
                    "backward": [
                        {"edge": [x, y], **_GRAPH.edges[(x, y)]}
                        for x, y in zip(path, path[1:])
                        if (x, y) in _GRAPH.edges and (x, y) != (a, b)],
                }
                if len(_GRAPH.cycles) < _MAX_CYCLES:
                    _GRAPH.cycles.append(cyc)
                cycles.append(cyc)
        for cyc in cycles:
            _emit("cycle", {"cycle": cyc["cycle"],
                            "evidence": cyc})


def _note_released(cls: str, lock_id: int) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][1] == lock_id:
            del held[i]
            return
    # release of a lock this thread never recorded (a Condition
    # handing the lock between threads) — ignore rather than corrupt


class LockdepLock:
    """Instrumented ``threading.Lock`` drop-in; ``name`` is the lock
    class key in the acquisition-order graph."""

    _reentrant = False

    def __init__(self, name: str):
        self.name = name
        self._lock = self._make_inner()
        with _GRAPH.lock:
            _GRAPH.classes[name] = _GRAPH.classes.get(name, 0) + 1

    def _make_inner(self):
        return threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        _note_attempt(self.name, id(self))
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            _held().append([self.name, id(self)])
        return ok

    def release(self):
        self._lock.release()
        _note_released(self.name, id(self))

    def locked(self):
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<{type(self).__name__} {self.name!r}>"


class LockdepRLock(LockdepLock):
    _reentrant = True

    def _make_inner(self):
        return threading.RLock()

    def locked(self):
        # RLock has no .locked() before 3.12; this probe reports
        # whether ANOTHER thread holds it (an owner's reentrant probe
        # succeeds, so self-held reads as unlocked — matches the
        # "would acquire block me" question callers actually ask)
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True


def make_lock(name: str) -> "threading.Lock | LockdepLock":
    """A lock participating in lockdep when ``SPARK_RAPIDS_TPU_LOCKDEP=1``
    is set at creation time; a plain ``threading.Lock`` otherwise
    (zero per-acquire cost on the off path)."""
    if not enabled():
        return threading.Lock()
    global _INSTALLED
    _INSTALLED = True
    return LockdepLock(name)


def make_rlock(name: str) -> "threading.RLock | LockdepRLock":
    if not enabled():
        return threading.RLock()
    global _INSTALLED
    _INSTALLED = True
    return LockdepRLock(name)


def note_blocking(op: str) -> None:
    """Mark a known blocking call site (socket send/recv, storage
    range read).  When the calling thread holds any instrumented lock,
    that's a lock held across I/O — recorded with the held stack and
    surfaced exactly like a cycle (minus the incident bundle: it is a
    latency bug, not a deadlock)."""
    if not _INSTALLED:
        return
    held = _held()
    if not held:
        return
    ev = {
        "op": op,
        "held": [c for c, _i in held],
        "thread": threading.current_thread().name,
        "stack": _stack_tail(),
    }
    with _GRAPH.lock:
        _GRAPH.blocking_total += 1
        if len(_GRAPH.blocking) < _MAX_BLOCKING:
            _GRAPH.blocking.append(ev)
    _emit("blocking", {"op": op, "held": ev["held"],
                       "evidence": ev})


def held_classes() -> List[str]:
    """Lock classes the calling thread currently holds (tests)."""
    return [c for c, _i in _held()]


def report() -> dict:
    """Flight-recorder-style JSON: the graph, every detected cycle
    with both directions' acquisition stacks, and the
    held-across-blocking events."""
    with _GRAPH.lock:
        return {
            "enabled": enabled(),
            "installed": _INSTALLED,
            "classes": dict(sorted(_GRAPH.classes.items())),
            "acquires": _GRAPH.acquires,
            "edges": [
                {"from": a, "to": b, "count": ev["count"]}
                for (a, b), ev in sorted(_GRAPH.edges.items())],
            "cycles": [dict(c) for c in _GRAPH.cycles],
            "blocking": [dict(b) for b in _GRAPH.blocking],
            "blocking_total": _GRAPH.blocking_total,
        }


def reset() -> None:
    """Drop the graph and all evidence (tests / smoke phases).  Lock
    classes and the installed flag survive — existing instrumented
    locks keep reporting into the fresh graph."""
    _GRAPH.reset()
