"""plan-verify: static checker over PR-11 stage plans (ISSUE 12
tentpole, engine 3).

The stage compiler (plan/compiler.py) traces a whole plan into one
XLA program — which means a malformed plan surfaces as an XLA trace
error three layers down ("expected int32, got bool" from inside a
segment_sum) with no mention of which NODE was wrong.  This verifier
runs BEFORE lowering (compile_stage/compile_pipeline call it once per
digest, memoized; ``SPARK_RAPIDS_TPU_PLAN_VERIFY=0`` is the escape
hatch) and turns every class of malformation into a typed
:class:`PlanVerifyError` that NAMES the offending node:

  * **SSA / binding** — a node referencing a column no input or
    earlier node defines, duplicate column definitions, outputs that
    nothing defines, ``Mask`` over a non-input name;
  * **node legality** — unknown Bin/Un ops, Sort ``num_keys`` out of
    range, Reduce kinds outside {sum, any}, Rollup modes outside
    {rollup, cube}, non-positive capacities/cardinalities/segment
    counts, backwards slices;
  * **digest purity** — every node must be hashable with
    recursively-immutable fields (str/int/float/bool/None/tuple/
    Expr/ColSpec); a list or dict smuggled into a frozen dataclass
    field makes ``plan.digest`` unstable across processes and silently
    forks the jit cache;
  * **dtype flow** (when the caller supplies input dtypes) — the
    expression algebra's promotion is walked against the hand-kernel
    promotion table (jax's, via ``jnp.promote_types``): boolean
    conditions for Where/filter-and, integer ids for gathers and
    segment aggregates, integer join keys;
  * **pipeline seams** — boundary count matches stage count, carried
    columns exist in the producing stage, and a boundary-fed ScanBind
    consumes ONLY carried columns (a column that exists upstream but
    is not carried works single-process and breaks distributed — the
    exact drift this check forbids).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu.plan import ir

_BIN_OPS = frozenset((
    "add", "sub", "mul", "div", "floordiv", "mod", "and", "or",
    "eq", "ne", "lt", "le", "gt", "ge", "max", "min"))
_UN_OPS = frozenset(("neg", "not", "sum", "i32", "i64", "f64", "b"))
_REDUCE_KINDS = frozenset(("sum", "any"))
_ROLLUP_MODES = frozenset(("rollup", "cube"))

_COMPARES = frozenset(("eq", "ne", "lt", "le", "gt", "ge"))

_IMMUTABLE_SCALARS = (str, int, float, bool, bytes, type(None))


class PlanVerifyError(ValueError):
    """Typed verification failure.  ``node`` is the offending node's
    canonical key (or a stage/pipeline name for seam errors) so the
    error message survives serialization across the shim."""

    def __init__(self, plan_name: str, node: str, reason: str):
        self.plan_name = plan_name
        self.node = node
        self.reason = reason
        super().__init__(
            f"plan {plan_name!r}: node {node}: {reason}")


def _node_label(node) -> str:
    try:
        k = node.key()
    except Exception:
        k = repr(node)
    return f"{type(node).__name__} {k[:80]}"


# ----------------------------------------------------------- purity


def _check_immutable(plan_name: str, label: str, value,
                     path: str) -> None:
    if isinstance(value, _IMMUTABLE_SCALARS):
        return
    if isinstance(value, tuple):
        for i, v in enumerate(value):
            _check_immutable(plan_name, label, v, f"{path}[{i}]")
        return
    if isinstance(value, (ir.Expr, ir.Node, ir.ColSpec,
                          ir.ShuffleBoundary)):
        for f, v in getattr(value, "__dataclass_fields__", {}).items():
            _check_immutable(plan_name, label, getattr(value, f),
                             f"{path}.{f}")
        return
    raise PlanVerifyError(
        plan_name, label,
        f"field {path} holds a {type(value).__name__} — node fields "
        f"must be immutable/hashable or the plan digest forks the "
        f"jit cache")


def _check_purity(plan_name: str, node) -> None:
    label = _node_label(node)
    _check_immutable(plan_name, label, node, "node")
    try:
        hash(node)
    except TypeError as e:
        raise PlanVerifyError(
            plan_name, label, f"node is unhashable ({e})") from e
    key = node.key()
    if not isinstance(key, str) or not key:
        raise PlanVerifyError(
            plan_name, label, "key() must return a non-empty string")


# ------------------------------------------------------ expr walking


def _expr_refs(e, out: List[Tuple[str, str]]) -> None:
    """Collect ('col'|'mask', name) references under an expression."""
    if isinstance(e, ir.Col):
        out.append(("col", e.name))
    elif isinstance(e, ir.Mask):
        out.append(("mask", e.input))
    elif isinstance(e, ir.Bin):
        _expr_refs(e.a, out)
        _expr_refs(e.b, out)
    elif isinstance(e, (ir.Un, ir.Sl)):
        _expr_refs(e.a, out)
    elif isinstance(e, ir.Where):
        _expr_refs(e.cond, out)
        _expr_refs(e.a, out)
        _expr_refs(e.b, out)
    elif isinstance(e, ir.Idx):
        _expr_refs(e.src, out)
        _expr_refs(e.idx, out)
    elif isinstance(e, ir.Stack):
        for p in e.parts:
            _expr_refs(p, out)


def _check_expr_ops(plan_name: str, label: str, e) -> None:
    if isinstance(e, ir.Bin):
        if e.op not in _BIN_OPS:
            raise PlanVerifyError(plan_name, label,
                                  f"unknown binary op {e.op!r}")
        _check_expr_ops(plan_name, label, e.a)
        _check_expr_ops(plan_name, label, e.b)
    elif isinstance(e, ir.Un):
        if e.op not in _UN_OPS:
            raise PlanVerifyError(plan_name, label,
                                  f"unknown unary op {e.op!r}")
        _check_expr_ops(plan_name, label, e.a)
    elif isinstance(e, ir.Where):
        for sub in (e.cond, e.a, e.b):
            _check_expr_ops(plan_name, label, sub)
    elif isinstance(e, ir.Idx):
        _check_expr_ops(plan_name, label, e.src)
        _check_expr_ops(plan_name, label, e.idx)
    elif isinstance(e, ir.Sl):
        if e.start < 0 or e.stop < e.start:
            raise PlanVerifyError(
                plan_name, label,
                f"backwards slice [{e.start}:{e.stop}]")
        _check_expr_ops(plan_name, label, e.a)
    elif isinstance(e, ir.Arange):
        if e.n < 0:
            raise PlanVerifyError(plan_name, label,
                                  f"negative Arange({e.n})")
    elif isinstance(e, ir.Stack):
        if not e.parts:
            raise PlanVerifyError(plan_name, label, "empty Stack")
        for p in e.parts:
            _check_expr_ops(plan_name, label, p)


def _node_exprs(node) -> List[ir.Expr]:
    out: List[ir.Expr] = []
    for f in getattr(node, "__dataclass_fields__", {}):
        v = getattr(node, f)
        if isinstance(v, ir.Expr):
            out.append(v)
        elif isinstance(v, tuple):
            out.extend(x for x in v if isinstance(x, ir.Expr))
    return out


# ------------------------------------------------------- dtype flow


class _Weak:
    """A weak python literal: adopts the other operand's dtype family
    exactly like an unpinned literal in the hand kernels."""

    def __init__(self, kind: str):  # 'int' | 'float' | 'bool'
        self.kind = kind

    def __repr__(self):
        return f"weak-{self.kind}"


def _promote(plan_name: str, label: str, a, b):
    import jax.numpy as jnp
    if isinstance(a, _Weak) and isinstance(b, _Weak):
        return a if a.kind == "float" or b.kind != "float" else b
    if isinstance(a, _Weak):
        a, b = b, a
    if isinstance(b, _Weak):
        if b.kind == "float" and not str(a).startswith("float"):
            return "float64"  # weak float promotes integer operands
        return a
    try:
        return str(jnp.promote_types(a, b))
    except Exception as e:
        raise PlanVerifyError(
            plan_name, label,
            f"dtypes {a} and {b} do not promote: {e}") from e


def _is_integer(dt) -> bool:
    return (isinstance(dt, _Weak) and dt.kind == "int") or (
        isinstance(dt, str) and (dt.startswith("int")
                                 or dt.startswith("uint")))


def _is_bool(dt) -> bool:
    return (isinstance(dt, _Weak) and dt.kind == "bool") or dt == "bool"


def _expr_dtype(plan_name: str, label: str, e, env: Dict[str, object]):
    """Static dtype of an expression under ``env`` (column -> dtype
    string or _Weak).  Mirrors compiler._eval's promotion behavior."""
    if isinstance(e, ir.Col):
        return env[e.name]
    if isinstance(e, ir.Mask):
        return "bool"
    if isinstance(e, ir.Lit):
        if e.dtype is not None:
            return str(e.dtype)
        if isinstance(e.value, bool):
            return _Weak("bool")
        if isinstance(e.value, int):
            return _Weak("int")
        if isinstance(e.value, float):
            return _Weak("float")
        return _Weak("int")
    if isinstance(e, ir.Bin):
        a = _expr_dtype(plan_name, label, e.a, env)
        b = _expr_dtype(plan_name, label, e.b, env)
        if e.op in _COMPARES:
            _promote(plan_name, label, a, b)   # must be promotable
            return "bool"
        if e.op in ("and", "or"):
            for side, dt in (("left", a), ("right", b)):
                if not (_is_bool(dt) or _is_integer(dt)):
                    raise PlanVerifyError(
                        plan_name, label,
                        f"bitwise {e.op!r} over non-bool/int "
                        f"{side} operand ({dt})")
            return _promote(plan_name, label, a, b)
        if e.op == "div":
            p = _promote(plan_name, label, a, b)
            return p if str(p).startswith("float") else "float64"
        return _promote(plan_name, label, a, b)
    if isinstance(e, ir.Un):
        a = _expr_dtype(plan_name, label, e.a, env)
        if e.op == "not":
            return a
        if e.op == "neg" or e.op == "sum":
            return a
        return {"i32": "int32", "i64": "int64",
                "f64": "float64", "b": "bool"}[e.op]
    if isinstance(e, ir.Where):
        c = _expr_dtype(plan_name, label, e.cond, env)
        if not _is_bool(c):
            raise PlanVerifyError(
                plan_name, label,
                f"Where condition has dtype {c}, expected bool")
        return _promote(plan_name, label,
                        _expr_dtype(plan_name, label, e.a, env),
                        _expr_dtype(plan_name, label, e.b, env))
    if isinstance(e, ir.Idx):
        idx = _expr_dtype(plan_name, label, e.idx, env)
        if not (_is_integer(idx) or _is_bool(idx)):
            raise PlanVerifyError(
                plan_name, label,
                f"gather index has dtype {idx}, expected integer")
        return _expr_dtype(plan_name, label, e.src, env)
    if isinstance(e, ir.Arange):
        return str(e.dtype)
    if isinstance(e, ir.Sl):
        return _expr_dtype(plan_name, label, e.a, env)
    if isinstance(e, ir.Stack):
        dts = [_expr_dtype(plan_name, label, p, env) for p in e.parts]
        out = dts[0]
        for d in dts[1:]:
            out = _promote(plan_name, label, out, d)
        return out
    raise PlanVerifyError(plan_name, label,
                          f"unknown expr {type(e).__name__}")


def _require_int(plan_name: str, label: str, what: str, dt) -> None:
    if not _is_integer(dt):
        raise PlanVerifyError(
            plan_name, label, f"{what} has dtype {dt}, expected an "
            f"integer dtype")


# ------------------------------------------------------- stage verify


def verify_stage(plan: ir.StagePlan,
                 input_dtypes: Optional[Dict[str, Tuple[str, ...]]]
                 = None) -> ir.StagePlan:
    """Verify one stage plan; returns it unchanged on success, raises
    :class:`PlanVerifyError` naming the offending node otherwise.
    ``input_dtypes`` (input name -> one dtype string per column)
    additionally enables dtype-flow checking."""
    name = plan.name
    defined: Dict[str, object] = {}
    input_names = set()
    for inp in plan.inputs:
        _check_purity(name, inp)
        if inp.name in input_names:
            raise PlanVerifyError(name, _node_label(inp),
                                  f"duplicate input {inp.name!r}")
        input_names.add(inp.name)
        if not inp.columns:
            raise PlanVerifyError(name, _node_label(inp),
                                  "ScanBind with no columns")
        dts: Tuple[str, ...] = ()
        if input_dtypes is not None:
            dts = tuple(input_dtypes.get(inp.name, ()))
            if dts and len(dts) != len(inp.columns):
                raise PlanVerifyError(
                    name, _node_label(inp),
                    f"input {inp.name!r} declares "
                    f"{len(inp.columns)} columns but "
                    f"{len(dts)} dtypes were supplied")
        for i, spec in enumerate(inp.columns):
            if spec.name in defined:
                raise PlanVerifyError(
                    name, _node_label(inp),
                    f"duplicate column {spec.name!r}")
            defined[spec.name] = dts[i] if i < len(dts) else None

    check_dtypes = input_dtypes is not None and all(
        v is not None for v in defined.values())

    for node in plan.nodes:
        label = _node_label(node)
        _check_purity(name, node)

        # -- duplicate definitions (before dtype flow assigns) --------
        for out in node.outs():
            if out in defined:
                raise PlanVerifyError(
                    name, label, f"duplicate column {out!r}")

        # -- SSA: every referenced column defined above ---------------
        refs: List[Tuple[str, str]] = []
        for e in _node_exprs(node):
            _check_expr_ops(name, label, e)
            _expr_refs(e, refs)
        for kind, ref in refs:
            if kind == "mask":
                if ref not in input_names:
                    raise PlanVerifyError(
                        name, label,
                        f"Mask({ref!r}) does not name a stage input")
            elif ref not in defined:
                raise PlanVerifyError(
                    name, label,
                    f"unbound column reference {ref!r}")

        # -- node-specific legality ----------------------------------
        if isinstance(node, ir.JoinProbe) and node.capacity < 1:
            raise PlanVerifyError(
                name, label,
                f"non-positive join capacity {node.capacity}")
        if isinstance(node, ir.SegmentSum) and node.num_segments < 1:
            raise PlanVerifyError(
                name, label,
                f"non-positive num_segments {node.num_segments}")
        if isinstance(node, ir.WindowSum) and node.num_partitions < 1:
            raise PlanVerifyError(
                name, label,
                f"non-positive num_partitions {node.num_partitions}")
        if isinstance(node, ir.Sort):
            if len(node.names) != len(node.operands):
                raise PlanVerifyError(
                    name, label,
                    f"{len(node.names)} names for "
                    f"{len(node.operands)} operands")
            if not (1 <= node.num_keys <= len(node.operands)):
                raise PlanVerifyError(
                    name, label,
                    f"num_keys {node.num_keys} outside "
                    f"[1, {len(node.operands)}]")
        if isinstance(node, ir.Reduce) \
                and node.kind not in _REDUCE_KINDS:
            raise PlanVerifyError(
                name, label, f"unknown Reduce kind {node.kind!r}")
        if isinstance(node, ir.Rollup):
            if node.mode not in _ROLLUP_MODES:
                raise PlanVerifyError(
                    name, label, f"unknown Rollup mode {node.mode!r}")
            if node.cards[0] < 1 or node.cards[1] < 1:
                raise PlanVerifyError(
                    name, label,
                    f"non-positive cardinalities {node.cards}")

        # -- dtype flow ----------------------------------------------
        if check_dtypes:
            env = defined
            if isinstance(node, ir.Project):
                env[node.out] = _expr_dtype(name, label, node.expr,
                                            env)
            elif isinstance(node, ir.JoinProbe):
                for side, e in (("left key", node.left),
                                ("right key", node.right)):
                    _require_int(name, label, side,
                                 _expr_dtype(name, label, e, env))
                p = node.prefix
                env[f"{p}.li"] = env[f"{p}.ri"] = "int32"
                env[f"{p}.valid"] = "bool"
                env[f"{p}.total"] = "int64"
            elif isinstance(node, ir.SegmentSum):
                _require_int(name, label, "segment ids",
                             _expr_dtype(name, label, node.ids, env))
                env[node.out] = _expr_dtype(name, label, node.value,
                                            env)
            elif isinstance(node, ir.Sort):
                for nm, op_ in zip(node.names, node.operands):
                    env[nm] = _expr_dtype(name, label, op_, env)
            elif isinstance(node, ir.Reduce):
                v = _expr_dtype(name, label, node.value, env)
                env[node.out] = "bool" if node.kind == "any" else v
            elif isinstance(node, ir.WindowSum):
                _require_int(name, label, "partition ids",
                             _expr_dtype(name, label, node.part, env))
                env[node.out] = _expr_dtype(name, label, node.value,
                                            env)
            elif isinstance(node, ir.WindowRank):
                _require_int(name, label, "partition ids",
                             _expr_dtype(name, label, node.part, env))
                _expr_dtype(name, label, node.order, env)
                env[node.out] = "int64"
            elif isinstance(node, ir.Rollup):
                for i, k in enumerate(node.keys):
                    _require_int(name, label, f"key {i}",
                                 _expr_dtype(name, label, k, env))
                c = _expr_dtype(name, label, node.mask, env)
                if not _is_bool(c):
                    raise PlanVerifyError(
                        name, label,
                        f"Rollup mask has dtype {c}, expected bool")
                v = _expr_dtype(name, label, node.value, env)
                for out in node.outs():
                    env[out] = ("int64" if ".cnt" in out else v)

        # -- definitions (dtype flow above already filled env slots
        # for the nodes it understands; plain None otherwise) ---------
        for out in node.outs():
            defined.setdefault(out, None)

    missing = [o for o in plan.outputs if o not in defined]
    if missing:
        raise PlanVerifyError(
            name, f"outputs of stage {name!r}",
            f"outputs reference undefined columns {missing}")
    return plan


def verify_pipeline(pipeline: ir.Pipeline,
                    input_dtypes: Optional[Dict[str, Tuple[str, ...]]]
                    = None) -> ir.Pipeline:
    """Verify every stage plus the shuffle-boundary seams."""
    name = pipeline.name
    if not pipeline.stages:
        raise PlanVerifyError(name, "pipeline", "no stages")
    if pipeline.boundaries and \
            len(pipeline.boundaries) != len(pipeline.stages) - 1:
        raise PlanVerifyError(
            name, "pipeline",
            f"{len(pipeline.boundaries)} boundaries for "
            f"{len(pipeline.stages)} stages (need stages-1)")
    for st in pipeline.stages:
        verify_stage(st, input_dtypes)
    for i, b in enumerate(pipeline.boundaries):
        prev, nxt = pipeline.stages[i], pipeline.stages[i + 1]
        label = f"ShuffleBoundary {b.key()}"
        if len(set(b.carry)) != len(b.carry):
            raise PlanVerifyError(name, label,
                                  "duplicate carried columns")
        prev_outs = set(prev.outputs)
        for c in b.carry:
            if c not in prev_outs:
                raise PlanVerifyError(
                    name, label,
                    f"carries {c!r} which stage {prev.name!r} does "
                    f"not output")
        carry = set(b.carry)
        for inp in nxt.inputs:
            cols = [c.name for c in inp.columns]
            if all(c in prev_outs for c in cols):
                # boundary-fed ScanBind: distributed execution ships
                # ONLY the carry, so consuming an uncarried upstream
                # column drifts single-process vs fleet
                stray = [c for c in cols if c not in carry]
                if stray:
                    raise PlanVerifyError(
                        name, _node_label(inp),
                        f"boundary-fed input consumes uncarried "
                        f"columns {stray}")
    return pipeline
