"""Checked-in metrics/knobs catalog (ISSUE 12 tentpole).

The repo's observable surface — every ``srt_*`` metric family and
every ``SPARK_RAPIDS_TPU_*`` env knob — accreted over eleven PRs with
no single source of truth: a family registered in code but missing
from docs/observability.md, or a knob read in some op module and
documented nowhere, was invisible until an operator needed it.  This
catalog is that source of truth, and srt-lint enforces it both ways:

  * every metric name passed to the :class:`MetricsRegistry`
    (``.counter``/``.gauge``/``.histogram`` with a literal name) must
    match ``srt_*`` AND appear in :data:`METRICS` (rules SRT001/002);
  * every ``os.environ``-read ``SPARK_RAPIDS_TPU_*`` knob must appear
    in :data:`KNOBS` (rule SRT003; dynamic families like
    ``SPARK_RAPIDS_TPU_PATH_<OP>`` match :data:`KNOB_WILDCARDS`);
  * :func:`check_docs` cross-checks the catalog against the docs tree
    (rule SRT008): metrics must appear in docs/observability.md,
    knobs in at least one docs/*.md (docs/analysis.md carries the
    full knob table; server knobs may ride docs/server.md's
    prefix-factored ``SPARK_RAPIDS_TPU_SERVER_*`` matrix).

Adding a metric or knob therefore means adding it HERE and to the
docs, or ``make analysis-smoke`` (and premerge) goes red.
"""

from __future__ import annotations

import os
from typing import Dict, List, Tuple

# --------------------------------------------------------------- metrics
# name -> (kind, one-line description).  Kind is the registry family
# kind ('counter' | 'gauge' | 'histogram'); SRT002 checks the
# registration call matches it.

METRICS: Dict[str, Tuple[str, str]] = {
    "srt_op_latency_ns": ("histogram", "host-side op bracket latency"),
    "srt_shuffle_write_bytes_total": ("counter", "kudo bytes serialized"),
    "srt_shuffle_write_time_ns_total": ("counter", "kudo write time"),
    "srt_shuffle_merge_rows_total": ("counter", "kudo merged rows"),
    "srt_shuffle_merge_time_ns_total": ("counter", "kudo merge time"),
    "srt_shuffle_link_bytes_total": (
        "counter", "shuffle bytes per process-boundary link"),
    "srt_shuffle_link_msgs_total": (
        "counter", "shuffle messages delivered per link"),
    "srt_shuffle_link_retries_total": (
        "counter", "shuffle link send retries (NAK/reconnect)"),
    "srt_fleet_epoch": ("gauge", "elastic-fleet membership epoch"),
    "srt_fleet_rebalances_total": (
        "counter", "membership changes that moved shard ownership"),
    "srt_fleet_deaths_total": ("counter", "peer ranks observed dead"),
    "srt_fleet_speculations_total": (
        "counter", "speculative re-executions by outcome"),
    "srt_fleet_resplits_total": (
        "counter", "hot partitions re-split into sub-partitions"),
    "srt_fleet_stale_naks_total": (
        "counter", "elastic frames fenced for a stale epoch"),
    "srt_shuffle_dup_dropped_total": (
        "counter", "duplicate (op, partition) deliveries dropped"),
    "srt_oom_retry_total": ("counter", "retry-OOM throws"),
    "srt_oom_split_retry_total": ("counter", "split-and-retry throws"),
    "srt_thread_blocked_time_ns_total": (
        "counter", "time blocked in the OOM state machine"),
    "srt_device_memory_allocated_bytes": (
        "gauge", "device bytes reserved through the adaptor"),
    "srt_hbm_bytes_in_use": ("gauge", "backend-reported HBM in use"),
    "srt_exchange_capacity_doublings_total": (
        "counter", "exchange capacity-retry doublings"),
    "srt_journal_dropped_total": (
        "counter", "journal events lost to ring wrap"),
    "srt_retry_episodes_total": ("counter", "failed retry episodes"),
    "srt_retry_attempts_total": ("counter", "retry attempts started"),
    "srt_retry_splits_total": ("counter", "split-and-retry halvings"),
    "srt_retry_time_lost_ns_total": (
        "counter", "compute burned by failed attempts"),
    "srt_kudo_corrupt_total": ("counter", "kudo integrity events"),
    "srt_kudo_resync_skipped_bytes_total": (
        "counter", "bytes skipped resyncing kudo streams"),
    "srt_jit_cache_hits_total": ("counter", "compile-cache hits"),
    "srt_jit_cache_misses_total": ("counter", "compile-cache misses"),
    "srt_jit_cache_evictions_total": (
        "counter", "compile-cache LRU evictions"),
    "srt_jit_compile_ns": ("histogram", "lower+compile wall time"),
    "srt_kernel_path_total": (
        "counter", "executions per calibrated kernel path"),
    "srt_stage_fusion_total": (
        "counter", "whole-stage executions by outcome"),
    "srt_incidents_total": ("counter", "incident bundles written"),
    "srt_incidents_suppressed_total": (
        "counter", "incident triggers suppressed"),
    "srt_memory_leak_total": (
        "counter", "tasks finished still holding device memory"),
    "srt_memory_leaked_bytes_total": (
        "counter", "device bytes held at task end"),
    "srt_span_duration_ns": ("histogram", "span durations"),
    "srt_spans_finished_total": ("counter", "spans finished"),
    "srt_server_admitted_total": ("counter", "server admissions"),
    "srt_server_rejected_total": ("counter", "typed server rejections"),
    "srt_server_completed_total": ("counter", "server jobs finished"),
    "srt_server_requeued_total": ("counter", "load-shed requeues"),
    "srt_server_queued": ("gauge", "queued jobs per tenant"),
    "srt_server_running": ("gauge", "running jobs per tenant"),
    "srt_server_tenant_device_bytes": (
        "gauge", "device bytes attributed per tenant"),
    "srt_server_fair_share_deficit": (
        "gauge", "scheduler vruntime deficit per tenant"),
    "srt_server_queue_wait_ns": (
        "histogram", "admission-to-dispatch wait"),
    "srt_server_watchdog_total": (
        "counter", "lifeguard watchdog interventions"),
    "srt_server_quarantine_total": (
        "counter", "poison-query breaker transitions"),
    "srt_server_drain_total": ("counter", "graceful-drain markers"),
    "srt_io_read_bytes_total": ("counter", "storage range-read bytes"),
    "srt_io_read_ns": ("histogram", "storage range-read latency"),
    "srt_io_files_total": ("counter", "parquet files decoded"),
    "srt_io_pages_total": ("counter", "parquet pages decoded"),
    "srt_io_rows_total": ("counter", "rows materialized from parquet"),
    "srt_io_decode_ns_total": ("counter", "parquet decode wall time"),
    # -- ISSUE 12: lockdep evidence --
    "srt_lockdep_cycles_total": (
        "counter", "lock-order cycles detected (ABBA potential)"),
    "srt_lockdep_blocking_total": (
        "counter", "locks held across known blocking calls"),
    # -- ISSUE 13: query profiles (EXPLAIN ANALYZE) --
    "srt_profile_queries_total": (
        "counter", "per-query profiles assembled at query end"),
    "srt_profile_assembly_ns": (
        "histogram", "wall time assembling one query profile"),
    "srt_profile_dropped_total": (
        "counter", "profile sessions dropped instead of assembled"),
    # -- ISSUE 16: telemetry plane & SLOs --
    "srt_timeseries_windows_total": (
        "counter", "time-series windows sampled since boot"),
    "srt_timeseries_tick_ns": (
        "histogram", "wall time taking one window snapshot"),
    "srt_timeseries_merge_total": (
        "counter", "fleet window-snapshot merges by outcome"),
    "srt_monitor_last_sample_age_s": (
        "gauge", "seconds since the Monitor thread last sampled"),
    "srt_slo_burn_rate": (
        "gauge", "per-tenant error-budget burn rate per window"),
    "srt_slo_attainment_ratio": (
        "gauge", "per-tenant since-boot SLO attainment"),
    "srt_slo_breaches_total": (
        "counter", "slo_burn alerts fired per tenant"),
    # -- ISSUE 17: time attribution & critical path --
    "srt_shuffle_wire_ns_total": (
        "counter", "exchange serialize+send wall time"),
    "srt_shuffle_wait_ns_total": (
        "counter", "exchange inbox/gather idle time by cause"),
    "srt_attribution_ns_total": (
        "counter", "attributed wall ns per tenant and bucket"),
    "srt_attribution_queries_total": (
        "counter", "attribution ledgers built by conservation verdict"),
    # -- ISSUE 18: tiered spill store & out-of-core operators --
    "srt_spill_bytes_total": (
        "counter", "device bytes spilled down-tier by stage and tier"),
    "srt_spill_restores_total": (
        "counter", "spilled batches streamed back by stage and tier"),
    "srt_spill_ns_total": (
        "counter", "spill-store wall ns by stage and direction"),
    "srt_spill_corrupt_total": (
        "counter", "corrupt spill payloads on read-back by outcome"),
    # -- ISSUE 19: semantic result/subplan cache --
    "srt_result_cache_hits_total": (
        "counter", "semantic-cache hits by scope and tenant"),
    "srt_result_cache_misses_total": (
        "counter", "semantic-cache misses by scope and tenant"),
    "srt_result_cache_evictions_total": (
        "counter", "semantic-cache LRU evictions by scope"),
    "srt_result_cache_bytes_total": (
        "counter", "payload bytes admitted into the cache by scope"),
    "srt_result_cache_incremental_folds_total": (
        "counter", "batches folded into resident partial states"),
    # -- ISSUE 20: per-node cardinality & statistics observatory --
    "srt_stats_observations_total": (
        "counter", "per-node row-count observations folded by stage"),
    "srt_stats_misestimate_total": (
        "counter", "cardinality misestimates by stage and plan node"),
    "srt_stats_rows_total": (
        "counter", "result rows returned to tenants by completed jobs"),
    "srt_stats_sketch_ns": (
        "histogram", "wall ns of one memoized column sketch pass"),
}

# ----------------------------------------------------------------- knobs
# name -> one-line description.  The docs cross-check requires each to
# appear somewhere under docs/ (docs/analysis.md holds the full table).

KNOBS: Dict[str, str] = {
    "SPARK_RAPIDS_TPU_METRICS": "=1 enables the metrics spine at import",
    "SPARK_RAPIDS_TPU_TRACE": "=1 enables span tracing at import",
    "SPARK_RAPIDS_TPU_LOCKDEP":
        "=1 instruments make_lock locks for lock-order detection",
    "SPARK_RAPIDS_TPU_PLAN_VERIFY":
        "=0 skips the plan-IR verifier before stage lowering",
    "SPARK_RAPIDS_TPU_FLIGHT_RECORDER": "=1 arms the flight recorder",
    "SPARK_RAPIDS_TPU_FLIGHT_RECORDER_DIR": "incident bundle directory",
    "SPARK_RAPIDS_TPU_FLIGHT_RECORDER_MAX_BYTES":
        "byte budget over the incident directory",
    "SPARK_RAPIDS_TPU_FLIGHT_RECORDER_HBM_BYTES":
        "arms the HBM-pressure detector at this threshold",
    "SPARK_RAPIDS_TPU_JIT_CACHE": "=0 disables the kernel compile cache",
    "SPARK_RAPIDS_TPU_JIT_CACHE_ENTRIES": "compile-cache entry budget",
    "SPARK_RAPIDS_TPU_JIT_CACHE_BYTES": "compile-cache byte budget",
    "SPARK_RAPIDS_TPU_STAGE_FUSION":
        "1|0|unset=auto: whole-stage fusion engine choice",
    "SPARK_RAPIDS_TPU_CALIB_CACHE":
        "calibration verdict file (empty disables the file layer)",
    "SPARK_RAPIDS_TPU_CALIB_CACHE_TTL": "verdict file TTL seconds",
    "SPARK_RAPIDS_TPU_CALIB_BUDGET_S": "calibration wall budget",
    "SPARK_RAPIDS_TPU_PALLAS_ROWCONV":
        "pin the Pallas row-conversion path on/off",
    "SPARK_RAPIDS_TPU_KUDO_CRC": "=0 disables kudo KCRC trailers",
    "SPARK_RAPIDS_TPU_DIST_MESH":
        "0=process harness, auto=attempt jax.distributed mesh",
    "SPARK_RAPIDS_TPU_DIST_FAULT":
        "inject corrupt|trunc|drop:dst:op or slow:dst:ms on a "
        "shuffle link",
    "SPARK_RAPIDS_TPU_DIST_TRACE_CTX":
        "launcher-seeded trace context for fleet trace stitching",
    "SPARK_RAPIDS_TPU_DIST_DIE":
        "inject a worker death (boot|q5:scan|q5:partials[:rc])",
    "SPARK_RAPIDS_TPU_DIST_RESPAWN":
        "=1 marks a respawned worker incarnation (rejoin + replay)",
    "SPARK_RAPIDS_TPU_FLEET_SPEC_DELAY_S":
        "speculation wall-clock floor for a missing partition",
    "SPARK_RAPIDS_TPU_FLEET_SKEW_RATIO":
        "payload-over-median ratio that re-splits a hot partition",
    "SPARK_RAPIDS_TPU_FLEET_BARRIER_S":
        "elastic-barrier deadline before departed ranks are dropped",
    "SPARK_RAPIDS_TPU_FLEET_RESPAWN":
        "=1: the elastic barrier awaits the full original world "
        "(a dead rank is being respawned)",
    "SPARK_RAPIDS_TPU_INGEST_DIR": "seeded parquet dataset directory",
    "SPARK_RAPIDS_TPU_INGEST_COMPRESSION":
        "codec for seeded parquet datasets",
    "SPARK_RAPIDS_TPU_PLATFORM":
        "jax platform pin applied in the shim's initialize()",
    "SPARK_RAPIDS_TPU_CPU_DEVICES":
        "virtual CPU device count for shim-driven mesh programs",
    "SPARK_RAPIDS_TPU_DISABLE_NATIVE":
        "=1 skips the native C++ runtime (pure-python fallbacks)",
    "SPARK_RAPIDS_TPU_FORCE_DEVICE_SHUFFLE":
        "force the device shuffle path regardless of backend",
    "SPARK_RAPIDS_TPU_FORCE_DEVICE_JOIN":
        "force the device join path regardless of backend",
    "SPARK_RAPIDS_TPU_FORCE_DEVICE_GROUPBY":
        "force the device groupby path regardless of backend",
    "SPARK_RAPIDS_TPU_FORCE_DEVICE_DECIMAL":
        "force the device decimal path regardless of backend",
    "SPARK_RAPIDS_TPU_FORCE_DEVICE_FROM_JSON":
        "force the device from_json path regardless of backend",
    "SPARK_RAPIDS_TPU_FORCE_DEVICE_RAW_MAP":
        "force the device raw-map path regardless of backend",
    "SPARK_RAPIDS_TPU_FORCE_DEVICE_PARSE_URI":
        "force the device parse_uri path regardless of backend",
    "SPARK_RAPIDS_TPU_FORCE_DEVICE_PROTOBUF":
        "force the device protobuf path regardless of backend",
    "SPARK_RAPIDS_TPU_JSON": "JSON engine pin (host|device_scan|...)",
    "SPARK_RAPIDS_TPU_JSON_MIN_ROWS": "device JSON row threshold",
    "SPARK_RAPIDS_TPU_JSON_TOKENIZER_THREADS":
        "tokenizer thread-pool width",
    "SPARK_RAPIDS_TPU_FROM_JSON_DEVICE_MIN":
        "from_json device row threshold",
    "SPARK_RAPIDS_TPU_RAW_MAP_DEVICE_MIN":
        "raw-map device row threshold",
    "SPARK_RAPIDS_TPU_PARSE_URI_DEVICE_MIN":
        "parse_uri device row threshold",
    "SPARK_RAPIDS_TPU_PARSE_URI_CACHE_BYTES":
        "parse_uri compiled-program cache budget",
    "SPARK_RAPIDS_TPU_PROTOBUF_DEVICE_MIN":
        "protobuf device row threshold",
    "SPARK_RAPIDS_TPU_PROTOBUF_REPEAT_CAP":
        "bound on repeated-field expansion",
    "SPARK_RAPIDS_TPU_STOD": "string-to-double engine pin",
    "SPARK_RAPIDS_TPU_STOD_MIN_ROWS": "stod device row threshold",
    "SPARK_RAPIDS_TPU_FTOS": "float-to-string engine pin",
    "SPARK_RAPIDS_TPU_FTOS_MIN_ROWS": "ftos device row threshold",
    "SPARK_RAPIDS_TPU_SHA": "SHA engine pin",
    "SPARK_RAPIDS_TPU_SHA_MIN_ROWS": "SHA device row threshold",
    "SPARK_RAPIDS_TPU_PATH_JOIN_INNER":
        "pin the calibrated inner-join engine "
        "(host_rank|host_hash|device_sort|device_hash)",
    "SPARK_RAPIDS_TPU_SERVER_MAX_CONCURRENCY": "server pool threads",
    "SPARK_RAPIDS_TPU_SERVER_MAX_QUEUE": "server admission queue depth",
    "SPARK_RAPIDS_TPU_SERVER_TENANT_MAX_INFLIGHT":
        "per-tenant in-flight quota",
    "SPARK_RAPIDS_TPU_SERVER_TENANT_MAX_BYTES":
        "per-tenant device-byte quota (0=unlimited)",
    "SPARK_RAPIDS_TPU_SERVER_MAX_REQUEUES":
        "load-shed demotions before a job fails alone",
    "SPARK_RAPIDS_TPU_SERVER_STALL_MS":
        "admission-stall incident threshold (0=off)",
    "SPARK_RAPIDS_TPU_SERVER_FINISHED_KEEP":
        "finished jobs kept pollable before eviction",
    "SPARK_RAPIDS_TPU_SERVER_DEFAULT_DEADLINE_S":
        "default per-query deadline (0=off)",
    "SPARK_RAPIDS_TPU_SERVER_HANG_S":
        "silent-worker hang threshold (0=off)",
    "SPARK_RAPIDS_TPU_SERVER_WATCHDOG_MS": "lifeguard scan cadence",
    "SPARK_RAPIDS_TPU_SERVER_QUARANTINE_FAILURES":
        "deaths before a signature quarantines (0=off)",
    "SPARK_RAPIDS_TPU_SERVER_QUARANTINE_COOLDOWN_S":
        "first quarantine cooldown (doubles, cap 8x)",
    "SPARK_RAPIDS_TPU_SERVER_DRAIN_DEADLINE_S":
        "in-flight budget for graceful drain",
    "SPARK_RAPIDS_TPU_SERVER_DRAIN_DIR": "drain flush directory",
    "SPARK_RAPIDS_TPU_SERVER_SOCKET": "unix-socket front-door path",
    "SPARK_RAPIDS_TPU_SERVER_SOCKET_IDLE_S":
        "per-connection read/idle timeout",
    # -- ISSUE 13: query profiles (EXPLAIN ANALYZE) --
    "SPARK_RAPIDS_TPU_PROFILE":
        "=1 enables per-query profile assembly (EXPLAIN ANALYZE)",
    "SPARK_RAPIDS_TPU_PROFILE_KEEP":
        "finished query profiles retained in the process ring "
        "(0=off)",
    "SPARK_RAPIDS_TPU_SERVER_PROFILE_KEEP":
        "query profiles the server retains per tenant (0=off)",
    # -- ISSUE 16: telemetry plane & SLOs --
    "SPARK_RAPIDS_TPU_TIMESERIES":
        "=1 enables the windowed time-series sampler at import",
    "SPARK_RAPIDS_TPU_TIMESERIES_WINDOW_S":
        "time-series window length seconds",
    "SPARK_RAPIDS_TPU_TIMESERIES_CAPACITY":
        "window-ring depth (windows retained)",
    "SPARK_RAPIDS_TPU_SLO":
        "=1 arms per-tenant SLO burn-rate monitoring at import",
    "SPARK_RAPIDS_TPU_SLO_CONFIG":
        "per-tenant SLO spec: inline JSON or @path",
    "SPARK_RAPIDS_TPU_SLO_FAST_S": "fast burn-rate window seconds",
    "SPARK_RAPIDS_TPU_SLO_SLOW_S": "slow burn-rate window seconds",
    "SPARK_RAPIDS_TPU_SLO_BURN_THRESHOLD":
        "burn rate both windows must reach to fire slo_burn",
    # -- ISSUE 17: time attribution & critical path --
    "SPARK_RAPIDS_TPU_ATTRIBUTION":
        "=1 builds a time-attribution ledger per profiled query",
    "SPARK_RAPIDS_TPU_ATTRIBUTION_TOLERANCE":
        "overcount fraction of wall before conservation is broken",
    # -- ISSUE 18: tiered spill store & out-of-core operators --
    "SPARK_RAPIDS_TPU_DEVICE_BUDGET_BYTES":
        "build-side device budget past which join/agg run out-of-core "
        "(unset=unlimited, the disabled path)",
    "SPARK_RAPIDS_TPU_SPILL_DIR": "disk-tier kudo spill directory",
    "SPARK_RAPIDS_TPU_SPILL_HOST_LIMIT_BYTES":
        "host-tier byte budget before spills demote to disk",
    "SPARK_RAPIDS_TPU_SPILL_PARTITIONS":
        "out-of-core hash partition count override (power of two)",
    # -- ISSUE 19: semantic result/subplan cache --
    "SPARK_RAPIDS_TPU_RESULT_CACHE":
        "=1 arms the semantic result/subplan cache (off by default)",
    "SPARK_RAPIDS_TPU_RESULT_CACHE_ENTRIES":
        "result-cache entry budget",
    "SPARK_RAPIDS_TPU_RESULT_CACHE_BYTES":
        "result-cache payload byte budget",
    # -- ISSUE 20: per-node cardinality & statistics observatory --
    "SPARK_RAPIDS_TPU_STATS":
        "=1 arms the per-node statistics collector (off by default)",
    "SPARK_RAPIDS_TPU_STATS_MISEST_RATIO":
        "actual/estimate divergence ratio that fires the misestimate "
        "sentinel",
    "SPARK_RAPIDS_TPU_STATS_STORE":
        "persistent stats-store file (empty string disables the file "
        "layer)",
    "SPARK_RAPIDS_TPU_STATS_STORE_TTL":
        "seconds before persisted per-node actuals expire",
    "SPARK_RAPIDS_TPU_STATS_SKETCH_ROWS":
        "rows one column sketch pass will look at (head slice)",
}

# env families read with a COMPUTED suffix (pinned_path's
# SPARK_RAPIDS_TPU_PATH_<OP>, ServerConfig.from_env's prefix + name).
# These cover only dynamic prefix-concatenation reads — a fully
# LITERAL env read must be in KNOBS by exact name, or new members of
# the biggest knob families would silently skip both the catalog rule
# and the docs cross-check.
KNOB_WILDCARDS: Tuple[str, ...] = (
    "SPARK_RAPIDS_TPU_PATH_",
    "SPARK_RAPIDS_TPU_SERVER_",
)


def knob_known(name: str) -> bool:
    """Exact catalog membership (literal env reads).  Wildcards are
    deliberately NOT consulted here — they exist for computed-suffix
    reads only (see KnobCatalogRule's 'prefix' path)."""
    return name in KNOBS


# ---------------------------------------------------------- docs check


def _docs(root: str) -> Dict[str, str]:
    out = {}
    ddir = os.path.join(root, "docs")
    try:
        names = sorted(os.listdir(ddir))
    except OSError:
        names = []
    for n in names:
        if n.endswith(".md"):
            p = os.path.join(ddir, n)
            try:
                with open(p, encoding="utf-8") as f:
                    out[os.path.join("docs", n)] = f.read()
            except OSError:
                pass
    rp = os.path.join(root, "README.md")
    if os.path.isfile(rp):
        with open(rp, encoding="utf-8") as f:
            out["README.md"] = f.read()
    return out


def check_docs(root: str) -> List[str]:
    """Catalog <-> docs cross-check (the SRT008 engine).  Returns
    human-readable problem strings (empty = clean):

      * every catalogued metric must appear in docs/observability.md;
      * every catalogued knob must appear in some docs/*.md or
        README.md — either by full name, or (server knobs) as its
        backtick-quoted suffix inside a file that names the
        ``SPARK_RAPIDS_TPU_SERVER_*`` family.
    """
    docs = _docs(root)
    problems: List[str] = []
    obs = docs.get(os.path.join("docs", "observability.md"), "")
    for name in sorted(METRICS):
        if name not in obs:
            problems.append(
                f"metric {name} is in analysis/catalog.py but not in "
                f"docs/observability.md")
    for name in sorted(KNOBS):
        found = any(name in t for t in docs.values())
        if not found and name.startswith("SPARK_RAPIDS_TPU_SERVER_"):
            suffix = "`" + name[len("SPARK_RAPIDS_TPU_SERVER_"):] + "`"
            found = any("SPARK_RAPIDS_TPU_SERVER_" in t and suffix in t
                        for t in docs.values())
        if not found:
            problems.append(
                f"knob {name} is in analysis/catalog.py but not "
                f"documented under docs/ or README.md")
    return problems
