"""Project-invariant static analysis + runtime race detection
(ISSUE 12): the repo's conventions, promoted to checked rules.

  lint.py         srt-lint AST rule framework (SRT000..SRT009)
  catalog.py      the checked-in srt_* metrics / SPARK_RAPIDS_TPU_*
                  knobs catalog the rules and docs cross-check
  lockdep.py      opt-in instrumented locks: acquisition-order graph,
                  ABBA cycle detection, lock-held-across-blocking
  plan_verify.py  typed verifier over PR-11 stage plans, run before
                  every lowering (PlanVerifyError instead of an XLA
                  trace error)

CLI: ``python -m spark_rapids_tpu.tools.srt_check`` (srt-check), gated
in ``make analysis-smoke`` -> ``make ci`` + ci/premerge.yaml.

Only :mod:`lockdep` is imported eagerly — it is adopted by the
metrics registry and the server at lock-creation time and must stay
stdlib-only; lint/plan_verify import on demand (plan_verify pulls
jax through the plan package).
"""

from spark_rapids_tpu.analysis.lockdep import (  # noqa: F401
    make_lock, make_rlock, note_blocking)
