"""srt-lint: AST-walking project-invariant rules (ISSUE 12 tentpole,
engine 1).

Eleven PRs of conventions, promoted to checked rules.  Each rule
encodes an invariant the repo actually relies on (the reference repo
enforces its analogs with clang-tidy + sanitizer premerge jobs):

  SRT000  a ``# srt-lint: disable=`` suppression must carry a reason
  SRT001  metric names registered on the MetricsRegistry match srt_*
  SRT002  ...and appear in analysis/catalog.py with the right kind
  SRT003  literal SPARK_RAPIDS_TPU_* env reads appear in the catalog
  SRT004  exceptions raised in shim/jni_entry.py are project-typed
  SRT005  no wall-clock/entropy (time.time, random, os.urandom, uuid)
          in digest-bearing modules (plan/ir, perf/calibrate,
          perf/jit_cache) — one impure key silently forks every cache
  SRT006  no jax/jnp dispatch or blocking I/O (socket, subprocess,
          fileio.read_range, time.sleep) lexically inside a
          ``with <lock>:`` body in observability/, server/, memory/
  SRT007  no bare ``except:`` / swallowed ``except BaseException:``
          (a handler with no re-raise) outside documented finalizers
  SRT008  the metrics/knobs catalog cross-checks against docs/
  SRT009  lock-heavy modules create locks via analysis.lockdep
          (make_lock/make_rlock), not bare threading.Lock()

Suppressions: ``# srt-lint: disable=SRT006 <reason>`` on the finding
line or the line above; ``# srt-lint: disable-file=SRT003 <reason>``
anywhere suppresses the rule for the whole file.  A reasonless
suppression is itself a finding (SRT000).

Output is golden-stable: findings sort by (path, line, rule) and the
JSON form is key-sorted, so the same tree always lints identically.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from spark_rapids_tpu.analysis import catalog

# ------------------------------------------------------------ findings


@dataclass(frozen=True)
class Finding:
    path: str          # repo-relative, forward slashes
    line: int
    rule: str
    message: str

    def as_dict(self) -> dict:
        return {"path": self.path, "line": self.line,
                "rule": self.rule, "message": self.message}


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)
    suppressed: int = 0
    files: int = 0

    def to_json(self) -> str:
        return json.dumps(
            {"version": 1,
             "files": self.files,
             "suppressed": self.suppressed,
             "findings": [f.as_dict() for f in self.findings]},
            sort_keys=True, indent=2)

    def render_text(self) -> str:
        out = []
        for f in self.findings:
            out.append(f"{f.path}:{f.line}: {f.rule} {f.message}")
        out.append(f"srt-lint: {len(self.findings)} finding(s), "
                   f"{self.suppressed} suppressed, "
                   f"{self.files} file(s)")
        return "\n".join(out)


# -------------------------------------------------------- suppressions

_SUPPRESS_RE = re.compile(
    r"#\s*srt-lint:\s*(disable|disable-file)=([A-Z0-9,]+)"
    r"(?:\s+(\S.*))?")


class _Suppressions:
    def __init__(self, src: str):
        self.by_line: Dict[int, set] = {}
        self.file_wide: set = set()
        self.bad: List[int] = []          # suppressions with no reason
        for i, text in enumerate(src.splitlines(), 1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            kind, rules, reason = m.group(1), m.group(2), m.group(3)
            if not reason or not reason.strip():
                self.bad.append(i)
                continue
            ids = {r for r in rules.split(",") if r}
            if kind == "disable-file":
                self.file_wide |= ids
            else:
                self.by_line.setdefault(i, set()).update(ids)

    def covers(self, line: int, rule: str) -> bool:
        if rule in self.file_wide:
            return True
        return (rule in self.by_line.get(line, ())
                or rule in self.by_line.get(line - 1, ()))


# ------------------------------------------------------------- helpers


def _attr_chain(node) -> List[str]:
    """['os', 'environ', 'get'] for os.environ.get — [] when the chain
    roots in a call/subscript (dynamic receiver)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class _ConstTable:
    """Per-scope ``name = "literal"`` (and ``name = "lit" + dynamic``)
    assignments, so env reads through a local like calibrate's
    ``env = "SPARK_RAPIDS_TPU_PATH_" + op`` still resolve (to a
    wildcard prefix)."""

    def __init__(self, tree: ast.AST):
        # (scope node id, name) -> ("const", value) | ("prefix", value)
        self.table: Dict[Tuple[int, str], Tuple[str, str]] = {}
        self.scope_of: Dict[int, int] = {}   # node id -> scope node id
        for scope in ast.walk(tree):
            if not isinstance(scope, (ast.Module, ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                continue
            sid = id(scope)
            # ast.walk is breadth-first, so deeper scopes assign later
            # and the innermost enclosing scope wins
            for stmt in ast.walk(scope):
                self.scope_of[id(stmt)] = sid
            for stmt in scope.body:
                if isinstance(stmt, ast.Assign) \
                        and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    res = _resolve_str(stmt.value, None)
                    if res is not None:
                        self.table[(sid, stmt.targets[0].id)] = res

    def lookup(self, node: ast.AST, name: str
               ) -> Optional[Tuple[str, str]]:
        sid = self.scope_of.get(id(node))
        if sid is None:
            return None
        return self.table.get((sid, name))


def _resolve_str(node, consts: Optional[Tuple[_ConstTable, ast.AST]]
                 ) -> Optional[Tuple[str, str]]:
    """("const", s) for a fully-literal string expression, ("prefix",
    p) when only a literal left side of a concatenation resolves."""
    s = _const_str(node)
    if s is not None:
        return ("const", s)
    if isinstance(node, ast.Name) and consts is not None:
        table, site = consts
        return table.lookup(site, node.id)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _resolve_str(node.left, consts)
        if left is None:
            return None
        right = _resolve_str(node.right, consts)
        if left[0] == "const" and right is not None \
                and right[0] == "const":
            return ("const", left[1] + right[1])
        return ("prefix", left[1])
    if isinstance(node, ast.JoinedStr):  # f-string: leading literal
        if node.values and (s := _const_str(node.values[0])) is not None:
            return ("prefix", s)
    return None


# ---------------------------------------------------------------- rules


class Rule:
    id = "SRT999"
    title = ""
    scope = "all files"

    def applies(self, relpath: str) -> bool:
        return True

    def run(self, ctx: "FileContext") -> List[Finding]:
        raise NotImplementedError


class FileContext:
    def __init__(self, relpath: str, src: str, tree: ast.AST):
        self.relpath = relpath
        self.src = src
        self.tree = tree
        self.consts = _ConstTable(tree)


class MetricNameRules(Rule):
    """SRT001 + SRT002 share one walk (same call sites)."""
    id = "SRT001"
    title = "registry metric names match srt_* and are catalogued"

    def run(self, ctx: FileContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("counter", "gauge",
                                           "histogram")
                    and node.args):
                continue
            name = _const_str(node.args[0])
            if name is None or not name.startswith("srt"):
                # non-srt literal receivers (pyarrow schemas etc.) and
                # dynamic names are out of scope for the prefix rule
                continue
            if not name.startswith("srt_"):
                out.append(Finding(
                    ctx.relpath, node.lineno, "SRT001",
                    f"metric {name!r} does not match the srt_* "
                    f"naming contract"))
                continue
            entry = catalog.METRICS.get(name)
            if entry is None:
                out.append(Finding(
                    ctx.relpath, node.lineno, "SRT002",
                    f"metric {name!r} is not in analysis/catalog.py "
                    f"(add it there and to docs/observability.md)"))
            elif entry[0] != node.func.attr:
                out.append(Finding(
                    ctx.relpath, node.lineno, "SRT002",
                    f"metric {name!r} registered as "
                    f"{node.func.attr} but catalogued as {entry[0]}"))
        return out


_ENV_READ_ATTRS = ("get", "setdefault", "pop", "__getitem__")


class KnobCatalogRule(Rule):
    id = "SRT003"
    title = "SPARK_RAPIDS_TPU_* env reads are catalogued"

    def _check_name(self, ctx, node, resolved) -> Optional[Finding]:
        kind, value = resolved
        if not value.startswith("SPARK_RAPIDS_TPU_"):
            return None
        if kind == "const":
            if not catalog.knob_known(value):
                return Finding(
                    ctx.relpath, node.lineno, "SRT003",
                    f"env knob {value!r} is not in "
                    f"analysis/catalog.py")
        else:  # prefix
            if value not in catalog.KNOB_WILDCARDS:
                return Finding(
                    ctx.relpath, node.lineno, "SRT003",
                    f"dynamic env knob family {value!r}* is not a "
                    f"catalogued wildcard")
        return None

    def run(self, ctx: FileContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            arg = None
            if isinstance(node, ast.Call):
                chain = _attr_chain(node.func)
                is_env = (chain[-1:] and chain[-1] in _ENV_READ_ATTRS
                          and "environ" in chain) \
                    or chain[-1:] == ["getenv"] \
                    or chain == ["os", "getenv"]
                if is_env and node.args:
                    arg = node.args[0]
            elif isinstance(node, ast.Subscript):
                chain = _attr_chain(node.value)
                if "environ" in chain:
                    arg = node.slice
            if arg is None:
                continue
            resolved = _resolve_str(arg, (ctx.consts, node))
            if resolved is None:
                continue
            f = self._check_name(ctx, node, resolved)
            if f is not None:
                out.append(f)
        return out


_BUILTIN_EXCS = {"Exception", "BaseException", "ValueError",
                 "TypeError", "RuntimeError", "KeyError", "IndexError",
                 "OSError", "IOError", "AttributeError"}


class ShimTypedRaiseRule(Rule):
    id = "SRT004"
    title = "shim entry raises project-typed exceptions"
    scope = "spark_rapids_tpu/shim/jni_entry.py"

    def applies(self, relpath: str) -> bool:
        return relpath.endswith("shim/jni_entry.py")

    def run(self, ctx: FileContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            exc = node.exc
            name = None
            if isinstance(exc, ast.Call):
                chain = _attr_chain(exc.func)
                name = chain[-1] if chain else None
            elif isinstance(exc, ast.Name):
                name = exc.id
            if name in _BUILTIN_EXCS:
                out.append(Finding(
                    ctx.relpath, node.lineno, "SRT004",
                    f"raise {name} in the shim entry: use a "
                    f"project-typed exception (shim/errors.py) so the "
                    f"JVM boundary maps it"))
        return out


DIGEST_MODULES = (
    "spark_rapids_tpu/plan/ir.py",
    "spark_rapids_tpu/perf/calibrate.py",
    "spark_rapids_tpu/perf/jit_cache.py",
)

_IMPURE_CALLS = {
    ("time", "time"), ("os", "urandom"),
    ("uuid", "uuid1"), ("uuid", "uuid4"),
    ("datetime", "now"), ("datetime", "utcnow"),
}
_IMPURE_ROOTS = {"random", "secrets"}


class DigestPurityRule(Rule):
    id = "SRT005"
    title = "digest-bearing modules stay wall-clock/entropy free"
    scope = "plan/ir.py, perf/calibrate.py, perf/jit_cache.py"

    def applies(self, relpath: str) -> bool:
        return relpath in DIGEST_MODULES

    def run(self, ctx: FileContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if not chain:
                continue
            bad = (tuple(chain[-2:]) in _IMPURE_CALLS
                   or chain[0] in _IMPURE_ROOTS)
            if bad:
                out.append(Finding(
                    ctx.relpath, node.lineno, "SRT005",
                    f"{'.'.join(chain)}() in a digest-bearing module "
                    f"— wall-clock/entropy must never reach a cache "
                    f"key or plan digest"))
        return out


_LOCK_DIR_PREFIXES = (
    "spark_rapids_tpu/observability/",
    "spark_rapids_tpu/server/",
    "spark_rapids_tpu/memory/",
)
_BLOCKING_ROOTS = {"jax", "jnp", "lax", "socket", "subprocess"}
_BLOCKING_ATTRS = {"read_range", "urlopen", "check_output",
                   "check_call", "sendall", "recv", "recv_into",
                   "accept", "connect", "makefile"}


def _looks_like_lock(expr) -> bool:
    name = None
    if isinstance(expr, ast.Name):
        name = expr.id
    elif isinstance(expr, ast.Attribute):
        name = expr.attr
    return name is not None and "lock" in name.lower()


class LockBlockingRule(Rule):
    id = "SRT006"
    title = "no device dispatch / blocking I/O under a held lock"
    scope = "observability/, server/, memory/"

    def applies(self, relpath: str) -> bool:
        return relpath.startswith(_LOCK_DIR_PREFIXES)

    @staticmethod
    def _walk_pruned(node):
        """Descendants of ``node`` minus any nested def/class/lambda
        subtree (a nested def's body does not run under the lock)."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            yield child
            yield from LockBlockingRule._walk_pruned(child)

    def _scan_body(self, ctx, body, lockname, out):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            for node in [stmt, *self._walk_pruned(stmt)]:
                if not isinstance(node, ast.Call):
                    continue
                chain = _attr_chain(node.func)
                if not chain:
                    continue
                blocking = None
                if chain[0] in _BLOCKING_ROOTS:
                    blocking = ".".join(chain)
                elif tuple(chain[-2:]) == ("time", "sleep"):
                    blocking = "time.sleep"
                elif chain[-1] in _BLOCKING_ATTRS:
                    blocking = ".".join(chain[-2:])
                if blocking:
                    out.append(Finding(
                        ctx.relpath, node.lineno, "SRT006",
                        f"{blocking}() inside `with {lockname}:` — "
                        f"device dispatch / blocking I/O under a held "
                        f"lock stalls every contending thread"))

    def run(self, ctx: FileContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.With):
                continue
            locknames = [ast.unparse(i.context_expr)
                         for i in node.items
                         if _looks_like_lock(i.context_expr)]
            if not locknames:
                continue
            self._scan_body(ctx, node.body, locknames[0], out)
        return out


class BareExceptRule(Rule):
    id = "SRT007"
    title = "no bare except / swallowed BaseException"

    def run(self, ctx: FileContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            bare = node.type is None
            base = (isinstance(node.type, ast.Name)
                    and node.type.id == "BaseException")
            if not (bare or base):
                continue
            reraises = any(isinstance(n, ast.Raise)
                           for stmt in node.body
                           for n in ast.walk(stmt))
            if reraises:
                continue
            what = "bare except:" if bare else "except BaseException:"
            out.append(Finding(
                ctx.relpath, node.lineno, "SRT007",
                f"{what} swallows KeyboardInterrupt/SystemExit — "
                f"catch Exception, re-raise, or suppress with a "
                f"documented finalizer reason"))
        return out


LOCK_ADOPTED_MODULES = (
    "spark_rapids_tpu/server/server.py",
    "spark_rapids_tpu/server/scheduler.py",
    "spark_rapids_tpu/server/admission.py",
    "spark_rapids_tpu/server/__init__.py",
    "spark_rapids_tpu/robustness/lifeguard.py",
    "spark_rapids_tpu/observability/registry.py",
    "spark_rapids_tpu/perf/jit_cache.py",
    "spark_rapids_tpu/perf/calibrate.py",
    "spark_rapids_tpu/shim/handles.py",
    "spark_rapids_tpu/shim/jni_entry.py",
    "spark_rapids_tpu/distributed/transport.py",
    "spark_rapids_tpu/distributed/service.py",
)


class LockdepAdoptionRule(Rule):
    id = "SRT009"
    title = "lock-heavy modules create locks via analysis.lockdep"
    scope = "server, scheduler, lifeguard, registry, jit_cache, " \
            "calibrate, handles, jni_entry, transport, service"

    def applies(self, relpath: str) -> bool:
        return relpath in LOCK_ADOPTED_MODULES

    def run(self, ctx: FileContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if tuple(chain[-2:]) in (("threading", "Lock"),
                                     ("threading", "RLock")):
                out.append(Finding(
                    ctx.relpath, node.lineno, "SRT009",
                    f"{'.'.join(chain)}() in a lockdep-adopted module "
                    f"— use analysis.lockdep.make_lock/make_rlock so "
                    f"the lock participates in order checking"))
        return out


RULES: Sequence[Rule] = (
    MetricNameRules(),
    KnobCatalogRule(),
    ShimTypedRaiseRule(),
    DigestPurityRule(),
    LockBlockingRule(),
    BareExceptRule(),
    LockdepAdoptionRule(),
)

RULE_TABLE: List[Tuple[str, str]] = (
    [("SRT000", "suppression comments must carry a reason")]
    + [(r.id, r.title) for r in RULES]
    + [("SRT002", "metric names appear in the catalog (kind-checked)"),
       ("SRT008", "catalog cross-checks against the docs tree")])


# ---------------------------------------------------------------- driver


def lint_source(src: str, relpath: str) -> Tuple[List[Finding], int]:
    """Lint one file's source.  Returns (unsuppressed findings,
    suppressed count).  Syntax errors surface as a single SRT-SYNTAX
    finding rather than an exception (the CLI must keep walking)."""
    relpath = relpath.replace(os.sep, "/")
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return ([Finding(relpath, e.lineno or 0, "SRT-SYNTAX",
                         f"file does not parse: {e.msg}")], 0)
    sup = _Suppressions(src)
    ctx = FileContext(relpath, src, tree)
    raw: List[Finding] = []
    for rule in RULES:
        if rule.applies(relpath):
            raw.extend(rule.run(ctx))
    for line in sup.bad:
        raw.append(Finding(relpath, line, "SRT000",
                           "suppression without a reason string — "
                           "say WHY the invariant does not apply"))
    kept, suppressed = [], 0
    for f in raw:
        if f.rule != "SRT000" and sup.covers(f.line, f.rule):
            suppressed += 1
        else:
            kept.append(f)
    return kept, suppressed


_DEFAULT_DIRS = ("spark_rapids_tpu", "scripts")


def default_files(root: str) -> List[str]:
    """The default lint scope: the package + scripts + repo-root
    python entry points (tests excluded — they exercise invariants by
    violating them)."""
    out: List[str] = []
    for d in _DEFAULT_DIRS:
        base = os.path.join(root, d)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(x for x in dirnames
                                 if x != "__pycache__")
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    try:
        root_files = sorted(os.listdir(root))
    except OSError:
        root_files = []
    for fn in root_files:
        if fn.endswith(".py"):
            out.append(os.path.join(root, fn))
    return out


def lint_paths(root: str, paths: Optional[Iterable[str]] = None,
               check_docs: bool = True) -> LintResult:
    """Lint ``paths`` (absolute or root-relative; default: the whole
    default scope) plus, when ``check_docs``, the catalog<->docs
    cross-check (SRT008, attributed to analysis/catalog.py)."""
    res = LintResult()
    files = [p if os.path.isabs(p) else os.path.join(root, p)
             for p in (paths if paths is not None
                       else default_files(root))]
    for path in files:
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except OSError:
            continue
        found, sup = lint_source(src, rel)
        res.findings.extend(found)
        res.suppressed += sup
        res.files += 1
    if check_docs:
        for problem in catalog.check_docs(root):
            res.findings.append(Finding(
                "spark_rapids_tpu/analysis/catalog.py", 0, "SRT008",
                problem))
    res.findings.sort(key=lambda f: (f.path, f.line, f.rule,
                                     f.message))
    return res
