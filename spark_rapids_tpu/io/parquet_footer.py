"""Parquet footer parsing + column pruning (reference
NativeParquetJni.cpp 917 LoC: host-side thrift TCompactProtocol parse,
column_pruner :126 / column_pruning_maps :88, case-insensitive schema
matching; ParquetFooter.java:225 readAndFilter).

The footer is decoded into a GENERIC thrift value tree (field ids
preserved, unknown fields kept verbatim), pruned, and re-encoded — so
everything the writer put in the footer survives except the pruned
columns, exactly the trimmed-footer contract."""

from __future__ import annotations

import struct
from typing import Dict, List, NamedTuple, Optional, Tuple

# thrift compact type ids
_T_BOOL_TRUE = 1
_T_BOOL_FALSE = 2
_T_BYTE = 3
_T_I16 = 4
_T_I32 = 5
_T_I64 = 6
_T_DOUBLE = 7
_T_BINARY = 8
_T_LIST = 9
_T_SET = 10
_T_MAP = 11
_T_STRUCT = 12

PARQUET_MAGIC = b"PAR1"


class ParquetFooterException(ValueError):
    """Typed footer failure: truncated thrift bytes, missing ``PAR1``
    magic, an impossible footer length, or a schema shape the flat
    reader cannot consume.  Subclasses :class:`ValueError` so callers
    that predate the type (and the reference's IllegalArgumentException
    shape) keep catching it."""


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def byte(self) -> int:
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def varint(self) -> int:
        result = 0
        shift = 0
        while True:
            b = self.byte()
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                return result
            shift += 7

    def zigzag(self) -> int:
        v = self.varint()
        return (v >> 1) ^ -(v & 1)

    def read_binary(self) -> bytes:
        n = self.varint()
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def read_value(self, ttype: int):
        if ttype == _T_BOOL_TRUE:
            return True
        if ttype == _T_BOOL_FALSE:
            return False
        if ttype == _T_BYTE:
            return self._read_byte_val()
        if ttype in (_T_I16, _T_I32, _T_I64):
            return self.zigzag()
        if ttype == _T_DOUBLE:
            v = struct.unpack_from("<d", self.buf, self.pos)[0]
            self.pos += 8
            return v
        if ttype == _T_BINARY:
            return self.read_binary()
        if ttype in (_T_LIST, _T_SET):
            return self.read_list()
        if ttype == _T_STRUCT:
            return self.read_struct()
        raise ValueError(f"unsupported thrift type {ttype}")

    def _read_byte_val(self) -> int:
        v = self.byte()
        return v - 256 if v >= 128 else v

    def read_list(self):
        head = self.byte()
        size = head >> 4
        etype = head & 0x0F
        if size == 15:
            size = self.varint()
        if etype in (_T_BOOL_TRUE, _T_BOOL_FALSE):
            # list bools are one byte per element (unlike struct fields)
            items = [self.byte() == _T_BOOL_TRUE for _ in range(size)]
        else:
            items = [self.read_value(etype) for _ in range(size)]
        return ("list", etype, items)

    def read_struct(self):
        fields: Dict[int, Tuple[int, object]] = {}
        fid = 0
        while True:
            head = self.byte()
            if head == 0:
                return ("struct", fields)
            delta = head >> 4
            ttype = head & 0x0F
            if delta:
                fid += delta
            else:
                fid = self.zigzag()
            if ttype in (_T_BOOL_TRUE, _T_BOOL_FALSE):
                fields[fid] = (ttype, ttype == _T_BOOL_TRUE)
            else:
                fields[fid] = (ttype, self.read_value(ttype))


class _Writer:
    def __init__(self):
        self.out = bytearray()

    def byte(self, b: int):
        self.out.append(b & 0xFF)

    def varint(self, v: int):
        v &= (1 << 64) - 1
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                self.byte(b | 0x80)
            else:
                self.byte(b)
                return

    def zigzag(self, v: int):
        self.varint((v << 1) ^ (v >> 63) if v < 0 else v << 1)

    def write_value(self, ttype: int, v):
        if ttype in (_T_BOOL_TRUE, _T_BOOL_FALSE):
            return  # encoded in the field header
        if ttype == _T_BYTE:
            self.byte(v & 0xFF)
        elif ttype in (_T_I16, _T_I32, _T_I64):
            self.zigzag(v)
        elif ttype == _T_DOUBLE:
            self.out += struct.pack("<d", v)
        elif ttype == _T_BINARY:
            self.varint(len(v))
            self.out += v
        elif ttype in (_T_LIST, _T_SET):
            _, etype, items = v
            if len(items) < 15:
                self.byte((len(items) << 4) | etype)
            else:
                self.byte(0xF0 | etype)
                self.varint(len(items))
            for item in items:
                # bool list elements carry a 1/2 byte each; writers may
                # declare the element type with either bool code
                if etype in (_T_BOOL_TRUE, _T_BOOL_FALSE):
                    self.byte(1 if item else 2)
                else:
                    self.write_value(etype, item)
        elif ttype == _T_STRUCT:
            self.write_struct(v)
        else:
            raise ValueError(f"unsupported thrift type {ttype}")

    def write_struct(self, sv):
        _, fields = sv
        last = 0
        for fid in sorted(fields):
            ttype, v = fields[fid]
            if ttype in (_T_BOOL_TRUE, _T_BOOL_FALSE):
                ttype = _T_BOOL_TRUE if v else _T_BOOL_FALSE
            delta = fid - last
            if 0 < delta <= 15:
                self.byte((delta << 4) | ttype)
            else:
                self.byte(ttype)
                self.zigzag(fid)
            self.write_value(ttype, v)
            last = fid
        self.byte(0)


# --------------------------------------------------------- footer model


def _sval(sv, fid, default=None):
    if sv is None:
        return default
    t = sv[1].get(fid)
    return default if t is None else t[1]


def parse_footer(data: bytes):
    """Thrift bytes (without the trailing length+PAR1) -> generic tree.
    Truncated or garbage buffers raise the typed
    :class:`ParquetFooterException` instead of a bare IndexError /
    struct.error bubbling out of the compact-protocol reader."""
    try:
        return _Reader(data).read_struct()
    except (IndexError, struct.error, ValueError, OverflowError,
            MemoryError) as e:
        # ValueError covers _Reader's unsupported-thrift-type raise on
        # garbage type nibbles (_Reader never raises the typed
        # exception itself, so this cannot double-wrap)
        raise ParquetFooterException(
            f"truncated or corrupt parquet footer "
            f"({len(data)} bytes): {e}") from e


def serialize_footer(tree) -> bytes:
    w = _Writer()
    w.write_struct(tree)
    return bytes(w.out)


def footer_tail_length(size: int, tail: bytes) -> int:
    """Validate a parquet file's 8-byte tail against its size and
    return the footer length — the ONE tail validation shared by the
    file-handle path below and the range-reading columnar reader.
    Every malformed-tail shape (short file, missing PAR1 magic, footer
    length pointing past the start of the file) raises the typed
    :class:`ParquetFooterException`."""
    if size < 12:  # magic + 4-byte length + leading magic
        raise ParquetFooterException(
            f"not a parquet file: {size} bytes is shorter than "
            f"the minimal header+footer")
    if tail[4:] != PARQUET_MAGIC:
        raise ParquetFooterException(
            "not a parquet file: missing PAR1 magic")
    flen = struct.unpack("<I", tail[:4])[0]
    if flen + 8 > size:
        raise ParquetFooterException(
            f"footer length {flen} exceeds file size {size}")
    return flen


def read_footer_from_file(path: str):
    """Extract and parse the footer from a .parquet file (typed
    failures per :func:`footer_tail_length` / :func:`parse_footer`)."""
    with open(path, "rb") as f:
        f.seek(0, 2)
        size = f.tell()
        if size >= 8:
            f.seek(size - 8)
        flen = footer_tail_length(size, f.read(8) if size >= 8
                                  else b"")
        f.seek(size - 8 - flen)
        return parse_footer(f.read(flen))


def _schema_elements(tree) -> List:
    return _sval(tree, 2)[2]


# parquet physical Type ids (parquet.thrift enum Type)
PHYS_BOOLEAN = 0
PHYS_INT32 = 1
PHYS_INT64 = 2
PHYS_INT96 = 3
PHYS_FLOAT = 4
PHYS_DOUBLE = 5
PHYS_BYTE_ARRAY = 6
PHYS_FIXED_LEN_BYTE_ARRAY = 7

PHYSICAL_TYPE_NAMES = {
    PHYS_BOOLEAN: "boolean", PHYS_INT32: "int32", PHYS_INT64: "int64",
    PHYS_INT96: "int96", PHYS_FLOAT: "float", PHYS_DOUBLE: "double",
    PHYS_BYTE_ARRAY: "byte_array",
    PHYS_FIXED_LEN_BYTE_ARRAY: "fixed_len_byte_array",
}


class SchemaLeaf(NamedTuple):
    """One flat schema column as the page reader consumes it: the
    (name, physical type, max definition level) mapping of the pruned
    footer, plus the logical-type hints needed to pick a column dtype.
    Leaf order is chunk order within every row group."""

    name: str
    physical_type: int          # PHYS_* id
    max_def_level: int          # 1 when OPTIONAL, 0 when REQUIRED
    type_length: int            # FIXED_LEN_BYTE_ARRAY width
    converted_type: Optional[int]   # legacy ConvertedType id
    scale: int                  # DECIMAL scale (parquet sign)
    logical: Optional[tuple]    # raw LogicalType thrift subtree


def schema_leaves(tree) -> List[SchemaLeaf]:
    """Flat-schema leaf mapping of a (possibly pruned) footer tree —
    the projection contract between the footer pruner and
    ``io/parquet_reader``.  Nested and repeated schemas raise the
    typed :class:`ParquetFooterException` (the flat reader cannot
    place their values)."""
    try:
        elems = _schema_elements(tree)
        out: List[SchemaLeaf] = []
        i = 1
        while i < len(elems):
            e = elems[i]
            name = _sval(e, 4, b"")
            name = name.decode("utf-8", "replace") \
                if isinstance(name, bytes) else str(name)
            if _sval(e, 5, 0):
                raise ParquetFooterException(
                    f"nested column {name!r}: flat schemas only")
            rep = _sval(e, 3, 0)
            if rep == 2:  # REPEATED
                raise ParquetFooterException(
                    f"repeated column {name!r}: flat schemas only")
            phys = _sval(e, 1)
            if phys is None:
                raise ParquetFooterException(
                    f"schema element {name!r} has no physical type")
            out.append(SchemaLeaf(name, int(phys),
                                  1 if rep == 1 else 0,
                                  int(_sval(e, 2, 0) or 0),
                                  _sval(e, 6),
                                  int(_sval(e, 7, 0) or 0),
                                  _sval(e, 10)))
            i += 1
        return out
    except (TypeError, IndexError, KeyError, AttributeError) as e:
        # corrupt-but-parseable thrift: field shapes the walk above
        # assumes (ints, lists, structs) can be anything — fold into
        # the typed contract instead of a bare TypeError (the typed
        # raises above are ValueError subclasses, outside this tuple)
        raise ParquetFooterException(
            f"malformed footer schema: {e}") from e


def schema_names(tree) -> List[str]:
    """Schema element names in order, root excluded — THE helper for
    asserting pruning results (used by the footer tests and the JNI
    surface tests; keeps field-id knowledge in one place)."""
    return [_sval(e, 4).decode() for e in _schema_elements(tree)[1:]
            if _sval(e, 4) is not None]


def prune_columns(tree, keep_names: List[str],
                  case_sensitive: bool = True):
    """Trim the footer to the requested TOP-LEVEL columns (nested
    subtrees of kept columns are preserved whole) — the common pruning
    shape of ParquetFooter.readAndFilter.  Delegates to the per-leaf
    pruner with a keep-whole spec, which also keeps the column_orders
    list aligned (the old standalone path left it unpruned, producing
    footers pyarrow rejects)."""
    return prune_columns_nested(tree, {n: None for n in keep_names},
                                case_sensitive=case_sensitive)


def read_and_filter(path: str, keep_names: List[str],
                    case_sensitive: bool = True) -> bytes:
    """ParquetFooter.readAndFilter: read, prune, return trimmed thrift
    bytes."""
    tree = read_footer_from_file(path)
    return serialize_footer(prune_columns(tree, keep_names,
                                          case_sensitive))


_DROP = object()  # unique missing-key sentinel (a str could collide)


def prune_columns_nested(tree, keep_spec: Dict,
                         case_sensitive: bool = True):
    """Per-leaf nested pruning (NativeParquetJni.cpp:126 column_pruner /
    filter_schema): `keep_spec` is a nested dict of schema-element
    names — `{"col": None}` keeps the whole subtree, `{"col": {...}}`
    keeps the group element and recurses, so pruning inside structs
    (including under parquet's list/map wrapper groups, which are
    addressed by their literal names, e.g.
    {"arr": {"list": {"element": {"a": None}}}}) drops unrequested
    leaves.  Row-group column chunks are pruned by LEAF ORDINAL — the
    reference's chunk_map — so dropping `b` inside a struct removes
    exactly that chunk.  Returns a new tree."""
    elems = _schema_elements(tree)

    def norm(s) -> str:
        t = s.decode("utf-8", "replace") if isinstance(s, bytes) else s
        return t if case_sensitive else t.lower()

    def norm_spec(spec):
        if spec is None:
            return None
        if not isinstance(spec, dict):
            raise TypeError(
                f"keep_spec values must be None or dict, got "
                f"{type(spec).__name__}")
        return {norm(k): norm_spec(v) for k, v in spec.items()}

    want_root = norm_spec(keep_spec)
    kept_elems: List = []
    kept_leaf_ordinals: List[int] = []
    leaf_counter = 0

    def count_leaves(i: int) -> Tuple[int, int]:
        """(subtree size, leaf count) of the flattened subtree at i."""
        nc = _sval(elems[i], 5, 0)
        if nc == 0:
            return 1, 1
        size, leaves = 1, 0
        j = i + 1
        for _ in range(nc):
            sz, lv = count_leaves(j)
            size += sz
            leaves += lv
            j += sz
        return size, leaves

    def keep_whole(i: int) -> int:
        nonlocal leaf_counter
        sz, lv = count_leaves(i)
        kept_elems.extend(elems[i:i + sz])
        kept_leaf_ordinals.extend(range(leaf_counter, leaf_counter + lv))
        leaf_counter += lv
        return sz

    def skip_whole(i: int) -> int:
        nonlocal leaf_counter
        sz, lv = count_leaves(i)
        leaf_counter += lv
        return sz

    def walk_children(i: int, nc: int, spec) -> Tuple[int, int]:
        """Process nc children starting at i under `spec`; returns
        (next index, number of kept children)."""
        kept_children = 0
        for _ in range(nc):
            name = norm(_sval(elems[i], 4, b""))
            child_spec = spec.get(name, _DROP) if spec else _DROP
            if child_spec is _DROP:
                i = i + skip_whole(i)
            elif child_spec is None:
                i = i + keep_whole(i)
                kept_children += 1
            else:
                child_nc = _sval(elems[i], 5, 0)
                if child_nc == 0:
                    # spec recurses into a leaf: keep the leaf itself
                    i = i + keep_whole(i)
                    kept_children += 1
                    continue
                slot = len(kept_elems)
                kept_elems.append(None)  # placeholder, fixed below
                i2, sub_kept = walk_children(i + 1, child_nc, child_spec)
                if sub_kept == 0:
                    kept_elems.pop(slot)  # nothing survived below
                else:
                    fields = dict(elems[i][1])
                    fields[5] = (_T_I32, sub_kept)
                    kept_elems[slot] = ("struct", fields)
                    kept_children += 1
                i = i2
        return i, kept_children

    root = elems[0]
    _, kept_top = walk_children(1, _sval(root, 5, 0), want_root)
    new_root_fields = dict(root[1])
    new_root_fields[5] = (_T_I32, kept_top)
    new_fields = dict(tree[1])
    new_fields[2] = (_T_LIST, ("list", _T_STRUCT,
                               [("struct", new_root_fields)] + kept_elems))

    # prune row-group column chunks by original leaf ordinal (chunk_map)
    keep_set = set(kept_leaf_ordinals)
    rg_entry = tree[1].get(4)
    if rg_entry is not None:
        new_rgs = []
        for rg in rg_entry[1][2]:
            rg_fields = dict(rg[1])
            cols_entry = rg_fields.get(1)
            if cols_entry is not None:
                new_cols = [cc for k, cc in enumerate(cols_entry[1][2])
                            if k in keep_set]
                rg_fields[1] = (_T_LIST, ("list", _T_STRUCT, new_cols))
            new_rgs.append(("struct", rg_fields))
        new_fields[4] = (_T_LIST, ("list", _T_STRUCT, new_rgs))
    # column_orders (FileMetaData field 7) holds one entry per LEAF and
    # must stay aligned with the surviving leaves
    co_entry = tree[1].get(7)
    if co_entry is not None:
        kept_co = [co for k, co in enumerate(co_entry[1][2])
                   if k in keep_set]
        new_fields[7] = (_T_LIST, ("list", co_entry[1][1], kept_co))
    return ("struct", new_fields)
