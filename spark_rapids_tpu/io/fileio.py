"""Pluggable file-IO layer (reference fileio/RapidsFileIO.java,
RapidsInputFile.java:32-100, SeekableInputStream.java:26-41,
RapidsOutputFile.java / RapidsOutputStream.java): an abstraction over
the underlying storage (local fs, object store, ...) consumed by the
iceberg/parquet readers.  The local implementation is the default, as
the reference's tests use the Hadoop local filesystem.

`read_vectored` preserves the reference's contract
(RapidsInputFile.java:68-95): ranges are validated against the output
buffer before any IO, empty range lists are a no-op, and reads are
performed through a single opened stream.
"""

from __future__ import annotations

import io
import os
import time
from contextlib import closing
from dataclasses import dataclass
from typing import List, Optional, Protocol

from spark_rapids_tpu import observability as _obs
from spark_rapids_tpu.analysis import lockdep


@dataclass(frozen=True)
class CopyRange:
    """One vectored-read request (RapidsInputFile.java:146-171)."""
    input_offset: int
    length: int
    output_offset: int


class SeekableInputStream(Protocol):
    """read()/seek()/tell() contract (SeekableInputStream.java:26-41)."""

    def read(self, n: int = -1) -> bytes: ...
    def seek(self, pos: int, whence: int = 0) -> int: ...
    def tell(self) -> int: ...
    def close(self) -> None: ...


class RapidsInputFile:
    """A readable file handle (RapidsInputFile.java:32)."""

    def get_length(self) -> int:
        raise NotImplementedError

    def open(self) -> SeekableInputStream:
        raise NotImplementedError

    def read_fully(self) -> bytes:
        with closing(self.open()) as f:
            return f.read()

    def read_vectored(self, output: bytearray,
                      ranges: List[CopyRange]) -> None:
        """Scatter byte ranges of this file into `output`
        (RapidsInputFile.java:68-95).  All ranges are validated before
        any byte is read."""
        if ranges is None:
            raise ValueError("copyRanges can't be null")
        if not ranges:
            return
        for r in ranges:
            if r.length < 0 or r.input_offset < 0 or r.output_offset < 0:
                raise ValueError(f"negative field in {r}")
            if r.output_offset + r.length > len(output):
                raise ValueError(
                    f"range {r} exceeds output buffer "
                    f"({len(output)} bytes)")
        with closing(self.open()) as f:
            for r in ranges:
                f.seek(r.input_offset)
                data = f.read(r.length)
                if len(data) != r.length:
                    raise EOFError(
                        f"short read: wanted {r.length} at "
                        f"{r.input_offset}, got {len(data)}")
                output[r.output_offset:r.output_offset + r.length] = data


class RapidsOutputFile:
    """A writable file handle (RapidsOutputFile.java:27)."""

    def create(self) -> io.BufferedWriter:
        raise NotImplementedError


class RapidsFileIO:
    """Factory for input/output files (RapidsFileIO.java:28).  Output
    is optional — the base class refuses, as the reference's default
    method does."""

    def new_input_file(self, path: str) -> RapidsInputFile:
        raise NotImplementedError

    def new_output_file(self, path: str) -> RapidsOutputFile:
        raise NotImplementedError("Output file not supported")


class _LocalInputFile(RapidsInputFile):
    def __init__(self, path: str):
        self._path = path

    def get_length(self) -> int:
        return os.path.getsize(self._path)

    def open(self) -> SeekableInputStream:
        return open(self._path, "rb")


class _LocalOutputFile(RapidsOutputFile):
    def __init__(self, path: str):
        self._path = path

    def create(self) -> io.BufferedWriter:
        return open(self._path, "wb")


class LocalFileIO(RapidsFileIO):
    """Local-filesystem implementation (the reference tests' Hadoop
    local-fs counterpart)."""

    def new_input_file(self, path: str) -> RapidsInputFile:
        return _LocalInputFile(path)

    def new_output_file(self, path: str) -> RapidsOutputFile:
        return _LocalOutputFile(path)


class RangeReader:
    """One opened stream serving many instrumented range fetches —
    the column-chunk loop opens the file ONCE per read_table, not once
    per chunk (a 212-column file is hundreds of chunks).  Every
    ``read`` folds into the observability spine
    (``srt_io_read_bytes_total`` / ``srt_io_read_ns`` + an ``io_read``
    journal event); short reads raise ``EOFError`` like
    ``read_vectored``."""

    def __init__(self, path: str,
                 fileio: Optional[RapidsFileIO] = None):
        self._path = path
        inp = (fileio or LocalFileIO()).new_input_file(path)
        self._length = inp.get_length()
        self._f = inp.open()

    @property
    def length(self) -> int:
        return self._length

    def read(self, offset: int, length: int) -> bytes:
        """Fetch exactly ``[offset, offset + length)``."""
        if offset < 0 or length < 0:
            raise ValueError(
                f"negative range: offset={offset} length={length}")
        # lockdep blocking marker: a storage fetch under a held lock
        # is the latency bug the analyzer hunts (one bool read when
        # lockdep is off)
        lockdep.note_blocking("fileio.read_range")
        t0 = time.perf_counter_ns()
        self._f.seek(offset)
        data = self._f.read(length)
        if len(data) != length:
            raise EOFError(
                f"short read: wanted {length} bytes at {offset} of "
                f"{self._path}, got {len(data)}")
        _obs.record_io_read(self._path, length,
                            time.perf_counter_ns() - t0)
        return data

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "RangeReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_range(path: str, offset: int, length: int,
               fileio: Optional[RapidsFileIO] = None) -> bytes:
    """One-shot :class:`RangeReader` fetch (opens, reads, closes) —
    the row-group column-chunk primitive for callers outside a batch
    loop."""
    with RangeReader(path, fileio) as r:
        return r.read(offset, length)

