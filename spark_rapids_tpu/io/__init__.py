"""Storage ingest layer: pluggable file IO (``fileio``), parquet
footer parse/prune (``parquet_footer``), the page decoders
(``page_decode``), the columnar reader (``parquet_reader``), and the
zero-copy Arrow C-interface door (``arrow_cabi``).

The typed failure surface is re-exported here: footers raise
``ParquetFooterException``, pages raise ``ParquetDecodeException``
(registered non-retryable with the retry drivers), Arrow hand-offs
raise ``ArrowIngestException``.
"""

from spark_rapids_tpu.io.parquet_footer import (  # noqa: F401
    ParquetFooterException)


def __getattr__(name):
    # lazy re-exports: keep `import spark_rapids_tpu.io.parquet_footer`
    # as light as the seed (page_decode pulls numpy + the retry driver)
    if name == "ParquetDecodeException":
        from spark_rapids_tpu.io.page_decode import ParquetDecodeException
        return ParquetDecodeException
    if name == "ArrowIngestException":
        from spark_rapids_tpu.io.arrow_cabi import ArrowIngestException
        return ArrowIngestException
    if name == "read_table":
        from spark_rapids_tpu.io.parquet_reader import read_table
        return read_table
    if name == "ingest":
        from spark_rapids_tpu.io.arrow_cabi import ingest
        return ingest
    if name == "read_range":
        from spark_rapids_tpu.io.fileio import read_range
        return read_range
    raise AttributeError(name)
