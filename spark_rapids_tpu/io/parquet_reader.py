"""Columnar Parquet reader: pruned footer -> row-group walk -> page
decode -> Arrow-backed device columns (reference: NativeParquetJni's
L3 Parquet kernels + ParquetFooter.readAndFilter; the storage half the
engine was missing between object storage and the TPC-DS pipelines).

Shape of the path:

  * the footer is parsed and PRUNED with ``parquet_footer`` — the
    projection pushdown IS the footer pruner, so the row groups walked
    below only contain the requested column chunks;
  * every column chunk is a range fetch on ONE opened
    ``fileio.RangeReader`` stream (no whole-file slurp, no per-chunk
    reopen; each fetch feeds ``srt_io_read_*``);
  * pages decode through ``page_decode`` (PLAIN, PLAIN_DICTIONARY /
    RLE_DICTIONARY, RLE/bit-packed definition levels) with per-run
    vectorized numpy — dictionary data pages are one index decode plus
    one take;
  * results assemble DIRECTLY into the existing device column layout:
    ``columns/column.py`` unpacked validity, ``bytesview``-convention
    string chars + int32 offsets, float64 as raw uint64 bits.

Supported: flat schemas (nullable everything) over BOOLEAN / INT32
(incl. date32, int8/16, decimal32) / INT64 (incl. timestamp-micros,
decimal64) / FLOAT / DOUBLE / BYTE_ARRAY (utf8 strings), v1 and v2
data pages, UNCOMPRESSED natively plus any codec pyarrow ships
(snappy/zstd/gzip/...).  Everything else raises the typed
``ParquetDecodeException`` / ``ParquetFooterException`` — which the
retry drivers treat as non-retryable.
"""

from __future__ import annotations

import os
import struct
import time
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import observability as _obs
from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import DType, Kind
from spark_rapids_tpu.columns.table import Table
from spark_rapids_tpu.io import page_decode as pd
from spark_rapids_tpu.io import parquet_footer as pf
from spark_rapids_tpu.io.fileio import RangeReader, RapidsFileIO
from spark_rapids_tpu.io.page_decode import ParquetDecodeException

# page types (parquet.thrift PageType)
_PAGE_DATA = 0
_PAGE_INDEX = 1
_PAGE_DICTIONARY = 2
_PAGE_DATA_V2 = 3

# codecs (parquet.thrift CompressionCodec) -> pyarrow codec names
_CODECS = {0: None, 1: "snappy", 2: "gzip", 4: "brotli", 5: "lz4",
           6: "zstd", 7: "lz4_raw"}

# legacy ConvertedType ids the dtype mapping consumes
_CT_UTF8 = 0
_CT_DECIMAL = 5
_CT_DATE = 6
_CT_TIMESTAMP_MILLIS = 9
_CT_TIMESTAMP_MICROS = 10
_CT_INT_8, _CT_INT_16 = 15, 16
_CT_UINT_8, _CT_UINT_16, _CT_UINT_32, _CT_UINT_64 = 11, 12, 13, 14


def _sval(sv, fid, default=None):
    return pf._sval(sv, fid, default)


def _parse_struct_at(buf: bytes, pos: int):
    """Parse one thrift-compact struct (page headers share the footer
    protocol) starting at ``pos``; returns (tree, next position)."""
    r = pf._Reader(buf)
    r.pos = pos
    try:
        tree = r.read_struct()
    except (IndexError, struct.error, ValueError, OverflowError,
            MemoryError) as e:
        raise ParquetDecodeException(
            f"truncated or corrupt page header at offset {pos}: "
            f"{e}") from e
    return tree, r.pos


def _decompress(data: bytes, codec: int, uncompressed_size: int) -> bytes:
    if codec == 0:
        return data
    name = _CODECS.get(codec)
    if name is None:
        raise ParquetDecodeException(f"unsupported codec id {codec}")
    try:
        import pyarrow as pa
        c = pa.Codec(name)
    except Exception as e:
        raise ParquetDecodeException(
            f"codec {name!r} unavailable on this image: {e}") from e
    try:
        out = c.decompress(data, uncompressed_size, asbytes=True)
    except Exception as e:
        raise ParquetDecodeException(
            f"{name} decompression failed: {e}") from e
    if len(out) != uncompressed_size:
        raise ParquetDecodeException(
            f"{name} page inflated to {len(out)} bytes, header "
            f"promised {uncompressed_size}")
    return out


# -------------------------------------------------------- dtype mapping


def _logical_field(leaf: pf.SchemaLeaf, fid: int):
    return _sval(leaf.logical, fid) if leaf.logical is not None else None


def _dtype_for_leaf(leaf: pf.SchemaLeaf) -> DType:
    """Column dtype for a flat leaf: physical type refined by the
    legacy ConvertedType (pyarrow still writes it for compat) with a
    LogicalType fallback."""
    phys, ct = leaf.physical_type, leaf.converted_type
    if phys == pf.PHYS_BOOLEAN:
        return dtypes.BOOL8
    if phys == pf.PHYS_INT32:
        if ct == _CT_DATE or _logical_field(leaf, 6) is not None:
            return dtypes.TIMESTAMP_DAYS
        if ct == _CT_DECIMAL:
            return dtypes.decimal32(-leaf.scale)
        if ct == _CT_INT_8:
            return dtypes.INT8
        if ct == _CT_INT_16:
            return dtypes.INT16
        if ct == _CT_UINT_8:
            return dtypes.UINT8
        if ct == _CT_UINT_16:
            return dtypes.UINT16
        if ct == _CT_UINT_32:
            return dtypes.UINT32
        return dtypes.INT32
    if phys == pf.PHYS_INT64:
        unit = _timestamp_unit(leaf)
        if ct == _CT_TIMESTAMP_MICROS or unit == "us":
            return dtypes.TIMESTAMP_MICROS
        if ct == _CT_TIMESTAMP_MILLIS or unit == "other":
            # silently returning raw millis/nanos as INT64 would be
            # off by 1000x against every TIMESTAMP_MICROS column —
            # refuse typed like the Arrow door does
            raise ParquetDecodeException(
                f"column {leaf.name!r}: only timestamp[us] is "
                f"supported (Spark timestamps are micros)")
        if ct == _CT_DECIMAL:
            return dtypes.decimal64(-leaf.scale)
        if ct == _CT_UINT_64:
            return dtypes.UINT64
        return dtypes.INT64
    if phys == pf.PHYS_FLOAT:
        return dtypes.FLOAT32
    if phys == pf.PHYS_DOUBLE:
        return dtypes.FLOAT64
    if phys == pf.PHYS_BYTE_ARRAY:
        return dtypes.STRING
    raise ParquetDecodeException(
        f"column {leaf.name!r}: physical type "
        f"{pf.PHYSICAL_TYPE_NAMES.get(phys, phys)} unsupported")


def _timestamp_unit(leaf: pf.SchemaLeaf) -> Optional[str]:
    """'us' for a micros LogicalType.TIMESTAMP, 'other' for any other
    unit (millis/nanos), None when the leaf has no timestamp logical
    type."""
    ts = _logical_field(leaf, 8)          # LogicalType.TIMESTAMP
    if ts is None:
        return None
    unit = _sval(ts, 2)                   # TimestampType.unit
    if unit is not None and _sval(unit, 2) is not None:  # MICROS
        return "us"
    return "other"


# ----------------------------------------------------- chunk metadata


class _ChunkMeta:
    __slots__ = ("codec", "num_values", "start", "nbytes", "path")

    def __init__(self, cc, leaf_name: str, file_size: int):
        md = _sval(cc, 3)
        if md is None:
            raise ParquetDecodeException(
                f"column chunk of {leaf_name!r} has no metadata")
        try:
            self.codec = int(_sval(md, 4, 0))
            self.num_values = int(_sval(md, 5, 0))
            data_off = _sval(md, 9)
            dict_off = _sval(md, 11)
            if data_off is None:
                raise ParquetDecodeException(
                    f"column chunk of {leaf_name!r} has no data "
                    f"offset")
            self.start = int(data_off if dict_off is None
                             else min(data_off, dict_off))
            self.nbytes = int(_sval(md, 7, 0))
        except TypeError as e:
            # corrupt-but-parseable metadata: fields holding the
            # wrong thrift shapes must fail typed, not as TypeError
            raise ParquetDecodeException(
                f"malformed chunk metadata of {leaf_name!r}: "
                f"{e}") from e
        # bounds-check against the file BEFORE any fetch: corrupt
        # offsets must fail typed, not as fileio range/EOF errors
        if self.num_values < 0 or self.nbytes < 0 or self.start < 0 \
                or self.start + self.nbytes > file_size:
            raise ParquetDecodeException(
                f"column chunk of {leaf_name!r} lies outside the "
                f"file: [{self.start}, {self.start + self.nbytes}) "
                f"of {file_size} bytes")
        self.path = leaf_name


# ------------------------------------------------------- chunk decode


def _decode_chunk(buf: bytes, leaf: pf.SchemaLeaf, meta: _ChunkMeta):
    """Decode one column chunk's pages.  Returns
    (fixed_vals | (chars, lens), mask or None, pages_decoded) where
    vals/lens carry only the NON-NULL values in row order and mask is
    the per-row validity (None == all valid)."""
    is_str = leaf.physical_type == pf.PHYS_BYTE_ARRAY
    pos, end = 0, len(buf)
    dictionary: Optional[pd.Dictionary] = None
    fixed_parts: List[np.ndarray] = []
    chars_parts: List[np.ndarray] = []
    lens_parts: List[np.ndarray] = []
    mask_parts: List[Tuple[int, Optional[np.ndarray]]] = []
    seen = 0
    pages = 0
    while seen < meta.num_values:
        if pos >= end:
            raise ParquetDecodeException(
                f"column chunk of {meta.path!r} truncated: "
                f"{seen}/{meta.num_values} values decoded")
        header, pos = _parse_struct_at(buf, pos)
        ptype = int(_sval(header, 1, -1))
        usize = int(_sval(header, 2, 0))
        csize = int(_sval(header, 3, 0))
        if csize < 0 or pos + csize > end:
            raise ParquetDecodeException(
                f"page body of {meta.path!r} overruns chunk "
                f"({csize} bytes at {pos}, chunk ends {end})")
        # memoryview slice: free, and uncompressed pages decode in
        # place (frombuffer/unpack_from/Codec all take buffer views)
        raw = memoryview(buf)[pos:pos + csize]
        pos += csize
        pages += 1
        if ptype == _PAGE_DICTIONARY:
            dph = _sval(header, 7)
            nvals = int(_sval(dph, 1, 0))
            dictionary = pd.decode_dictionary_page(
                _decompress(raw, meta.codec, usize),
                leaf.physical_type, nvals)
            continue
        if ptype == _PAGE_INDEX:
            continue
        if ptype == _PAGE_DATA:
            vals, mask, nvals = _decode_data_page_v1(
                raw, header, leaf, meta, dictionary)
        elif ptype == _PAGE_DATA_V2:
            vals, mask, nvals = _decode_data_page_v2(
                raw, header, leaf, meta, dictionary)
        else:
            raise ParquetDecodeException(
                f"unknown page type {ptype} in {meta.path!r}")
        if is_str:
            chars_parts.append(vals[0])
            lens_parts.append(vals[1])
        else:
            fixed_parts.append(vals)
        mask_parts.append((nvals, mask))
        seen += nvals
    if seen != meta.num_values:
        raise ParquetDecodeException(
            f"column chunk of {meta.path!r} decoded {seen} values, "
            f"metadata promised {meta.num_values}")
    mask = _merge_masks(mask_parts, seen)
    if is_str:
        chars = (np.concatenate(chars_parts) if chars_parts
                 else np.empty(0, np.uint8))
        lens = (np.concatenate(lens_parts) if lens_parts
                else np.empty(0, np.int32))
        return (chars, lens), mask, pages
    vals = (np.concatenate(fixed_parts) if fixed_parts
            else np.empty(0, np.uint8 if
                          leaf.physical_type == pf.PHYS_BOOLEAN
                          else pd._PLAIN_NP[leaf.physical_type]))
    return vals, mask, pages


def _stitch_masks(pairs, total: int) -> np.ndarray:
    """(count, mask-or-None) segments -> one bool mask (None segments
    are all-valid) — the one stitching loop shared by the page-level
    and row-group-level merges."""
    out = np.empty(total, np.bool_)
    at = 0
    for n, m in pairs:
        out[at:at + n] = True if m is None else m
        at += n
    return out


def _merge_masks(parts: List[Tuple[int, Optional[np.ndarray]]],
                 total: int) -> Optional[np.ndarray]:
    if all(m is None for _, m in parts):
        return None
    return _stitch_masks(parts, total)


def _decode_values(data: bytes, dpos: int, leaf: pf.SchemaLeaf,
                   meta: _ChunkMeta, dictionary, encoding: int,
                   nvalid: int):
    """Value section of a data page -> non-null values (np array for
    fixed width, (chars, lens) for strings)."""
    if encoding in (pd.ENC_RLE_DICTIONARY, pd.ENC_PLAIN_DICTIONARY):
        if dictionary is None:
            raise ParquetDecodeException(
                f"{meta.path!r}: dictionary-encoded data page before "
                f"any dictionary page")
        idx = pd.decode_dictionary_indices(data, dpos, len(data),
                                           nvalid)
        return pd.dictionary_take(dictionary, idx)
    if (encoding == pd.ENC_RLE
            and leaf.physical_type == pf.PHYS_BOOLEAN):
        # v2 booleans: RLE-of-bit-width-1 with a 4-byte length prefix
        if dpos + 4 > len(data):
            raise ParquetDecodeException(
                f"{meta.path!r}: truncated RLE boolean block")
        nbytes = int.from_bytes(data[dpos:dpos + 4], "little")
        vals, _ = pd.decode_hybrid(data, dpos + 4,
                                   min(dpos + 4 + nbytes, len(data)),
                                   1, nvalid)
        return vals.astype(np.uint8)
    if encoding != pd.ENC_PLAIN:
        raise ParquetDecodeException(
            f"{meta.path!r}: value encoding {encoding} unsupported "
            f"(PLAIN, RLE booleans, and RLE_DICTIONARY only)")
    if leaf.physical_type == pf.PHYS_BYTE_ARRAY:
        chars, lens, _ = pd.decode_plain_byte_array(
            data, dpos, len(data), nvalid)
        return chars, lens
    vals, _ = pd.decode_plain_fixed(data, dpos, len(data),
                                    leaf.physical_type, nvalid)
    return vals


def _decode_data_page_v1(raw: bytes, header, leaf: pf.SchemaLeaf,
                         meta: _ChunkMeta, dictionary):
    dph = _sval(header, 5)
    if dph is None:
        raise ParquetDecodeException(
            f"data page of {meta.path!r} missing its header")
    nvals = int(_sval(dph, 1, 0))
    encoding = int(_sval(dph, 2, 0))
    dl_enc = int(_sval(dph, 3, pd.ENC_RLE))
    data = _decompress(raw, meta.codec, int(_sval(header, 2, 0)))
    levels, dpos = pd.decode_def_levels_v1(
        data, 0, len(data), leaf.max_def_level, nvals, dl_enc)
    if levels is None:
        mask, nvalid = None, nvals
    else:
        mask = levels == np.uint32(leaf.max_def_level)
        nvalid = int(mask.sum())
    vals = _decode_values(data, dpos, leaf, meta, dictionary,
                          encoding, nvalid)
    return vals, mask, nvals


def _decode_data_page_v2(raw: bytes, header, leaf: pf.SchemaLeaf,
                         meta: _ChunkMeta, dictionary):
    d2 = _sval(header, 8)
    if d2 is None:
        raise ParquetDecodeException(
            f"v2 data page of {meta.path!r} missing its header")
    nvals = int(_sval(d2, 1, 0))
    nnulls = int(_sval(d2, 2, 0))
    encoding = int(_sval(d2, 4, 0))
    dl_len = int(_sval(d2, 5, 0))
    rl_len = int(_sval(d2, 6, 0))
    compressed = bool(_sval(d2, 7, True))
    if rl_len:
        raise ParquetDecodeException(
            f"{meta.path!r}: repetition levels in a flat column")
    if dl_len > len(raw):
        raise ParquetDecodeException(
            f"{meta.path!r}: v2 level section overruns page")
    mask = None
    if leaf.max_def_level > 0:
        # v2 levels: hybrid runs with NO 4-byte prefix, never compressed
        levels, _ = pd.decode_hybrid(raw, 0, dl_len,
                                     leaf.max_def_level.bit_length(),
                                     nvals)
        mask = levels == np.uint32(leaf.max_def_level)
        # the header's num_nulls sizes the value decode below; if it
        # disagrees with the levels, assembly would scatter N values
        # into M slots — fail typed here instead of a numpy shape error
        if int(mask.sum()) != nvals - nnulls:
            raise ParquetDecodeException(
                f"{meta.path!r}: v2 page num_nulls={nnulls} disagrees "
                f"with its definition levels "
                f"({nvals - int(mask.sum())} nulls encoded)")
    elif nnulls:
        raise ParquetDecodeException(
            f"{meta.path!r}: v2 page claims {nnulls} nulls in a "
            f"REQUIRED column")
    body = raw[dl_len:]
    if compressed and meta.codec:
        body = _decompress(body, meta.codec,
                           int(_sval(header, 2, 0)) - dl_len - rl_len)
    vals = _decode_values(body, 0, leaf, meta, dictionary, encoding,
                          nvals - nnulls)
    return vals, mask, nvals


# ----------------------------------------------------- column assembly


def _merge_group_masks(masks, group_rows, n) -> Optional[np.ndarray]:
    """Row-group masks -> one per-row bool mask, or None when every
    row is valid (an OPTIONAL column with zero nulls keeps the
    all-valid fast path: no validity buffer materializes)."""
    if all(m is None for m in masks):
        return None
    mask = _stitch_masks(zip(group_rows, masks), n)
    return None if mask.all() else mask


def _build_fixed_column(dtype: DType, parts: List[np.ndarray],
                        masks: List[Optional[np.ndarray]],
                        group_rows: List[int]) -> Column:
    n = sum(group_rows)
    mask = _merge_group_masks(masks, group_rows, n)
    vals = (np.concatenate(parts) if parts
            else np.empty(0, np.int64))
    if mask is not None:
        full = np.zeros(n, vals.dtype)
        full[mask] = vals
        vals = full
        validity = jnp.asarray(mask.astype(np.uint8))
    else:
        validity = None
    target = dtype.np_dtype
    if vals.dtype != target:
        vals = vals.astype(target)
    if dtype.kind == Kind.FLOAT64:
        vals = vals.view(np.uint64)
    return Column(dtype, n, data=jnp.asarray(vals), validity=validity)


def _build_string_column(parts: List[Tuple[np.ndarray, np.ndarray]],
                         masks: List[Optional[np.ndarray]],
                         group_rows: List[int]) -> Column:
    n = sum(group_rows)
    chars = (np.concatenate([c for c, _ in parts]) if parts
             else np.empty(0, np.uint8))
    lens = (np.concatenate([ln for _, ln in parts]) if parts
            else np.empty(0, np.int32))
    mask = _merge_group_masks(masks, group_rows, n)
    if mask is not None:
        full = np.zeros(n, np.int64)
        full[mask] = lens
        lens = full
        validity = jnp.asarray(mask.astype(np.uint8))
    else:
        validity = None
    offsets = np.zeros(n + 1, np.int64)
    np.cumsum(lens, out=offsets[1:])
    if offsets[-1] > np.iinfo(np.int32).max:
        raise ParquetDecodeException(
            f"string column exceeds int32 offsets "
            f"({int(offsets[-1])} chars)")
    return Column(dtypes.STRING, n, data=jnp.asarray(chars),
                  validity=validity,
                  offsets=jnp.asarray(offsets.astype(np.int32)))


# -------------------------------------------------------------- reader


def read_table(path: str, columns: Optional[Sequence[str]] = None,
               case_sensitive: bool = True,
               fileio: Optional[RapidsFileIO] = None) -> Table:
    """Read a flat-schema parquet file into a named device
    :class:`Table`.  ``columns`` prunes the footer first (projection
    pushdown — unrequested chunks are never fetched); ``None`` reads
    everything.  Emits an ``io_read`` span, per-fetch
    ``srt_io_read_*`` metrics, and one ``io_file`` journal record."""
    span = _obs.TRACER.span("io_read", kind="io",
                            attrs={"file": os.path.basename(path)})
    # ONE opened stream serves the footer + every column-chunk fetch
    with span, RangeReader(path, fileio) as rr:
        size = rr.length
        t_all = time.perf_counter_ns()
        flen = pf.footer_tail_length(
            size, rr.read(size - 8, 8) if size >= 8 else b"")
        tree = pf.parse_footer(rr.read(size - 8 - flen, flen))
        if columns is not None:
            # dedup, order-preserving: a repeated request resolves to
            # one leaf, and the missing-list check below stays honest
            columns = list(dict.fromkeys(columns))
            tree = pf.prune_columns(tree, list(columns),
                                    case_sensitive=case_sensitive)
        leaves = pf.schema_leaves(tree)
        if columns is not None and len(leaves) != len(columns):
            have = {lf.name if case_sensitive else lf.name.lower()
                    for lf in leaves}
            missing = [c for c in columns
                       if (c if case_sensitive else c.lower())
                       not in have]
            raise pf.ParquetFooterException(
                f"columns not in {os.path.basename(path)}: {missing}")
        col_dtypes = [_dtype_for_leaf(lf) for lf in leaves]
        try:
            rg_entry = tree[1].get(4)
            rgs = rg_entry[1][2] if rg_entry is not None else []
            group_rows = [int(_sval(rg, 3, 0)) for rg in rgs]
        except (TypeError, IndexError, KeyError, AttributeError) as e:
            raise pf.ParquetFooterException(
                f"malformed row-group list: {e}") from e
        read_bytes = flen + 8
        decode_ns = 0
        pages_total = 0
        parts = [[] for _ in leaves]
        masks = [[] for _ in leaves]
        for rg, rows in zip(rgs, group_rows):
            cols_entry = _sval(rg, 1)
            chunks = cols_entry[2] if cols_entry is not None else []
            if len(chunks) != len(leaves):
                raise ParquetDecodeException(
                    f"row group has {len(chunks)} chunks for "
                    f"{len(leaves)} schema leaves")
            for j, (leaf, cc) in enumerate(zip(leaves, chunks)):
                meta = _ChunkMeta(cc, leaf.name, size)
                if meta.num_values != rows:
                    raise ParquetDecodeException(
                        f"chunk of {leaf.name!r} holds "
                        f"{meta.num_values} values in a {rows}-row "
                        f"group (nested data in a flat column?)")
                buf = rr.read(meta.start, meta.nbytes)
                read_bytes += meta.nbytes
                t0 = time.perf_counter_ns()
                vals, mask, pages = _decode_chunk(buf, leaf, meta)
                decode_ns += time.perf_counter_ns() - t0
                pages_total += pages
                parts[j].append(vals)
                masks[j].append(mask)
        t0 = time.perf_counter_ns()
        out_cols = []
        for leaf, dt, p, m in zip(leaves, col_dtypes, parts, masks):
            if dt.is_string:
                out_cols.append(_build_string_column(p, m, group_rows))
            else:
                out_cols.append(_build_fixed_column(dt, p, m,
                                                    group_rows))
        decode_ns += time.perf_counter_ns() - t0
        num_rows = sum(group_rows)
        span.set_attr("rows", num_rows)
        span.set_attr("columns", len(leaves))
        span.set_attr("bytes", read_bytes)
        span.set_attr("pages", pages_total)
        span.set_attr("wall_ns", time.perf_counter_ns() - t_all)
        _obs.record_io_file(path, columns=len(leaves),
                            pages=pages_total, rows=num_rows,
                            read_bytes=read_bytes, decode_ns=decode_ns)
        # ingest-epoch door (ISSUE 19): a successful read notes the
        # file with a size+mtime fingerprint — the result cache's
        # epoch for this source bumps only when the bytes CHANGED, so
        # re-reading an unchanged file keeps warm results warm
        try:
            from spark_rapids_tpu.perf.result_cache import note_ingest
            st = os.stat(path)
            note_ingest(path, f"{st.st_size}:{st.st_mtime_ns}")
        except Exception:
            pass   # epoch accounting must never fail a read
        # footer row count -> the stats plane's estimate side
        # (ISSUE 20): a plan scanning this source inherits the footer
        # count as its input-cardinality estimate
        if _obs.STATS.enabled:
            _obs.STATS.note_source_rows(path, num_rows,
                                        origin="parquet_footer")
        return Table(out_cols, names=[lf.name for lf in leaves])
