"""Parquet page decoders: PLAIN, PLAIN_DICTIONARY/RLE_DICTIONARY, and
the RLE/bit-packed hybrid for definition levels and dictionary indices
(reference: the L3 Parquet kernels behind NativeParquetJni — here the
host-side half that feeds the Arrow-backed device column layout).

Vectorization contract: the decoders loop per RUN (an RLE or
bit-packed run covers many values) and per PAGE, never per VALUE, on
every fixed-width path — each run body is one ``np.frombuffer`` /
``np.unpackbits`` / broadcast, and a dictionary data page decodes as
one index decode plus one ``np.take``.  The only per-value walk left
is the PLAIN ``BYTE_ARRAY`` length-prefix scan (an inherently
sequential format); dictionary-encoded strings — what Spark-shaped
writers emit — take the vectorized gather.

Every malformed-input shape raises the typed
:class:`ParquetDecodeException`, which the retry drivers treat as
NON-retryable (a corrupt page never heals by recompute; registered
via ``robustness.retry.register_non_retryable`` at import).
"""

from __future__ import annotations

import struct
from typing import Optional, Tuple

import numpy as np

# parquet Encoding enum (parquet.thrift)
ENC_PLAIN = 0
ENC_PLAIN_DICTIONARY = 2
ENC_RLE = 3
ENC_BIT_PACKED = 4
ENC_RLE_DICTIONARY = 8

from spark_rapids_tpu.io.parquet_footer import (  # noqa: E402
    PHYS_BOOLEAN, PHYS_BYTE_ARRAY, PHYS_DOUBLE, PHYS_FLOAT, PHYS_INT32,
    PHYS_INT64, PHYSICAL_TYPE_NAMES)

_PLAIN_NP = {
    PHYS_INT32: np.dtype("<i4"),
    PHYS_INT64: np.dtype("<i8"),
    PHYS_FLOAT: np.dtype("<f4"),
    PHYS_DOUBLE: np.dtype("<f8"),
}


from spark_rapids_tpu.memory.exceptions import CudfException  # noqa: E402


class ParquetDecodeException(CudfException):
    """Typed, terminal page-decode failure (truncated page, impossible
    run lengths, unsupported encoding/physical type, dictionary index
    out of range).  Subclasses :class:`CudfException` — the reference
    surfaces decode failures as engine exceptions — which lands it in
    the retry drivers' RETRYABLE catch set, so it is REGISTERED
    non-retryable below and the drivers escalate on the first attempt:
    re-reading a corrupt page produces the same bytes forever."""


def _register_non_retryable() -> None:
    from spark_rapids_tpu.robustness import retry as _retry
    _retry.register_non_retryable(ParquetDecodeException)


_register_non_retryable()


def _varint(buf: bytes, pos: int, end: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= end:
            raise ParquetDecodeException(
                "truncated varint in RLE/bit-packed run header")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ParquetDecodeException("runaway varint in run header")


def decode_hybrid(buf: bytes, pos: int, end: int, bit_width: int,
                  count: int) -> Tuple[np.ndarray, int]:
    """RLE/bit-packed hybrid (parquet spec §RLE): ``count`` values of
    ``bit_width`` bits from ``buf[pos:end]``.  Returns (uint32 values,
    next position).  Per-run vectorized: an RLE run is one broadcast
    fill, a bit-packed run is one unpackbits + one matvec."""
    out = np.empty(count, np.uint32)
    if count == 0:
        return out, pos
    if bit_width == 0:
        out[:] = 0
        return out, pos
    if bit_width > 32:
        raise ParquetDecodeException(
            f"hybrid bit width {bit_width} > 32")
    byte_w = (bit_width + 7) // 8
    powers = (np.uint32(1) << np.arange(bit_width, dtype=np.uint32))
    filled = 0
    while filled < count:
        header, pos = _varint(buf, pos, end)
        if header & 1:  # bit-packed run: (header >> 1) groups of 8
            ngroups = header >> 1
            nbytes = ngroups * bit_width
            if ngroups == 0 or pos + nbytes > end:
                raise ParquetDecodeException(
                    f"bit-packed run overruns page "
                    f"({nbytes} bytes at {pos}, page ends {end})")
            bits = np.unpackbits(
                np.frombuffer(buf, np.uint8, nbytes, pos),
                bitorder="little")
            vals = (bits.reshape(-1, bit_width).astype(np.uint32)
                    * powers).sum(axis=1, dtype=np.uint32)
            pos += nbytes
            n = min(ngroups * 8, count - filled)
            out[filled:filled + n] = vals[:n]
        else:  # RLE run: one value repeated (header >> 1) times
            run = header >> 1
            if run == 0:
                raise ParquetDecodeException("zero-length RLE run")
            if pos + byte_w > end:
                raise ParquetDecodeException(
                    "RLE run value overruns page")
            v = int.from_bytes(buf[pos:pos + byte_w], "little")
            pos += byte_w
            n = min(run, count - filled)
            out[filled:filled + n] = v
        filled += n
    return out, pos


def decode_def_levels_v1(buf: bytes, pos: int, end: int,
                         max_level: int, num_values: int,
                         encoding: int
                         ) -> Tuple[Optional[np.ndarray], int]:
    """Definition levels of a v1 data page: 4-byte length prefix then
    an RLE/bit-packed hybrid of ``num_values`` levels.  Returns
    (levels or None when the column is REQUIRED, position past the
    level bytes)."""
    if max_level == 0:
        return None, pos
    if encoding not in (ENC_RLE, ENC_BIT_PACKED):
        raise ParquetDecodeException(
            f"definition-level encoding {encoding} unsupported")
    if encoding == ENC_BIT_PACKED:
        raise ParquetDecodeException(
            "legacy BIT_PACKED definition levels unsupported "
            "(write with a parquet-format >= 2.0 writer)")
    if pos + 4 > end:
        raise ParquetDecodeException("truncated definition-level block")
    (nbytes,) = struct.unpack_from("<I", buf, pos)
    pos += 4
    if pos + nbytes > end:
        raise ParquetDecodeException(
            f"definition levels ({nbytes} bytes) overrun page")
    levels, _ = decode_hybrid(buf, pos, pos + nbytes,
                              max_level.bit_length(), num_values)
    return levels, pos + nbytes


def decode_plain_fixed(buf: bytes, pos: int, end: int, phys: int,
                       count: int) -> Tuple[np.ndarray, int]:
    """PLAIN fixed-width values: one ``np.frombuffer``.  BOOLEAN is
    bit-packed LSB-first: one ``np.unpackbits``."""
    if phys == PHYS_BOOLEAN:
        nbytes = (count + 7) // 8
        if pos + nbytes > end:
            raise ParquetDecodeException("truncated PLAIN boolean run")
        bits = np.unpackbits(np.frombuffer(buf, np.uint8, nbytes, pos),
                             bitorder="little")[:count]
        return bits.astype(np.uint8), pos + nbytes
    dt = _PLAIN_NP.get(phys)
    if dt is None:
        raise ParquetDecodeException(
            f"PLAIN decode of physical type "
            f"{PHYSICAL_TYPE_NAMES.get(phys, phys)} unsupported")
    nbytes = count * dt.itemsize
    if pos + nbytes > end:
        raise ParquetDecodeException(
            f"truncated PLAIN {PHYSICAL_TYPE_NAMES[phys]} values "
            f"(want {nbytes} bytes at {pos}, page ends {end})")
    return np.frombuffer(buf, dt, count, pos), pos + nbytes


def _scan_byte_array(buf: bytes, pos: int, end: int, count: int
                     ) -> Tuple[np.ndarray, np.ndarray, int]:
    """The sequential BYTE_ARRAY length-prefix walk shared by PLAIN
    data pages and dictionary pages: ``count`` (uint32-length, bytes)
    pairs -> (in-buffer starts, lengths, end position)."""
    lens = np.empty(count, np.int64)
    starts = np.empty(count, np.int64)
    p = pos
    for i in range(count):
        if p + 4 > end:
            raise ParquetDecodeException(
                f"truncated BYTE_ARRAY length prefix "
                f"(value {i} of {count})")
        (ln,) = struct.unpack_from("<I", buf, p)
        p += 4
        if p + ln > end:
            raise ParquetDecodeException(
                f"BYTE_ARRAY value {i} ({ln} bytes) overruns page")
        starts[i] = p
        lens[i] = ln
        p += ln
    return starts, lens, p


def decode_plain_byte_array(buf: bytes, pos: int, end: int, count: int
                            ) -> Tuple[np.ndarray, np.ndarray, int]:
    """PLAIN BYTE_ARRAY: the length-prefix walk is sequential by
    format; the character copy is one vectorized gather.  Returns
    (chars uint8, lengths int32, position)."""
    starts, lens, p = _scan_byte_array(buf, pos, end, count)
    chars = gather_ragged(np.frombuffer(buf, np.uint8), starts, lens)
    return chars, lens.astype(np.int32), p


def gather_ragged(src_u8: np.ndarray, starts: np.ndarray,
                  lens: np.ndarray) -> np.ndarray:
    """Concatenate ``src_u8[starts[i]:starts[i]+lens[i]]`` for every i
    as ONE fancy-index gather (no per-value python)."""
    total = int(lens.sum())
    if total == 0:
        return np.empty(0, np.uint8)
    out_off = np.zeros(len(lens), np.int64)
    np.cumsum(lens[:-1], out=out_off[1:])
    flat = (np.repeat(starts - out_off, lens)
            + np.arange(total, dtype=np.int64))
    return src_u8[flat]


class Dictionary:
    """Decoded dictionary page: fixed-width values as one np array, or
    byte-array values as (chars, starts, lens)."""

    __slots__ = ("phys", "values", "chars", "starts", "lens")

    def __init__(self, phys: int, values=None, chars=None, starts=None,
                 lens=None):
        self.phys = phys
        self.values = values
        self.chars = chars
        self.starts = starts
        self.lens = lens

    @property
    def size(self) -> int:
        return (len(self.values) if self.values is not None
                else len(self.lens))


def decode_dictionary_page(data: bytes, phys: int,
                           num_values: int) -> Dictionary:
    """Dictionary pages are PLAIN-encoded values of the column's
    physical type (PLAIN_DICTIONARY in old headers means the same)."""
    if phys == PHYS_BYTE_ARRAY:
        # keep the in-buffer starts for the gather path (chars here is
        # the packed dictionary, starts/lens index into it)
        starts, lens, _ = _scan_byte_array(data, 0, len(data),
                                           num_values)
        return Dictionary(phys, chars=np.frombuffer(data, np.uint8),
                          starts=starts, lens=lens)
    vals, _ = decode_plain_fixed(data, 0, len(data), phys, num_values)
    return Dictionary(phys, values=vals)


def decode_dictionary_indices(data: bytes, pos: int, end: int,
                              count: int) -> np.ndarray:
    """RLE_DICTIONARY data-page payload: one bit-width byte then a
    hybrid run of ``count`` dictionary indices."""
    if count == 0:
        return np.empty(0, np.uint32)
    if pos >= end:
        raise ParquetDecodeException(
            "dictionary-index block missing its bit-width byte")
    bit_width = data[pos]
    idx, _ = decode_hybrid(data, pos + 1, end, int(bit_width), count)
    return idx


def dictionary_take(dic: Dictionary, idx: np.ndarray):
    """Gather dictionary values at ``idx`` — the one-take hot path.
    Fixed width returns an np array; BYTE_ARRAY returns
    (chars, lens)."""
    if dic.size and int(idx.max(initial=0)) >= dic.size:
        raise ParquetDecodeException(
            f"dictionary index {int(idx.max())} out of range "
            f"(dictionary holds {dic.size} values)")
    if dic.values is not None:
        if dic.size == 0 and len(idx):
            raise ParquetDecodeException(
                "data page references an empty dictionary")
        return dic.values[idx]
    if dic.size == 0 and len(idx):
        raise ParquetDecodeException(
            "data page references an empty dictionary")
    lens = dic.lens[idx]
    chars = gather_ragged(dic.chars, dic.starts[idx], lens)
    return chars, lens.astype(np.int32)
