"""Zero-copy Arrow C-data-interface ingest: wrap the buffers of a
pyarrow RecordBatch (or anything exporting ``__arrow_c_array__``) as
device Columns WITHOUT copying — the "hand batches across the JVM
boundary for free" door ("Zero-Cost, Arrow-Enabled Data Interface for
Apache Spark", PAPERS.md).

Zero-copy contract:

  * fixed-width data buffers, string offsets, and string chars become
    numpy views ALIASING the Arrow memory (pointer identity holds:
    ``col.data.__array_interface__['data'][0] ==
    buffer.address + offset * itemsize``).  float64 stays zero-copy —
    the raw-bits convention is a dtype VIEW of the same memory;
    decimal128 likewise reshapes the 16-byte limbs in place.
  * only layout mismatches copy: Arrow's packed validity bitmaps and
    bit-packed booleans expand to the engine's unpacked uint8 masks
    (an O(rows/8 -> rows) expansion, never a value copy).
  * lifetime is safe without the caller keeping the batch alive: every
    numpy view holds a reference to its ``pyarrow.Buffer``, which owns
    the allocation — freeing the RecordBatch (or its handle in the
    shim registry) cannot pull memory out from under a column.
  * the views are HOST residents; the first device op uploads them
    exactly like any host-constructed column.  ``jnp``-level ops
    consume them unchanged (numpy arrays are valid pytree leaves).

Sliced batches (``batch.offset != 0``) stay zero-copy for fixed-width
columns (a numpy slice is pointer arithmetic); sliced STRING columns
would need re-based offsets, so they take one normalizing copy and
are the documented exception.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import DType


class ArrowIngestException(ValueError):
    """Typed ingest refusal: not an Arrow batch, or a column type /
    layout outside the zero-copy contract."""


def _np_view(buf, np_dtype, offset_items: int, count: int):
    """Zero-copy numpy view of ``count`` items of a pyarrow Buffer
    starting ``offset_items`` in (slices of numpy views stay views)."""
    return np.frombuffer(buf, dtype=np_dtype)[
        offset_items:offset_items + count]


def _unpack_bits(buf, offset: int, count: int) -> np.ndarray:
    """Arrow packed LSB-first bits -> unpacked uint8 0/1 (the one
    layout conversion that must copy)."""
    nbytes = (offset + count + 7) // 8
    bits = np.unpackbits(np.frombuffer(buf, np.uint8, nbytes),
                         bitorder="little")
    return bits[offset:offset + count]


def _wrap_column(arr, pa) -> Column:
    t = arr.type
    n = len(arr)
    off = arr.offset
    bufs = arr.buffers()
    validity = None
    if arr.null_count:
        validity = _unpack_bits(bufs[0], off, n)

    if pa.types.is_boolean(t):
        data = _unpack_bits(bufs[1], off, n)
        return Column(dtypes.BOOL8, n, data=data, validity=validity)

    if pa.types.is_timestamp(t):
        if t.unit != "us":
            raise ArrowIngestException(
                f"timestamp unit {t.unit!r} unsupported (Spark "
                f"timestamps are micros)")
        data = _np_view(bufs[1], np.int64, off, n)
        return Column(dtypes.TIMESTAMP_MICROS, n, data=data,
                      validity=validity)

    fixed = _FIXED_TYPES(pa).get(t.id)
    if fixed is not None:
        dt, np_dt = fixed
        data = _np_view(bufs[1], np_dt, off, n)
        if dt.kind == dtypes.Kind.FLOAT64:
            data = data.view(np.uint64)   # raw-bits convention, no copy
        return Column(dt, n, data=data, validity=validity)

    if pa.types.is_decimal128(t):
        limbs = np.frombuffer(bufs[1], np.int32).reshape(-1, 4)[
            off:off + n]
        return Column(dtypes.decimal128(-t.scale), n, data=limbs,
                      validity=validity)

    if pa.types.is_string(t) or pa.types.is_binary(t):
        offs = (_np_view(bufs[1], np.int32, off, n + 1)
                if bufs[1] is not None else np.zeros(1, np.int32))
        chars = np.frombuffer(bufs[2], np.uint8) if bufs[2] is not None \
            else np.empty(0, np.uint8)
        if len(offs) and int(offs[0]) != 0:
            # sliced string column: re-base offsets + trim chars (the
            # documented copy exception — offsets must start at 0)
            base = int(offs[0])
            chars = chars[base:int(offs[-1])].copy()
            offs = (offs - base).astype(np.int32)
        return Column(dtypes.STRING, n, data=chars, validity=validity,
                      offsets=offs if len(offs)
                      else np.zeros(1, np.int32))

    raise ArrowIngestException(
        f"arrow type {t} is outside the zero-copy ingest contract "
        f"(fixed-width, bool, decimal128, utf8/binary)")


def _FIXED_TYPES(pa):
    """pyarrow type id -> (DType, numpy view dtype).  Built lazily so
    the module imports without pyarrow present."""
    global _FIXED_CACHE
    if _FIXED_CACHE is None:
        _FIXED_CACHE = {
            pa.int8().id: (dtypes.INT8, np.int8),
            pa.int16().id: (dtypes.INT16, np.int16),
            pa.int32().id: (dtypes.INT32, np.int32),
            pa.int64().id: (dtypes.INT64, np.int64),
            pa.uint8().id: (dtypes.UINT8, np.uint8),
            pa.uint16().id: (dtypes.UINT16, np.uint16),
            pa.uint32().id: (dtypes.UINT32, np.uint32),
            pa.uint64().id: (dtypes.UINT64, np.uint64),
            pa.float32().id: (dtypes.FLOAT32, np.float32),
            pa.float64().id: (dtypes.FLOAT64, np.float64),
            pa.date32().id: (dtypes.TIMESTAMP_DAYS, np.int32),
        }
    return _FIXED_CACHE


_FIXED_CACHE = None


def ingest(obj) -> Tuple[List[Column], List[str]]:
    """Wrap an Arrow batch as device columns without copying.

    Accepts a ``pyarrow.RecordBatch``, a single-chunk
    ``pyarrow.Table``, or ANY object exporting the Arrow C data
    interface (``__arrow_c_array__`` — the PyCapsule protocol a
    JVM/Spark caller's FFI surface speaks); the C-interface import is
    itself zero-copy.  Returns ``(columns, names)``."""
    try:
        import pyarrow as pa
    except ImportError as e:  # pragma: no cover - image ships pyarrow
        raise ArrowIngestException(
            f"arrow ingest requires pyarrow: {e}") from e
    if isinstance(obj, pa.Table):
        # refuse BEFORE any chunk combining: combine_chunks() would
        # deep-copy a multi-chunk table, silently breaking the
        # pointer-identity contract this door exists to keep
        if any(obj.column(i).num_chunks > 1
               for i in range(obj.num_columns)):
            raise ArrowIngestException(
                "multi-chunk Table cannot ingest zero-copy; hand over "
                "RecordBatches individually")
        batches = obj.to_batches()
        batch = batches[0] if batches else pa.record_batch(
            [pa.array([], f.type) for f in obj.schema], obj.schema)
    elif isinstance(obj, pa.RecordBatch):
        batch = obj
    elif hasattr(obj, "__arrow_c_array__"):
        batch = pa.record_batch(obj)   # zero-copy C-interface import
    else:
        raise ArrowIngestException(
            f"cannot ingest {type(obj).__name__}: expected a pyarrow "
            f"RecordBatch/Table or an __arrow_c_array__ exporter")
    cols = [_wrap_column(batch.column(i), pa)
            for i in range(batch.num_columns)]
    return cols, list(batch.schema.names)


def ingest_table(obj):
    """:func:`ingest` packaged as a named :class:`Table`."""
    from spark_rapids_tpu.columns.table import Table
    cols, names = ingest(obj)
    return Table(cols, names=names)
