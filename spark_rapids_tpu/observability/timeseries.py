"""Windowed time-series telemetry over the metrics registry
(ISSUE 16 tentpole, subsystem 1 of 3).

Every registry instrument is cumulative-since-boot — the right shape
for Prometheus scrapes and post-mortem dumps, but useless for "what
is tenant A's p99 *right now*".  The ``TimeseriesSampler`` closes the
gap without touching the hot path: a periodic ``tick()`` (driven by
the existing ``utils.telemetry.Monitor`` thread in production, by
explicit calls with an injected clock in tests) snapshots the
registry and appends ONE bounded ring entry holding the *delta*
since the previous tick:

  * counters    -> per-window increments (rate = delta / dur_s);
  * gauges      -> last value at tick time;
  * histograms  -> per-window ``bucket_counts``/sum/count deltas, so
                   percentiles estimated from a window are *recent*,
                   not diluted by everything since boot.

Conservation invariant (the fleet-reconciliation gate): the first
tick's delta is the full since-boot total, so the sum of every
window's counter deltas equals the registry's final cumulative value
exactly — rank 0's merged fleet timeseries can be checked against
each rank's own ``metrics_rank{r}.json`` dump to the byte.

``FleetTimeseries`` is the rank-0 side: workers publish their ring
as JSON snapshots (CTRL frames over the shuffle sockets, or dump-dir
files for the launcher tier) and the merger folds them keyed by
(epoch, rank, window seq) — snapshots from a stale fleet epoch are
fenced by the PR-14 membership machinery, re-delivered windows are
deduped by sequence number.

Disabled cost: ``maybe_tick``/``tick`` return after ONE attribute
read when ``enabled`` is False — same switch discipline as every
other observability hook (gated by scripts/slo_smoke.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple


def histogram_quantile(buckets: List[float], bucket_counts: List[int],
                       q: float) -> float:
    """Estimate the q-quantile (0..1) from PER-BUCKET (non-cumulative)
    counts — the registry snapshot's and the window record's shared
    ``bucket_counts`` format.  Linear interpolation within the target
    bucket; the +Inf bucket clamps to the largest finite bound (an
    underestimate by construction).  Kept semantically identical to
    ``tools.metrics_report.histogram_quantile`` — tools must not be
    imported from here (they import us)."""
    total = sum(bucket_counts)
    if total <= 0:
        return 0.0
    target = q * total
    cum = 0.0
    for i, n in enumerate(bucket_counts):
        if cum + n >= target and n > 0:
            if i >= len(buckets):          # +Inf bucket
                return float(buckets[-1]) if buckets else 0.0
            lo = float(buckets[i - 1]) if i > 0 else 0.0
            hi = float(buckets[i])
            return lo + (hi - lo) * (target - cum) / n
        cum += n
    return float(buckets[-1]) if buckets else 0.0


def _series_key(labels) -> str:
    """Stable flat key for a labelled series inside a window record
    (JSON dict keys must be strings; label values never contain the
    separator — the registry only ever sees identifier-ish values and
    the ``__other__`` overflow key)."""
    return "|".join(str(v) for v in labels)


class TimeseriesSampler:
    """Bounded ring of per-window registry delta snapshots.

    ``tick()`` is cheap but not free (a full registry snapshot), so it
    runs at window granularity off the Monitor thread — never inline
    with query work.  All public methods are safe to call concurrently
    with ticks (one lock around ring mutation/reads; the registry has
    its own per-series locks)."""

    def __init__(self, registry, *, window_s: float = 5.0,
                 capacity: int = 120,
                 clock: Callable[[], float] = time.monotonic,
                 wall_clock: Callable[[], float] = time.time,
                 families: Optional[Tuple[str, ...]] = None,
                 on_tick: Optional[Callable[[int], None]] = None):
        self.enabled = False
        self.registry = registry
        self.window_s = float(window_s)
        self.capacity = int(capacity)
        self.families = tuple(families) if families else None
        self.on_tick = on_tick
        self._clock = clock
        self._wall_clock = wall_clock
        self._lock = threading.Lock()
        self._windows: deque = deque(maxlen=self.capacity)
        self._prev: Dict[str, dict] = {}
        self._seq = 0
        self._last_tick: Optional[float] = None

    # ------------------------------------------------------- sampling

    def _take(self) -> Dict[str, dict]:
        """Selective registry fold: with a ``families`` watch list only
        those families are snapshotted (``family_snapshot`` holds each
        family's series locks one at a time), else the whole registry."""
        if self.families is None:
            return self.registry.snapshot()
        out: Dict[str, dict] = {}
        for name in self.families:
            fam = self.registry.family_snapshot(name)
            if fam is not None:
                out[name] = fam
        return out

    @staticmethod
    def _delta_family(fam: dict, prev: Optional[dict]) -> Optional[dict]:
        """One family's window contribution, or None when nothing moved.
        Counter/histogram series that did not change this window are
        dropped from the record (they contribute zero to every sum);
        gauges always record their last value."""
        kind = fam.get("kind")
        prev_series: Dict[str, dict] = {}
        if prev is not None:
            for s in prev.get("series", []):
                prev_series[_series_key(s["labels"])] = s
        if kind == "gauge":
            vals = {_series_key(s["labels"]): s["value"]
                    for s in fam.get("series", [])}
            return {"kind": kind, "values": vals} if vals else None
        if kind == "counter":
            vals = {}
            for s in fam.get("series", []):
                key = _series_key(s["labels"])
                p = prev_series.get(key)
                d = s["value"] - (p["value"] if p else 0)
                if d:
                    vals[key] = d
            return {"kind": kind, "values": vals} if vals else None
        if kind == "histogram":
            series = {}
            for s in fam.get("series", []):
                key = _series_key(s["labels"])
                p = prev_series.get(key)
                if p is None:
                    bc = list(s["bucket_counts"])
                    dsum, dcount = s["sum"], s["count"]
                else:
                    bc = [a - b for a, b in
                          zip(s["bucket_counts"], p["bucket_counts"])]
                    dsum = s["sum"] - p["sum"]
                    dcount = s["count"] - p["count"]
                if dcount or dsum or any(bc):
                    series[key] = {"bucket_counts": bc, "sum": dsum,
                                   "count": dcount}
            if not series:
                return None
            return {"kind": kind, "buckets": list(fam.get("buckets", [])),
                    "series": series}
        return None

    def tick(self, now: Optional[float] = None) -> Optional[dict]:
        """Append one window: the registry delta since the previous
        tick.  Returns the window record (also retained in the ring),
        or None when the sampler is disabled."""
        if not self.enabled:
            return None
        t0 = time.perf_counter_ns()
        now = self._clock() if now is None else now
        snap = self._take()
        with self._lock:
            last = self._last_tick
            dur = (now - last) if last is not None else self.window_s
            window = {
                "window": self._seq,
                "t_unix_ms": int(self._wall_clock() * 1000),
                "dur_s": max(float(dur), 1e-9),
                "counters": {}, "gauges": {}, "histograms": {},
            }
            for name, fam in snap.items():
                d = self._delta_family(fam, self._prev.get(name))
                if d is None:
                    continue
                kind = d.pop("kind")
                if kind == "counter":
                    window["counters"][name] = d["values"]
                elif kind == "gauge":
                    window["gauges"][name] = d["values"]
                else:
                    window["histograms"][name] = d
            self._prev = snap
            self._windows.append(window)
            self._seq += 1
            self._last_tick = now
        if self.on_tick is not None:
            self.on_tick(time.perf_counter_ns() - t0)
        return window

    def maybe_tick(self, now: Optional[float] = None) -> Optional[dict]:
        """Tick only when a full window has elapsed — the Monitor
        thread calls this every sample period regardless of the
        configured window.  One attribute read when disabled."""
        if not self.enabled:
            return None
        now = self._clock() if now is None else now
        if self._last_tick is not None and \
                now - self._last_tick < self.window_s:
            return None
        return self.tick(now)

    # --------------------------------------------------------- queries

    def windows(self, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            ws = list(self._windows)
        return ws if n is None else ws[-n:]

    def last_tick_age_s(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds since the last tick, None before the first one —
        the liveness signal behind ``srt_monitor_last_sample_age_s``."""
        if self._last_tick is None:
            return None
        now = self._clock() if now is None else now
        return max(0.0, now - self._last_tick)

    def recent_histogram(self, family: str, key: Optional[str] = None,
                         n: Optional[int] = None):
        """Fold the last ``n`` windows' histogram deltas for one family
        (one series ``key``, or all series summed when None).  Returns
        ``(buckets, bucket_counts, sum, count)`` — feed straight into
        ``histogram_quantile`` for a *recent* percentile — or None when
        the family never appeared."""
        buckets: Optional[List[float]] = None
        counts: Optional[List[float]] = None
        total_sum = 0.0
        total_count = 0
        for w in self.windows(n):
            fam = w["histograms"].get(family)
            if fam is None:
                continue
            if buckets is None:
                buckets = fam["buckets"]
                counts = [0.0] * (len(buckets) + 1)
            for skey, s in fam["series"].items():
                if key is not None and skey != key:
                    continue
                for i, c in enumerate(s["bucket_counts"]):
                    counts[i] += c
                total_sum += s["sum"]
                total_count += s["count"]
        if buckets is None:
            return None
        return buckets, counts, total_sum, total_count

    def rate(self, family: str, key: Optional[str] = None,
             n: Optional[int] = None) -> float:
        """Recent per-second rate of a counter family (one series or
        all series summed) over the last ``n`` windows."""
        total = 0.0
        dur = 0.0
        for w in self.windows(n):
            dur += w["dur_s"]
            vals = w["counters"].get(family)
            if not vals:
                continue
            if key is None:
                total += sum(vals.values())
            else:
                total += vals.get(key, 0)
        return total / dur if dur > 0 else 0.0

    def snapshot(self) -> dict:
        """JSON-able ring dump — the unit FleetTimeseries merges and
        ``timeseries_rank{r}.json`` persists."""
        return {"window_s": self.window_s, "capacity": self.capacity,
                "windows": self.windows()}

    def reset(self) -> None:
        with self._lock:
            self._windows.clear()
            self._prev = {}
            self._seq = 0
            self._last_tick = None


def sum_counter_windows(windows: List[dict], family: str
                        ) -> Dict[str, float]:
    """Fold a window list's counter deltas for one family into
    per-series totals — the reconciliation primitive: over a rank's
    FULL ring this equals the rank's cumulative registry value."""
    out: Dict[str, float] = {}
    for w in windows:
        for key, d in (w.get("counters", {}).get(family) or {}).items():
            out[key] = out.get(key, 0) + d
    return out


class FleetTimeseries:
    """Rank 0's merged view of every worker's windowed snapshots.

    ``offer()`` is the single entry point for both transports (CTRL
    frames and dump-dir polling) and is idempotent: re-delivered
    windows are deduped per (rank, window seq), and a snapshot carrying
    a fleet epoch older than the newest one seen is fenced outright —
    a zombie pre-rebalance worker cannot smear its stale tenant stats
    into the live view (the same staleness rule the PR-14 data frames
    obey)."""

    def __init__(self, capacity_per_rank: int = 240):
        self.capacity_per_rank = int(capacity_per_rank)
        self._lock = threading.Lock()
        self._epoch = 0
        self._ranks: Dict[int, dict] = {}

    def offer(self, snap: dict) -> str:
        """Fold one per-rank snapshot ``{"rank", "epoch", "window_s",
        "windows": [...], ...}``.  Returns "merged", "dup" (no new
        windows) or "stale_epoch" (fenced)."""
        rank = int(snap.get("rank", -1))
        epoch = int(snap.get("epoch", 0))
        with self._lock:
            if epoch < self._epoch:
                return "stale_epoch"
            self._epoch = max(self._epoch, epoch)
            st = self._ranks.setdefault(rank, {
                "last_seq": -1, "epoch": epoch,
                "windows": deque(maxlen=self.capacity_per_rank),
                "meta": {},
            })
            st["epoch"] = epoch
            for k, v in snap.items():
                if k not in ("rank", "epoch", "windows"):
                    st["meta"][k] = v
            fresh = 0
            for w in snap.get("windows", []):
                seq = int(w.get("window", -1))
                if seq <= st["last_seq"]:
                    continue
                st["windows"].append(w)
                st["last_seq"] = seq
                fresh += 1
            return "merged" if fresh else "dup"

    @property
    def epoch(self) -> int:
        return self._epoch

    def ranks(self) -> List[int]:
        with self._lock:
            return sorted(self._ranks)

    def rank_windows(self, rank: int) -> List[dict]:
        with self._lock:
            st = self._ranks.get(rank)
            return list(st["windows"]) if st else []

    def merged(self) -> dict:
        """One JSON-able fleet view keyed by epoch/rank — the shape
        srt-top renders and the fleet-reconciliation gate inspects."""
        with self._lock:
            ranks = {}
            for rank in sorted(self._ranks):
                st = self._ranks[rank]
                ranks[str(rank)] = {
                    "epoch": st["epoch"],
                    "last_window": st["last_seq"],
                    "windows": list(st["windows"]),
                    "meta": dict(st["meta"]),
                }
            return {"epoch": self._epoch, "ranks": ranks}

    def totals(self, family: str) -> Dict[str, Dict[str, float]]:
        """Per-rank counter totals for one family over every retained
        window — compare against each rank's own registry dump."""
        out: Dict[str, Dict[str, float]] = {}
        for rank in self.ranks():
            out[str(rank)] = sum_counter_windows(
                self.rank_windows(rank), family)
        return out
