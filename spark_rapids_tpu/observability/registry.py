"""Process-wide metrics registry (counters, gauges, histograms).

The reference scatters its numbers across three surfaces — CUPTI
activity records (profiler/), NVML polled gauges (NVMLMonitor.java),
and the RmmSpark getAndReset* per-task counters
(SparkResourceAdaptorJni.cpp) — each with its own consumer.  This
registry is the single spine those islands feed here: named metric
families with small bounded label sets, safe under concurrent writers,
exposable as Prometheus text format or a JSON snapshot.

Design constraints (ISSUE 1 tentpole):

  * near-zero cost when disabled: every mutator first reads one module
    bool (`_enabled` via the owning registry) and returns — no locks,
    no allocation on the fast path;
  * bounded label sets: a family caps its distinct label tuples
    (default 64); once full, updates with unseen tuples collapse into a
    single ``__other__`` series and `dropped_series` counts those
    collapsed updates (unseen tuples are deliberately not remembered —
    that map is exactly what must not grow), so a cardinality bug can
    never make exposition unbounded;
  * thread-safe: one lock per child series (updates are a handful of
    integer ops), one lock per family for child creation.
"""

from __future__ import annotations

import json
import threading

from spark_rapids_tpu.analysis.lockdep import make_lock
from typing import Dict, List, Optional, Sequence, Tuple

# Latency buckets in nanoseconds: 1us .. 10s decades, the range host-side
# op brackets actually land in (sub-us brackets are measurement noise).
DEFAULT_LATENCY_BUCKETS_NS = (
    1_000, 10_000, 100_000, 1_000_000, 10_000_000,
    100_000_000, 1_000_000_000, 10_000_000_000)

_OTHER = "__other__"


def _fmt_value(v) -> str:
    """Prometheus sample value: integers render without exponent."""
    if isinstance(v, float) and not v.is_integer():
        return repr(v)
    return str(int(v))


def _escape_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
                 .replace("\n", "\\n")


class _Series:
    """One labelled child: a value cell (counter/gauge) or histogram
    state.  All mutation under its own small lock."""

    __slots__ = ("lock", "value", "bucket_counts", "sum", "count")

    def __init__(self, n_buckets: int = 0):
        self.lock = make_lock("metrics.series")
        self.value = 0
        if n_buckets:
            self.bucket_counts = [0] * (n_buckets + 1)  # +inf tail
            self.sum = 0
            self.count = 0


class _Family:
    """Base for one named metric family with a declared label schema."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 labels: Sequence[str], max_series: int):
        self.registry = registry
        self.name = name
        self.help = help
        self.label_names = tuple(labels)
        self.max_series = max_series
        self.dropped_series = 0
        self._lock = make_lock("metrics.family")
        self._children: Dict[Tuple[str, ...], _Series] = {}

    # -- child management --------------------------------------------------

    def _n_buckets(self) -> int:
        return 0

    def _child(self, labels: Optional[Tuple[str, ...]]) -> _Series:
        key = tuple(str(v) for v in labels) if labels else ()
        if len(key) != len(self.label_names):
            raise ValueError(
                f"{self.name}: got {len(key)} label values for "
                f"{len(self.label_names)} declared labels")
        c = self._children.get(key)
        if c is not None:
            return c
        with self._lock:
            c = self._children.get(key)
            if c is None:
                if key and len(self._children) >= self.max_series:
                    # bounded label set: collapse into the overflow
                    # series rather than growing without limit (counts
                    # every collapsed update, not distinct tuples —
                    # remembering tuples is the growth being prevented)
                    self.dropped_series += 1
                    key = (_OTHER,) * len(self.label_names)
                    c = self._children.get(key)
                    if c is not None:
                        return c
                c = _Series(self._n_buckets())
                self._children[key] = c
        return c

    def reset(self):
        with self._lock:
            self._children.clear()
            self.dropped_series = 0

    # -- exposition --------------------------------------------------------

    def _label_str(self, key: Tuple[str, ...], extra: str = "") -> str:
        parts = [f'{n}="{_escape_label(v)}"'
                 for n, v in zip(self.label_names, key)]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def expose(self, out: List[str]):
        out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} {self.kind}")
        with self._lock:
            items = sorted(self._children.items())
        for key, c in items:
            out.append(
                f"{self.name}{self._label_str(key)} {_fmt_value(c.value)}")

    def snapshot(self) -> dict:
        with self._lock:
            items = sorted(self._children.items())
        return {
            "kind": self.kind, "help": self.help,
            "labels": list(self.label_names),
            "series": [{"labels": list(k), "value": c.value}
                       for k, c in items],
        }


class Counter(_Family):
    kind = "counter"

    def inc(self, value=1, labels: Optional[Tuple[str, ...]] = None):
        if not self.registry.enabled:
            return
        c = self._child(labels)
        with c.lock:
            c.value += value


class Gauge(_Family):
    kind = "gauge"

    def set(self, value, labels: Optional[Tuple[str, ...]] = None):
        if not self.registry.enabled:
            return
        c = self._child(labels)
        with c.lock:
            c.value = value

    def add(self, value, labels: Optional[Tuple[str, ...]] = None):
        if not self.registry.enabled:
            return
        c = self._child(labels)
        with c.lock:
            c.value += value


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, registry, name, help, labels, max_series,
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_NS):
        super().__init__(registry, name, help, labels, max_series)
        self.buckets = tuple(sorted(buckets))

    def _n_buckets(self) -> int:
        return len(self.buckets)

    def observe(self, value, labels: Optional[Tuple[str, ...]] = None):
        if not self.registry.enabled:
            return
        c = self._child(labels)
        i = 0
        for b in self.buckets:           # ~8 entries: linear scan wins
            if value <= b:
                break
            i += 1
        with c.lock:
            c.bucket_counts[i] += 1
            c.sum += value
            c.count += 1

    def expose(self, out: List[str]):
        out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} histogram")
        with self._lock:
            items = sorted(self._children.items())
        for key, c in items:
            # snapshot under the series lock: a torn read across a
            # concurrent observe would scrape count != sum-of-buckets
            with c.lock:
                bucket_counts = list(c.bucket_counts)
                total, n_obs = c.sum, c.count
            cum = 0
            for b, n in zip(self.buckets, bucket_counts):
                cum += n
                le = 'le="%s"' % _fmt_value(b)
                out.append(f"{self.name}_bucket"
                           f"{self._label_str(key, le)} {cum}")
            cum += bucket_counts[-1]
            inf = 'le="+Inf"'
            out.append(f"{self.name}_bucket"
                       f"{self._label_str(key, inf)} {cum}")
            out.append(f"{self.name}_sum{self._label_str(key)} "
                       f"{_fmt_value(total)}")
            out.append(f"{self.name}_count{self._label_str(key)} "
                       f"{n_obs}")

    def _series_state(self, c: _Series) -> dict:
        with c.lock:
            return {"bucket_counts": list(c.bucket_counts),
                    "sum": c.sum, "count": c.count}

    def snapshot(self) -> dict:
        with self._lock:
            items = sorted(self._children.items())
        return {
            "kind": "histogram", "help": self.help,
            "labels": list(self.label_names),
            "buckets": list(self.buckets),
            "series": [{"labels": list(k), **self._series_state(c)}
                       for k, c in items],
        }


class MetricsRegistry:
    """Named metric families; the process normally holds ONE of these
    (spark_rapids_tpu.observability.METRICS)."""

    def __init__(self, enabled: bool = False, max_series: int = 64):
        self.enabled = enabled
        self.default_max_series = max_series
        self._lock = make_lock("metrics.registry")
        self._families: Dict[str, _Family] = {}

    # -- family creation (idempotent: same name returns same family) ------

    def _family(self, cls, name, help, labels, max_series, **kw):
        with self._lock:
            f = self._families.get(name)
            if f is not None:
                if type(f) is not cls:
                    raise ValueError(
                        f"metric {name} already registered as {f.kind}")
                return f
            f = cls(self, name, help, labels,
                    max_series or self.default_max_series, **kw)
            self._families[name] = f
            return f

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = (),
                max_series: int = 0) -> Counter:
        return self._family(Counter, name, help, labels, max_series)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = (),
              max_series: int = 0) -> Gauge:
        return self._family(Gauge, name, help, labels, max_series)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_NS,
                  max_series: int = 0) -> Histogram:
        return self._family(Histogram, name, help, labels, max_series,
                            buckets=buckets)

    # -- lifecycle ---------------------------------------------------------

    def reset(self):
        """Zero every family's series (families stay registered so
        module-level instrument handles remain valid)."""
        with self._lock:
            fams = list(self._families.values())
        for f in fams:
            f.reset()

    # -- exposition --------------------------------------------------------

    def expose_text(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        out: List[str] = []
        with self._lock:
            fams = sorted(self._families.items())
        for _, f in fams:
            f.expose(out)
        return "\n".join(out) + ("\n" if out else "")

    def snapshot(self) -> dict:
        with self._lock:
            fams = sorted(self._families.items())
        return {name: f.snapshot() for name, f in fams}

    def family_snapshot(self, name: str) -> Optional[dict]:
        """One family's snapshot (None when unregistered) — consumers
        that diff a handful of named families (the query profiler)
        must not pay a whole-registry walk per read."""
        with self._lock:
            f = self._families.get(name)
        return f.snapshot() if f is not None else None

    def snapshot_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)
