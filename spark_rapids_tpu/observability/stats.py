"""Data-statistics plane (ISSUE 20 tentpole).

Six observability PRs made *time* fully observable; nothing observed
the *data*.  This module is the cardinality & statistics observatory:

  * vectorized one-pass sketches over device columns — a KMV
    distinct-count sketch (bottom-k of a splitmix64 hash), a
    space-saving heavy-hitter sketch, min/max/null-fraction, and an
    equi-width histogram — all plain numpy over the column's host
    view, no extra device dispatches;
  * the :class:`StatsCollector` singleton (``observability.STATS``)
    that folds per-node observed row counts tapped out of fused
    stages (plan/compiler.py) into per-node actuals, joins them
    against registered *estimates* (Parquet footer row counts,
    catalog generator sizes), and fires the misestimate sentinel when
    actual/estimate divergence exceeds
    ``SPARK_RAPIDS_TPU_STATS_MISEST_RATIO``;
  * the persistent :class:`StatsStore`, keyed by (plan digest, node
    id, source ingest-epoch vector from perf/result_cache) with the
    same file-cache discipline as perf/calibrate.py (atomic
    tmp+replace writes, TTL, {} on torn reads) — actuals and sketches
    survive across processes, and a source's ingest-epoch bump
    naturally starts a fresh key.

Cost discipline (the tracer's noop contract): with
``SPARK_RAPIDS_TPU_STATS`` off every hook is ONE attribute read —
the compiler checks ``STATS.enabled`` before building any
observation, and :func:`StatsCollector.note_stage` is never reached.

The module is dependency-light on purpose: the metric/journal/trigger
fan-out is injected by ``observability/__init__`` through the
``on_observation``/``on_misestimate``/``on_sketch`` callbacks (the
profiler's ``enabled_ref`` pattern), so tests build isolated
collectors and the layering rule (instrumented layers import
observability, never the reverse) holds.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from spark_rapids_tpu.analysis.lockdep import make_rlock

STATS_VERSION = 1

# sketch defaults: KMV bottom-k (relative NDV error ~ 1/sqrt(k-1),
# ~1.6% at 4096), space-saving counter budget, histogram bins
KMV_K = 4096
HH_CAPACITY = 64
HIST_BINS = 16

DEFAULT_MISEST_RATIO = 8.0
DEFAULT_TTL_S = 7 * 86400.0

# journal/profile payloads stay bounded: a stage with hundreds of
# nodes still reports at most this many per-node rows
_MAX_NODES_REPORTED = 64


def misest_ratio() -> float:
    """Sentinel threshold (dynamic read, like fusion_mode): actual
    vs estimate divergence past this ratio fires the misestimate
    chain."""
    try:
        return float(os.environ.get(
            "SPARK_RAPIDS_TPU_STATS_MISEST_RATIO",
            DEFAULT_MISEST_RATIO))
    except ValueError:
        return DEFAULT_MISEST_RATIO


def sketch_row_cap() -> int:
    """Rows a single sketch pass will look at (head slice): bounds
    host-copy cost on huge columns; the cap is generous because the
    pass is one-shot per (stage, input, epoch vector)."""
    try:
        return int(os.environ.get(
            "SPARK_RAPIDS_TPU_STATS_SKETCH_ROWS", str(1 << 20)))
    except ValueError:
        return 1 << 20


# ------------------------------------------------------------------ hashing


def _hash64(values: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 over a column's bit pattern — the KMV
    sketch's uniform hash.  Floats hash their IEEE bits (NaN patterns
    collapse to one canonical NaN), non-numeric dtypes hash through
    python ``hash`` per UNIQUE value (one pass over the distinct set,
    not the column)."""
    a = np.asarray(values)
    if a.dtype.kind == "f":
        a = a.astype(np.float64, copy=False)
        a = np.where(np.isnan(a), np.float64("nan"), a)
        a = a.view(np.uint64)
    elif a.dtype.kind in "iub":
        a = a.astype(np.int64, copy=False).view(np.uint64)
    else:
        u, inv = np.unique(a.astype(str), return_inverse=True)
        hu = np.fromiter(
            (hash(x) & 0xFFFFFFFFFFFFFFFF for x in u),
            dtype=np.uint64, count=len(u))
        a = hu[inv]
    z = a + np.uint64(0x9E3779B97F4A7C15)
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


# ----------------------------------------------------------------- sketches


def kmv_sketch(values, k: int = KMV_K) -> dict:
    """KMV (bottom-k) distinct-count sketch.  Below ``k`` distinct
    hashes the answer is EXACT; past it the k-th smallest hash
    position estimates NDV as ``(k-1) / U_(k)`` with ``U_(k)`` the
    normalized k-th minimum — standard error ~ ``1/sqrt(k-2)``."""
    h = np.unique(_hash64(values))
    if h.size < k:
        return {"k": int(k), "exact": True, "ndv": int(h.size)}
    kth = np.partition(h, k - 1)[k - 1]
    u = (float(kth) + 1.0) / float(2 ** 64)
    ndv = (k - 1) / u if u > 0 else float(h.size)
    return {"k": int(k), "exact": False, "kth": int(kth),
            "ndv": int(round(ndv))}


def heavy_hitter_sketch(values, capacity: int = HH_CAPACITY) -> dict:
    """Space-saving heavy-hitter sketch: at most ``capacity`` live
    counters; a new value at capacity evicts the minimum counter and
    inherits its count as overestimation error.  Guarantees: every
    value with true frequency > n/capacity is present, and each
    reported ``count`` overestimates the true one by at most ``err``.
    The pass is vectorized per chunk (np.unique folds duplicates
    before the counter merge touches python)."""
    a = np.asarray(values).reshape(-1)
    counters: Dict[object, List[int]] = {}   # value -> [count, err]
    n = int(a.size)
    chunk = 1 << 16
    for lo in range(0, n, chunk):
        u, c = np.unique(a[lo:lo + chunk], return_counts=True)
        for v, cnt in zip(u.tolist(), c.tolist()):
            slot = counters.get(v)
            if slot is not None:
                slot[0] += cnt
            elif len(counters) < capacity:
                counters[v] = [cnt, 0]
            else:
                m = min(counters, key=lambda x: counters[x][0])
                floor = counters[m][0]
                del counters[m]
                counters[v] = [floor + cnt, floor]
    items = sorted(
        ([v, int(cc[0]), int(cc[1])] for v, cc in counters.items()),
        key=lambda it: (-it[1], str(it[0])))
    return {"capacity": int(capacity), "n": n, "items": items}


def heavy_hitter_topk(sketch: dict, k: int) -> list:
    """Top-``k`` values by estimated count (the sketch already sorts
    descending)."""
    return [it[0] for it in sketch.get("items", [])[:k]]


def histogram_sketch(values, bins: int = HIST_BINS) -> Optional[dict]:
    """Equi-width histogram over the finite values (exact counts —
    equi-width needs only min/max, known after the same pass).  None
    for non-numeric columns or all-NaN input."""
    a = np.asarray(values).reshape(-1)
    if a.dtype.kind not in "iufb" or a.size == 0:
        return None
    a = a.astype(np.float64, copy=False)
    a = a[np.isfinite(a)]
    if a.size == 0:
        return None
    lo, hi = float(a.min()), float(a.max())
    if lo == hi:
        return {"bins": 1, "lo": lo, "hi": hi, "counts": [int(a.size)]}
    counts, _edges = np.histogram(a, bins=bins, range=(lo, hi))
    return {"bins": int(bins), "lo": lo, "hi": hi,
            "counts": [int(c) for c in counts]}


def column_stats(values, *, kmv_k: int = KMV_K,
                 hh_capacity: int = HH_CAPACITY,
                 bins: int = HIST_BINS,
                 max_rows: Optional[int] = None) -> dict:
    """One-pass column statistics: rows, null fraction (NaN for
    floats), min/max, KMV NDV, heavy hitters, equi-width histogram.
    ``max_rows`` head-slices the column first (the sketch-cost cap);
    ``rows`` still reports the slice actually observed."""
    a = np.asarray(values).reshape(-1)
    if max_rows is not None and a.size > max_rows:
        a = a[:max_rows]
    rows = int(a.size)
    null_frac = 0.0
    mn = mx = None
    if a.dtype.kind == "f" and rows:
        nan = int(np.isnan(a).sum())
        null_frac = nan / rows
        fin = a[np.isfinite(a)]
        if fin.size:
            mn, mx = float(fin.min()), float(fin.max())
    elif a.dtype.kind in "iub" and rows:
        mn, mx = int(a.min()), int(a.max())
    kmv = kmv_sketch(a, k=kmv_k) if rows else \
        {"k": kmv_k, "exact": True, "ndv": 0}
    return {
        "rows": rows,
        "null_frac": round(null_frac, 6),
        "min": mn,
        "max": mx,
        "ndv": int(kmv["ndv"]),
        "ndv_exact": bool(kmv.get("exact")),
        "kmv": kmv,
        "heavy_hitters": heavy_hitter_sketch(a, capacity=hh_capacity)
        if rows else {"capacity": hh_capacity, "n": 0, "items": []},
        "histogram": histogram_sketch(a, bins=bins),
    }


# --------------------------------------------------------------- stats store


def store_path() -> str:
    """Persistent stats file (calibrate.py's cache_path contract):
    env-pointed, tempdir default, empty string disables the file
    layer (the process cache still works)."""
    return os.environ.get(
        "SPARK_RAPIDS_TPU_STATS_STORE",
        os.path.join(tempfile.gettempdir(), "srt_stats_store.json"))


def _load(path: str) -> dict:
    if not path:
        return {}
    try:
        with open(path) as f:
            d = json.load(f)
        return d if isinstance(d, dict) else {}
    except (OSError, ValueError):
        return {}


def _save(path: str, d: dict) -> None:
    """Atomic tmp+replace (the calibrate.py discipline): a reader
    racing a truncate-write would see torn JSON, read {}, and the
    next save would wipe every persisted actual."""
    if not path:
        return
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(d, f)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _ttl() -> float:
    try:
        return float(os.environ.get(
            "SPARK_RAPIDS_TPU_STATS_STORE_TTL", DEFAULT_TTL_S))
    except ValueError:
        return DEFAULT_TTL_S


def epoch_signature(epochs: Dict[str, int]) -> str:
    """Canonical ingest-epoch vector: part of every store key, so a
    source's epoch bump (perf/result_cache.note_ingest) retires the
    old actuals instead of averaging stale data in."""
    return ",".join(f"{k}:{int(v)}" for k, v in sorted(epochs.items()))


class StatsStore:
    """Persistent per-node actuals + sketches, keyed
    ``plan_digest|node|epoch_signature``.  Process dict for the hot
    path, JSON file (atomic writes, TTL) for cross-process reuse."""

    def __init__(self, path_fn: Callable[[], str] = store_path):
        self._path_fn = path_fn
        self._lock = make_rlock("observability.stats_store")
        self._proc: Dict[str, dict] = {}
        self._loaded = False

    @staticmethod
    def key(plan_digest: str, node: str,
            epochs: Dict[str, int]) -> str:
        return f"{plan_digest}|{node}|{epoch_signature(epochs)}"

    def _load_once_locked(self) -> None:
        if self._loaded:
            return
        self._loaded = True
        now = time.time()  # srt-lint: disable=SRT005 wall-clock TTL of the on-disk store; expiry never folds into a digest or cache key
        for k, rec in _load(self._path_fn()).items():
            if not isinstance(rec, dict):
                continue
            try:
                fresh = now - float(rec.get("t", 0)) < _ttl()
            except (TypeError, ValueError):
                fresh = False
            if fresh:
                self._proc[k] = rec

    def record(self, plan_digest: str, node: str,
               epochs: Dict[str, int], rows: int,
               sketch: Optional[dict] = None,
               persist: bool = True) -> dict:
        """Fold one observation; returns the merged record
        ({rows, calls, sketch?})."""
        k = self.key(plan_digest, node, epochs)
        with self._lock:
            self._load_once_locked()
            rec = self._proc.get(k)
            if rec is None:
                rec = {"rows": int(rows), "calls": 0}
            rec["rows"] = int(rows)
            rec["calls"] = int(rec.get("calls", 0)) + 1
            if sketch is not None:
                rec["sketch"] = sketch
            # srt-lint: disable=SRT005 wall-clock stamp read back only by the TTL check; never part of a key
            rec["t"] = time.time()
            self._proc[k] = rec
            if persist:
                path = self._path_fn()
                d = _load(path)
                d[k] = rec
                _save(path, d)
            return dict(rec)

    def lookup(self, plan_digest: str, node: str,
               epochs: Dict[str, int]) -> Optional[dict]:
        k = self.key(plan_digest, node, epochs)
        with self._lock:
            self._load_once_locked()
            rec = self._proc.get(k)
            return dict(rec) if rec is not None else None

    def clear(self) -> int:
        """Drop process entries AND the file (operator reset door)."""
        with self._lock:
            n = len(self._proc)
            self._proc.clear()
            self._loaded = True
            _save(self._path_fn(), {})
            return n

    def reset(self) -> None:
        """Process-side reset only (tests): the file layer keeps its
        entries — point SPARK_RAPIDS_TPU_STATS_STORE at a throwaway
        file to isolate."""
        with self._lock:
            self._proc.clear()
            self._loaded = False


# ------------------------------------------------------------ the collector


def _ingest_epochs(sources) -> Dict[str, int]:
    """Current ingest-epoch vector for a stage's input names (PR 19's
    registry; a source nobody bumped reads 0).  Lazy import keeps the
    observability <- perf layering acyclic at import time."""
    try:
        from spark_rapids_tpu.perf.result_cache import ingest_epoch
        return {str(s): int(ingest_epoch(str(s))) for s in sources}
    except Exception:
        return {str(s): 0 for s in sources}


class StatsCollector:
    """Process-wide estimate registry + observation folder + sentinel.

    ``enabled`` is the one-attribute-read gate the compiler checks
    before building any observation.  ``on_observation(stage, n)``,
    ``on_misestimate(stage, node, est, actual, ratio, first)`` and
    ``on_sketch(ns)`` are the accounting hooks observability/__init__
    points at the ``srt_stats_*`` families."""

    def __init__(self, store: Optional[StatsStore] = None,
                 on_observation: Optional[Callable] = None,
                 on_misestimate: Optional[Callable] = None,
                 on_sketch: Optional[Callable] = None):
        self.enabled = False
        self.store = store if store is not None else StatsStore()
        self.on_observation = on_observation
        self.on_misestimate = on_misestimate
        self.on_sketch = on_sketch
        self._lock = make_rlock("observability.stats")
        # (stage, node) -> {"rows": int, "origin": str}
        self._estimates: Dict[Tuple[str, str], dict] = {}
        # source -> {"rows": int, "origin": str} (parquet footers …)
        self._sources: Dict[str, dict] = {}
        # last stats section per stage (snapshot/debug surface)
        self._last: Dict[str, dict] = {}
        # sketch memo: (stage, input, epoch_sig) -> column stats
        self._sketches: Dict[Tuple[str, str, str], dict] = {}
        # sentinel once-per-key discipline: the flight-recorder
        # bundle fires on the FIRST detection of a (stage, node)
        # misestimate; repeats still count the metric
        self._misest_fired: set = set()
        self._observations = 0
        self._misestimates = 0

    # ------------------------------------------------------- estimates

    def register_estimate(self, stage: str, node: str, rows: int,
                          origin: str = "manual") -> None:
        """Expected row count for one plan node (``input:<name>`` for
        scan inputs).  Catalog runners register generator sizes;
        tests/operators seed deliberate misestimates through the same
        door."""
        with self._lock:
            self._estimates[(str(stage), str(node))] = {
                "rows": int(rows), "origin": str(origin)}

    def register_input_estimates(self, stage: str,
                                 rows_by_input: Dict[str, int],
                                 origin: str = "catalog") -> None:
        for name, rows in rows_by_input.items():
            self.register_estimate(stage, f"input:{name}", rows,
                                   origin=origin)

    def note_source_rows(self, source: str, rows: int,
                         origin: str = "parquet_footer") -> None:
        """Footer-derived estimate for an ingest source (io/ layer):
        consulted as the fallback when no per-node estimate was
        registered for an input of the same name."""
        with self._lock:
            self._sources[str(source)] = {"rows": int(rows),
                                          "origin": str(origin)}

    def estimate_for(self, stage: str, node: str) -> Optional[dict]:
        with self._lock:
            est = self._estimates.get((str(stage), str(node)))
            if est is None and node.startswith("input:"):
                est = self._sources.get(node[len("input:"):])
            return dict(est) if est is not None else None

    def forget_estimates(self) -> None:
        with self._lock:
            self._estimates.clear()
            self._sources.clear()
            self._misest_fired.clear()

    # ----------------------------------------------------- observation

    def _check_misestimate(self, stage: str, node: str,
                           est_rows: int, actual: int) -> Optional[float]:
        """Symmetric divergence ratio when past the threshold, else
        None (the +1 smoothing keeps 0-row actuals finite)."""
        ratio = max((actual + 1) / (est_rows + 1),
                    (est_rows + 1) / (actual + 1))
        if ratio < misest_ratio():
            return None
        return ratio

    def _sketch_for(self, stage: str, name: str, epoch_sig: str,
                    column) -> Optional[dict]:
        """Column stats memoized per (stage, input, epoch vector):
        the sketch pass runs ONCE per key per process, then rides the
        store."""
        key = (stage, name, epoch_sig)
        with self._lock:
            hit = self._sketches.get(key)
        if hit is not None:
            return hit
        try:
            t0 = time.monotonic_ns()
            cs = column_stats(np.asarray(column),
                              max_rows=sketch_row_cap())
            ns = time.monotonic_ns() - t0
        except Exception:
            return None
        hook = self.on_sketch
        if hook is not None:
            try:
                hook(ns)
            except Exception:
                pass
        with self._lock:
            if len(self._sketches) > 512:
                self._sketches.clear()
            self._sketches[key] = cs
        return cs

    def note_stage(self, observation: dict,
                   columns: Optional[Dict[str, object]] = None
                   ) -> Optional[dict]:
        """Fold one stage execution's observed row counts (the
        compiler's tap vector, already host-side ints) into the
        store, join estimates, run the sentinel, and return the
        profile's per-stage ``stats`` section.  Never raises — stats
        must not fail the query they describe."""
        if not self.enabled:
            return None
        try:
            return self._note_stage(observation, columns or {})
        except Exception:
            return None

    def _note_stage(self, observation: dict,
                    columns: Dict[str, object]) -> dict:
        stage = str(observation.get("stage", "?"))
        plan_digest = str(observation.get("plan_digest", "?"))
        inputs = list(observation.get("inputs", ()))
        tapped = list(observation.get("nodes", ()))
        epochs = _ingest_epochs([i["name"] for i in inputs])
        epoch_sig = epoch_signature(epochs)

        nodes: List[dict] = []
        rows_in = 0
        for i in inputs:
            name, rows = str(i["name"]), int(i["rows"])
            rows_in += rows
            row = {"node": f"input:{name}", "kind": "input",
                   "rows": rows}
            col = columns.get(name)
            if col is not None:
                cs = self._sketch_for(stage, name, epoch_sig, col)
                if cs is not None:
                    row["ndv"] = cs["ndv"]
                    row["null_frac"] = cs["null_frac"]
            nodes.append(row)
        for t in tapped[:_MAX_NODES_REPORTED]:
            row = {"node": str(t["node"]), "kind": str(t["kind"]),
                   "rows": int(t["rows"])}
            denom = int(t.get("rows_in", 0)) or rows_in
            if t["kind"] == "Project" and denom > 0:
                row["selectivity"] = round(int(t["rows"]) / denom, 6)
            nodes.append(row)

        misestimates = []
        for row in nodes:
            est = self.estimate_for(stage, row["node"])
            if est is None:
                continue
            row["est"] = int(est["rows"])
            row["est_origin"] = est["origin"]
            ratio = self._check_misestimate(
                stage, row["node"], int(est["rows"]), row["rows"])
            if ratio is None:
                continue
            row["misestimate"] = True
            row["ratio"] = round(ratio, 2)
            misestimates.append(row)
            with self._lock:
                self._misestimates += 1
                first = (stage, row["node"]) not in self._misest_fired
                self._misest_fired.add((stage, row["node"]))
            hook = self.on_misestimate
            if hook is not None:
                try:
                    hook(stage=stage, node=row["node"],
                         est=int(est["rows"]), actual=row["rows"],
                         ratio=row["ratio"], first=first)
                except Exception:
                    pass

        for row in nodes:
            sketch = None
            if row["kind"] == "input":
                name = row["node"][len("input:"):]
                sketch = self._sketches.get((stage, name, epoch_sig))
                if sketch is not None:
                    # the persisted copy keeps the compact sketches,
                    # not the full histogram-of-everything payload
                    sketch = {"ndv": sketch["ndv"],
                              "null_frac": sketch["null_frac"],
                              "min": sketch["min"],
                              "max": sketch["max"],
                              "kmv": sketch["kmv"],
                              "heavy_hitters":
                                  sketch["heavy_hitters"],
                              "histogram": sketch["histogram"]}
            self.store.record(plan_digest, row["node"], epochs,
                              row["rows"], sketch=sketch)

        section = {
            "version": STATS_VERSION,
            "epochs": epochs,
            "rows_in": rows_in,
            "rows_out": (int(tapped[-1]["rows"]) if tapped else None),
            "nodes": nodes,
        }
        with self._lock:
            self._observations += len(nodes)
            self._last[stage] = section
        hook = self.on_observation
        if hook is not None:
            try:
                hook(stage, nodes, misestimates)
            except Exception:
                pass
        return section

    # ------------------------------------------------------------ read

    def last(self, stage: str) -> Optional[dict]:
        with self._lock:
            s = self._last.get(str(stage))
            return dict(s) if s is not None else None

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "observations": self._observations,
                "misestimates": self._misestimates,
                "estimates": {
                    f"{s}/{n}": dict(v)
                    for (s, n), v in sorted(self._estimates.items())},
                "sources": {k: dict(v) for k, v
                            in sorted(self._sources.items())},
                "stages": {k: dict(v) for k, v
                           in sorted(self._last.items())},
            }

    def reset(self) -> None:
        with self._lock:
            self._estimates.clear()
            self._sources.clear()
            self._last.clear()
            self._sketches.clear()
            self._misest_fired.clear()
            self._observations = 0
            self._misestimates = 0
        self.store.reset()
