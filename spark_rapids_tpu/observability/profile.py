"""Per-query profiles: EXPLAIN ANALYZE for every query (ISSUE 13
tentpole).

The reference ships a dedicated profiler sidecar (``profiler/``: CUPTI
activity capture -> flatbuffers -> ``profile_converter``) because
process-wide counters never answer "where did *this query's* time
go".  Our PR 1-12 telemetry has the same gap: metrics, spans, journal
and flight recorder are all process-scoped rings.  This module closes
it by assembling, at query end, ONE typed artifact per query from
seams that already exist:

  * stage records   — plan/compiler.py reports every stage execution
                      (plan digest, fused/unfused engine, wall ns,
                      compile-vs-cache-hit, dispatch count, per-input
                      rows/bucket/pad-waste) while a session is
                      active on the executing thread;
  * metric deltas   — per-task rows from the RmmSpark-bound
                      :class:`TaskMetricsTable` plus registry family
                      deltas (``srt_shuffle_link_*`` per-peer bytes,
                      jit-cache hits/misses) between begin and end;
  * journal window  — retry/OOM episodes, kernel-path and calibration
                      events scoped to the session's thread/tasks by
                      the records' own attribution fields;
  * spans           — finished spans keyed by the query-root
                      trace_id captured at begin.

``world=N`` rank profiles merge into ONE fleet profile
(:func:`merge_profiles`): the launcher-seeded trace context proves the
ranks belong together, per-stage wall is the max over ranks (the
critical path), and the per-rank walls survive as a skew table.
:func:`diff_profiles` compares two profiles per stage and flags
regressions beyond a threshold — the per-node guardrail the
bench-trajectory BENCH_* files cannot give.

Cost discipline (the tracer's noop contract): with profiling disabled
every hook is ONE attribute read — ``begin`` returns None, ``end(None)``
returns None, ``active()`` is False before any dict is touched — so
``SPARK_RAPIDS_TPU_PROFILE=0`` adds no measurable per-query overhead.

The module is dependency-free within the package: the journal, task
table, tracer and registry are injected by ``observability/__init__``
(the ``enabled_ref`` pattern), so tests build isolated profilers.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from spark_rapids_tpu.analysis.lockdep import make_lock

PROFILE_VERSION = 1

# registry families whose begin->end deltas ride the artifact (kept
# small on purpose: the profile stores deltas, never whole snapshots)
_DELTA_FAMILIES = (
    "srt_shuffle_link_bytes_total",
    "srt_shuffle_link_msgs_total",
    "srt_jit_cache_hits_total",
    "srt_jit_cache_misses_total",
)

# journal kinds folded into the artifact when their ``thread`` (or
# ``task``) attribution matches the session
_THREAD_KINDS = ("retry_episode", "kernel_path", "oom_retry",
                 "oom_split_retry", "thread_unblocked",
                 "shuffle_wire", "shuffle_wait",
                 "spill", "spill_restore", "spill_wait",
                 "spill_corrupt", "result_cache")

# the TaskMetricsTable's shared fallback row (threads with no RmmSpark
# binding).  It is process-wide, so its deltas are only trustworthy
# when this session was ALONE for its whole lifetime — a concurrent
# session's ops would otherwise leak into this profile's attribution
_UNATTRIBUTED = -1


def _family_values(fam: Optional[dict]) -> Dict[tuple, float]:
    """{label tuple: value} for one counter/gauge family snapshot
    (missing family = empty)."""
    out: Dict[tuple, float] = {}
    for s in (fam or {}).get("series", []):
        out[tuple(s.get("labels") or ())] = s.get("value", 0)
    return out


def _family_of(registry, name: str) -> Optional[dict]:
    """One family's snapshot WITHOUT walking the whole registry
    (``family_snapshot`` where available; a duck-typed registry
    falls back to its full snapshot)."""
    if registry is None:
        return None
    fn = getattr(registry, "family_snapshot", None)
    if fn is not None:
        return fn(name)
    return (registry.snapshot() or {}).get(name)


def _delta(now: Dict[tuple, float],
           base: Dict[tuple, float]) -> Dict[tuple, float]:
    out = {}
    for k, v in now.items():
        d = v - base.get(k, 0)
        if d:
            out[k] = d
    return out


class ProfileSession:
    """One query being profiled on one thread.  Created by
    :meth:`QueryProfiler.begin`; everything here is a begin-time
    snapshot the assembly diffs against."""

    __slots__ = ("query_id", "tenant", "query", "rank", "world",
                 "queue_wait_ns", "thread", "t0_ns", "t0_unix_ms",
                 "seq0", "trace_id", "task_ids", "task_base",
                 "registry_base", "stage_records", "shared")

    def __init__(self, query_id: str, tenant: str, query: str,
                 rank: int, world: int, *, thread: int, seq0: int,
                 trace_id: Optional[str], task_ids: List[int],
                 task_base: Dict[int, dict], registry_base: dict,
                 queue_wait_ns: int = 0):
        self.query_id = query_id
        self.tenant = tenant
        self.query = query
        self.rank = rank
        self.world = world
        self.queue_wait_ns = queue_wait_ns
        self.thread = thread
        self.t0_ns = time.monotonic_ns()
        self.t0_unix_ms = int(time.time() * 1000)
        self.seq0 = seq0
        self.trace_id = trace_id
        self.task_ids = task_ids
        self.task_base = task_base
        self.registry_base = registry_base
        self.stage_records: List[dict] = []
        # another session overlapped this one at some point: the
        # shared UNATTRIBUTED task row is no longer this query's
        self.shared = False


class QueryProfiler:
    """Process-wide per-query profile assembler.

    ``journal``/``tasks``/``tracer``/``registry`` are the live
    observability singletons (or test doubles); ``keep`` bounds the
    finished-profile ring; ``on_profile(profile, assembly_ns)`` is the
    accounting hook ``observability/__init__`` points at the
    ``srt_profile_*`` families."""

    def __init__(self, journal=None, tasks=None, tracer=None,
                 registry=None, keep: int = 16,
                 on_profile: Optional[Callable[[dict, int], None]]
                 = None,
                 on_drop: Optional[Callable[[str], None]] = None):
        self.enabled = False
        self.journal = journal
        self.tasks = tasks
        self.tracer = tracer
        self.registry = registry
        self.on_profile = on_profile
        self.on_drop = on_drop
        self._lock = make_lock("observability.profile")
        self._sessions: Dict[int, ProfileSession] = {}
        # keep <= 0 disables retention (the server-side knob's 0=off
        # contract): profiles are still assembled and returned, but
        # last()/retained() stay empty and bundles carry no
        # profile.json
        self._keep = max(int(keep), 0)
        self._retained: deque = deque(maxlen=max(self._keep, 1))
        self._assembled = 0
        self._dropped: Dict[str, int] = {}

    # ------------------------------------------------------------ state

    def active(self) -> bool:
        """Is a session open on the calling thread?  ONE attribute
        read when profiling is off (the hot-path guard the compiler
        hook uses before building any stage record)."""
        if not self.enabled:
            return False
        return threading.get_ident() in self._sessions

    def _drop(self, reason: str) -> None:
        with self._lock:
            self._dropped[reason] = self._dropped.get(reason, 0) + 1
        hook = self.on_drop
        if hook is not None:
            try:
                hook(reason)
            except Exception:
                pass  # accounting must never break the query path

    # ------------------------------------------------------------ begin

    def begin(self, query_id: str, tenant: str = "", query: str = "",
              rank: int = 0, world: int = 1, queue_wait_ns: int = 0
              ) -> Optional[ProfileSession]:
        """Open a session bound to the CALLING thread (the thread the
        stage executions will run on).  Returns None when disabled, or
        when the thread already profiles a query (the outer session
        wins; the nested begin is counted dropped).  ``queue_wait_ns``
        is the server's admission-to-dispatch wait: the profile's own
        wall starts at begin, so the pre-dispatch story must be handed
        in for the attribution ledger to see the whole
        admission-to-result wall."""
        if not self.enabled:
            return None
        thread = threading.get_ident()
        with self._lock:
            if thread in self._sessions:
                nested = True
            else:
                nested = False
                self._sessions[thread] = None  # reserve before the
                #                                snapshots below
        if nested:
            self._drop("nested")
            return None
        # snapshots OUTSIDE the profiler lock (registry/task locks are
        # theirs to take; ours only guards the session map), and
        # inside the same never-fail-the-query umbrella end() has —
        # a snapshot failure must also release the reservation, or
        # this thread reads "nested" forever and profiling dies on it
        try:
            trace_id = None
            if self.tracer is not None:
                ctx = self.tracer.current_context()
                if ctx is not None:
                    trace_id = f"{ctx.trace_id:016x}"
            task_ids = (list(self.tasks.tasks_for(thread))
                        if self.tasks is not None else [])
            task_base = {}
            if self.tasks is not None:
                rollup = self.tasks.rollup()
                task_base = {t: rollup[t] for t in task_ids
                             if t in rollup}
            registry_base = {
                name: _family_values(_family_of(self.registry, name))
                for name in _DELTA_FAMILIES} \
                if self.registry is not None else {}
            sess = ProfileSession(
                str(query_id), str(tenant), str(query), int(rank),
                int(world), thread=thread,
                seq0=(self.journal.total_emitted
                      if self.journal is not None else 0),
                trace_id=trace_id, task_ids=task_ids,
                task_base=task_base, registry_base=registry_base,
                queue_wait_ns=max(int(queue_wait_ns), 0))
        except Exception:
            with self._lock:
                if self._sessions.get(thread) is None:
                    self._sessions.pop(thread, None)
            self._drop("begin_error")
            return None
        with self._lock:
            self._sessions[thread] = sess
            if len(self._sessions) > 1:
                # overlapping sessions share the process-wide
                # UNATTRIBUTED task row — mark EVERY live session so
                # none of them claims that row's deltas as its own
                for s in self._sessions.values():
                    if s is not None:
                        s.shared = True
        return sess

    # ----------------------------------------------------- stage feed

    def note_stage(self, record: dict) -> None:
        """One stage execution on the calling thread (plan/compiler's
        hook).  Callers gate on :meth:`active` so a disabled run never
        builds the record dict."""
        if not self.enabled:
            return
        sess = self._sessions.get(threading.get_ident())
        if sess is None:
            self._drop("no_session")
            return
        if len(sess.stage_records) < 4096:  # runaway-loop backstop
            sess.stage_records.append(record)

    # -------------------------------------------------------------- end

    def end(self, session: Optional[ProfileSession]
            ) -> Optional[dict]:
        """Close the session and assemble the profile artifact.
        ``end(None)`` (the disabled begin's return) is a no-op.  The
        artifact is retained in the last-K ring AND returned."""
        if session is None:
            return None
        t_end_ns = time.monotonic_ns()
        with self._lock:
            if self._sessions.get(session.thread) is session:
                del self._sessions[session.thread]
        t0 = time.monotonic_ns()
        try:
            profile = self._assemble(session, t_end_ns)
        except Exception:
            # a profile must never fail the query it describes
            self._drop("assembly_error")
            return None
        assembly_ns = time.monotonic_ns() - t0
        with self._lock:
            if self._keep > 0:
                self._retained.append(profile)
            self._assembled += 1
        hook = self.on_profile
        if hook is not None:
            try:
                hook(profile, assembly_ns)
            except Exception:
                pass
        return profile

    def note_external(self, profile: dict) -> Optional[dict]:
        """Retain an externally-assembled profile (a warm cache hit
        never opens a session — there is no execution to observe —
        but its artifact must still land in the last-K ring and fire
        the profile-end hook so attribution and retention see it)."""
        if not self.enabled:
            return None
        with self._lock:
            if self._keep > 0:
                self._retained.append(profile)
            self._assembled += 1
        hook = self.on_profile
        if hook is not None:
            try:
                hook(profile, 0)
            except Exception:
                pass
        return profile

    # -------------------------------------------------------- assembly

    def _assemble(self, sess: ProfileSession, t_end_ns: int) -> dict:
        stages = self._fold_stages(sess.stage_records)
        hot = max(stages, key=lambda s: s["wall_ns"], default=None)
        profile = {
            "profile_version": PROFILE_VERSION,
            "query_id": sess.query_id,
            "tenant": sess.tenant,
            "query": sess.query,
            "rank": sess.rank,
            "world": sess.world,
            "trace_id": sess.trace_id,
            "t_unix_ms": sess.t0_unix_ms,
            "wall_ns": t_end_ns - sess.t0_ns,
            "queue_wait_ns": sess.queue_wait_ns,
            "stages": stages,
            "hot_stage": hot["stage"] if hot else None,
        }
        profile.update(self._fold_journal(sess))
        profile.update(self._fold_tasks(sess))
        profile.update(self._fold_registry(sess))
        profile.update(self._fold_spans(sess))
        return profile

    @staticmethod
    def _fold_stages(records: List[dict]) -> List[dict]:
        """Aggregate raw stage executions per (stage, digest, engine)
        in first-execution order — a capacity-retry re-run folds into
        its row as another call."""
        order: List[tuple] = []
        agg: Dict[tuple, dict] = {}
        for r in records:
            key = (r.get("stage"), r.get("digest"), r.get("engine"))
            a = agg.get(key)
            if a is None:
                a = dict(r)
                a["calls"] = 0
                a["wall_ns"] = 0
                a["compiled"] = False
                a["compile_ns"] = 0
                agg[key] = a
                order.append(key)
            a["calls"] += 1
            a["wall_ns"] += int(r.get("wall_ns", 0))
            a["compiled"] = a["compiled"] or bool(r.get("compiled"))
            a["compile_ns"] += int(r.get("compile_ns", 0))
            # the dispatch window widens to cover every execution
            if "t_end_ns" in r:
                a["t_end_ns"] = max(int(a.get("t_end_ns", 0)),
                                    int(r["t_end_ns"]))
            # per-node data statistics (ISSUE 20): last execution
            # wins — counts describe one run, not a sum over retries
            if r.get("stats") is not None:
                a["stats"] = r["stats"]
        return [agg[k] for k in order]

    def _fold_journal(self, sess: ProfileSession) -> dict:
        if self.journal is None:
            return {"retries": {}, "oom": {}, "kernel_paths": {},
                    "events": {}, "shuffle": {}, "spill": {},
                    "cache": {}}
        window = [r for r in self.journal.records()
                  if r.get("seq", 0) > sess.seq0]
        tasks = set(sess.task_ids)

        def mine(r: dict) -> bool:
            if r.get("thread") == sess.thread:
                return True
            t = r.get("task")
            if isinstance(t, list):
                return bool(tasks.intersection(t))
            return t in tasks if t is not None else False

        retries = {"episodes": 0, "attempts": 0, "splits": 0,
                   "lost_ns": 0, "outcomes": {}}
        oom = {"retry": 0, "split_retry": 0, "blocked_ns": 0}
        shuffle = {"wire_ns": 0, "wait_ns": 0, "spec_wait_ns": 0}
        spill = {"bytes": 0, "spills": 0, "restores": 0, "ns": 0,
                 "wait_ns": 0, "corrupt": 0, "tiers": {}}
        cache = {"hits": 0, "misses": 0, "puts": 0, "evictions": 0,
                 "folds": 0, "lookup_ns": 0, "bytes": 0}
        kernel_paths: Dict[str, int] = {}
        events: Dict[str, int] = {}
        for r in window:
            kind = r.get("kind", "?")
            # the per-kind counts honor the same attribution filter
            # as the folds below: a record another thread/task wrote
            # during the window is that query's story, not this one's
            if not mine(r):
                continue
            events[kind] = events.get(kind, 0) + 1
            if kind not in _THREAD_KINDS:
                continue
            if kind == "retry_episode":
                retries["episodes"] += 1
                retries["attempts"] += int(r.get("attempts", 0))
                retries["splits"] += int(r.get("splits", 0))
                retries["lost_ns"] += int(r.get("lost_ns", 0))
                out = str(r.get("outcome", "?"))
                retries["outcomes"][out] = \
                    retries["outcomes"].get(out, 0) + 1
            elif kind == "oom_retry":
                oom["retry"] += 1
            elif kind == "oom_split_retry":
                oom["split_retry"] += 1
            elif kind == "thread_unblocked":
                oom["blocked_ns"] += int(r.get("blocked_ns", 0))
            elif kind == "kernel_path":
                k = f"{r.get('op', '?')}:{r.get('path', '?')}"
                kernel_paths[k] = kernel_paths.get(k, 0) + 1
            elif kind == "shuffle_wire":
                shuffle["wire_ns"] += int(r.get("wire_ns", 0))
            elif kind == "shuffle_wait":
                shuffle["wait_ns"] += int(r.get("wait_ns", 0))
                shuffle["spec_wait_ns"] += int(r.get("spec_ns", 0))
            elif kind == "spill":
                spill["spills"] += 1
                spill["bytes"] += int(r.get("bytes", 0))
                spill["ns"] += int(r.get("ns", 0))
                tier = str(r.get("tier", "?"))
                spill["tiers"][tier] = spill["tiers"].get(tier, 0) + 1
            elif kind == "spill_restore":
                spill["restores"] += 1
                spill["ns"] += int(r.get("ns", 0))
            elif kind == "spill_wait":
                spill["wait_ns"] += int(r.get("ns", 0))
            elif kind == "spill_corrupt":
                spill["corrupt"] += 1
            elif kind == "result_cache":
                ev = str(r.get("event", "?"))
                if ev == "hit":
                    cache["hits"] += 1
                    cache["lookup_ns"] += int(r.get("ns", 0))
                elif ev == "miss":
                    cache["misses"] += 1
                    cache["lookup_ns"] += int(r.get("ns", 0))
                elif ev == "put":
                    cache["puts"] += 1
                    cache["bytes"] += int(r.get("bytes", 0))
                elif ev == "eviction":
                    cache["evictions"] += 1
                elif ev == "fold":
                    cache["folds"] += 1
        return {"retries": retries, "oom": oom, "shuffle": shuffle,
                "spill": spill, "kernel_paths": kernel_paths,
                "events": events, "cache": cache}

    def _fold_tasks(self, sess: ProfileSession) -> dict:
        """Per-task metric deltas for the session's RmmSpark-bound
        tasks (ops seen by OTHER tasks between begin and end never
        leak in — this is the task-scoped attribution the issue
        demands).  The shared UNATTRIBUTED fallback row only counts
        when this session was ALONE for its whole lifetime: under
        overlapping sessions (an adaptorless server pool) that row
        mixes every thread's ops, so claiming it would attribute a
        neighbor tenant's work to this query."""
        if self.tasks is None:
            return {"ops": {}, "tasks": {}}
        rollup = self.tasks.rollup()
        # tasks bound DURING the query (the server registers the rmm
        # task before the runner starts, but a late pool binding must
        # still attribute) are unioned with the begin-time set
        ids = set(sess.task_ids) | \
            set(self.tasks.tasks_for(sess.thread))
        if sess.shared:
            ids.discard(_UNATTRIBUTED)
        ops: Dict[str, dict] = {}
        tasks_out: Dict[str, dict] = {}
        for tid in sorted(ids):
            now = rollup.get(tid)
            if now is None:
                continue
            base = sess.task_base.get(tid, {})
            base_ops = base.get("ops", {})
            row = {}
            for field in ("shuffle_write_bytes", "shuffle_merge_rows",
                          "retry_oom", "split_retry_oom",
                          "blocked_time_ns", "lost_time_ns"):
                d = now.get(field, 0) - base.get(field, 0)
                if d:
                    row[field] = d
            for op, o in now.get("ops", {}).items():
                b = base_ops.get(op, {})
                calls = o.get("calls", 0) - b.get("calls", 0)
                t_ns = o.get("time_ns", 0) - b.get("time_ns", 0)
                if calls or t_ns:
                    a = ops.setdefault(op, {"calls": 0, "time_ns": 0})
                    a["calls"] += calls
                    a["time_ns"] += t_ns
            if row:
                tasks_out[str(tid)] = row
        return {"ops": ops, "tasks": tasks_out}

    def _per_peer_delta(self, base: dict,
                        name: str) -> Dict[str, Dict[str, int]]:
        """{direction: {peer: delta}} for one (direction, peer)
        labelled link family."""
        out: Dict[str, Dict[str, int]] = {}
        for labels, d in _delta(
                _family_values(_family_of(self.registry, name)),
                base.get(name, {})).items():
            direction = labels[0] if labels else "?"
            peer = labels[1] if len(labels) > 1 else "?"
            out.setdefault(direction, {})[peer] = int(d)
        return out

    def _fold_registry(self, sess: ProfileSession) -> dict:
        if self.registry is None:
            return {"shuffle_links": {}, "jit": {}}
        links = self._per_peer_delta(
            sess.registry_base, "srt_shuffle_link_bytes_total")
        msgs = self._per_peer_delta(
            sess.registry_base, "srt_shuffle_link_msgs_total")
        jit: Dict[str, dict] = {}
        for name, field in (("srt_jit_cache_hits_total", "hits"),
                            ("srt_jit_cache_misses_total", "misses")):
            for labels, d in _delta(
                    _family_values(_family_of(self.registry, name)),
                    sess.registry_base.get(name, {})).items():
                kernel = labels[0] if labels else "?"
                jit.setdefault(kernel, {})[field] = int(d)
        out = {"shuffle_links": {"bytes": links}, "jit": jit}
        if msgs:
            out["shuffle_links"]["msgs"] = msgs
        return out

    def _fold_spans(self, sess: ProfileSession) -> dict:
        if self.tracer is None or sess.trace_id is None:
            return {"spans": {}}
        by_kind: Dict[str, int] = {}
        n = 0
        for r in self.tracer.records():
            if r.get("trace_id") != sess.trace_id:
                continue
            n += 1
            k = r.get("span_kind", "?")
            by_kind[k] = by_kind.get(k, 0) + 1
        return {"spans": {"count": n, "by_kind": by_kind}}

    # ------------------------------------------------------------- read

    def last(self) -> Optional[dict]:
        """Most recently assembled profile (what a flight-recorder
        bundle freezes as ``profile.json``)."""
        with self._lock:
            return self._retained[-1] if self._retained else None

    def retained(self) -> List[dict]:
        with self._lock:
            return list(self._retained)

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled,
                    "active_sessions": len(self._sessions),
                    "assembled": self._assembled,
                    "retained": len(self._retained),
                    "dropped": dict(self._dropped)}

    def reset(self) -> None:
        with self._lock:
            self._sessions.clear()
            self._retained.clear()
            self._assembled = 0
            self._dropped.clear()


# ------------------------------------------------------------ fleet merge


def merge_profiles(profiles: List[dict]) -> dict:
    """Merge ``world=N`` rank profiles into ONE fleet profile.

    The launcher-seeded trace context is the join key: all ranks of
    one query share a trace_id, and the merge records whether that
    held (``trace_consistent``).  Per-stage wall is the MAX over ranks
    — the critical path a reader cares about — while every rank's own
    wall survives in the per-stage ``per_rank_wall_ns`` map and the
    ``skew`` table (max/min ratio per stage).  Shuffle-link bytes keep
    per-rank resolution (that is the per-link skew evidence ROADMAP
    item 3 wants)."""
    if not profiles:
        raise ValueError("merge_profiles: no profiles given")
    if len(profiles) == 1:
        return dict(profiles[0])
    ranks = []
    seen = set()
    for i, p in enumerate(profiles):
        r = int(p.get("rank", i))
        if r in seen:           # two single-process dumps: reindex
            r = max(seen) + 1
        seen.add(r)
        ranks.append(r)
    trace_ids = {p.get("trace_id") for p in profiles
                 if p.get("trace_id")}
    # "consistent" is a positive claim: EVERY profile must carry the
    # SAME trace id.  Profiles without ids (tracing off) cannot prove
    # they belong to one fleet, so the merge flags them rather than
    # silently blessing unrelated runs
    consistent = len(trace_ids) == 1 and \
        all(p.get("trace_id") for p in profiles)
    order: List[tuple] = []
    agg: Dict[tuple, dict] = {}
    for rank, p in zip(ranks, profiles):
        for s in p.get("stages", []):
            key = (s.get("stage"), s.get("digest"))
            a = agg.get(key)
            if a is None:
                a = dict(s)
                a["calls"] = 0
                a["wall_ns"] = 0
                a["compiled"] = False
                a["per_rank_wall_ns"] = {}
                agg[key] = a
                order.append(key)
            a["calls"] += int(s.get("calls", 1))
            a["compiled"] = a["compiled"] or bool(s.get("compiled"))
            w = int(s.get("wall_ns", 0))
            a["per_rank_wall_ns"][str(rank)] = \
                a["per_rank_wall_ns"].get(str(rank), 0) + w
            engines = {s.get("engine"), a.get("engine")}
            if len(engines - {None}) > 1:
                a["engine"] = "mixed"
            # per-node data statistics (ISSUE 20): rows SUM across
            # ranks (each rank saw its shard), every rank's own count
            # survives in per_rank_rows, and a misestimate flagged by
            # ANY rank stays flagged
            st = s.get("stats")
            if st is not None:
                ms = a.get("stats")
                if ms is None or "_idx" not in ms:
                    ms = {"version": st.get("version"),
                          "epochs": st.get("epochs"),
                          "rows_in": 0, "rows_out": None,
                          "nodes": [], "_idx": {}}
                    a["stats"] = ms
                ms["rows_in"] += int(st.get("rows_in") or 0)
                if st.get("rows_out") is not None:
                    ms["rows_out"] = ((ms["rows_out"] or 0)
                                      + int(st["rows_out"]))
                for n in st.get("nodes", []):
                    mn = ms["_idx"].get(n["node"])
                    if mn is None:
                        mn = dict(n)
                        mn["rows"] = 0
                        mn["per_rank_rows"] = {}
                        ms["_idx"][n["node"]] = mn
                        ms["nodes"].append(mn)
                    mn["rows"] += int(n.get("rows", 0))
                    mn["per_rank_rows"][str(rank)] = \
                        int(n.get("rows", 0))
                    if n.get("misestimate"):
                        mn["misestimate"] = True
                        mn["ratio"] = max(float(n.get("ratio", 0)),
                                          float(mn.get("ratio", 0)))
    skew = []
    for key in order:
        a = agg[key]
        walls = a["per_rank_wall_ns"]
        a["wall_ns"] = max(walls.values(), default=0)
        lo = min(walls.values(), default=0)
        row = {"stage": a["stage"], "digest": a.get("digest"),
               "per_rank_wall_ns": dict(walls),
               "max_wall_ns": a["wall_ns"], "min_wall_ns": lo}
        row["skew_ratio"] = (round(a["wall_ns"] / lo, 3)
                             if lo > 0 else None)
        skew.append(row)
    stages = [agg[k] for k in order]
    for s in stages:
        if isinstance(s.get("stats"), dict):
            s["stats"].pop("_idx", None)
    hot = max(stages, key=lambda s: s["wall_ns"], default=None)

    def _sum_field(field: str, sub: Optional[str] = None) -> dict:
        out: Dict[str, float] = {}
        for p in profiles:
            d = p.get(field) or {}
            if sub is not None:
                d = d.get(sub) or {}
            for k, v in d.items():
                if isinstance(v, (int, float)):
                    out[k] = out.get(k, 0) + v
        return out

    merged = {
        "profile_version": PROFILE_VERSION,
        "fleet": True,
        "world": max([int(p.get("world", 1)) for p in profiles]
                     + [len(profiles)]),
        "ranks": sorted(ranks),
        "query": profiles[0].get("query"),
        "query_id": profiles[0].get("query_id"),
        "tenant": profiles[0].get("tenant"),
        "trace_id": (next(iter(trace_ids))
                     if len(trace_ids) == 1 else None),
        "trace_consistent": consistent,
        "t_unix_ms": min(int(p.get("t_unix_ms", 0))
                         for p in profiles),
        "wall_ns": max(int(p.get("wall_ns", 0)) for p in profiles),
        "queue_wait_ns": max(int(p.get("queue_wait_ns", 0) or 0)
                             for p in profiles),
        "per_rank_wall_ns": {str(r): int(p.get("wall_ns", 0))
                             for r, p in zip(ranks, profiles)},
        "stages": stages,
        "hot_stage": hot["stage"] if hot else None,
        "skew": skew,
        "shuffle_links": {
            "per_rank": {str(r): p.get("shuffle_links") or {}
                         for r, p in zip(ranks, profiles)}},
        "retries": {k: int(v) for k, v in
                    _sum_field("retries").items()},
        "oom": {k: int(v) for k, v in _sum_field("oom").items()},
        "shuffle": {k: int(v) for k, v in
                    _sum_field("shuffle").items()},
        "spill": {k: int(v) for k, v in
                  _sum_field("spill").items()},
        "cache": {k: int(v) for k, v in
                  _sum_field("cache").items()},
        "kernel_paths": {k: int(v) for k, v in
                         _sum_field("kernel_paths").items()},
    }
    return merged


# ------------------------------------------------------------------ diff


def diff_profiles(baseline: dict, current: dict, *,
                  threshold: float = 1.5,
                  min_delta_ns: int = 1_000_000) -> List[dict]:
    """Per-stage regression check: flag every stage whose mean wall
    per call grew past ``threshold`` x the baseline AND by more than
    ``min_delta_ns`` (the floor keeps micro-stage jitter out).
    Stages are matched by NAME (a re-tuned plan changes its digest but
    remains the same logical stage).  Stages present ONLY in the
    baseline — dropped by a re-plan — are reported as ``removed`` rows
    (a vanished stage is a plan change worth seeing, not a silent
    no-op), after the regressions.  Returns findings, most-regressed
    first; regressions carry ``kind == "regression"``; an output with
    only ``removed`` rows means no wall regression."""

    def per_stage(p: dict) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for s in p.get("stages", []):
            a = out.setdefault(str(s.get("stage")),
                               {"calls": 0, "wall_ns": 0})
            a["calls"] += int(s.get("calls", 1))
            a["wall_ns"] += int(s.get("wall_ns", 0))
        for a in out.values():
            a["mean_ns"] = (a["wall_ns"] / a["calls"]
                            if a["calls"] else 0.0)
        return out

    base, cur = per_stage(baseline), per_stage(current)
    findings: List[dict] = []
    for stage, c in cur.items():
        b = base.get(stage)
        if b is None or b["mean_ns"] <= 0:
            continue        # new stages are a plan change, not a
            #                 wall regression
        ratio = c["mean_ns"] / b["mean_ns"]
        if ratio >= threshold \
                and c["mean_ns"] - b["mean_ns"] >= min_delta_ns:
            findings.append({
                "stage": stage,
                "kind": "regression",
                "base_mean_ms": round(b["mean_ns"] / 1e6, 3),
                "cur_mean_ms": round(c["mean_ns"] / 1e6, 3),
                "ratio": round(ratio, 2),
            })
    findings.sort(key=lambda f: -f["ratio"])
    for stage in sorted(set(base) - set(cur)):
        b = base[stage]
        findings.append({
            "stage": stage,
            "kind": "removed",
            "base_mean_ms": round(b["mean_ns"] / 1e6, 3),
            "base_calls": b["calls"],
        })
    return findings
