"""Structured hierarchical tracing: query -> stage -> task -> op spans.

The reference answers "where did this query's time go" with NVTX ranges
around every native op plus a 4.9k-LoC CUPTI profiler streaming a
timeline Nsight can render.  Our PR-1 spine counts things (histograms,
per-task rollups, journal events) but its op brackets are flat and
unparented — it cannot say WHY task 17 was slow, only that it was.
This module adds the missing causality: a process-wide :class:`Tracer`
producing spans with

  * identity      — ``trace_id`` / ``span_id`` / ``parent_id`` (64-bit),
  * time          — monotonic ``t_ns`` start + ``dur_ns``,
  * attribution   — the RmmSpark thread->task binding is consulted at
                    span start, so every span is task-attributed with no
                    per-callsite plumbing,
  * causality     — a per-thread context stack parents each new span
                    under the innermost open one; remote contexts
                    (e.g. carried inside the kudo shuffle wire format)
                    can be activated to re-parent work across threads
                    and processes, and spans can carry ``links`` to
                    other spans' contexts (the shuffle merge links back
                    to every writer span it consumed).

Finished spans land in a bounded ring (a long-lived executor can trace
forever; exports see the most recent ``capacity`` spans plus a drop
count) and are handed to an ``on_finish`` hook — the observability
package points that hook at the EventJournal (span records ride the
same JSONL dump) and at a span-duration histogram in MetricsRegistry
(Prometheus exposition picks up per-op latency distributions for free).

Everything is OFF by default.  When disabled, ``start_span`` returns a
shared no-op span after ONE attribute read — no allocation, no lock —
so the instrumented layers (op_range, kudo, exchange, models) can call
unconditionally.

The module is dependency-free within the package: the task lookup and
the finish hook are injected by ``observability/__init__`` (the same
``enabled_ref`` pattern the journal uses), so tests can build isolated
tracers.
"""

from __future__ import annotations

import json
import random
import threading
import time
from collections import deque
from typing import Callable, Dict, List, NamedTuple, Optional, Union

MAX_ATTRS = 16          # bounded attributes per span
MAX_ATTR_STR = 256      # value strings truncated beyond this
ROOT_PARENT = 0         # parent_id of a trace root


class SpanContext(NamedTuple):
    """The portable identity of a span — what crosses thread, process,
    and shuffle-wire boundaries (16 bytes on the kudo wire)."""

    trace_id: int
    span_id: int


# os.urandom-backed and independent of the global Mersenne Twister:
# forked executor processes (or a test's random.seed) must never
# generate colliding id sequences — the multi-process trace merge in
# tools/trace_export keys spans by span_id across all input files
_ID_RNG = random.SystemRandom()


def _new_id() -> int:
    """Non-zero 64-bit id (0 is the ROOT_PARENT sentinel)."""
    while True:
        v = _ID_RNG.getrandbits(64)
        if v:
            return v


def _clean_attr_value(v):
    """Bound one attribute value (strings truncated, objects repr'd)."""
    if not isinstance(v, (int, float, bool)) and v is not None:
        v = str(v)
        if len(v) > MAX_ATTR_STR:
            v = v[:MAX_ATTR_STR] + "..."
    return v


def _clean_attrs(attrs: Optional[dict]) -> Optional[dict]:
    """Bound attribute count and value size (a runaway attribute dict
    must not make the span ring unbounded in bytes)."""
    if not attrs:
        return None
    out = {}
    for i, (k, v) in enumerate(attrs.items()):
        if i >= MAX_ATTRS:
            out["__attrs_dropped__"] = len(attrs) - MAX_ATTRS
            break
        out[str(k)] = _clean_attr_value(v)
    return out


class Span:
    """One open span.  Context-manager friendly; idempotent ``end``."""

    __slots__ = ("tracer", "trace_id", "span_id", "parent_id", "name",
                 "span_kind", "t0_ns", "thread", "task", "attrs",
                 "links", "_attached", "_ended", "_remote", "_stack")

    def __init__(self, tracer: "Tracer", trace_id: int, span_id: int,
                 parent_id: int, name: str, span_kind: str,
                 task, attrs: Optional[dict], attached: bool,
                 remote: bool = False):
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.span_kind = span_kind
        self.t0_ns = time.monotonic_ns()
        self.thread = threading.get_ident()
        self.task = task
        self.attrs = attrs
        self.links: List[SpanContext] = []
        self._attached = attached
        self._ended = False
        self._remote = remote
        # the context-stack LIST this span was pushed onto (set by the
        # tracer when attach=True): ending a span from a different
        # thread must pop the ORIGIN thread's stack, not the ender's
        self._stack: Optional[List["Span"]] = None

    # ------------------------------------------------------------ api

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_attr(self, key: str, value) -> "Span":
        a = dict(self.attrs) if self.attrs else {}
        dropped = a.pop("__attrs_dropped__", 0)
        key = str(key)
        if key not in a and len(a) >= MAX_ATTRS:
            # evict the OLDEST attribute: a late write (the 'error'
            # marker at span exit, byte counts known only at the end of
            # a shuffle write) carries more signal than the first thing
            # recorded at span start
            del a[next(iter(a))]
            dropped += 1
        a[key] = _clean_attr_value(value)
        if dropped:
            a["__attrs_dropped__"] = dropped
        self.attrs = a
        return self

    def add_link(self, ctx: SpanContext) -> "Span":
        if len(self.links) < 64:  # bounded, like attributes
            self.links.append(SpanContext(*ctx))
        return self

    def end(self) -> None:
        if self._ended:
            return
        self._ended = True
        self.tracer._finish(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        if exc and exc[0] is not None:
            self.set_attr("error", getattr(exc[0], "__name__",
                                           str(exc[0])))
        self.end()

    def __repr__(self):
        return (f"Span({self.name!r} kind={self.span_kind} "
                f"trace={self.trace_id:016x} span={self.span_id:016x})")


class _NoopSpan:
    """Returned when tracing is disabled: absorbs the whole Span API."""

    __slots__ = ()
    trace_id = span_id = parent_id = 0
    name = span_kind = ""
    links = ()

    @property
    def context(self):
        return None

    def set_attr(self, key, value):
        return self

    def add_link(self, ctx):
        return self

    def end(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        pass


NOOP_SPAN = _NoopSpan()


class _ThreadStack(threading.local):
    def __init__(self):
        self.stack: List[Span] = []


class Tracer:
    """Process-wide span factory + bounded finished-span ring.

    ``task_lookup``: zero-arg callable returning the current thread's
    task-id list (observability wires it to ``TASKS.tasks_for``); None
    leaves spans task-less.  ``on_finish``: called with each finished
    span's record dict (observability wires journal + histogram)."""

    def __init__(self, capacity: int = 65536,
                 task_lookup: Optional[Callable[[], list]] = None,
                 on_finish: Optional[Callable[[dict], None]] = None):
        self.enabled = False
        self.capacity = capacity
        self.task_lookup = task_lookup
        self.on_finish = on_finish
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._dropped = 0
        self._ctx = _ThreadStack()

    # ------------------------------------------------------ span start

    def start_span(self, name: str, kind: str = "op",
                   attrs: Optional[dict] = None,
                   parent: Union[Span, SpanContext, None] = None,
                   attach: bool = True):
        """Open a span.  Parent resolution: explicit ``parent`` wins,
        else the innermost open span on this thread, else a fresh trace
        root.  ``attach=False`` records the span without putting it on
        the thread's context stack (episodes that may close out of
        order, e.g. OOM block/unblock)."""
        if not self.enabled:
            return NOOP_SPAN
        stack = self._ctx.stack
        if parent is None and stack:
            parent = stack[-1]
        if parent is None:
            trace_id, parent_id = _new_id(), ROOT_PARENT
        elif isinstance(parent, Span):
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:  # SpanContext (or any (trace_id, span_id) pair)
            trace_id, parent_id = parent[0], parent[1]
        task = None
        if self.task_lookup is not None:
            try:
                ids = self.task_lookup()
                if ids:
                    task = ids[0] if len(ids) == 1 else list(ids)
            except Exception:
                task = None
        span = Span(self, trace_id, _new_id(), parent_id, name, kind,
                    task, _clean_attrs(attrs), attach)
        if attach:
            span._stack = stack
            stack.append(span)
        return span

    def span(self, name: str, kind: str = "op",
             attrs: Optional[dict] = None,
             parent: Union[Span, SpanContext, None] = None):
        """``with tracer.span(...)`` sugar (start_span is the long
        form; both return the Span which is its own context manager)."""
        return self.start_span(name, kind=kind, attrs=attrs,
                               parent=parent)

    # --------------------------------------------------------- context

    def current_context(self) -> Optional[SpanContext]:
        """The innermost open span's context on this thread (what the
        kudo writer embeds in the wire header), or None."""
        stack = self._ctx.stack
        return stack[-1].context if stack else None

    def activate(self, ctx: Optional[SpanContext]):
        """Adopt a remote context as this thread's current parent for
        the duration of the ``with`` block — the shuffle-read side uses
        this to re-parent its spans under the writing task's span.  A
        None ctx (or disabled tracer) is a no-op placeholder so callers
        never branch."""
        if not self.enabled or ctx is None:
            return NOOP_SPAN
        span = Span(self, ctx[0], ctx[1], ROOT_PARENT, "<remote>",
                    "remote", None, None, attached=True, remote=True)
        # a remote placeholder reuses the remote span's OWN id as its
        # span_id so children parent directly to the remote span
        span._stack = self._ctx.stack
        span._stack.append(span)
        return span

    # ---------------------------------------------------------- finish

    def _finish(self, span: Span) -> None:
        stack = span._stack
        if stack is not None:
            # tolerate out-of-order (and cross-thread) ends: remove the
            # span from the stack it was PUSHED onto, wherever it sits
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] is span:
                    del stack[i]
                    break
        if span._remote:
            return  # placeholder: nothing to record
        rec = {
            "kind": "span",
            "name": span.name,
            "span_kind": span.span_kind,
            "trace_id": f"{span.trace_id:016x}",
            "span_id": f"{span.span_id:016x}",
            "parent_id": (f"{span.parent_id:016x}"
                          if span.parent_id else None),
            "t_ns": span.t0_ns,
            "dur_ns": time.monotonic_ns() - span.t0_ns,
            "thread": span.thread,
        }
        if span.task is not None:
            rec["task"] = span.task
        if span.attrs:
            rec["attrs"] = span.attrs
        if span.links:
            rec["links"] = [{"trace_id": f"{c.trace_id:016x}",
                             "span_id": f"{c.span_id:016x}"}
                            for c in span.links]
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
            self._ring.append(rec)
        hook = self.on_finish
        if hook is not None:
            try:
                hook(rec)
            except Exception:
                pass  # exporters must never break the traced code path

    # ------------------------------------------------------------ read

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def records(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def drain(self) -> List[Dict]:
        """Return AND clear the finished-span ring (the flush verb the
        shim's ``tracing_flush`` uses between export intervals)."""
        with self._lock:
            recs = list(self._ring)
            self._ring.clear()
            return recs

    def requeue(self, recs: List[Dict]) -> None:
        """Put drained records back AHEAD of anything recorded since —
        a failed flush (disk full mid-write) must not lose spans.  If
        the combined set overflows capacity, the oldest fall off and
        are counted dropped, like any ring append."""
        with self._lock:
            total = recs + list(self._ring)
            overflow = len(total) - self._ring.maxlen
            if overflow > 0:
                self._dropped += overflow
            self._ring.clear()
            self._ring.extend(total)  # deque(maxlen) keeps the newest

    def depth(self) -> int:
        """Open-span depth on the calling thread (tests)."""
        return len(self._ctx.stack)

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._dropped = 0

    # ------------------------------------------------------------ dump

    def dump_jsonl(self, path_or_file) -> int:
        """Write the finished-span ring as JSON Lines (one process's
        input file for tools/trace_export.py).  Path writes are atomic
        (tmp + rename).  Returns record count."""
        from spark_rapids_tpu.observability.dumpio import dump_via

        recs = self.records()

        def _write(f):
            for r in recs:
                f.write(json.dumps(r) + "\n")
            return len(recs)

        return dump_via(path_or_file, _write)
