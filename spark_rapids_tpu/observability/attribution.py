"""Per-query wall-clock attribution: where did the time go (ISSUE 17
tentpole, half one).

PR 13 profiles say what each stage cost and PR 15/16 say whether a
tenant's SLO is burning, but neither answers the operator's question
on a p99 miss: which nanoseconds of THIS query's admission-to-result
wall were queue wait vs compile vs fused compute vs shuffle wire vs
blocked-on-memory vs straggler wait?  This module classifies a
finished profile artifact (``observability/profile.py``) into an
exhaustive, non-overlapping bucket set:

  queue_wait        server admission -> dispatch (``queue_wait_ns``
                    stamped into the profile by the server)
  compile           ``stage_compile`` build time inside stage walls
                    (``compile_ns`` on stage records; a cache hit is 0)
  compute_fused     fused-engine stage wall minus its compile share
  compute_unfused   every other engine's stage wall minus compile
  shuffle_wire      serialize+send segments (``shuffle_wire`` journal
                    events from distributed/service.py) and kudo
                    write/merge work
  shuffle_wait      inbox idle: blocked waiting on peers' frames
  speculation_wait  gather idle attributable to parts with a live
                    speculation decision (PR 14 stragglers)
  spill_wait        synchronous tiered-store work on this thread:
                    ensure_headroom victim spills + restore round
                    trips (memory/spill.py)
  cache_lookup      semantic result/subplan cache consults
                    (perf/result_cache.py): a warm hit's whole wall
                    IS this bucket; stage/subplan consults happen
                    outside the timed stage walls, so the bucket is
                    counted directly, never carved from compute
  oom_blocked       BUFN time (``thread_unblocked`` blocked_ns)
  retry_lost        failed retry attempts' wall (episodes' lost_ns)
  other             the residual — reported, never silently dropped

Conservation contract (the PR 16 idiom, adapted): the buckets sum to
the measured admission-to-result wall within a smoke-gated tolerance.
The residual is ``other``; when the known buckets OVERCOUNT the wall
(double-attributed seams are a bug, not a rounding error) the excess
is reported as ``overcount_ns`` and ``conserved`` goes false past the
tolerance.  ``attribution-smoke`` gates both directions on clean and
chaos runs.

OOM-blocked and retry-lost nanoseconds happen ON the query thread
inside stage execution, so a naive sum would double-count them against
compute.  The ledger carves them out of the compute buckets
(proportionally, clamped at zero) so the bucket set stays
non-overlapping; whatever cannot be carved (a retry outside any stage)
surfaces as overcount instead of vanishing.

Dependency-free and pure: ledger in, ledger out — the module never
touches the live singletons, so tests and tools feed it synthetic
profiles.  ``observability/__init__`` owns the enabled switch and the
``srt_attribution_*`` accounting.
"""

from __future__ import annotations

from typing import Dict, List, Optional

ATTRIBUTION_VERSION = 1

# every ledger carries ALL buckets, zeros included — a reader must
# never wonder whether a bucket was measured-zero or not-implemented
BUCKETS = (
    "queue_wait",
    "compile",
    "compute_fused",
    "compute_unfused",
    "shuffle_wire",
    "shuffle_wait",
    "speculation_wait",
    "spill_wait",
    "cache_lookup",
    "oom_blocked",
    "retry_lost",
    "other",
)

# the waste buckets an operator hunts on a tail-latency miss — the
# chaos smoke asserts the injected cause dominates THIS set (compute
# legitimately dominates most walls; that is not a finding)
OVERHEAD_BUCKETS = (
    "queue_wait",
    "shuffle_wire",
    "shuffle_wait",
    "speculation_wait",
    "spill_wait",
    "cache_lookup",
    "oom_blocked",
    "retry_lost",
)

# fraction of the measured wall the known buckets may overcount before
# the ledger declares conservation broken (clock granularity + seam
# jitter live below this; double-counted seams blow through it)
DEFAULT_TOLERANCE = 0.25


def _stage_split(stages: List[dict]) -> Dict[str, int]:
    """(compile, compute_fused, compute_unfused) from the folded stage
    rows.  ``compile_ns`` is carved out of the stage's own wall so the
    two never overlap; records from before the stamp existed simply
    report compile 0 (the bucket degrades, the sum still conserves)."""
    compile_ns = 0
    fused = 0
    unfused = 0
    for s in stages or ():
        if str(s.get("engine", "")) == "cached":
            # a cache-hit stage's "wall" is its lookup, already owned
            # by the cache_lookup bucket — counting it here would
            # double-attribute those nanoseconds
            continue
        wall = int(s.get("wall_ns", 0))
        c = min(int(s.get("compile_ns", 0)), wall)
        compile_ns += c
        if str(s.get("engine", "")) == "fused":
            fused += wall - c
        else:
            unfused += wall - c
    return {"compile": compile_ns, "compute_fused": fused,
            "compute_unfused": unfused}


def _carve(buckets: Dict[str, int], amount: int,
           victims: tuple) -> int:
    """Remove ``amount`` ns from ``victims`` proportionally to their
    size (largest absorbs most), clamped at zero.  Returns what could
    NOT be carved — the caller reports it as overcount rather than
    letting the ledger double-claim those nanoseconds."""
    remaining = amount
    while remaining > 0:
        live = [v for v in victims if buckets.get(v, 0) > 0]
        if not live:
            break
        total = sum(buckets[v] for v in live)
        progress = False
        for v in live:
            take = min(buckets[v],
                       max(1, remaining * buckets[v] // total))
            take = min(take, remaining)
            if take > 0:
                buckets[v] -= take
                remaining -= take
                progress = True
            if remaining <= 0:
                break
        if not progress:
            break
    return remaining


def attribute_profile(profile: dict, *,
                      tolerance: float = DEFAULT_TOLERANCE) -> dict:
    """Build the time-attribution ledger for ONE rank's profile
    artifact.  Total wall = admission queue wait (when the server
    stamped it) + the profile's execution wall."""
    exec_wall = int(profile.get("wall_ns", 0))
    queue_wait = max(int(profile.get("queue_wait_ns", 0) or 0), 0)
    wall = queue_wait + exec_wall

    buckets: Dict[str, int] = {b: 0 for b in BUCKETS}
    buckets["queue_wait"] = queue_wait
    buckets.update(_stage_split(profile.get("stages") or []))

    shuffle = profile.get("shuffle") or {}
    buckets["shuffle_wire"] = int(shuffle.get("wire_ns", 0))
    buckets["shuffle_wait"] = int(shuffle.get("wait_ns", 0))
    buckets["speculation_wait"] = int(shuffle.get("spec_wait_ns", 0))

    oom_blocked = int((profile.get("oom") or {}).get("blocked_ns", 0))
    retry_lost = int((profile.get("retries") or {}).get("lost_ns", 0))
    spill_wait = int((profile.get("spill") or {}).get("wait_ns", 0))
    # cache consults run OUTSIDE the timed stage walls (and a warm
    # hit has no stages at all), so the bucket counts directly —
    # carving it from compute would break conservation exactly on the
    # warm-hit profiles it exists to explain
    buckets["cache_lookup"] = int(
        (profile.get("cache") or {}).get("lookup_ns", 0))
    # blocked/lost/spill time happened inside stage walls on this
    # thread: carve it out of compute so the buckets stay
    # non-overlapping
    uncarved = _carve(buckets, oom_blocked + retry_lost + spill_wait,
                      ("compute_unfused", "compute_fused"))
    buckets["oom_blocked"] = oom_blocked
    buckets["retry_lost"] = retry_lost
    buckets["spill_wait"] = spill_wait

    known = sum(buckets[b] for b in BUCKETS if b != "other")
    overcount = max(known - wall, 0) if wall > 0 else max(known, 0)
    buckets["other"] = max(wall - known, 0)
    tol_ns = int(tolerance * wall)
    conserved = overcount <= tol_ns

    nonzero = {b: v for b, v in buckets.items() if v > 0}
    dominant = max(nonzero, key=nonzero.get) if nonzero else None
    overhead = {b: buckets[b] for b in OVERHEAD_BUCKETS
                if buckets[b] > 0}
    dominant_overhead = (max(overhead, key=overhead.get)
                         if overhead else None)

    return {
        "attribution_version": ATTRIBUTION_VERSION,
        "query_id": profile.get("query_id"),
        "tenant": profile.get("tenant", ""),
        "query": profile.get("query", ""),
        "rank": int(profile.get("rank", 0)),
        "world": int(profile.get("world", 1)),
        "wall_ns": wall,
        "exec_wall_ns": exec_wall,
        "buckets": buckets,
        "fractions": {b: (round(v / wall, 4) if wall > 0 else 0.0)
                      for b, v in buckets.items()},
        "dominant": dominant,
        "dominant_overhead": dominant_overhead,
        "overcount_ns": overcount + uncarved,
        "tolerance": tolerance,
        "conserved": conserved and uncarved <= tol_ns,
    }


def attribute_many(profiles: List[dict], *,
                   tolerance: float = DEFAULT_TOLERANCE) -> dict:
    """Fleet rollup: per-rank ledgers plus a combined bucket view.
    Conservation is a PER-RANK claim (the fleet wall is the max over
    ranks, so summed buckets legitimately exceed it when ranks overlap
    in time); the rollup's ``conserved`` is the AND over ranks."""
    if not profiles:
        raise ValueError("attribute_many: no profiles given")
    per_rank = {}
    for i, p in enumerate(profiles):
        led = attribute_profile(p, tolerance=tolerance)
        r = led["rank"]
        if str(r) in per_rank:      # reindex colliding dumps
            r = max(int(k) for k in per_rank) + 1
            led["rank"] = r
        per_rank[str(r)] = led
    combined: Dict[str, int] = {b: 0 for b in BUCKETS}
    for led in per_rank.values():
        for b, v in led["buckets"].items():
            combined[b] = combined.get(b, 0) + v
    total = sum(combined.values())
    nonzero = {b: v for b, v in combined.items() if v > 0}
    overhead = {b: combined[b] for b in OVERHEAD_BUCKETS
                if combined[b] > 0}
    return {
        "attribution_version": ATTRIBUTION_VERSION,
        "fleet": len(per_rank) > 1,
        "query_id": profiles[0].get("query_id"),
        "tenant": profiles[0].get("tenant", ""),
        "query": profiles[0].get("query", ""),
        "wall_ns": max(led["wall_ns"] for led in per_rank.values()),
        "per_rank": per_rank,
        "buckets": combined,
        "fractions": {b: (round(v / total, 4) if total > 0 else 0.0)
                      for b, v in combined.items()},
        "dominant": (max(nonzero, key=nonzero.get)
                     if nonzero else None),
        "dominant_overhead": (max(overhead, key=overhead.get)
                              if overhead else None),
        "conserved": all(led["conserved"]
                         for led in per_rank.values()),
    }


def diff_attribution(baseline: dict, current: dict,
                     *, min_delta_ns: int = 1_000_000
                     ) -> List[dict]:
    """Per-bucket regression attribution for ``srt-explain --diff``:
    which bucket absorbed the extra wall ("q5 got 40% slower and it is
    all shuffle_wait on rank 1").  Returns rows sorted by absolute
    growth, largest first; buckets that shrank ride along with
    negative deltas so the reader sees where the time MOVED."""
    b = baseline.get("buckets") or {}
    c = current.get("buckets") or {}
    wall_delta = (int(current.get("wall_ns", 0))
                  - int(baseline.get("wall_ns", 0)))
    rows: List[dict] = []
    for bucket in BUCKETS:
        d = int(c.get(bucket, 0)) - int(b.get(bucket, 0))
        if abs(d) < min_delta_ns:
            continue
        rows.append({
            "bucket": bucket,
            "base_ms": round(int(b.get(bucket, 0)) / 1e6, 3),
            "cur_ms": round(int(c.get(bucket, 0)) / 1e6, 3),
            "delta_ms": round(d / 1e6, 3),
            "share_of_delta": (round(d / wall_delta, 3)
                               if wall_delta > 0 else None),
        })
    rows.sort(key=lambda r: -abs(r["delta_ms"]))
    return rows


def hot_rank(ledger: dict, bucket: Optional[str] = None) -> Optional[str]:
    """Which rank holds the most nanoseconds of ``bucket`` (or of the
    rollup's dominant bucket) — the "on rank 1" half of the diff
    message.  None for single-rank ledgers."""
    per_rank = ledger.get("per_rank") or {}
    if not per_rank:
        return None
    bucket = bucket or ledger.get("dominant")
    if bucket is None:
        return None
    best, best_v = None, -1
    for r, led in sorted(per_rank.items()):
        v = int((led.get("buckets") or {}).get(bucket, 0))
        if v > best_v:
            best, best_v = r, v
    return best
