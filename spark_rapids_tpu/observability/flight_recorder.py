"""Flight recorder: anomaly-triggered incident bundles.

The reference stack answers "what was the process doing when it died"
with CUPTI profiler dumps and RmmSpark's thread-state dump; our PR 1-4
spine keeps the same evidence — metrics, journal events, finished
spans, the OOM ledger — but only in bounded in-process rings, so by
the time a human looks at a dead query the interesting records have
rotated out.  This module is the black box: always on (when enabled),
near-zero overhead on the quiet path, and at the moment of failure it
freezes every ring into one self-contained on-disk *incident bundle*
that ``tools/doctor.py`` (``srt-doctor``) can diagnose offline.

Bundle layout (one directory per incident, renamed into place whole so
a half-written bundle is never visible):

    incident-<unix_ms>-<kind>-<seq>/
      trigger.json        what fired: kind, severity, detail, cause
                          chain (exception types/messages, and the
                          full attempt history for RetryExhausted)
      metrics.json        full registry snapshot + per-task rollup
                          (wall-clock anchored: snapshot_unix_ms,
                          uptime_s)
      journal.jsonl       journal ring tail + task_rollup records +
                          registry_snapshot (metrics_report format)
      spans.jsonl         finished-span ring tail (trace_export
                          format)
      memory_ledger.json  SparkResourceAdaptor.memory_ledger(): per
                          thread/task allocation totals, watermarks,
                          OOM-state timeline
      threads.json        python-level stacks of every live thread +
                          the adaptor's thread states
      jit_cache.json      perf/jit_cache stats
      fault_rules.json    the fault injector's live rule set
      env.json            process/config fingerprint (SPARK_RAPIDS_*
                          env, versions, argv, pid)
      MANIFEST.json       written LAST: file sizes + bundle version —
                          its presence marks the bundle complete

Safety valves: a minimum interval between bundles (rate limit) and a
global byte budget over the output directory — a crash-looping
executor fills its budget once and then only counts suppressions,
never the disk.  When a bundle would exceed the remaining budget the
journal/span tails are halved stepwise before giving up.

Knobs: ``SPARK_RAPIDS_TPU_FLIGHT_RECORDER`` (=1 enables at import),
``SPARK_RAPIDS_TPU_FLIGHT_RECORDER_DIR`` (default ``./srt_incidents``),
``SPARK_RAPIDS_TPU_FLIGHT_RECORDER_MAX_BYTES`` (default 64 MiB),
``SPARK_RAPIDS_TPU_FLIGHT_RECORDER_HBM_BYTES`` (arms the HBM-pressure
detector).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional

from spark_rapids_tpu.observability import anomaly
from spark_rapids_tpu.observability.dumpio import atomic_write

ENABLE_ENV = "SPARK_RAPIDS_TPU_FLIGHT_RECORDER"
DIR_ENV = "SPARK_RAPIDS_TPU_FLIGHT_RECORDER_DIR"
MAX_BYTES_ENV = "SPARK_RAPIDS_TPU_FLIGHT_RECORDER_MAX_BYTES"
HBM_BYTES_ENV = "SPARK_RAPIDS_TPU_FLIGHT_RECORDER_HBM_BYTES"

DEFAULT_DIR = "srt_incidents"
DEFAULT_MAX_BYTES = 64 << 20
DEFAULT_MIN_INTERVAL_S = 30.0
BUNDLE_VERSION = 1
MANIFEST = "MANIFEST.json"

# journal/span tail sizes tried in order until the bundle fits the
# remaining byte budget
_TAIL_STEPS = (4096, 1024, 256, 64)
MAX_CAUSE_CHAIN = 8


def exception_chain(e: Optional[BaseException]) -> List[dict]:
    """Walk ``__cause__``/``__context__`` into a bounded JSON-able
    chain, innermost last.  RetryExhausted contributes its attempt
    history — the cause chain IS the triage surface."""
    out: List[dict] = []
    seen = set()
    while e is not None and len(out) < MAX_CAUSE_CHAIN:
        if id(e) in seen:
            break
        seen.add(id(e))
        rec = {"type": type(e).__name__, "message": str(e)[:500]}
        attempts = getattr(e, "attempts", None)
        if attempts and isinstance(attempts, list):
            hist = []
            for a in attempts[-16:]:
                hist.append({
                    "index": getattr(a, "index", None),
                    "kind": getattr(a, "kind", None),
                    "error": getattr(a, "error", None),
                    "elapsed_ns": getattr(a, "elapsed_ns", None),
                    "split_depth": getattr(a, "split_depth", 0),
                    "batch_size": getattr(a, "batch_size", None),
                })
            rec["attempts"] = hist
        out.append(rec)
        e = e.__cause__ or e.__context__
    return out


def _jsonable(v, depth: int = 0):
    """Best-effort conversion of trigger detail to JSON-able values
    (a trigger must never fail because a caller passed an object)."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if depth >= 4:
        return str(v)[:200]
    if isinstance(v, dict):
        return {str(k)[:64]: _jsonable(x, depth + 1)
                for k, x in list(v.items())[:32]}
    if isinstance(v, (list, tuple, set)):
        return [_jsonable(x, depth + 1) for x in list(v)[:32]]
    return str(v)[:200]


class FlightRecorder:
    """One per process (``observability.FLIGHT``); tests build their
    own with synthetic clocks."""

    def __init__(self, enabled: bool = False,
                 out_dir: Optional[str] = None,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 min_interval_s: float = DEFAULT_MIN_INTERVAL_S,
                 clock=time.monotonic, wallclock=time.time,
                 straggler: Optional[anomaly.StragglerDetector] = None,
                 retry_storm: Optional[anomaly.RetryStormDetector] = None,
                 hbm: Optional[anomaly.HbmPressureDetector] = None,
                 leak: Optional[anomaly.LeakDetector] = None):
        self.enabled = enabled
        self.out_dir = out_dir or DEFAULT_DIR
        self.max_bytes = int(max_bytes)
        self.min_interval_s = float(min_interval_s)
        self.clock = clock
        self.wallclock = wallclock
        self.straggler = straggler or anomaly.StragglerDetector(
            clock=clock)
        self.retry_storm = retry_storm or anomaly.RetryStormDetector(
            clock=clock)
        self.hbm = hbm or anomaly.HbmPressureDetector(clock=clock)
        self.leak = leak or anomaly.LeakDetector()
        self._lock = threading.Lock()
        # serializes whole dumps: the byte-budget read and the write
        # it authorizes must not interleave across threads, or two
        # concurrent triggers jointly overshoot the budget
        self._dump_lock = threading.Lock()
        self._last_trigger_t: Optional[float] = None
        self._last_error_t: Optional[float] = None
        self._seq = 0
        self._bundles_written = 0
        self._bytes_written = 0
        self._suppressed: Dict[str, int] = {}
        self._last_trigger: Optional[dict] = None

    @classmethod
    def from_env(cls, environ=os.environ) -> "FlightRecorder":
        enabled = environ.get(ENABLE_ENV, "") not in ("", "0")
        out_dir = environ.get(DIR_ENV) or DEFAULT_DIR
        try:
            max_bytes = int(environ.get(MAX_BYTES_ENV, ""))
        except ValueError:
            max_bytes = DEFAULT_MAX_BYTES
        if max_bytes <= 0:
            max_bytes = DEFAULT_MAX_BYTES
        hbm = None
        try:
            hbm_bytes = int(environ.get(HBM_BYTES_ENV, ""))
            if hbm_bytes > 0:
                hbm = anomaly.HbmPressureDetector(
                    threshold_bytes=hbm_bytes)
        except ValueError:
            pass
        return cls(enabled=enabled, out_dir=out_dir,
                   max_bytes=max_bytes, hbm=hbm)

    def configure(self, out_dir: Optional[str] = None,
                  max_bytes: Optional[int] = None,
                  min_interval_s: Optional[float] = None) -> None:
        with self._lock:
            if out_dir:
                self.out_dir = out_dir
            if max_bytes is not None and max_bytes > 0:
                self.max_bytes = int(max_bytes)
            if min_interval_s is not None and min_interval_s >= 0:
                self.min_interval_s = float(min_interval_s)

    # ------------------------------------------------------- detectors
    # Feeds called from observability's record helpers.  Each is one
    # method call + the detector's few deque/dict ops; callers gate on
    # `FLIGHT.enabled` first so the disabled path is one attribute read.

    def observe_span(self, rec: dict) -> None:
        if rec.get("span_kind") != "stage":
            return
        task = rec.get("task")
        fire = self.straggler.observe(rec.get("name", "?"),
                                      rec.get("dur_ns", 0), task=task)
        if fire:
            self.trigger("straggler", severity="warn", **fire)

    def observe_retry_episode(self, name: str, outcome: str) -> None:
        fire = self.retry_storm.observe(name)
        if fire:
            fire["last_outcome"] = outcome
            self.trigger("retry_storm", severity="warn", **fire)

    def observe_hbm(self, device, bytes_in_use: int) -> None:
        fire = self.hbm.observe(device, bytes_in_use)
        if fire:
            self.trigger("hbm_pressure", severity="warn", **fire)

    def observe_task_leak(self, task_id: int, leaked_bytes: int,
                          holders=()) -> None:
        fire = self.leak.observe(task_id, leaked_bytes, holders)
        if fire:
            self.trigger("memory_leak", severity="error", **fire)

    # --------------------------------------------------------- trigger

    def trigger(self, kind: str, cause: Optional[BaseException] = None,
                force: bool = False, severity: str = "error",
                **detail) -> Optional[str]:
        """Freeze an incident bundle.  Returns the bundle path, or
        None when disabled/suppressed.  ``force=True`` (the shim's
        ``incident_dump``) bypasses the enabled flag and the rate
        limit but still honors the byte budget."""
        if not self.enabled and not force:
            return None
        now = self.clock()
        with self._lock:
            # severity-aware rate limit: an error trigger is only
            # limited by previous ERROR bundles — a warn bundle (a
            # retry storm fired by the very episode that then
            # exhausts) must never shadow the terminal bundle whose
            # cause chain is the whole point.  Warn triggers are
            # limited by everything.
            last = (self._last_error_t if severity == "error"
                    else self._last_trigger_t)
            if not force and last is not None \
                    and now - last < self.min_interval_s:
                self._suppressed["rate_limit"] = \
                    self._suppressed.get("rate_limit", 0) + 1
                self._count("suppressed", "rate_limit")
                return None
            prev_t, prev_e = self._last_trigger_t, self._last_error_t
            self._last_trigger_t = now
            if severity == "error":
                self._last_error_t = now
            self._seq += 1
            seq = self._seq
        record = {
            "kind": kind,
            "severity": severity,
            "seq": seq,
            "t_unix_ms": int(self.wallclock() * 1000),
            "t_mono_ns": time.monotonic_ns(),
            "pid": os.getpid(),
            "thread": threading.get_ident(),
            "detail": _jsonable(detail),
            "cause_chain": exception_chain(cause),
        }
        with self._lock:
            self._last_trigger = record
        try:
            path = self._dump_bundle(record)
        except Exception:
            # the recorder must never take down the failing code path
            # it is documenting.  Roll back the rate-limit stamps: a
            # TRANSIENT write failure (disk momentarily full) must not
            # shadow the next genuine incident.  (Byte-budget
            # suppression keeps the stamps — retrying cannot help
            # until the budget changes.)
            with self._lock:
                if self._last_trigger_t == now:
                    self._last_trigger_t = prev_t
                if self._last_error_t == now:
                    self._last_error_t = prev_e
                self._suppressed["error"] = \
                    self._suppressed.get("error", 0) + 1
            self._count("suppressed", "error")
            return None
        if path is not None:
            self._count("written", kind)
        return path

    def _count(self, what: str, label: str) -> None:
        """Fold recorder activity into the metrics registry (lazy
        import: this module must stay import-clean of the package)."""
        try:
            from spark_rapids_tpu import observability as obs
            if what == "written":
                obs.INCIDENTS_TOTAL.inc(labels=(label,))
            else:
                obs.INCIDENTS_SUPPRESSED.inc(labels=(label,))
        except Exception:
            pass

    # ------------------------------------------------------------ dump

    def _existing_bytes(self) -> int:
        """Total size of complete bundles already in the output dir —
        counted from their manifests so the budget survives process
        restarts and concurrent writers."""
        total = 0
        try:
            names = os.listdir(self.out_dir)
        except OSError:
            return 0
        for name in names:
            if name.endswith(".tmp"):
                continue  # crash leftovers are litter, not bundles —
            #             they must not eat the budget forever
            try:
                with open(os.path.join(self.out_dir, name,
                                       MANIFEST)) as f:
                    total += int(json.load(f).get("total_bytes", 0))
            except (OSError, ValueError):
                continue
        return total

    def _collect_fixed_files(self, record: dict) -> Dict[str, str]:
        """Render every tail-independent bundle file to a string
        (sizes must be known before anything touches disk: the byte
        budget is a promise).  Rendered ONCE per trigger — only the
        journal/span tails re-render while shrinking to the budget."""
        from spark_rapids_tpu import observability as obs
        files: Dict[str, str] = {}
        files["trigger.json"] = json.dumps(record, indent=2,
                                           sort_keys=True, default=str)
        files["metrics.json"] = json.dumps(obs.snapshot(),
                                           sort_keys=True)

        ledger: dict = {}
        states: List[dict] = []
        try:
            from spark_rapids_tpu.memory import rmm_spark
            adaptor = rmm_spark.installed_adaptor()
            if adaptor is not None:
                ledger = adaptor.memory_ledger()
                states = adaptor.thread_state_dump()
        except Exception:
            ledger = {"error": "memory ledger unavailable"}
        files["memory_ledger.json"] = json.dumps(ledger, indent=2,
                                                 sort_keys=True,
                                                 default=str)
        files["threads.json"] = json.dumps(
            {"python": self._python_threads(), "adaptor": states},
            indent=2, sort_keys=True, default=str)

        try:
            from spark_rapids_tpu.perf import jit_cache
            files["jit_cache.json"] = json.dumps(
                jit_cache.CACHE.stats(), sort_keys=True, default=str)
        except Exception:
            files["jit_cache.json"] = "{}"

        try:
            from spark_rapids_tpu.utils import fault_injection as fi
            inj = fi.installed()
            files["fault_rules.json"] = json.dumps(
                inj.active_rules() if inj is not None else [])
        except Exception:
            files["fault_rules.json"] = "[]"

        # ISSUE 13: the most recent per-query profile rides every
        # bundle so srt-doctor can name the slowest plan node, not
        # just the slowest thread.  Only written when one exists —
        # a profiler-off process keeps its bundle layout unchanged.
        try:
            prof = obs.PROFILER.last()
            if prof is not None:
                files["profile.json"] = json.dumps(
                    prof, indent=2, sort_keys=True, default=str)
        except Exception:
            pass   # a malformed profile must not block the bundle

        # ISSUE 17: the last query's time-attribution ledger freezes
        # alongside the profile so srt-doctor can name the dominant
        # bucket at incident time.  Attribution-off processes keep
        # their bundle layout unchanged.
        try:
            led = obs.attribution_last()
            if led is not None:
                files["attribution.json"] = json.dumps(
                    led, indent=2, sort_keys=True, default=str)
        except Exception:
            pass   # a torn ledger must not block the bundle

        files["env.json"] = json.dumps(self._env_fingerprint(),
                                       indent=2, sort_keys=True)
        return files

    @staticmethod
    def _collect_tail_files(tail: int) -> Dict[str, str]:
        """The two ring dumps whose size scales with ``tail``."""
        from spark_rapids_tpu import observability as obs
        lines = [json.dumps(r, default=str)
                 for r in obs.JOURNAL.records()[-tail:]]
        for task_id, d in obs.TASKS.rollup().items():
            lines.append(json.dumps(
                {"kind": "task_rollup", "task": task_id, **d}))
        lines.append(json.dumps({"kind": "registry_snapshot",
                                 "registry": obs.METRICS.snapshot()}))
        return {
            "journal.jsonl": "\n".join(lines) + "\n",
            "spans.jsonl": "".join(
                json.dumps(r, default=str) + "\n"
                for r in obs.TRACER.records()[-tail:]),
        }

    @staticmethod
    def _python_threads() -> List[dict]:
        frames = sys._current_frames()
        out = []
        for t in threading.enumerate():
            frame = frames.get(t.ident)
            stack = (traceback.format_stack(frame, limit=24)
                     if frame is not None else [])
            out.append({"ident": t.ident, "name": t.name,
                        "daemon": t.daemon,
                        "stack": [s.rstrip() for s in stack]})
        return out

    @staticmethod
    def _env_fingerprint() -> dict:
        env = {k: v for k, v in sorted(os.environ.items())
               if k.startswith(("SPARK_RAPIDS_TPU_", "FAULT_INJECTOR_",
                                "JAX_", "XLA_", "BENCH_"))}
        fp = {"pid": os.getpid(), "argv": sys.argv,
              "python": sys.version.split()[0],
              "platform": sys.platform, "env": env}
        try:
            import jax
            fp["jax"] = jax.__version__
        except Exception:
            pass
        return fp

    def _dump_bundle(self, record: dict) -> Optional[str]:
        with self._dump_lock:
            return self._dump_bundle_locked(record)

    def _dump_bundle_locked(self, record: dict) -> Optional[str]:
        kind = "".join(c if c.isalnum() or c in "_-" else "_"
                       for c in record["kind"])[:40]
        os.makedirs(self.out_dir, exist_ok=True)
        remaining = self.max_bytes - self._existing_bytes()
        # sizes are ON-DISK (UTF-8) bytes, not character counts — the
        # budget is a promise about the directory, not about str lens
        fixed = {k: v.encode("utf-8")
                 for k, v in self._collect_fixed_files(record).items()}
        for tail in _TAIL_STEPS:
            files = dict(fixed, **{
                k: v.encode("utf-8")
                for k, v in self._collect_tail_files(tail).items()})
            # +1024: headroom for the manifest itself
            if sum(len(v) for v in files.values()) + 1024 <= remaining:
                break
        else:
            # even the smallest tails blow the budget: suppress
            with self._lock:
                self._suppressed["byte_budget"] = \
                    self._suppressed.get("byte_budget", 0) + 1
            self._count("suppressed", "byte_budget")
            return None
        name = (f"incident-{record['t_unix_ms']}-{kind}"
                f"-{record['seq']:03d}")
        final = os.path.join(self.out_dir, name)
        n = 0
        while os.path.exists(final):
            n += 1
            final = os.path.join(self.out_dir, f"{name}.{n}")
        tmp = final + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        try:
            sizes = {}
            for fname, content in files.items():
                atomic_write(os.path.join(tmp, fname),
                             lambda f, c=content: f.write(c),
                             mode="wb")
                sizes[fname] = len(content)
            manifest = {
                "bundle_version": BUNDLE_VERSION,
                "trigger_kind": record["kind"],
                "severity": record["severity"],
                "seq": record["seq"],
                "t_unix_ms": record["t_unix_ms"],
                "files": sizes,
                "total_bytes": sum(sizes.values()),
            }
            # manifest LAST: its presence marks a complete bundle
            atomic_write(os.path.join(tmp, MANIFEST),
                         lambda f: f.write(json.dumps(manifest,
                                                      indent=2,
                                                      sort_keys=True)))
            os.rename(tmp, final)
        except BaseException:
            import shutil
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        with self._lock:
            self._bundles_written += 1
            self._bytes_written += manifest["total_bytes"]
        return final

    # ------------------------------------------------------ inspection

    def incident_list(self) -> List[dict]:
        """Complete bundles under the output dir (manifest-bearing),
        oldest first."""
        out: List[dict] = []
        try:
            names = sorted(os.listdir(self.out_dir))
        except OSError:
            return out
        for name in names:
            if name.endswith(".tmp"):
                continue  # a bundle still being assembled
            path = os.path.join(self.out_dir, name)
            try:
                with open(os.path.join(path, MANIFEST)) as f:
                    m = json.load(f)
            except (OSError, ValueError):
                continue
            out.append({"path": path,
                        "kind": m.get("trigger_kind"),
                        "severity": m.get("severity"),
                        "seq": m.get("seq"),
                        "t_unix_ms": m.get("t_unix_ms"),
                        "total_bytes": m.get("total_bytes")})
        out.sort(key=lambda r: (r["t_unix_ms"] or 0, r["seq"] or 0,
                                r["path"]))
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "dir": self.out_dir,
                "max_bytes": self.max_bytes,
                "min_interval_s": self.min_interval_s,
                "bundles_written": self._bundles_written,
                "bytes_written": self._bytes_written,
                "suppressed": dict(self._suppressed),
                "last_trigger": self._last_trigger,
            }
