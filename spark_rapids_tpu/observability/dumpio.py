"""Atomic file dumps: tmp-file + rename for every observability sink.

Every dump this package writes (journal JSONL, span JSONL, incident
bundles) may race a crash — the whole point of the flight recorder is
that the process is usually dying when these files matter.  A plain
``open(path, "w")`` that dies mid-write leaves a truncated JSONL that
the doctor/exporters then choke on, which is exactly when they must
not.  This helper is the one place that gets the dance right:

  * write to a uniquely-named sibling tmp file (same directory, so the
    rename is not a cross-device copy),
  * flush + fsync before the rename (the rename must never beat the
    data to disk),
  * ``os.replace`` into place (atomic on POSIX; readers see either the
    old complete file or the new complete file, never a torn one),
  * unlink the tmp on ANY failure so aborted dumps leave no litter.

Callers that accept "path or open file" keep their file-object branch
untouched — a caller-owned stream's durability is the caller's
contract.
"""

from __future__ import annotations

import os
import tempfile
from typing import Callable, TypeVar

T = TypeVar("T")

# mkstemp creates 0600 files; a dump must end up with the same
# permissions a plain open(path, "w") would have produced (0666 minus
# umask), or cross-user readers — log shippers, the JVM side — lose
# access.  Read the umask once at import (single-threaded there; the
# set/restore dance is not thread-safe later).
_UMASK = os.umask(0)
os.umask(_UMASK)


def atomic_write(path: str, writer: Callable[..., T], mode: str = "w") -> T:
    """Run ``writer(f)`` against a tmp file, then atomically replace
    ``path`` with it.  Returns whatever ``writer`` returns.  On any
    failure the tmp file is removed and ``path`` is left exactly as it
    was (present and complete, or absent)."""
    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=directory,
                               prefix=os.path.basename(path) + ".",
                               suffix=".tmp")
    try:
        os.fchmod(fd, 0o666 & ~_UMASK)
        with os.fdopen(fd, mode) as f:
            result = writer(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return result
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def dump_via(path_or_file, writer: Callable[..., T]) -> T:
    """Shared path-or-file dispatch: an open file object is written
    directly (caller owns its lifecycle); a path goes through
    :func:`atomic_write`."""
    if hasattr(path_or_file, "write"):
        return writer(path_or_file)
    return atomic_write(path_or_file, writer)
